"""Shared measurement harness for bench.py and experiments/scaling.py.

One copy of the recipe (build trainer -> synthetic device batch -> warmup ->
timed windows) so the headline bench and the experiment tables stay
comparable — the throughput-meter role of the reference
(/root/reference/train_ddp.py:224-243), done without host syncs in the loop.

Timing methodology (important): the synchronization point is a **value
fetch** (`jax.device_get` of a step output), not `block_until_ready`. On the
tunneled bench backend `block_until_ready` can return before execution
finishes, which inflated a round-2 measurement to 484 TFLOP/s on a
197 TFLOP/s chip. A value fetch cannot lie — the bytes must exist — but it
carries a constant round-trip cost, so the rate is computed by **window
differencing**: time T(k) for k steps and T(2k) for 2k steps (each
fetch-synced) and report k / (T(2k) - T(k)). Constant per-window overhead
(tunnel RTT, dispatch, fetch) cancels exactly. Windows auto-grow until the
differenced time is large enough to trust.
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def build_image_trainer(devices: Sequence[jax.Device], bf16: bool,
                        model_name: str = "resnet18", image_hw: int = 32,
                        num_classes: int = 10, zero1: bool = False,
                        grad_sync: Optional[dict] = None,
                        mesh_spec: Optional[str] = None):
    """(trainer, state, mesh) for an image-classification config on a pure-DP
    mesh over `devices` (the benchmark workload, BASELINE.json:8).
    ``zero1`` switches the trainer to the sharded weight update;
    ``grad_sync`` holds TrainConfig overrides for the explicit reducer
    (bucket_cap_mb / wire_dtype / overlap_grad_sync / grad_accum).
    ``mesh_spec`` may name BATCH axes only ("slice=2,data=-1", the
    int8_hier tiered-wire arms) — image models ship replicated-only
    partition rules, so a model/seq axis is rejected upstream."""
    from ..data import CIFAR10_MEAN, CIFAR10_STD
    from ..models import get_model
    from ..parallel import MeshSpec, build_mesh
    from ..training import TrainConfig, Trainer
    from ..training.optim import sgd
    from ..training.tasks import ImageClassificationTask

    spec = (MeshSpec.parse(mesh_spec) if mesh_spec
            else MeshSpec(data=len(devices)))
    mesh = build_mesh(spec, devices=list(devices))
    dtype = jnp.bfloat16 if bf16 else jnp.float32
    model = get_model(model_name, num_classes=num_classes, dtype=dtype)
    task = ImageClassificationTask(mean=CIFAR10_MEAN, std=CIFAR10_STD,
                                   augment=True, compute_dtype=dtype)
    trainer = Trainer(task, mesh, TrainConfig(seed=0, bf16=bf16,
                                              zero1=zero1,
                                              **(grad_sync or {})))
    state = trainer.init_state(
        model, np.zeros((1, image_hw, image_hw, 3), np.float32),
        sgd(0.1, momentum=0.9, weight_decay=5e-4), jax.random.PRNGKey(0))
    return trainer, state, mesh


def is_lm_model(model_name: str) -> bool:
    """One source of truth for the image-vs-LM dispatch (bench + drivers)."""
    return model_name.startswith(("gpt2", "bert"))


def lm_vocab(model_name: str) -> int:
    return 30522 if model_name.startswith("bert") else 50257


def build_lm_trainer(devices: Sequence[jax.Device], bf16: bool,
                     model_name: str, seq_len: int,
                     model_kwargs: Optional[dict] = None,
                     zero1: bool = False,
                     grad_sync: Optional[dict] = None,
                     mesh_spec: Optional[str] = None):
    """(trainer, state, mesh) for a language-model config (gpt2_*/bert_base,
    BASELINE.json:11-12) on a pure-DP mesh, AdamW, real vocab sizes.
    `model_kwargs` overrides architecture fields (CI smoke runs shrink the
    model; benchmarks use the real sizes). ``grad_sync`` — see
    `build_image_trainer`. ``mesh_spec`` ("data=-1,model=2") builds the
    2-D explicit TP x FSDP mesh (the gpt2_355m_fsdp_tp bench arm); the
    vocab pads to lcm(128, model) exactly as train.py pads it."""
    import math

    from ..models import get_model
    from ..parallel import MeshSpec, build_mesh
    from ..training import TrainConfig, Trainer
    from ..training.optim import adamw
    from ..training.tasks import (
        LanguageModelingTask, MaskedLMTask, MoeLanguageModelingTask,
    )

    spec = (MeshSpec.parse(mesh_spec) if mesh_spec
            else MeshSpec(data=len(devices)))
    mesh = build_mesh(spec, devices=list(devices))
    model_n = dict(mesh.shape).get("model", 1)
    dtype = jnp.bfloat16 if bf16 else jnp.float32
    kwargs = dict(model_kwargs or {})
    if model_n > 1:
        kwargs.setdefault("pad_vocab_to_multiple_of",
                          math.lcm(128, model_n))
    from ..ops.flash_attention import (
        flash_backend_supported, flash_supports_length,
    )

    if "attention_fn" not in kwargs and flash_backend_supported() \
            and flash_supports_length(seq_len):
        # Benchmark with the flash kernel — the fast path users get via
        # --attention flash (auto default): 42% faster than the einsum path
        # for GPT-2 @ S=1024 on v5e. Legal for BERT too (bidirectional,
        # causal=False; padding masks ride the kernel). The length gate
        # matches resolve_attention: a seq_len with no usable block (e.g.
        # 2056) falls back to the einsum path instead of erroring at trace.
        from ..ops import make_flash_attention_fn

        kwargs["attention_fn"] = make_flash_attention_fn(
            causal=not model_name.startswith("bert"))
    model = get_model(model_name, dtype=dtype, max_position=max(seq_len, 512),
                      **kwargs)
    if model_name.startswith("bert"):
        task = MaskedLMTask(compute_dtype=dtype)
    elif "moe" in model_name:
        # measuring an MoE step without the router load-balancing loss
        # would time a step nobody trains
        task = MoeLanguageModelingTask(compute_dtype=dtype)
    else:
        task = LanguageModelingTask(compute_dtype=dtype)
    from ..parallel.mesh import BATCH_AXES, batch_shard_count

    trainer = Trainer(task, mesh, TrainConfig(seed=0, bf16=bf16,
                                              zero1=zero1,
                                              **(grad_sync or {})),
                      rules=type(model).partition_rules())
    # zero1/fsdp shard the update; the AdamW global-norm clip must psum
    # across the shards or each replica clips by its own shard's norm
    # (optim.py). On a single batch shard the Trainer runs the replicated
    # (non-shard_map) path, where a psum over the batch axes would hit
    # unbound axis names — shard_axes must follow the SAME passthrough
    # condition.
    fsdp = bool((grad_sync or {}).get("fsdp_explicit"))
    explicit_tp = fsdp and model_n > 1
    # zero1 on a model-axis mesh runs the per-leaf GSPMD update OUTSIDE
    # shard_map, where a batch-axes psum in the clip would hit unbound
    # axis names — the same exclusion train.py applies
    sharded = ((zero1 and model_n <= 1) or fsdp) \
        and (batch_shard_count(mesh) > 1 or explicit_tp)
    from ..parallel.mesh import MODEL

    shard_axes = None
    clip_weights = None
    if sharded:
        shard_axes = (((MODEL,) + BATCH_AXES) if explicit_tp
                      else BATCH_AXES)
    if explicit_tp:
        # the clip's norm psum rides (model,) + batch axes; the TP layout
        # stores model-replicated leaves once per model shard, so their
        # squared contributions down-weight 1/M — the ONE derivation
        # train.py also uses (parallel/sharding.py)
        from ..parallel.sharding import tp_clip_weights_for_model

        clip_weights = tp_clip_weights_for_model(
            model, type(model).partition_rules(), model_n,
            np.zeros((model_n, seq_len), np.int32))
    tx = adamw(1e-4, shard_axes=shard_axes,
               clip_leaf_weights=clip_weights)
    state = trainer.init_state(model, np.zeros((1, seq_len), np.int32),
                               tx, jax.random.PRNGKey(0))
    return trainer, state, mesh


def build_trainer(devices: Sequence[jax.Device], bf16: bool, model_name: str,
                  seq_len: int = 512, image_hw: int = 32,
                  num_classes: int = 10,
                  lm_overrides: Optional[dict] = None,
                  zero1: bool = False,
                  grad_sync: Optional[dict] = None,
                  mesh_spec: Optional[str] = None):
    """Model-family dispatch used by bench.py AND the experiment drivers —
    the same `--model` string must measure the same config everywhere.
    ``mesh_spec`` ("data=-1,model=2") builds a 2-D mesh for the explicit
    TP x FSDP arms — LM models only (image models ship replicated-only
    partition rules)."""
    if is_lm_model(model_name):
        return build_lm_trainer(devices, bf16, model_name, seq_len,
                                lm_overrides, zero1=zero1,
                                grad_sync=grad_sync, mesh_spec=mesh_spec)
    if mesh_spec:
        # image models may tier their BATCH axes (slice=2,data=-1 — the
        # int8_hier arms); any non-batch axis > 1 needs partition rules
        # image models don't have
        from ..parallel import MeshSpec
        from ..parallel.mesh import BATCH_AXES

        sizes = dataclasses.asdict(MeshSpec.parse(mesh_spec))
        bad = {a: s for a, s in sizes.items()
               if s not in (1,) and a not in BATCH_AXES}
        if bad:
            raise ValueError(
                f"mesh_spec={mesh_spec!r} puts {bad} on non-batch axes; "
                f"{model_name} has no TP/seq/pipe form — image models "
                "accept batch-axis tiers only (slice/data/fsdp)")
    return build_image_trainer(devices, bf16, model_name, image_hw,
                               num_classes, zero1=zero1,
                               grad_sync=grad_sync, mesh_spec=mesh_spec)


def make_synth_batch(mesh, model_name: str, per_device_batch: int,
                     seq_len: int = 512, image_hw: int = 32,
                     num_classes: int = 10):
    """(sharded batch, global batch) matching `build_trainer`'s config."""
    if is_lm_model(model_name):
        return synth_token_batch(mesh, per_device_batch, seq_len,
                                 lm_vocab(model_name))
    return synth_image_batch(mesh, per_device_batch, image_hw, num_classes)


def synth_image_batch(mesh, per_device_batch: int, image_hw: int = 32,
                      num_classes: int = 10):
    """(sharded_batch, global_batch): deterministic uint8 batch on the mesh."""
    from ..parallel import shard_batch
    from ..parallel.mesh import batch_shard_count

    global_batch = per_device_batch * batch_shard_count(mesh)
    rng = np.random.RandomState(0)
    batch = shard_batch({
        "image": rng.randint(0, 256, (global_batch, image_hw, image_hw, 3)
                             ).astype(np.uint8),
        "label": rng.randint(0, num_classes, global_batch).astype(np.int32),
        "weight": np.ones(global_batch, np.float32),
    }, mesh)
    return batch, global_batch


def synth_token_batch(mesh, per_device_batch: int, seq_len: int,
                      vocab_size: int = 50257):
    """(sharded_batch, global_batch): deterministic token batch on the mesh."""
    from ..parallel import shard_batch
    from ..parallel.mesh import batch_shard_count

    global_batch = per_device_batch * batch_shard_count(mesh)
    rng = np.random.RandomState(0)
    batch = shard_batch({
        "input_ids": rng.randint(0, vocab_size,
                                 (global_batch, seq_len)).astype(np.int32),
        "weight": np.ones(global_batch, np.float32),
    }, mesh)
    return batch, global_batch


def trace_exposed_comm(build_fn, key=None, steps: int = 3):
    """Best-effort exposed-comm fraction of a train step
    (`trace_analysis.comm_overlap_split` over a short jax.profiler
    capture). ``build_fn() -> (trainer, state, batch)`` must build a
    SACRIFICIAL trainer/state: the jitted step donates its input state, so
    a capture that dies mid-step consumes those buffers — they must never
    be the ones a timed run still needs. Returns the percentage, or None
    on any failure (the number is an observability nicety, never worth
    failing a measurement for).
    """
    import tempfile

    from .trace_analysis import capture_step_trace, comm_overlap_split

    try:
        trainer, state, batch = build_fn()
        key = jax.random.PRNGKey(0) if key is None else key
        state, _ = trainer._train_step(state, batch, key)  # warmup/compile
        with tempfile.TemporaryDirectory(prefix="comm_trace_") as td:
            capture_step_trace(trainer._train_step, state, batch, key, td,
                               steps=steps)
            return comm_overlap_split(td)["exposed_frac_pct"]
    except Exception:
        return None


def _fetch(metrics) -> float:
    """True completion sync: pull a step-output VALUE to the host. Unlike
    block_until_ready this cannot return before the program has executed."""
    return float(jax.device_get(metrics["weight"]))


def _run_window(step_fn: Callable, state, batch, key, n: int):
    """Dispatch n steps and fetch-sync; returns (state, wall seconds)."""
    t0 = time.perf_counter()
    metrics = None
    for _ in range(n):
        state, metrics = step_fn(state, batch, key)
    if metrics is not None:
        _fetch(metrics)
    return state, time.perf_counter() - t0


def timed_steps(step_fn: Callable, state, batch, global_batch: int,
                steps: int, repeats: int = 3, warmup: int = 3,
                min_window_s: float = 0.5,
                max_steps: int = 2048) -> Tuple[float, float]:
    """Median (steps/sec, samples/sec) over `repeats` differenced windows.

    `step_fn(state, batch, key) -> (state, metrics)` may be a jitted function
    or an AOT-compiled executable. Warmup covers compile + autotuning. Each
    repeat measures T(steps) and T(2*steps) and reports
    steps / (T(2*steps) - T(steps)) — constant sync overhead cancels. If the
    differenced time is below `min_window_s`, the window doubles (up to
    `max_steps`) so tunnel-latency noise cannot dominate the rate.
    """
    from .flops import MeasurementError

    key = jax.random.PRNGKey(0)
    for _ in range(max(warmup, 1)):
        state, metrics = step_fn(state, batch, key)
    _fetch(metrics)

    # Auto-size the window: the differenced interval must dwarf timing noise.
    # The break condition keeps t1/t2 from the n they were measured at — a
    # stale-timing exit here would inflate the rate 2x.
    n = steps
    while True:
        state, t1 = _run_window(step_fn, state, batch, key, n)
        state, t2 = _run_window(step_fn, state, batch, key, 2 * n)
        if t2 - t1 >= min_window_s or 2 * n >= max_steps:
            break
        n *= 2

    # A non-positive (or tiny) differenced interval means overhead variance
    # swamped the n-step work — that window is NOISE, not a rate. Publishing
    # n/epsilon would be the impossible-throughput failure class this
    # harness exists to prevent, so bad windows are retried and a window
    # budget exhausted is a loud MeasurementError, never a number.
    floor = max(1e-4, 0.05 * min_window_s)
    rates: list = []
    bad = 0
    if t2 - t1 >= floor:
        rates.append(n / (t2 - t1))
    else:
        bad += 1
    while len(rates) < repeats and bad < repeats + 3:
        state, t1 = _run_window(step_fn, state, batch, key, n)
        state, t2 = _run_window(step_fn, state, batch, key, 2 * n)
        if t2 - t1 >= floor:
            rates.append(n / (t2 - t1))
        else:
            bad += 1
    if not rates:
        raise MeasurementError(
            f"timing windows of {n}..{2 * n} steps produced no positive "
            f"differenced interval (last T(2n)-T(n) = {t2 - t1:.4f}s) — "
            "backend timing is too noisy to report a throughput")
    sps = float(np.median(rates))
    return sps, sps * global_batch


def _contract_check(trainer, state, optimized_text: str, lowered,
                    zero1: bool, grad_sync: Optional[dict],
                    per_device_batch: int = 0,
                    seq_len: int = 0) -> Optional[dict]:
    """Evaluate the HLO contract rules against the measured executable and
    return {"pass": bool, "violations": [...]} for the bench row — the
    per-arm pass/fail bench history tracks across PRs (ISSUE 3).
    Best-effort by design: a checker failure is recorded as an error
    string, never a measurement failure."""
    try:
        from ..analysis.hlo_rules import (
            StepArtifacts, check_artifacts, preopt_hlo_text,
            replicated_large_buffers,
        )
        from ..parallel.grad_sync import build_bucket_plan
        from ..parallel.mesh import batch_shard_count

        cfg = dict(grad_sync or {})
        cfg["zero1"] = bool(zero1)
        cfg["donate_state"] = trainer.config.donate_state
        is_fsdp = bool(cfg.get("fsdp_explicit"))
        try:
            preopt = preopt_hlo_text(lowered)
        except Exception:
            preopt = None
        plan = build_bucket_plan(state.params,
                                 float(cfg.get("bucket_cap_mb", 0.0)))
        artifacts = StepArtifacts(
            name="bench",
            optimized_text=optimized_text,
            preopt_text=preopt,
            config=cfg,
            backend=jax.default_backend(),
            n_shards=batch_shard_count(trainer.mesh),
            total_grad_bytes=plan.total_bytes,
            replicated_state_buffers=(
                replicated_large_buffers(state.opt_state, 8192)
                if (zero1 or is_fsdp) else ()),
            replicated_param_buffers=(
                replicated_large_buffers(state.params, 8192)
                if is_fsdp else ()),
            layer_group_padded_sizes=(
                trainer._fsdp_plan.padded_group_sizes
                if is_fsdp and trainer._fsdp_plan is not None else ()),
        )
        tp_psums, tp_gathers = trainer.tp_expected_model_collectives()
        artifacts = dataclasses.replace(
            artifacts, model_shards=trainer._tp_n,
            tp_expected_psums=tp_psums,
            tp_expected_model_gathers=tp_gathers,
            tp_ce_stat_elements=trainer.tp_expected_ce_stat_elements(
                per_device_batch, seq_len),
            slice_shards=(trainer._hier.n_slices
                          if trainer._hier is not None else 1))
        findings = check_artifacts(artifacts)
        return {"pass": not findings,
                "violations": [f.as_dict() for f in findings]}
    except Exception as e:  # noqa: BLE001 - observability must not kill a run
        return {"pass": None, "error": f"{type(e).__name__}: {e}"}


def checkpoint_save_ab(state, base_dir: Optional[str] = None) -> dict:
    """Sync-vs-async checkpoint blocked-time A/B on the measured state —
    the ``save_blocked_ms`` bench instrument (training/checkpoint.py).

    Saves the state once through a synchronous CheckpointManager and once
    through the async (snapshot-then-write) default, into a throwaway
    directory, and reports the milliseconds the CALLING thread spent
    blocked inside ``save`` for each — the step-time stall a training loop
    pays per save. Under async the blocked time collapses to ~the
    device→host ``snapshot_ms``; the sync number is the stall the
    background writer kills. ``write_ms`` is the drained background-write
    wall (the work that moved OFF the critical path). Best-effort: an I/O
    failure returns ``{"error": ...}``, never a measurement failure."""
    import shutil
    import tempfile

    from ..training.checkpoint import CheckpointManager

    base = Path(tempfile.mkdtemp(prefix="dpt-ckpt-ab-", dir=base_dir))
    try:
        out = {}
        # Discarded warm-up save: the first save in a process pays one-time
        # orbax/TensorStore costs (driver registry, thread pools) that are
        # neither arm's steady-state stall — without this they land on
        # whichever arm runs first and skew the A/B.
        warm = CheckpointManager(str(base / "warmup"), max_to_keep=1,
                                 async_save=False)
        try:
            warm.save(1, state, epoch=0)
        finally:
            warm.close()
        for mode, async_save in (("sync", False), ("async", True)):
            mgr = CheckpointManager(str(base / mode), max_to_keep=1,
                                    async_save=async_save)
            try:
                mgr.save(1, state, epoch=0)
                blocked = mgr.save_blocked_ms
                t0 = time.perf_counter()
                mgr.wait()
                drain_ms = (time.perf_counter() - t0) * 1e3
                out[f"{mode}_blocked_ms"] = round(blocked, 1)
                if async_save:
                    out["snapshot_ms"] = round(mgr.snapshot_ms, 1)
                    out["write_ms"] = round(drain_ms, 1)
            finally:
                mgr.close()
        return out
    except Exception as e:  # noqa: BLE001 - observability must not kill a run
        return {"error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(base, ignore_errors=True)


def build_serving_engine(devices: Sequence[jax.Device], model_name: str,
                         buckets: Sequence[int] = (16, 32), rows: int = 8,
                         max_new_tokens: int = 8, serve_dtype: str = "fp32",
                         model_overrides: Optional[dict] = None,
                         ckpt_dir: Optional[str] = None,
                         train_config=None, seed: int = 0,
                         optimizer: str = "auto", momentum: float = 0.9,
                         weight_decay: float = 5e-4,
                         mesh_spec: Optional[str] = None,
                         config=None, engine_cls=None,
                         min_positions: int = 0):
    """(engine, mesh) for a serving config on a pure-DP mesh — the serving
    sibling of `build_trainer`, so bench rows and the CLI measure the same
    engine. Without ``ckpt_dir`` the weights are random-init (a smoke of
    the serving path, not a served model — the row says so); with it, the
    newest manifest-verified checkpoint restores through the same template
    machinery a training resume uses (``train_config`` carries the
    training run's zero1/fsdp/wire flags when they differ from defaults).

    ``config``/``engine_cls`` swap in a richer config + engine pair
    (`build_slot_engine` passes PagedServeConfig + SlotEngine) while every
    other knob — checkpoint templates, mesh validation, vocab/positions
    sizing — stays this one code path; ``min_positions`` widens the LM's
    position table when the engine's padded view (pages) outgrows
    ``max(buckets) + max_new_tokens``.

    The restore template's optimizer chain must STRUCTURALLY match the
    training run's (orbax validates the opt_state tree): the template is
    built exactly as train.py builds it — ``make_optimizer`` with a
    callable (constant) schedule and no grad clip — and ``optimizer`` /
    ``momentum`` / ``weight_decay`` are the knobs that change the chain's
    structure (a zero momentum/decay drops a transform). "auto" picks the
    family recipe: adamw for LM models, sgd for vision (train.py's CLI
    default is sgd everywhere; pass ``optimizer="sgd"`` for an LM trained
    that way).
    """
    from ..models import get_model
    from ..parallel import MeshSpec, build_mesh
    from ..serving.engine import InferenceEngine, ServeConfig
    from ..training.optim import make_optimizer, make_schedule

    # --mesh (ISSUE 13 satellite): default stays the 1-D pure-DP mesh —
    # every existing invocation unchanged; "data=N,model=M" serves big
    # models TP-sharded over the model axis via the GSPMD rules
    # (validate_mesh rejects axes the served model cannot use).
    spec = (MeshSpec.parse(mesh_spec) if mesh_spec
            else MeshSpec(data=len(devices)))
    mesh = build_mesh(spec, devices=list(devices))
    cfg = config if config is not None else ServeConfig(
        buckets=tuple(buckets), rows=rows,
        max_new_tokens=max_new_tokens, serve_dtype=serve_dtype)
    serve_dtype = cfg.serve_dtype
    dtype = jnp.bfloat16 if serve_dtype == "bf16" else jnp.float32
    if optimizer == "auto":
        optimizer = "adamw" if is_lm_model(model_name) else "sgd"
    tx = make_optimizer(optimizer, make_schedule("constant", 0.1),
                        momentum=momentum, weight_decay=weight_decay)
    if not is_lm_model(model_name):
        # --model-overrides applies here too: a resnet trained with
        # num_classes=100 must be able to build a matching template
        model = get_model(model_name, dtype=dtype,
                          **(model_overrides or {}))
        sample = np.zeros((1, 32, 32, 3), np.float32)
    else:
        kwargs = dict(model_overrides or {})
        need = max(max(cfg.buckets) + cfg.max_new_tokens, min_positions)
        kwargs.setdefault("max_position", max(512, need))
        model = get_model(model_name, dtype=dtype, **kwargs)
        sample = np.zeros((1, min(cfg.buckets)), np.int32)
    rules = (type(model).partition_rules()
             if hasattr(type(model), "partition_rules") else None)
    from ..parallel.mesh import validate_mesh

    validate_mesh(mesh, rules=rules)
    serve_rules = rules if dict(mesh.shape).get("model", 1) > 1 else None
    cls = engine_cls if engine_cls is not None else InferenceEngine
    if ckpt_dir:
        engine = cls.from_checkpoint(
            ckpt_dir, model, mesh, cfg, tx, sample,
            train_config=train_config, rules=serve_rules)
    else:
        variables = model.init(jax.random.PRNGKey(seed), sample, train=False)
        engine = cls(model, mesh, cfg, variables["params"],
                     batch_stats=variables.get("batch_stats"),
                     rules=serve_rules)
    return engine, mesh


def build_slot_engine(devices: Sequence[jax.Device], model_name: str,
                      buckets: Sequence[int] = (8, 16), rows: int = 8,
                      max_new_tokens: int = 8, kv_dtype: str = "fp32",
                      page_size: int = 8, prefix_sharing: bool = True,
                      n_pages: int = 0, prefix_skip: bool = True, **kw):
    """(SlotEngine, mesh) — the token-granular sibling of
    `build_serving_engine` (same checkpoint templates, mesh validation and
    sizing; ``**kw`` forwards model_overrides/ckpt_dir/train_config/...).
    The engine decodes over a paged, optionally int8 KV pool
    (serving/continuous.py); ``min_positions`` is derived here because the
    gathered dense view is ``pages_per_slot * page_size`` wide — page
    padding can outgrow ``max(buckets) + max_new_tokens``."""
    from ..serving.continuous import SlotEngine
    from ..serving.paged import PagedServeConfig

    cfg = PagedServeConfig(
        buckets=tuple(buckets), rows=rows, max_new_tokens=max_new_tokens,
        page_size=page_size, kv_dtype=kv_dtype, n_pages=n_pages,
        prefix_sharing=prefix_sharing, prefix_skip=prefix_skip)
    return build_serving_engine(
        devices, model_name, buckets=buckets, rows=rows,
        max_new_tokens=max_new_tokens, config=cfg, engine_cls=SlotEngine,
        min_positions=cfg.pages_per_slot * cfg.page_size, **kw)


def build_spec_engine(devices: Sequence[jax.Device], model_name: str,
                      draft_model_name: str,
                      buckets: Sequence[int] = (8, 16), rows: int = 8,
                      max_new_tokens: int = 8, page_size: int = 8,
                      prefix_sharing: bool = True, n_pages: int = 0,
                      prefix_skip: bool = True, draft_k: int = 4,
                      draft_overrides: Optional[dict] = None,
                      seed: int = 0, **kw):
    """(SpeculativeEngine, mesh) — `build_slot_engine` with a draft LM
    riding along. The target side goes through the exact
    `build_serving_engine` path (checkpoint templates, mesh validation,
    position sizing) via an engine_cls closure that injects the draft;
    the draft itself is ALWAYS random-init fp32 here (it is a throughput
    device, not a served artifact — acceptance is exact-match against the
    target, so draft weights change speed, never the emitted stream).

    The draft model's position table is sized from the DRAFT padded view:
    speculative.py widens ``max_new_tokens`` by K (the last propose run of
    a request writes draft k/v past the target frontier), so its
    pages_per_slot can outgrow the target's.
    """
    from ..models import get_model
    from ..serving.paged import PagedServeConfig
    from ..serving.speculative import SpeculativeEngine

    cfg = PagedServeConfig(
        buckets=tuple(buckets), rows=rows, max_new_tokens=max_new_tokens,
        page_size=page_size, kv_dtype="fp32", n_pages=n_pages,
        prefix_sharing=prefix_sharing, prefix_skip=prefix_skip)
    dcfg = dataclasses.replace(
        cfg, max_new_tokens=max_new_tokens + draft_k, n_pages=0)
    dkwargs = dict(draft_overrides or {})
    dkwargs.setdefault("max_position",
                       max(512, dcfg.pages_per_slot * dcfg.page_size))
    draft = get_model(draft_model_name, dtype=jnp.float32, **dkwargs)
    dvars = draft.init(jax.random.PRNGKey(seed + 1),
                       np.zeros((1, min(cfg.buckets)), np.int32),
                       train=False)

    class _SpecEngine(SpeculativeEngine):
        def __init__(self, model, mesh, config, params, **ekw):
            super().__init__(model, mesh, config, params, draft,
                             dvars["params"], spec_k=draft_k, **ekw)

    return build_serving_engine(
        devices, model_name, buckets=buckets, rows=rows,
        max_new_tokens=max_new_tokens, config=cfg, engine_cls=_SpecEngine,
        min_positions=cfg.pages_per_slot * cfg.page_size, seed=seed, **kw)


def measure_serving(model_name: str = "gpt2_124m", n_requests: int = 24,
                    offered_rps: float = 16.0,
                    buckets: Sequence[int] = (16, 32), rows: int = 8,
                    max_new_tokens: int = 8, serve_dtype: str = "fp32",
                    mixed_want: bool = False,
                    devices: Optional[Sequence[jax.Device]] = None,
                    model_overrides: Optional[dict] = None,
                    ckpt_dir: Optional[str] = None, seed: int = 0,
                    optimizer: str = "auto", momentum: float = 0.9,
                    weight_decay: float = 5e-4,
                    train_config=None,
                    mesh_spec: Optional[str] = None) -> dict:
    """Serving latency/throughput at FIXED offered load — the serving row
    of the bench table (`serving bench` prints it).

    A load generator submits ``n_requests`` mixed-length prompts on a
    deterministic 1/``offered_rps`` cadence into the request queue while
    the engine worker drains it (continuous batching); per-request latency
    is submit -> result. Reports p50/p99 latency, achieved request and
    token throughput, the engine's compile census
    (``recompiles_after_warmup`` MUST be 0 — the contract the acceptance
    test asserts), and the served checkpoint's provenance when one was
    loaded. Offered load is what the schedule ASKS for; ``achieved_rps``
    is what the engine absorbed — an overloaded engine shows the gap
    honestly instead of averaging it away.

    ``mixed_want=True`` is the serving-traffic workload of the
    continuous-batching A/B: each request WANTS a per-request number of
    tokens (1..max_new, same rng stream as the token-granular row). The
    iteration engine has no per-request decode length — every batch
    member decodes the full ``max_new_tokens`` — so ``tokens_per_sec``
    counts only the WANTED tokens: the decode cycles spent past a
    request's want are the convoy waste this mode exists to measure,
    not throughput to credit.
    """
    import threading

    from ..serving.batching import RequestQueue, serve_forever

    devices = list(devices) if devices is not None else jax.devices()
    engine, mesh = build_serving_engine(
        devices, model_name, buckets=buckets, rows=rows,
        max_new_tokens=max_new_tokens, serve_dtype=serve_dtype,
        model_overrides=model_overrides, ckpt_dir=ckpt_dir, seed=seed,
        optimizer=optimizer, momentum=momentum,
        weight_decay=weight_decay, train_config=train_config,
        mesh_spec=mesh_spec)
    if not engine.is_token:
        # the load generator submits token prompts; an image engine would
        # crash mid-warmup with a confusing traceback instead of this
        raise ValueError(
            f"serving bench drives token models (gpt2/bert); {model_name} "
            "serves images — use `serving smoke` or engine.serve_images")

    # warmup: compile every bucket AND execute once per bucket, so the
    # timed window measures steady state — then pin the compile census
    engine.warmup()
    rng = np.random.RandomState(seed)
    # prompt ids from the SERVED model's vocab (overridden CI models
    # shrink it below the family default lm_vocab reports)
    vocab = int(getattr(engine.model, "vocab_size", 0)) or 256
    for b in engine.config.buckets:
        engine.serve_tokens([rng.randint(0, max(vocab, 2), b)
                             .astype(np.int32)])
    compiles_warm = engine.compiles

    lens = [int(rng.randint(1, max(engine.config.buckets) + 1))
            for _ in range(n_requests)]
    prompts = [rng.randint(0, max(vocab, 2), n).astype(np.int32)
               for n in lens]
    # drawn AFTER the prompts so both A/B rows (this and
    # measure_serving_continuous) see identical prompt AND want streams
    wants = ([int(rng.randint(1, max_new_tokens + 1))
              for _ in range(n_requests)] if mixed_want
             else [max_new_tokens] * n_requests)
    queue = RequestQueue(engine.config.buckets)
    stop = threading.Event()
    worker = threading.Thread(target=serve_forever,
                              args=(engine, queue, stop), daemon=True)
    worker.start()
    gap = 1.0 / max(offered_rps, 1e-9)
    reqs = []
    t_start = time.perf_counter()
    for i, p in enumerate(prompts):
        # fixed offered load: submit on schedule, never "when ready"
        lag = t_start + i * gap - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        reqs.append(queue.submit(p))
    for r in reqs:
        r.result(timeout=600.0)
    stop.set()
    worker.join(timeout=60.0)

    lat_ms = np.array([(r.t_done - r.t_submit) * 1e3 for r in reqs])
    window_s = max(max(r.t_done for r in reqs) - t_start, 1e-9)
    recompiles = engine.compiles - compiles_warm
    row = {
        "mode": "serving",
        "model": model_name,
        "serve_dtype": serve_dtype,
        "buckets": list(engine.config.buckets),
        "rows": rows,
        "max_new_tokens": max_new_tokens,
        "n_requests": n_requests,
        "mixed_want": mixed_want,
        "offered_rps": offered_rps,
        "achieved_rps": round(n_requests / window_s, 2),
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "mean_ms": round(float(lat_ms.mean()), 2),
        # only generating (causal-LM) engines produce tokens; a bert
        # embedding bench must not report a throughput for tokens that
        # were never generated. Under mixed_want only the WANTED tokens
        # count — the engine decoded max_new for everyone regardless
        **({"tokens_per_sec": round(sum(wants) / window_s, 1)}
           if engine.is_lm else {}),
        "compiles": engine.compiles,
        "recompiles_after_warmup": recompiles,
        "checkpoint": engine.checkpoint_info,
    }
    if serve_dtype == "int8":
        from ..serving.engine import int8_weight_bytes

        row["weight_bytes"] = int8_weight_bytes(engine._served)
    # per-arm contract verdict, exactly like the training rows: the decode
    # step of the largest bucket must keep its promises (no host
    # transfers, cache donated). Decode exists only for causal LMs; a
    # bert arm records the skip instead of a spurious error. Best-effort
    # — observability never kills a measurement.
    if engine.is_lm:
        try:
            from ..analysis.hlo_rules import (
                check_artifacts, serving_artifacts,
            )

            artifacts = serving_artifacts(
                engine, max(engine.config.buckets), name="bench-serving")
            findings = check_artifacts(artifacts)
            row["contracts"] = {
                "pass": not findings,
                "violations": [f.as_dict() for f in findings]}
        except Exception as e:  # noqa: BLE001
            row["contracts"] = {"pass": None,
                                "error": f"{type(e).__name__}: {e}"}
    else:
        row["contracts"] = {"pass": None,
                            "skipped": "no decode step (not a causal LM)"}
    return row


def measure_serving_continuous(model_name: str = "gpt2_124m",
                               n_requests: int = 24,
                               offered_rps: float = 16.0,
                               buckets: Sequence[int] = (8, 16),
                               rows: int = 8, max_new_tokens: int = 8,
                               kv_dtype: str = "fp32", page_size: int = 8,
                               mixed_want: bool = False,
                               replicas: int = 1,
                               kill_replica: bool = False,
                               temperature: float = 0.0, top_p: float = 1.0,
                               draft_model: Optional[str] = None,
                               draft_k: int = 4,
                               shared_frac: float = 0.0,
                               prefix_skip: bool = True,
                               devices: Optional[Sequence[jax.Device]] = None,
                               model_overrides: Optional[dict] = None,
                               ckpt_dir: Optional[str] = None, seed: int = 0,
                               optimizer: str = "auto",
                               momentum: float = 0.9,
                               weight_decay: float = 5e-4,
                               train_config=None,
                               mesh_spec: Optional[str] = None) -> dict:
    """Token-granular serving at fixed offered load — the continuous-
    batching row next to `measure_serving`'s iteration-granular one (same
    load schedule, same prompts, so the two rows are an apples-to-apples
    A/B on tok/s and tail latency).

    ``replicas`` in-process slot engines sit behind the stdlib `Router`
    (least-depth dispatch, resubmit-on-death); ``kill_replica=True``
    injects one replica death mid-load — the acceptance drill: every
    request still completes, the survivors absorb the resubmissions, and
    the compile census stays at warmup (``recompiles_after_warmup`` must
    be 0 across joins, leaves, AND the death). The row also carries the
    paged pool's HBM bytes against the dense fp32 baseline
    (``kv_bytes_ratio`` — the int8-paged >= 3x claim is a recorded
    number, not prose) and per-request TTFT percentiles (prefill emits
    token #0, so TTFT is an admission-latency instrument the
    iteration-granular engine cannot improve on).

    ``draft_model`` arms speculative decoding (fp32-only): each replica
    becomes a SpeculativeEngine + SpeculativeScheduler pair, and the row
    grows ``accept_ratio`` / ``accepted_per_verify`` / ``spec_rounds`` —
    the emitted streams stay BITWISE what the plain row emits (PARITY.md:
    acceptance is exact match), so the A/B is pure speed.
    ``shared_frac`` arms prefix-resident admission: that fraction of
    requests carry one identical page-aligned prompt, and the row grows
    ``prefill_skips`` / ``tail_resumes`` plus a warm/cold TTFT split —
    the zero-prefill admission claim as recorded numbers.
    """
    from ..serving.router import InProcessReplica, Router

    if draft_model is not None and kv_dtype != "fp32":
        # fail at the bench boundary with the bench's vocabulary, not
        # three layers down in SpeculativeEngine.__init__
        raise ValueError(
            f"--draft needs kv_dtype=fp32 (got {kv_dtype}): the verify "
            "window's in-view rows are fresh fp32 while the int8 path "
            "reads dequantized page bytes — the bitwise pin would break")
    devices = list(devices) if devices is not None else jax.devices()
    # Each replica gets its own DISJOINT device slice — the fleet
    # topology (replicas never share chips), and a hard requirement
    # in-process: the row-sharded decode step carries collectives, and
    # two schedulers racing collective programs over OVERLAPPING devices
    # deadlock in the CPU backend's rendezvous.
    per = len(devices) // replicas
    slices = ([devices[i * per:(i + 1) * per] for i in range(replicas)]
              if replicas > 1 and per >= 1 else [devices] * replicas)
    engines = []
    for i in range(replicas):
        common = dict(
            buckets=buckets, rows=rows, max_new_tokens=max_new_tokens,
            page_size=page_size, prefix_skip=prefix_skip,
            model_overrides=model_overrides, ckpt_dir=ckpt_dir, seed=seed,
            optimizer=optimizer, momentum=momentum,
            weight_decay=weight_decay, train_config=train_config,
            mesh_spec=mesh_spec)
        if draft_model is not None:
            # the draft inherits the target's overrides: a vocab override
            # must hit BOTH sides (acceptance compares token ids)
            engine, _ = build_spec_engine(
                slices[i], model_name, draft_model, draft_k=draft_k,
                draft_overrides=model_overrides, **common)
        else:
            engine, _ = build_slot_engine(
                slices[i], model_name, kv_dtype=kv_dtype, **common)
        engine.warmup()
        engines.append(engine)
    compiles_warm = [e.compiles for e in engines]

    rng = np.random.RandomState(seed)
    vocab = int(getattr(engines[0].model, "vocab_size", 0)) or 256
    lens = [int(rng.randint(1, max(engines[0].config.buckets) + 1))
            for _ in range(n_requests)]
    prompts = [rng.randint(0, max(vocab, 2), n).astype(np.int32)
               for n in lens]
    # same rng order as measure_serving (lens, prompts, wants): identical
    # want stream on both sides of the A/B. HERE the wants are honored —
    # a slot retires at its want and the freed capacity admits the next
    # request, which is the continuous-batching win being measured.
    wants = ([int(rng.randint(1, max_new_tokens + 1))
              for _ in range(n_requests)] if mixed_want
             else [max_new_tokens] * n_requests)
    # prefix-resident arm: ``shared_frac`` of the requests carry ONE
    # identical page-aligned prompt. The first such request on a replica
    # prefills and registers the pages; every later one finds the whole
    # prefix resident and admits with ZERO prefill dispatch
    # (``prefill_skips`` is the census, the warm/cold TTFT split below is
    # the latency receipt). The shared indices are rng-spread over the
    # schedule so warm requests face the same queue depths cold ones do —
    # the extra draws come AFTER the lens/prompts/wants stream, so the
    # A/B against measure_serving stays intact.
    shared_idx: set = set()
    if shared_frac > 0:
        n_shared = int(round(shared_frac * n_requests))
        top = max(engines[0].config.buckets)
        shared_len = min(max(page_size, top // page_size * page_size), top)
        shared_prompt = rng.randint(0, max(vocab, 2),
                                    shared_len).astype(np.int32)
        if n_shared >= 1:
            shared_idx = set(
                int(j) for j in rng.choice(n_requests, size=n_shared,
                                           replace=False))
            for j in shared_idx:
                prompts[j] = shared_prompt

    router = Router([InProcessReplica(f"r{i}", e)
                     for i, e in enumerate(engines)])
    kill_at = n_requests // 3 if (kill_replica and replicas > 1) else None
    gap = 1.0 / max(offered_rps, 1e-9)
    reqs, sub_at = [], []
    t_start = time.perf_counter()
    for i, p in enumerate(prompts):
        lag = t_start + i * gap - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        sub_at.append(time.perf_counter())
        reqs.append(router.submit(p, max_new_tokens=wants[i],
                                  temperature=temperature, top_p=top_p))
        if kill_at is not None and i == kill_at:
            # the injected death: everything in flight on r0 fails with
            # ReplicaDead and the router resubmits it to the survivors
            router.replicas["r0"].kill()
    results = [r.result(timeout=600.0) for r in reqs]
    # True completion stamps: RouterRequest.t_done is the WORKER's
    # set_result time, not the moment this collection loop got around to
    # calling result(). Stamping here instead would charge every request
    # that finished during the pacing loop for the rest of the submission
    # window — at 20 rps x 32 requests that's seconds of phantom p99.
    done_at = [r.t_done for r in reqs]
    # "alive" means survived the RUN — snapshot before stop() tears the
    # scheduler threads down (after it, every replica reads unhealthy)
    alive = {name: rep.healthy() for name, rep in router.replicas.items()}
    router.stop()

    # submit -> completion wall latency AT THE ROUTER (a resubmitted
    # request's clock keeps running through its replica's death — the retry
    # is paid, not hidden), same stamps measure_serving reads (Request.t_done)
    lat_ms = np.array([(d - s) * 1e3 for s, d in zip(sub_at, done_at)])
    ttft_ms = np.array([res.queue_wait_s * 1e3 for res in results])
    window_s = max(max(done_at) - t_start, 1e-9)
    n_tokens = int(sum(res.tokens.size for res in results))
    per_replica = {}
    for name, rep in router.replicas.items():
        mine = [(reqs[i], lat_ms[i]) for i in range(n_requests)
                if reqs[i].replica_name == name]
        per_replica[name] = {
            "served": rep.scheduler.served,
            "alive": alive[name],
            **({"p50_ms": round(float(np.percentile(
                    [m for _, m in mine], 50)), 2),
                "p99_ms": round(float(np.percentile(
                    [m for _, m in mine], 99)), 2)} if mine else {}),
        }
    scheds = [rep.scheduler for rep in router.replicas.values()]
    engine = engines[0]
    row = {
        "mode": "serving_continuous",
        "granularity": "token",
        "model": model_name,
        "kv_dtype": kv_dtype,
        "page_size": page_size,
        "buckets": list(engine.config.buckets),
        "rows": rows,
        "max_new_tokens": max_new_tokens,
        "n_requests": n_requests,
        "mixed_want": mixed_want,
        "completed": len(results),
        "offered_rps": offered_rps,
        "achieved_rps": round(n_requests / window_s, 2),
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "mean_ms": round(float(lat_ms.mean()), 2),
        "ttft_p50_ms": round(float(np.percentile(ttft_ms, 50)), 2),
        "ttft_p99_ms": round(float(np.percentile(ttft_ms, 99)), 2),
        "tokens_per_sec": round(n_tokens / window_s, 1),
        "compiles": sum(e.compiles for e in engines),
        "recompiles_after_warmup": sum(
            e.compiles - w for e, w in zip(engines, compiles_warm)),
        "replicas": replicas,
        "replica_deaths": sum(r.replica_deaths for r in reqs),
        "per_replica": per_replica,
        # the admission fast-path census: skips dispatched NO prefill,
        # resumes prefilled only the non-resident tail
        "prefix_skip": prefix_skip,
        "prefill_skips": sum(s.prefill_skips for s in scheds),
        "tail_resumes": sum(s.tail_resumes for s in scheds),
        "shared_frac": shared_frac,
        "draft": draft_model,
        # the HBM story: the paged (optionally int8) pool vs what the
        # dense fp32 cache would hold for the same rows at the top rung
        "paged_kv_bytes": engine.paged_bytes(),
        "dense_kv_bytes": engine.dense_baseline_bytes(),
        "checkpoint": engine.checkpoint_info,
    }
    row["kv_bytes_ratio"] = round(
        row["dense_kv_bytes"] / max(row["paged_kv_bytes"], 1), 2)
    if draft_model is not None:
        rounds = sum(s.spec_rounds for s in scheds)
        proposed = sum(s.spec_proposed for s in scheds)
        accepted = sum(s.spec_accepted for s in scheds)
        row["draft_k"] = draft_k
        row["spec_rounds"] = rounds
        # accept_ratio is the draft's hit rate; accepted_per_verify is
        # the speed-up currency — mean draft tokens banked per target
        # forward (the bonus token rides on top of it)
        row["accept_ratio"] = round(accepted / max(proposed, 1), 3)
        row["accepted_per_verify"] = round(accepted / max(rounds, 1), 2)
        row["draft_kv_bytes"] = engine.draft_bytes()
        row["backend"] = jax.default_backend()
        if row["backend"] != "tpu":
            # same discipline as device_time_split's backend caveat:
            # a non-TPU row names its own limits instead of passing as
            # a chip measurement (experiments/results/README.md)
            row["caveat"] = (
                "cpu mesh: draft and verify thunks serialize (no ICI "
                "overlap), so tok/s understates the speculative win; "
                "random-init drafts pin accept_ratio near zero — only "
                "trained draft/target pairs on a chip measure real "
                "acceptance economics")
    if shared_idx:
        # warm = shared-prompt requests AFTER their replica's primer (the
        # one that paid the prefill and registered the pages); everything
        # else is the cold arm. Attribution is by final replica, so a
        # resubmitted primer stays a primer on the survivor.
        primers, seen = set(), set()
        for i in sorted(shared_idx):
            name = reqs[i].replica_name
            if name not in seen:
                seen.add(name)
                primers.add(i)
        warm = [float(ttft_ms[i]) for i in shared_idx if i not in primers]
        cold = [float(ttft_ms[i]) for i in range(n_requests)
                if i not in shared_idx or i in primers]
        if warm:
            row["ttft_warm_p50_ms"] = round(
                float(np.percentile(warm, 50)), 2)
        if cold:
            row["ttft_cold_p50_ms"] = round(
                float(np.percentile(cold, 50)), 2)
    try:
        from ..analysis.hlo_rules import (
            check_artifacts, paged_serving_artifacts,
        )

        findings = check_artifacts(
            paged_serving_artifacts(engine, name="bench-paged"))
        if draft_model is not None:
            from ..analysis.hlo_rules import spec_serving_artifacts

            findings.extend(check_artifacts(
                spec_serving_artifacts(engine, name="bench-spec")))
        row["contracts"] = {
            "pass": not findings,
            "violations": [f.as_dict() for f in findings]}
    except Exception as e:  # noqa: BLE001 - observability never kills a row
        row["contracts"] = {"pass": None,
                            "error": f"{type(e).__name__}: {e}"}
    return row


def measure_config(model_name: str, per_device_batch: int, steps: int,
                   bf16: bool, repeats: int = 3, seq_len: int = 512,
                   image_hw: int = 32, num_classes: int = 10,
                   devices: Optional[Sequence[jax.Device]] = None,
                   true_fp32: bool = True, min_window_s: float = 0.5,
                   zero1: bool = False,
                   grad_sync: Optional[dict] = None,
                   comm_trace: bool = False,
                   ckpt_ab: bool = False,
                   mesh_spec: Optional[str] = None) -> dict:
    """Full self-verifying measurement of one training config.

    Returns a dict with samples/s, FLOPs from XLA cost analysis AND the
    analytic jaxpr matmul/conv model, the detected chip peak, and mfu_pct.
    Raises flops.MeasurementError if the implied FLOP/s exceeds the chip peak
    (a broken measurement must never be reported as a result).

    When ``bf16=False`` and ``true_fp32``, the whole config is traced under
    ``jax.default_matmul_precision("highest")`` so the fp32 arm really runs
    fp32 matmul passes — without this, TPU "fp32" matmuls default to bf16 MXU
    passes and an AMP comparison measures nothing (the reference's AMP-vs-FP32
    experiment, /root/reference/README.md:31).

    Every result carries the gradient-sync bucket census of the measured
    executable (``grad_sync_census``: gradient-sized collective count +
    wire dtypes) so bench history can track overlap/bucketing efficiency
    across PRs; ``comm_trace=True`` additionally captures a short
    jax.profiler trace and records the exposed-comm fraction
    (``comm_overlap_split``) — best-effort, never a measurement failure.
    ``ckpt_ab=True`` additionally records ``save_blocked_ms`` — the
    sync-vs-async checkpoint blocked-time A/B (``checkpoint_save_ab``) on
    this config's real state.
    """
    import contextlib

    from . import flops as flops_mod

    devices = list(devices) if devices is not None else jax.devices()
    is_lm = is_lm_model(model_name)

    ctx = (jax.default_matmul_precision("highest")
           if (not bf16 and true_fp32) else contextlib.nullcontext())
    with ctx:
        trainer, state, mesh = build_trainer(
            devices, bf16, model_name, seq_len, image_hw, num_classes,
            zero1=zero1, grad_sync=grad_sync, mesh_spec=mesh_spec)
        batch, global_batch = make_synth_batch(
            mesh, model_name, per_device_batch, seq_len, image_hw,
            num_classes)

        key = jax.random.PRNGKey(0)
        # AOT-compile once: cost analysis reads the exact executable we time.
        lowered = trainer._train_step.lower(state, batch, key)
        compiled = lowered.compile()

        xla_flops = flops_mod.xla_flops_per_step(compiled)
        # fsdp_explicit states hold flat-sharded params — the analytic
        # model needs them back in model shapes (train.py does the same)
        analytic_fwd = flops_mod.jaxpr_matmul_flops(
            lambda s, b: trainer.task.loss_and_metrics(
                s, trainer._fsdp_unflatten(s.params) if trainer._fsdp
                else s.params, b, key, train=True)[0], state, batch)

        from ..parallel.grad_sync import emit_wire_accounting
        from ..parallel.mesh import batch_shard_count
        from .trace_analysis import grad_sync_census

        optimized_text = compiled.as_text()
        sync_census = grad_sync_census(optimized_text)
        contracts = _contract_check(trainer, state, optimized_text, lowered,
                                    zero1=zero1, grad_sync=grad_sync,
                                    per_device_batch=per_device_batch,
                                    seq_len=seq_len)
        # per-replica wire accounting of the configured sync mode (the
        # gather-int8 break-even and the multihop flat ~2 B/element as
        # recorded bench numbers). One call computes the row values AND
        # emits the telemetry counters (emit_wire_accounting is THE
        # emission site — the stream and the bench row read the same
        # numbers by construction; no-op stream-side when no recorder is
        # configured). The helper's conventions are the bucketed/
        # replicated reducer's; zero1's split wire (compressed scatter +
        # exact param gather) is out of its scope — omitted. The gather
        # split (ISSUE 7) is recorded for real fsdp trainers only:
        # state.params' flat leaves carry the same padded totals as the
        # model shapes.
        wire_bytes = None
        gather_bytes = None
        tp_bytes = None
        if not zero1:
            # explicit TP: the trainer assembles the (params, cfg) pair —
            # data-axis terms over the TP-LOCAL template, model-axis psum
            # bytes in their own counter row (axis="model")
            acct_params, acct_cfg = trainer.wire_accounting_inputs(
                state, grad_sync or {}, global_batch, seq_len)
            acct = emit_wire_accounting(
                acct_params, acct_cfg, batch_shard_count(trainer.mesh),
                model=model_name)
            wire_bytes = acct["wire_bytes_per_replica"]
            tp_bytes = acct.get("tp_psum_bytes_per_replica")
            if trainer._fsdp:
                gather_bytes = acct.get("fsdp_gather_bytes")

        exposed_comm_pct = None
        if comm_trace and len(devices) > 1:
            def _sacrificial():
                trainer_t, state_t, mesh_t = build_trainer(
                    devices, bf16, model_name, seq_len, image_hw,
                    num_classes, zero1=zero1, grad_sync=grad_sync,
                    mesh_spec=mesh_spec)
                batch_t, _ = make_synth_batch(
                    mesh_t, model_name, per_device_batch, seq_len, image_hw,
                    num_classes)
                return trainer_t, state_t, batch_t

            exposed_comm_pct = trace_exposed_comm(_sacrificial, key=key)

        # checkpoint blocked-time A/B BEFORE the timed windows: the step
        # donates the state buffers, so after timed_steps this state is
        # consumed — and the saves must not sit inside a timing window.
        save_blocked = checkpoint_save_ab(state) if ckpt_ab else None

        # the exposed-comm split rides the stream too (wire-byte counters
        # were already emitted by emit_wire_accounting above)
        if exposed_comm_pct is not None:
            from .. import telemetry
            telemetry.counter("exposed_comm_pct", exposed_comm_pct,
                              model=model_name)

        sps, samples_per_s = timed_steps(compiled, state, batch, global_batch,
                                         steps, repeats,
                                         min_window_s=min_window_s)

    n_dev = len(devices)
    peak = flops_mod.chip_peak_tflops(devices[0])
    # MFU numerator: the analytic matmul/conv model (FMA = 2 FLOPs — the
    # convention the chip-peak tables use). XLA's cost analysis is the
    # cross-check: it counts the compiled executable but uses FMA = 1 and
    # skips custom-call lowerings, so it should land within ~[0.25x, 1.5x]
    # of the analytic count, not be the headline.
    step_flops = 3.0 * analytic_fwd if analytic_fwd else xla_flops
    crosscheck_warning = None
    if xla_flops and analytic_fwd:
        ratio = xla_flops / (3.0 * analytic_fwd)
        if not (0.2 <= ratio <= 2.0):
            crosscheck_warning = (
                f"XLA cost analysis ({xla_flops:.3g}) vs analytic 3x-forward "
                f"({3.0 * analytic_fwd:.3g}) disagree by {ratio:.2f}x — one "
                "FLOPs instrument is miscounting this model")
    ctx_str = (f"{model_name} b={per_device_batch} on "
               f"{n_dev}x {devices[0].device_kind}")
    mfu = flops_mod.mfu_pct(step_flops, sps, peak * n_dev if peak else None)
    # Validate BOTH instruments: if either implies >peak the measurement is
    # broken, even when the headline instrument happens to undercount.
    warning = flops_mod.check_mfu(mfu, context=ctx_str)
    flops_mod.check_mfu(
        flops_mod.mfu_pct(xla_flops, sps, peak * n_dev if peak else None),
        context=ctx_str + " (XLA cost-analysis instrument)")

    result = {
        "model": model_name,
        "bf16": bf16,
        **({"zero1": True} if zero1 else {}),
        **({"grad_sync": grad_sync} if grad_sync else {}),
        "per_device_batch": per_device_batch,
        "global_batch": global_batch,
        "steps_per_sec": round(sps, 4),
        "samples_per_sec": round(samples_per_s, 2),
        "samples_per_sec_chip": round(samples_per_s / n_dev, 2),
        "flops_per_step_xla": xla_flops,
        "flops_per_step_analytic3x": 3.0 * analytic_fwd,
        "tflops_per_sec": (round(step_flops * sps / 1e12, 2)
                           if step_flops else None),
        "chip_peak_tflops_bf16": peak,
        "mfu_pct": round(mfu, 2) if mfu is not None else None,
        # overlap-efficiency instruments (ISSUE 2): the bucket census of
        # the measured executable, and (comm_trace) the exposed-comm split
        "grad_collectives": sync_census["n_collectives"],
        "grad_wire_dtypes": sync_census["wire_dtypes"],
        **({"wire_bytes_per_replica": wire_bytes}
           if wire_bytes is not None else {}),
        **({"fsdp_gather_bytes": gather_bytes}
           if gather_bytes is not None else {}),
        **({"tp_psum_bytes_per_replica": tp_bytes}
           if tp_bytes is not None else {}),
        **({"mesh_spec": mesh_spec} if mesh_spec else {}),
        # per-arm parallelism-contract verdict (analysis/hlo_rules.py):
        # bench history records whether the measured executable kept its
        # collective/wire/donation promises, not just how fast it ran
        "contracts": contracts,
    }
    if save_blocked is not None:
        # the async-checkpointing instrument (ISSUE 6): ms the train loop
        # spends blocked per save, sync vs snapshot-then-write
        result["save_blocked_ms"] = save_blocked
    if exposed_comm_pct is not None:
        result["exposed_comm_pct"] = exposed_comm_pct
    if is_lm:
        result["seq_len"] = seq_len
        result["tokens_per_sec"] = round(samples_per_s * seq_len, 1)
    else:
        result["image_hw"] = image_hw
    if warning:
        result["mfu_warning"] = warning
    if crosscheck_warning:
        result["flops_crosscheck_warning"] = crosscheck_warning
    return result
