"""Scaling / mixed-precision / gradient-sync experiments.

Produces, with data, every table the reference's README sketches as an empty
outline (/root/reference/README.md:27-35):

* ``scaling``  — global throughput and linear-scaling efficiency on 1..N-chip
  data-parallel meshes (the "Single vs multi-GPU" table; the BASELINE north
  star is >=90% efficiency at 8 chips).
* ``batch``    — throughput vs per-device batch size.
* ``amp``      — bf16 vs fp32 step time (the "AMP vs FP32" comparison; on TPU
  bf16 replaces CUDA AMP, no GradScaler — SURVEY.md §2b).
* ``zero1``    — replicated vs ZeRO-1 sharded weight update (reduce-scatter
  grads, 1/N optimizer update per replica, all-gather params — Xu et al.,
  PAPERS.md) on the same data-parallel mesh, with the static weight-update
  census proving which collectives each compiled step actually runs.
* ``grad_sync`` — the explicit bucketed/compressed reducer
  (parallel/grad_sync.py, the native DDP-reducer rebuild) vs the implicit
  XLA path: throughput, the static bucket/wire-dtype census of each
  compiled step, and the trace-derived exposed-comm fraction (overlap
  efficiency) per mode.
* ``hier``    — two-tier topology-aware sync (wire_dtype="int8_hier") on a
  slice=2 tiered mesh vs the flat wires: tier-classified collective census
  + per-tier wire bytes (the slow-tier slice-count-independence claim as
  recorded numbers).
* ``gradsync`` — the gradient-synchronization share of step time (the
  README's literal "~X%" placeholder, README.md:35). Three instruments:
  (a) measured: per-device-constant-batch step time on 1 chip vs N chips —
      the extra time at N is the communication/sync overhead DDP hides in
      hooks and XLA hides in fused collectives;
  (b) static: a census of collective ops (all-reduce/all-gather/...) in the
      optimized HLO of the compiled step, with operand bytes — read from the
      compiled executable the way the reference would read an nsys timeline;
  (c) trace-derived: a jax.profiler capture parsed by trace_analysis.py,
      collective time summed against XLA-op busy time.
* ``pipeline`` — GPipe bubble measurement: pipelined-GPT-2 throughput vs
  microbatch count against the pure-DP layout of the same model
  (bubble fraction (P-1)/(M+P-1); parallel/pipeline.py).

Output: a markdown table on stdout + rows appended to a CSV so the scaling
plots can be regenerated. Honest-measurement notes: on a single host the
"chips" are members of one mesh (real ICI collectives on TPU, ring emulation
on the CPU test backend); multi-host DCN numbers require a pod run.
"""

from __future__ import annotations

import argparse
import csv as csv_mod
import sys
import time
from pathlib import Path
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import honor_platform_env

honor_platform_env()  # allow JAX_PLATFORMS=cpu virtual-mesh runs


# One measurement harness shared with bench.py (experiments/harness.py) so
# the headline bench and these tables stay comparable — including the
# image-vs-LM dispatch (harness.build_trainer / make_synth_batch), so the
# same --model string measures the same config everywhere.
from .harness import build_trainer, is_lm_model, make_synth_batch, timed_steps  # noqa: E402

# CI smoke runs shrink LM architectures (full-size bert/gpt2 on the CPU test
# mesh costs minutes per build); real measurements never set this.
_LM_TINY = dict(hidden_dim=64, depth=2, num_heads=2, mlp_dim=128)


def _setup(devices, bf16: bool, args, per_device_batch=None, zero1=False,
           grad_sync=None):
    """(trainer, state, mesh, batch, global_batch) for args' config — the
    trainer and its batch are built together so they can never mismatch."""
    lm_kw = None
    if args.lm_tiny and is_lm_model(args.model):
        lm_kw = dict(_LM_TINY)
        if args.model.startswith("gpt2"):
            lm_kw.pop("mlp_dim")  # gpt2 derives mlp from hidden_dim
    trainer, state, mesh = build_trainer(devices, bf16, args.model,
                                         args.seq_len, lm_overrides=lm_kw,
                                         zero1=zero1, grad_sync=grad_sync)
    batch, gb = make_synth_batch(mesh, args.model,
                                 per_device_batch or args.batch_size,
                                 args.seq_len)
    return trainer, state, mesh, batch, gb


def _measure(trainer, state, batch, global_batch: int, args) -> Tuple[float, float]:
    """(steps/sec, samples/sec) for the jitted train step."""
    sps, samples = timed_steps(trainer._train_step, state, batch,
                               global_batch, args.steps,
                               repeats=args.repeats,
                               min_window_s=args.min_window_s)
    return sps, samples


def _emit(rows: List[dict], csv_path: Optional[str]) -> None:
    if not rows:
        return
    cols = list(rows[0].keys())
    widths = [max(len(str(r.get(c, ""))) for r in rows + [dict(zip(cols, cols))])
              for c in cols]
    line = "| " + " | ".join(c.ljust(w) for c, w in zip(cols, widths)) + " |"
    sep = "|-" + "-|-".join("-" * w for w in widths) + "-|"
    print(line)
    print(sep)
    for r in rows:
        print("| " + " | ".join(str(r.get(c, "")).ljust(w)
                                for c, w in zip(cols, widths)) + " |")
    if csv_path:
        path = Path(csv_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        new = not path.exists()
        with open(path, "a", newline="") as f:
            w = csv_mod.DictWriter(f, fieldnames=cols)
            if new:
                w.writeheader()
            w.writerows(rows)
        print(f"\n(rows appended to {path})")


def run_scaling(args) -> List[dict]:
    devices = jax.devices()
    counts = [c for c in (1, 2, 4, 8, 16) if c <= len(devices)]
    rows = []
    base = None
    for c in counts:
        trainer, state, _, batch, gb = _setup(devices[:c], args.bf16, args)
        _, sps = _measure(trainer, state, batch, gb, args)
        base = base or sps
        rows.append({
            "chips": c,
            "global_samples_per_s": round(sps, 1),
            "per_chip_samples_per_s": round(sps / c, 1),
            "scaling_efficiency_pct": round(100.0 * sps / (base * c), 1),
        })
    return rows


def run_batch_sweep(args) -> List[dict]:
    devices = jax.devices()
    rows = []
    batches = (tuple(int(b) for b in args.batch_list.split(","))
               if args.batch_list else (32, 64, 128, 256, 512))
    for b in batches:
        trainer, state, _, batch, gb = _setup(devices, args.bf16, args,
                                              per_device_batch=b)
        _, sps = _measure(trainer, state, batch, gb, args)
        rows.append({"per_device_batch": b,
                     "global_samples_per_s": round(sps, 1)})
    return rows


def run_amp(args) -> List[dict]:
    devices = jax.devices()
    rows = []
    sps_by_prec = {}
    for bf16 in (False, True):
        trainer, state, _, batch, gb = _setup(devices, bf16, args)
        _, sps = _measure(trainer, state, batch, gb, args)
        sps_by_prec[bf16] = sps
        rows.append({"precision": "bf16" if bf16 else "fp32",
                     "global_samples_per_s": round(sps, 1)})
    rows.append({"precision": "bf16_speedup",
                 "global_samples_per_s":
                     round(sps_by_prec[True] / sps_by_prec[False], 3)})
    return rows


# The static HLO census lives with the other gradient-sync instruments in
# trace_analysis.py; re-exported here because this module is its historical
# home (tests and notebooks import it from scaling).
from .trace_analysis import (  # noqa: E402,F401
    collective_census, weight_update_census,
)


def run_gradsync(args) -> List[dict]:
    devices = jax.devices()
    n = len(devices)
    rows = []

    # (a) measured: constant per-device batch, 1 chip vs N chips
    trainer1, state1, _, batch1, gb1 = _setup(devices[:1], args.bf16, args)
    step1, _ = _measure(trainer1, state1, batch1, gb1, args)
    t1 = 1.0 / step1
    rows.append({"measurement": "step_time_1chip_ms", "value": round(t1 * 1e3, 3)})
    if n > 1:
        trainerN, stateN, _, batchN, gbN = _setup(devices, args.bf16, args)

        # (b) static: collective census of the compiled N-chip step.
        # Lower/compile BEFORE the timed run: _measure runs the donating
        # jitted step on stateN, after which its buffers are deleted on
        # backends that honor donation (TPU) — lowering afterwards would
        # depend on donated-away state (ADVICE r1).
        compiled = trainerN._train_step.lower(
            stateN, batchN, jax.random.PRNGKey(0)).compile()

        stepN, _ = _measure(trainerN, stateN, batchN, gbN, args)
        tN = 1.0 / stepN
        share = max(0.0, 1.0 - t1 / tN)
        rows.append({"measurement": f"step_time_{n}chip_ms",
                     "value": round(tN * 1e3, 3)})
        rows.append({"measurement": "grad_sync_share_1vsN_pct",
                     "value": round(100.0 * share, 1)})

        # (c) trace-derived: the jax.profiler timeline read-off the README
        # placeholder calls for (README.md:35). Fresh state: _measure donated
        # stateN's buffers.
        import tempfile

        from .trace_analysis import capture_step_trace, collective_share

        trainerT, stateT, _, batchT, _gbT = _setup(devices, args.bf16, args)
        keyT = jax.random.PRNGKey(0)
        stateT, _ = trainerT._train_step(stateT, batchT, keyT)  # warmup
        with tempfile.TemporaryDirectory(prefix="gradsync_trace_") as td:
            capture_step_trace(trainerT._train_step, stateT, batchT, keyT,
                               td, steps=max(3, min(args.steps, 10)))
            trace = collective_share(td)
        rows.append({"measurement": "grad_sync_share_trace_pct",
                     "value": trace["share_pct"]})
        rows.append({"measurement": "trace_collective_ms",
                     "value": round(trace["collective_us"] / 1e3, 3)})
        rows.append({"measurement": "trace_xla_op_ms",
                     "value": round(trace["op_us"] / 1e3, 3)})
        print("\nTrace-derived collective time by op (jax.profiler):")
        for op, us in trace["by_op"].items() or {"(none)": 0.0}.items():
            print(f"  {op:<20} {us / 1e3:.3f} ms")

        census = collective_census(compiled.as_text())
        print("\nCollective ops in the compiled train step "
              "(the DDP reducer's all-reduces, as XLA scheduled them):")
        for c in census:
            print(f"  {c['count']:>3}x {c['op']:<20} {c['result_shape']}")
        if not census:
            print("  (none — single-device or fully fused)")
    return rows


def run_zero1(args) -> List[dict]:
    """Replicated vs ZeRO-1 sharded weight update on the same devices.

    The experiment the zero1 flag exists for (Xu et al., PAPERS.md): same
    model, same data-parallel mesh, once with the replicated DDP-style
    update and once with reduce-scatter/sharded-update/all-gather. Reports
    throughput plus the static weight-update census of each compiled step —
    the census must show the gradient all-reduces GONE in the zero1 arm
    (replaced by reduce-scatter + all-gather), or the mode is silently not
    engaged and the throughput comparison measures nothing.
    """
    devices = jax.devices()
    if len(devices) < 2:
        return [{"update": "skipped",
                 "global_samples_per_s": "needs >= 2 devices"}]
    rows = []
    sps_by_mode = {}
    for zero1 in (False, True):
        trainer, state, _, batch, gb = _setup(devices, args.bf16, args,
                                              zero1=zero1)
        # Lower/compile BEFORE the timed run (donation deletes state buffers
        # on backends that honor it — same ordering as run_gradsync).
        compiled = trainer._train_step.lower(
            state, batch, jax.random.PRNGKey(0)).compile()
        census = weight_update_census(compiled.as_text())
        _, sps = _measure(trainer, state, batch, gb, args)
        sps_by_mode[zero1] = sps
        rows.append({
            "update": "zero1" if zero1 else "replicated",
            "global_samples_per_s": round(sps, 1),
            "grad_all_reduce": census["all-reduce"],
            "reduce_scatter": census["reduce-scatter"],
            "all_gather": census["all-gather"],
        })
    rows.append({"update": "zero1_speedup",
                 "global_samples_per_s":
                     round(sps_by_mode[True] / sps_by_mode[False], 3),
                 "grad_all_reduce": "", "reduce_scatter": "",
                 "all_gather": ""})
    return rows


def run_grad_sync(args) -> List[dict]:
    """The explicit reducer (parallel/grad_sync.py) vs the implicit XLA
    path on the same devices: bucketed fp32, bf16, int8+EF and multi-hop
    int8 wire, each row carrying (a) throughput, (b) the static
    `grad_sync_census` of the compiled step — gradient-sized collective
    count and wire dtypes, the proof the mode is engaged — (c) the
    `wire_bytes_per_replica` accounting of the mode (the gather-form int8's
    ~(n-1)·S growth and the multihop form's flat ~2·S as RECORDED numbers,
    not docstring claims), and (d) the trace-derived exposed-comm fraction
    (`comm_overlap_split`), the overlap-efficiency number DDP users read
    off nsys timelines. `--bucket-cap-mb` sets the cap (default 25, DDP's
    default); `--grad-accum` > 1 exercises the in-scan overlap (plus a
    no-overlap arm isolating its win).
    """
    from ..parallel.grad_sync import wire_bytes_for_config
    from ..parallel.mesh import batch_shard_count
    from .harness import trace_exposed_comm
    from .trace_analysis import grad_sync_census, preopt_hlo_text

    devices = jax.devices()
    if len(devices) < 2:
        return [{"mode": "skipped",
                 "global_samples_per_s": "needs >= 2 devices"}]
    cap = args.bucket_cap_mb
    accum = args.grad_accum
    modes = [("implicit", None),
             ("bucketed_fp32", dict(bucket_cap_mb=cap))]
    if accum > 1:
        modes.append(("bucketed_fp32_no_overlap",
                      dict(bucket_cap_mb=cap, overlap_grad_sync=False)))
    modes += [("bucketed_bf16", dict(bucket_cap_mb=cap, wire_dtype="bf16")),
              ("bucketed_int8", dict(bucket_cap_mb=cap, wire_dtype="int8")),
              ("bucketed_int8_multihop",
               dict(bucket_cap_mb=cap, wire_dtype="int8_multihop"))]

    rows = []
    for mode, gs in modes:
        gs_full = dict(gs or {}, grad_accum=accum) if (gs or accum > 1) \
            else gs
        trainer, state, mesh, batch, gb = _setup(devices, args.bf16, args,
                                                 grad_sync=gs_full)
        key = jax.random.PRNGKey(0)
        lowered = trainer._train_step.lower(state, batch, key)
        compiled = lowered.compile()
        census = grad_sync_census(compiled.as_text())
        # wire read: pre-optimization HLO (bf16 survives only there on CPU)
        # — except for the implicit mode, whose collectives are inserted by
        # GSPMD during compilation and don't exist pre-optimization
        wire = census["wire_dtypes"]
        try:
            pre = grad_sync_census(preopt_hlo_text(lowered))["wire_dtypes"]
            if pre:
                wire = pre
        except Exception:
            pass

        # time the SAME executable the census describes (AOT `compiled` —
        # re-timing trainer._train_step would pay a second compile AND
        # measure a different program than the one censused)
        _, sps = timed_steps(compiled, state, batch, gb, args.steps,
                             repeats=args.repeats,
                             min_window_s=args.min_window_s)

        # trace the same config with a sacrificial trainer/state (the
        # timed run donated this one's buffers)
        def _sacrificial(gs=gs_full):
            tr, st, _, ba, _ = _setup(devices, args.bf16, args, grad_sync=gs)
            return tr, st, ba

        exposed = trace_exposed_comm(_sacrificial, key=key)
        # the mode's per-replica wire accounting: the implicit path syncs
        # the same gradient bytes an uncapped fp32 reducer would
        wire_bytes = wire_bytes_for_config(state.params, gs_full,
                                           batch_shard_count(mesh))
        rows.append({
            "mode": mode,
            "global_samples_per_s": round(sps, 1),
            "grad_collectives": census["n_collectives"],
            "wire_dtypes": "+".join(sorted(wire)) or "-",
            "wire_bytes_per_replica": wire_bytes,
            "exposed_comm_pct": exposed if exposed is not None else "-",
        })
    return rows


def run_hier(args) -> List[dict]:
    """Two-tier topology-aware gradient sync (wire_dtype="int8_hier") vs
    the flat wires, on the same devices factored into a tiered
    slice=2 x data=N/2 mesh: per bucket an EXACT fp32 reduce-scatter
    inside the slice (fast ICI tier), the s8+EF multihop exchange across
    slices (slow DCN tier), and an exact intra-slice all-gather back.

    Each row carries (a) throughput, (b) the TIER-classified collective
    census of the compiled step (analysis/hlo_rules.replica_group_tier:
    intra-slice groups are consecutive-id runs, cross-slice groups are
    strided combs; "spanning" counts collectives riding the whole mesh —
    flat traffic that ignores the hierarchy), and (c) the per-replica
    wire bytes split by tier (`wire_bytes_split_for_config`) — the
    slow-tier ~2·S/n_inner B/replica (i.e. ~2·S per slice, independent
    of the slice count) as a RECORDED number next to the flat modes'
    all-one-tier totals."""
    from ..analysis.hlo_rules import grad_sync_census, replica_group_tier
    from ..parallel.grad_sync import wire_bytes_split_for_config
    from ..parallel.mesh import batch_shard_count

    devices = jax.devices()
    n = len(devices)
    if n < 4:
        return [{"mode": "skipped",
                 "global_samples_per_s":
                     "needs >= 4 devices (slice=2 x data>=2)"}]
    cap = args.bucket_cap_mb
    mesh_spec = f"slice=2,data={n // 2}"
    lm_kw = None
    if args.lm_tiny and is_lm_model(args.model):
        lm_kw = dict(_LM_TINY)
        if args.model.startswith("gpt2"):
            lm_kw.pop("mlp_dim")
    modes = [("flat_fp32", dict(bucket_cap_mb=cap)),
             ("flat_int8_multihop",
              dict(bucket_cap_mb=cap, wire_dtype="int8_multihop")),
             ("int8_hier", dict(bucket_cap_mb=cap, wire_dtype="int8_hier"))]
    if args.grad_accum > 1:
        modes.append(("int8_hier_accum",
                      dict(bucket_cap_mb=cap, wire_dtype="int8_hier",
                           grad_accum=args.grad_accum)))
    rows = []
    for mode, gs in modes:
        trainer, state, mesh = build_trainer(
            devices, args.bf16, args.model, args.seq_len, lm_overrides=lm_kw,
            grad_sync=gs, mesh_spec=mesh_spec)
        batch, gb = make_synth_batch(mesh, args.model, args.batch_size,
                                     args.seq_len)
        nb = batch_shard_count(mesh)
        n_slices = dict(mesh.shape).get("slice", 1)
        compiled = trainer._train_step.lower(
            state, batch, jax.random.PRNGKey(0)).compile()
        by_tier: dict = {}
        for r in grad_sync_census(compiled.as_text())["rows"]:
            t = replica_group_tier(r["replica_groups"], n_slices,
                                   nb // n_slices)
            t = t if t in ("ici", "dcn") else "spanning"
            by_tier[t] = by_tier.get(t, 0) + r["count"]
        split = wire_bytes_split_for_config(state.params,
                                            dict(gs, slices=n_slices), nb)
        _, sps = timed_steps(compiled, state, batch, gb, args.steps,
                             repeats=args.repeats,
                             min_window_s=args.min_window_s)
        rows.append({
            "mode": mode,
            "global_samples_per_s": round(sps, 1),
            "ici_collectives": by_tier.get("ici", 0),
            "dcn_collectives": by_tier.get("dcn", 0),
            "spanning_collectives": by_tier.get("spanning", 0),
            "wire_bytes_ici": split["ici"],
            "wire_bytes_dcn": split["dcn"],
        })
    return rows


def run_fsdp(args) -> List[dict]:
    """Replicated vs explicit full-parameter FSDP on the same devices
    (training/loop.py fsdp_explicit; SimpleFSDP, PAPERS.md): same model,
    same data-parallel mesh, once with replicated params (the DDP layout)
    and once with params + moments flat-sharded 1/N at rest, gathered
    just-in-time per layer — plus the fully compressed int8_multihop arm
    (s8 gradient scatter with EF + s8 param gathers).

    Each row carries (a) throughput, (b) the per-layer collective census
    of the compiled step — all-gather count must equal the LayerPlan's
    group count, scatters must land as 1/N chunks (the analysis/ fsdp
    contracts, read here as recorded numbers), (c) the at-rest per-replica
    parameter bytes — the memory-division claim as a number, not a
    docstring — and (d) `wire_bytes_per_replica` with its
    `fsdp_gather_bytes` term split out, so the gather-traffic cost of the
    mode is accounted per wire dtype (the int8_multihop gathers are
    ~1 B/element, n-independent; fp32 gathers are exact at ~4 B/element).
    `--grad-accum` > 1 exercises the in-scan per-layer scatter overlap."""
    from ..parallel.grad_sync import fsdp_gather_bytes, wire_bytes_for_config
    from ..parallel.mesh import batch_shard_count
    from .trace_analysis import grad_sync_census

    devices = jax.devices()
    if len(devices) < 2:
        return [{"mode": "skipped",
                 "global_samples_per_s": "needs >= 2 devices"}]
    accum = args.grad_accum
    modes = [("replicated", None),
             ("fsdp_fp32", dict(fsdp_explicit=True)),
             ("fsdp_int8_multihop",
              dict(fsdp_explicit=True, wire_dtype="int8_multihop"))]
    rows = []
    for mode, gs in modes:
        gs_full = (dict(gs or {}, grad_accum=accum)
                   if (gs or accum > 1) else gs)
        trainer, state, mesh, batch, gb = _setup(devices, args.bf16, args,
                                                 grad_sync=gs_full)
        n = batch_shard_count(mesh)
        compiled = trainer._train_step.lower(
            state, batch, jax.random.PRNGKey(0)).compile()
        census = grad_sync_census(compiled.as_text())
        by_op = census["by_op"]
        # at-rest parameter residency per replica: fsdp's flat leaves are
        # sharded 1/N, the replicated arm holds every byte everywhere
        param_bytes = sum(
            int(leaf.size) * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(state.params))
        at_rest = param_bytes // n if trainer._fsdp else param_bytes
        wire = (gs or {}).get("wire_dtype", "fp32")
        gather_bytes = (fsdp_gather_bytes(state.params, wire, n)
                        if trainer._fsdp else 0)
        wire_bytes = wire_bytes_for_config(state.params, gs_full, n)
        _, sps = timed_steps(compiled, state, batch, gb, args.steps,
                             repeats=args.repeats,
                             min_window_s=args.min_window_s)
        rows.append({
            "mode": mode,
            "global_samples_per_s": round(sps, 1),
            "all_gathers": by_op.get("all-gather", 0),
            "grad_scatters": (by_op.get("reduce-scatter", 0)
                              + by_op.get("all-to-all", 0)),
            "grad_all_reduce": by_op.get("all-reduce", 0),
            "param_bytes_at_rest_per_replica": at_rest,
            "wire_bytes_per_replica": wire_bytes,
            "fsdp_gather_bytes": gather_bytes,
        })
    return rows


def run_tp(args) -> List[dict]:
    """Explicit TP x FSDP on the 2-D ("data","model") mesh vs 1-D layouts
    of the same LM on the same devices (ISSUE 13): replicated, fsdp
    (1-D), and fsdp x TP at model=2 (plus model=4 when the device count
    allows a data axis >= 2 beside it).

    Each row carries (a) throughput, (b) the axis-classified collective
    census of the compiled step — model-axis psums must equal the
    trainer's tp-psum-signature budget, param gathers/scatters must ride
    the data axes only (the analysis/ rules, read here as recorded
    numbers), (c) at-rest per-device parameter bytes (the 1/(N*M)
    division claim as a number), and (d) the wire split:
    `wire_bytes_per_replica` (data-axis, computed over the TP-LOCAL
    slices — the 1/M reduction) next to `tp_psum_bytes_per_replica`
    (model-axis activation traffic)."""
    from ..parallel.grad_sync import wire_bytes_for_config
    from ..parallel.mesh import batch_shard_count
    from .harness import build_lm_trainer, synth_token_batch
    from ..analysis.hlo_rules import collective_census, replica_group_axis

    devices = jax.devices()
    n = len(devices)
    if n < 2:
        return [{"mode": "skipped",
                 "global_samples_per_s": "needs >= 2 devices"}]
    if not is_lm_model(args.model):
        return [{"mode": "skipped",
                 "global_samples_per_s": "tp is an LM experiment "
                                         "(--model gpt2_*)"}]
    lm_kw = None
    if args.lm_tiny:
        lm_kw = dict(_LM_TINY)
        if args.model.startswith("gpt2"):
            lm_kw.pop("mlp_dim")
    meshes = [("replicated", None, None),
              ("fsdp", dict(fsdp_explicit=True), None),
              ("fsdp_tp_m2", dict(fsdp_explicit=True), f"data={n // 2},model=2")]
    if n >= 8:
        meshes.append(("fsdp_tp_m4", dict(fsdp_explicit=True),
                       f"data={n // 4},model=4"))
    rows = []
    for mode, gs, mesh_spec in meshes:
        try:
            trainer, state, mesh = build_lm_trainer(
                devices, args.bf16, args.model, args.seq_len,
                model_kwargs=lm_kw, grad_sync=gs, mesh_spec=mesh_spec)
        except ValueError as e:
            # infeasible arm for this model/device combo (heads not
            # divisible by the TP degree, not enough devices): recorded,
            # never silently dropped
            rows.append({"mode": mode,
                         "global_samples_per_s": f"skipped ({e})"})
            continue
        batch, gb = synth_token_batch(mesh, args.batch_size, args.seq_len)
        nb = batch_shard_count(mesh)
        model_n = dict(mesh.shape).get("model", 1)
        compiled = trainer._train_step.lower(
            state, batch, jax.random.PRNGKey(0)).compile()
        by_axis: dict = {}
        for r in collective_census(compiled.as_text()):
            ax = (replica_group_axis(r["replica_groups"], nb, model_n)
                  if model_n > 1 else "data")
            key = (r["op"], ax)
            by_axis[key] = by_axis.get(key, 0) + r["count"]
        param_bytes = sum(
            int(leaf.size) * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(state.params))
        at_rest = sum(
            int(sh.data.size) * sh.data.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(state.params)
            for sh in leaf.addressable_shards[:1]) if trainer._fsdp \
            else param_bytes
        acct_params = (trainer._fsdp_local_template
                       if trainer._tp_n > 1 else state.params)
        cfg = dict(gs or {})
        tp_bytes = trainer.tp_wire_bytes(gb // nb, args.seq_len)
        wire_bytes = wire_bytes_for_config(acct_params, cfg, nb)
        _, sps = timed_steps(compiled, state, batch, gb, args.steps,
                             repeats=args.repeats,
                             min_window_s=args.min_window_s)
        rows.append({
            "mode": mode,
            "global_samples_per_s": round(sps, 1),
            "model_axis_psums": by_axis.get(("all-reduce", "model"), 0),
            "model_axis_gathers": by_axis.get(("all-gather", "model"), 0),
            "data_axis_gathers": by_axis.get(("all-gather", "data"), 0),
            "data_axis_scatters": (by_axis.get(("reduce-scatter", "data"), 0)
                                   + by_axis.get(("all-to-all", "data"), 0)),
            "param_bytes_at_rest_per_device": at_rest,
            "wire_bytes_per_replica": wire_bytes,
            "tp_psum_bytes_per_replica": tp_bytes,
        })
    return rows


def run_pipeline(args) -> List[dict]:
    """GPipe bubble measurement: pipelined GPT-2 throughput vs microbatch
    count, against the pure-DP layout of the same model on the same devices.

    The GPipe bubble fraction is (P-1)/(M+P-1) for P stages and M
    microbatches — throughput should approach the DP baseline as M grows.
    No analogue exists in the reference (DDP only); this quantifies the
    cost/benefit of the `pipe` mesh axis (parallel/pipeline.py).
    """
    import numpy as _np

    from ..models.gpt2_pipe import GPT2PipeLMHead
    from ..parallel import MeshSpec, build_mesh, shard_batch
    from ..training import TrainConfig, Trainer
    from ..training.optim import adamw
    from ..training.tasks import LanguageModelingTask

    devices = jax.devices()
    n = len(devices)
    if n < 2:
        return [{"config": "skipped", "samples_per_s": "needs >= 2 devices"}]

    p_stages = 2
    seq, vocab, hidden, depth, heads = 64, 256, 128, 4, 4
    gb = (n // p_stages) * 8  # local batch 8 per shard: M in {1,2,4,8} divides
    rng = _np.random.RandomState(0)
    raw = {
        "input_ids": rng.randint(0, vocab, (gb, seq)).astype(_np.int32),
        "weight": _np.ones(gb, _np.float32),
    }

    def measure(mesh, model, rules):
        trainer = Trainer(LanguageModelingTask(), mesh, TrainConfig(seed=0),
                          rules=rules)
        state = trainer.init_state(model, _np.zeros((1, seq), _np.int32),
                                   adamw(1e-3), jax.random.PRNGKey(0))
        batch = shard_batch(raw, mesh)
        sps, samples = timed_steps(trainer._train_step, state, batch, gb,
                                   args.steps, repeats=args.repeats,
                                   min_window_s=args.min_window_s)
        return samples

    rows = []
    # pure-DP baseline: same model as a plain scan over layers (pipe=1
    # degenerates to sequential), all devices on the batch
    mesh_dp = build_mesh(MeshSpec(data=n), devices=devices)
    model_dp = GPT2PipeLMHead(mesh=mesh_dp, num_microbatches=1,
                              vocab_size=vocab, hidden_dim=hidden,
                              depth=depth, num_heads=heads, max_position=seq)
    sps_dp = measure(mesh_dp, model_dp, GPT2PipeLMHead.partition_rules())
    rows.append({"config": f"dp={n} (baseline)", "microbatches": "-",
                 "samples_per_s": round(sps_dp, 1),
                 "bubble_predicted_pct": 0.0, "vs_dp_pct": 100.0})

    mesh_pp = build_mesh(MeshSpec(pipe=p_stages, data=n // p_stages),
                         devices=devices)
    for m in (1, 2, 4, 8):
        model_pp = GPT2PipeLMHead(mesh=mesh_pp, num_microbatches=m,
                                  vocab_size=vocab, hidden_dim=hidden,
                                  depth=depth, num_heads=heads,
                                  max_position=seq)
        sps = measure(mesh_pp, model_pp, GPT2PipeLMHead.partition_rules())
        bubble = (p_stages - 1) / (m + p_stages - 1)
        rows.append({
            "config": f"pipe={p_stages},data={n // p_stages}",
            "microbatches": m,
            "samples_per_s": round(sps, 1),
            "bubble_predicted_pct": round(100.0 * bubble, 1),
            "vs_dp_pct": round(100.0 * sps / sps_dp, 1),
        })
    return rows


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("experiment",
                   choices=["scaling", "batch", "amp", "gradsync",
                            "grad_sync", "hier", "zero1", "fsdp", "tp",
                            "pipeline"])
    p.add_argument("--model", default="resnet18")
    p.add_argument("--batch-size", default=128, type=int,
                   help="per-device batch (ref semantics, train_ddp.py:27)")
    p.add_argument("--steps", default=20, type=int)
    p.add_argument("--repeats", default=3, type=int)
    p.add_argument("--min-window-s", default=0.5, type=float,
                   help="minimum differenced timing window (lower it for "
                        "CI smoke runs)")
    p.add_argument("--batch-list", default=None, type=str,
                   help="comma-separated per-device batches for the 'batch' "
                        "sweep (default 32,64,128,256,512)")
    p.add_argument("--lm-tiny", action="store_true",
                   help="shrink LM architectures for CI smoke runs "
                        "(never use for real measurements)")
    p.add_argument("--seq-len", default=512, type=int,
                   help="sequence length for LM models (--model gpt2_*/"
                        "bert_base; e.g. the BERT-512 grad-sync profiling "
                        "run, BASELINE config 4)")
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--bucket-cap-mb", default=25.0, type=float,
                   help="bucket cap for the 'grad_sync' experiment "
                        "(training/loop.py explicit reducer; DDP's "
                        "default is 25)")
    p.add_argument("--grad-accum", default=1, type=int,
                   help="gradient accumulation for the 'grad_sync' and "
                        "'fsdp' experiments (> 1 exercises the in-scan "
                        "overlap; grad_sync adds a no-overlap arm)")
    p.add_argument("--csv", default=None,
                   help="append rows to this CSV (plots regenerate from it)")
    args = p.parse_args(argv)

    fn = {"scaling": run_scaling, "batch": run_batch_sweep, "amp": run_amp,
          "gradsync": run_gradsync, "grad_sync": run_grad_sync,
          "hier": run_hier, "zero1": run_zero1, "fsdp": run_fsdp,
          "tp": run_tp, "pipeline": run_pipeline}[args.experiment]
    print(f"# {args.experiment} — {args.model}, "
          f"{'bf16' if args.bf16 else 'fp32'}, "
          f"{len(jax.devices())} device(s) [{jax.default_backend()}]\n")
    rows = fn(args)
    _emit(rows, args.csv)


if __name__ == "__main__":
    main(sys.argv[1:])
