"""Regenerate the README benchmark table from committed provenance.

Every completed `bench.py` run appends its full result (all configs) to
``experiments/results/bench_history.jsonl``. This tool renders that log as
the markdown table the README's "Benchmarks" section carries, so every
number in the README is regenerable from JSON in the repo (VERDICT r4
missing #2; the reference's README promises result tables it never fills,
/root/reference/README.md:25-35):

    python -m distributed_pytorch_training_tpu.experiments.report
    python -m distributed_pytorch_training_tpu.experiments.report --latest
    python -m distributed_pytorch_training_tpu.experiments.report --all
    python -m distributed_pytorch_training_tpu.experiments.report --write

The default MERGES history entries: the full config matrix is measured in
chunked ``bench.py --only <labels>`` runs (each sized to finish inside one
driver deadline — see bench.py EXTRA_CONFIGS), so one entry rarely holds
every row. The merged view takes, per config, the newest measurement on the
newest chip kind, with a per-row timestamp. --latest prints the last entry
alone; --all lists one summary line per entry so regressions stay visible.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

HISTORY = Path(__file__).resolve().parent / "results" / "bench_history.jsonl"

_LABELS = {
    "resnet18": "ResNet-18 / CIFAR-10",
    "resnet50": "ResNet-50 / ImageNet-shape",
    "vit_b16": "ViT-B/16 / ImageNet-shape",
    "gpt2_124m": "GPT-2 124M",
    "gpt2_355m": "GPT-2 355M",
    "bert_base": "BERT-base MLM",
    "gpt2_moe": "GPT-2-MoE 8-expert",
}


def _label(cfg: dict, headline_model: Optional[str]) -> str:
    name = _LABELS.get(cfg.get("model", "?"), cfg.get("model", "?"))
    if cfg.get("seq_len"):
        name += f" @ S={cfg['seq_len']}"
    # the headline is the LABEL-LESS resnet18 bf16 row; labeled probes of
    # the same model (e.g. resnet18_b8192) must not render as a second
    # indistinguishable "(headline)" claim
    if cfg.get("model") == headline_model and cfg.get("bf16") \
            and not cfg.get("label"):
        name += " (headline)"
    if not cfg.get("bf16"):
        # the label-less fp32 row is the headline's baseline arm and renders
        # indented under it; a labeled fp32 extra stands alone
        name = (f"{name.strip()} — fp32 `HIGHEST` arm" if cfg.get("label")
                else f"&nbsp;&nbsp;same, fp32 `HIGHEST` baseline "
                     f"({name.strip()})")
    return name


def _rate(cfg: dict) -> str:
    v = cfg.get("samples_per_sec_chip")
    if v is None:
        return "—"
    s = f"{v:,.0f}"
    if cfg.get("tokens_per_sec"):
        s += f" ({cfg['tokens_per_sec'] / 1e3:,.0f}k tok/s)"
    return s


def render_table(entry: dict) -> str:
    headline_model = entry.get("metric", "").split("_")[0]  # "resnet18"
    vs = entry.get("vs_baseline")
    # bench.py deliberately degrades vs_baseline to null when the fp32 arm
    # fails — say so instead of printing "None" into the README
    vs = "n/a (fp32 arm failed)" if vs is None else vs
    lines = [
        f"Measured on {entry.get('n_chips', '?')}x "
        f"{entry.get('chip', 'unknown chip')} "
        f"({entry.get('timestamp', 'no timestamp')}, "
        f"`vs_baseline` bf16-over-true-fp32 = {vs}):",
        "",
        "| config | per-chip batch | samples/s/chip | MFU |",
        "|---|---|---|---|",
    ]
    for cfg in entry.get("configs", []):
        mfu = cfg.get("mfu_pct")
        lines.append(
            f"| {_label(cfg, headline_model)} "
            f"| {cfg.get('per_device_batch', '?')} "
            f"| {_rate(cfg)} "
            f"| {'—' if mfu is None else f'{mfu}%'} |")
    if entry.get("configs_skipped"):
        lines.append("")
        lines.append("(skipped under the bench deadline: "
                     + ", ".join(str(s) for s in entry["configs_skipped"])
                     + ")")
    return "\n".join(lines)


def _cfg_key(cfg: dict) -> str:
    """Stable identity of one measured config across history entries."""
    return cfg.get("label") or "_".join(str(x) for x in (
        cfg.get("model"), f"b{cfg.get('per_device_batch')}",
        f"s{cfg.get('seq_len')}" if cfg.get("seq_len") else "",
        "bf16" if cfg.get("bf16") else "fp32") if x)


def merge_entries(entries: List[dict]):
    """Newest measurement per config on the newest measuring chip kind.

    Chunked ``--only`` runs each contribute 1-2 configs; the merged view is
    the full-matrix table the README carries. Returns (chip, vs_baseline,
    rows) where rows is ``[(cfg, source_entry), ...]`` in first-seen order.
    """
    chip = next((e.get("chip") for e in reversed(entries)
                 if e.get("configs")), None)
    rows: dict = {}
    vs = None
    for e in entries:
        if e.get("chip") != chip:
            continue
        for cfg in e.get("configs", []):
            rows[_cfg_key(cfg)] = (cfg, e)
        if e.get("vs_baseline") is not None:
            vs = e["vs_baseline"]
    return chip, vs, list(rows.values())


def render_merged(entries: List[dict]) -> str:
    chip, vs, rows = merge_entries(entries)
    headline_model = "resnet18"
    lines = [
        f"Full matrix, merged from {len(entries)} committed history "
        f"entr{'y' if len(entries) == 1 else 'ies'} on {chip} "
        f"(newest measurement per config; `vs_baseline` "
        f"bf16-over-true-fp32 = {vs if vs is not None else 'n/a'}):",
        "",
        "| config | per-chip batch | samples/s/chip | MFU | measured |",
        "|---|---|---|---|---|",
    ]
    for cfg, e in rows:
        mfu = cfg.get("mfu_pct")
        lines.append(
            f"| {_label(cfg, headline_model)} "
            f"| {cfg.get('per_device_batch', '?')} "
            f"| {_rate(cfg)} "
            f"| {'—' if mfu is None else f'{mfu}%'} "
            f"| {e.get('timestamp', '?')} |")
    measured = {_cfg_key(cfg) for cfg, _ in rows}
    never = [k for e in entries if e.get("chip") == chip
             for k in e.get("configs_skipped", []) if k not in measured]
    if never:
        lines += ["", "(still unmeasured on this chip: "
                  + ", ".join(sorted(set(never))) + ")"]
    return "\n".join(lines)


README = HISTORY.parents[3] / "README.md"
_MARK_BEGIN = "<!-- bench-table:begin"
_MARK_END = "<!-- bench-table:end -->"


def write_readme_table(entries: List[dict], readme: Path = README) -> bool:
    """Replace the committed-measurements table between the bench-table
    markers in README.md with the merged render, so the README stays a pure
    projection of bench_history.jsonl (the reverse direction — trusting a
    hand-edited table — is what VERDICT r4 called 'indistinguishable from
    fiction'). Returns True iff the file changed."""
    text = readme.read_text()
    try:
        begin = text.index(_MARK_BEGIN)
        begin_nl = text.index("\n", begin) + 1
        end = text.index(_MARK_END, begin_nl)
    except ValueError:
        raise SystemExit(
            f"report: {readme} has no bench-table markers "
            f"({_MARK_BEGIN} ... {_MARK_END})")
    # the FULL merged render, preamble included: the preamble carries the
    # chip kind and vs_baseline, which must be regenerated too — otherwise
    # the README's speedup claim stays a hand-edited number one paragraph
    # above freshly generated rows
    new = text[:begin_nl] + render_merged(entries).strip() + "\n" + text[end:]
    if new == text:
        return False
    readme.write_text(new)
    return True


def load_history(path: Path) -> List[dict]:
    if not path.exists():
        return []
    out = []
    for i, line in enumerate(path.read_text().splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            # A watchdog SIGTERM landing mid-append leaves a truncated
            # trailing line; the readable history must survive it.
            print(f"report: WARNING: skipping unparseable line {i} of "
                  f"{path} (truncated append?)", file=sys.stderr)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--history", default=str(HISTORY))
    mode = p.add_mutually_exclusive_group()
    mode.add_argument("--all", action="store_true",
                      help="one summary line per history entry instead of "
                           "the merged full-matrix table")
    mode.add_argument("--latest", action="store_true",
                      help="table for the latest entry alone (no merging "
                           "across chunked runs)")
    mode.add_argument("--write", action="store_true",
                      help="rewrite the committed-measurements table "
                           "between the bench-table markers in README.md "
                           "from the merged history")
    args = p.parse_args(argv)

    entries = load_history(Path(args.history))
    if not entries:
        print(f"no history at {args.history} — run `python bench.py` on the "
              "target chip first; every completed run appends here",
              file=sys.stderr)
        return 1
    if args.all:
        for e in entries:
            print(f"{e.get('timestamp', '?'):>20}  "
                  f"{e.get('n_chips', '?')}x {e.get('chip', '?'):<12} "
                  f"{e.get('metric', '?')}: {e.get('value')} "
                  f"{e.get('unit', '')} (vs_baseline {e.get('vs_baseline')})")
        return 0
    if args.latest:
        print(render_table(entries[-1]))
        return 0
    if args.write:
        changed = write_readme_table(entries)
        print(f"report: README table "
              f"{'updated' if changed else 'already current'}")
        return 0
    print(render_merged(entries))
    return 0


if __name__ == "__main__":
    sys.exit(main())
