"""Capacity probes: real feeds behind ``CapacityWatch(probe=...)``.

`resilience.capacity.CapacityWatch` has carried an optional ``probe``
hook since ISSUE 12 — a zero-arg callable returning the fleet's current
replica capacity — but until now nothing real was plugged into it. Two
feeds live here:

* :func:`heartbeat_capacity_probe` — capacity read off the relay/port
  registry `resilience.heartbeat` already maintains: each registered
  port vouches for an equal share of the fleet, so ``total * up_ports //
  n_ports``. This is the CPU-mesh-honest probe: the registry is the one
  liveness source bench, train, and the deathwatch already share.
* :class:`FileCapacityFeed` — the documented interface stub for
  EXTERNAL feeds (GKE node-pool state, GCE preemption notices): any
  zero-arg callable returning an int is a valid probe, and the file
  form is the smallest adapter — an agent writes the current replica
  count to a path, the watch polls it. A feed that raises or hangs is
  legitimate steady-state behavior for an external endpoint; the watch
  CONTAINS it (degrades to the last committed reading with a loud
  ``capacity_probe_errors`` event — see ``CapacityWatch.available``),
  so feed authors do not need their own retry shell.

Probes return TOTAL capacity (how many replicas could run now), not a
delta; the watch clamps to ``[0, total]`` and commits via its own
lose/sync/restore bookkeeping.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence

from ..resilience import heartbeat


def heartbeat_capacity_probe(total: int,
                             ports: Optional[Sequence[int]] = None,
                             timeout: float = 0.2) -> Callable[[], int]:
    """A probe reading capacity off the heartbeat relay registry.

    ``total`` is the full-fleet replica count the watch was built with;
    each registered port (default: `heartbeat.relay_ports`) vouches for
    an equal share, so 2 of 3 ports up on an 8-replica fleet reads as
    ``8 * 2 // 3 = 5``. With every port dark the probe reads 0 — the
    watch's clamp and grow-threshold logic decide what to do with it.
    """
    if total < 0:
        raise ValueError("total capacity must be >= 0")
    fixed = list(ports) if ports is not None else None

    def probe() -> int:
        plist = fixed if fixed is not None else heartbeat.relay_ports()
        if not plist:
            return total  # nothing registered: no evidence of loss
        snapshot = heartbeat.registry_snapshot(plist, timeout=timeout)
        up = sum(1 for alive in snapshot.values() if alive)
        return (total * up) // len(plist)

    return probe


class FileCapacityFeed:
    """External-feed adapter: read the current replica capacity from a
    file an outside agent maintains (GKE/GCE preemption watchers,
    cluster schedulers). The file holds one integer; a missing file,
    unreadable content, or a hung filesystem raises — and that is FINE:
    ``CapacityWatch.available`` contains probe failures by design
    (last-known reading + a ``capacity_probe_errors`` counter event),
    so this adapter stays a dumb read with no retry logic of its own."""

    def __init__(self, path: str):
        self.path = str(path)

    def __call__(self) -> int:
        with open(self.path, "r", encoding="utf-8") as fh:
            return int(fh.read().strip())

    def write(self, capacity: int) -> None:
        """Test/demo helper: atomically publish a reading the way a real
        agent should (write-then-rename, so the feed never reads a torn
        value)."""
        tmp = f"{self.path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(f"{int(capacity)}\n")
        os.replace(tmp, self.path)
