"""control/ — the self-driving fleet (ISSUE 20).

The observability plane measures (stragglers, exposed-comm ratio,
capacity); the control plane can act (reshard, grow, shrink, relaunch);
this package is the policy layer between them, with three disciplines:

* **every decision is a record** — one typed
  :class:`~.decisions.ControlDecision` per action (detect / evict /
  grow / retune / refuse), emitted on the telemetry stream, rendered by
  ``telemetry summary``, counted on ``/metrics``;
* **every commit is gated** — the ONLY path from policy to the
  Supervisor's re-plan surface is :func:`~.apply.apply_decision`
  (enforced by the ``control-decisions-gated`` analysis rule), and
  tuner candidates must pass the ``control_replan`` contract before
  they touch the run;
* **every action lands at a segment boundary** — the drained,
  checkpoint-anchored point elastic resizes already use, so control
  never changes the numerics of a segment in flight (PARITY.md).

Proven end to end by ``resilience chaos --autopilot``: an injected
persistent loader-stall straggler is named, evicted (shrink via the
elastic path), re-admitted when capacity returns, and the post-resize
segment is bitwise against a clean same-seed continuation.
"""

from __future__ import annotations

from .apply import BASE_CONTRACT, apply_decision, contract_gate  # noqa: F401
from .autopilot import Autopilot  # noqa: F401
from .decisions import (  # noqa: F401
    CONTROL_DECISION_KIND,
    DECISION_ACTIONS,
    ControlDecision,
    emit_decision,
)
from .probe import FileCapacityFeed, heartbeat_capacity_probe  # noqa: F401
from .straggler import StragglerEvictionPolicy  # noqa: F401
from .tuner import TUNABLE_KEYS, PerfTuner  # noqa: F401
