"""`apply_decision` — the ONE entry from policy to the re-plan surface.

Every other module in control/ measures, accumulates, and proposes;
this one commits. The split is enforced, not aspirational: the
``control-decisions-gated`` analysis rule (analysis/ast_rules.py) flags
any call into the Supervisor/trainer re-plan surface
(``boundary_shrink`` / ``boundary_retune`` / ``reshard_train_state`` /
``plan_elastic_world`` / the replan callbacks) from a control/ module
other than this file — a policy that resharded the fleet directly would
bypass the contract gate and the decision log at once.

Gating:

* ``evict`` goes straight to ``Supervisor.boundary_shrink`` — a shrink
  re-uses the elastic re-plan path whose census identity the
  ``elastic_reshard``/``elastic_grow`` contracts already pin, so there
  is nothing new to lower. The Supervisor still refuses (decision
  ``applied=False``) when the shrink is not viable: no smaller world
  divides the batch, or the boundary checkpoint did not anchor.
* ``retune`` must first pass :func:`contract_gate`: the candidate
  overrides are applied to the ``control_replan`` base contract and the
  full HLO rule set runs over the lowered result. ANY finding — or a
  config the matrix cannot even lower — refuses the candidate with a
  logged ``refuse`` decision and the run continues on the old config.

Both paths emit the finalized :class:`~.decisions.ControlDecision`
(applied or refused) inside a ``control_apply`` span, so the stream
shows the gate's wall time next to its verdict.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import telemetry as _telemetry
from .decisions import ControlDecision, emit_decision
from .tuner import TUNABLE_KEYS

# The contract the tuner's candidates are evaluated as overrides of
# (analysis/contracts.py CONTRACT_MATRIX).
BASE_CONTRACT = "control_replan"

GateResult = Tuple[bool, List[str]]


def contract_gate(overrides: Dict[str, Any],
                  base_contract: str = BASE_CONTRACT) -> GateResult:
    """Evaluate a candidate config against the contract matrix.

    Returns ``(ok, refusals)``: ``ok`` only when the candidate lowered
    AND every HLO rule passed. Refusals carry the findings (or the
    lowering error) verbatim — they become the ``refuse`` decision's
    evidence. A candidate touching a non-tunable key is refused without
    lowering anything."""
    bad = sorted(k for k in overrides if k not in TUNABLE_KEYS)
    if bad:
        return False, [f"non-tunable override key(s) {bad} "
                       f"(knobs: {TUNABLE_KEYS})"]
    from ..analysis.contracts import get_contract
    from ..analysis.hlo_rules import run_contract_matrix

    base = get_contract(base_contract)
    candidate = dataclasses.replace(
        base, name=f"{base.name}_candidate",
        config={**base.config, **overrides})
    try:
        findings, statuses = run_contract_matrix(contracts=[candidate])
    except Exception as e:  # a config the matrix cannot even lower
        return False, [f"{type(e).__name__}: {e}"]
    refusals = [str(f) for f in findings]
    status = statuses.get(candidate.name, "missing")
    if status != "pass":
        refusals.append(f"contract status: {status}")
    return (not refusals), refusals


def apply_decision(supervisor, decision: ControlDecision, *, report,
                   state, epoch: int, step: int,
                   gate: Optional[Callable[[Dict[str, Any]], GateResult]]
                   = None) -> Tuple[Any, ControlDecision]:
    """Commit (or refuse) one decision; returns ``(state, finalized)``.

    ``state`` is the live train state at the segment boundary —
    returned resharded/adopted when the action applied, unchanged when
    it was refused or deferred. The finalized decision (the one actually
    emitted) records ``applied`` and the worlds it moved between;
    refusals are emitted as action ``refuse`` with the original action
    and the gate's findings in the evidence."""
    if gate is None:
        gate = contract_gate
    with _telemetry.span("control_apply", action=decision.action):
        if decision.action == "evict":
            return _apply_evict(supervisor, decision, report=report,
                                state=state, epoch=epoch, step=step)
        if decision.action == "retune":
            return _apply_retune(supervisor, decision, report=report,
                                 state=state, epoch=epoch, step=step,
                                 gate=gate)
    raise ValueError(
        f"action {decision.action!r} is not applicable "
        "(apply_decision commits 'evict' and 'retune'; 'detect'/'grow'/"
        "'refuse' are observations — emit them directly)")


def _refusal(decision: ControlDecision, reasons: List[str], *,
             epoch: int, step: int, world: int) -> ControlDecision:
    return emit_decision(ControlDecision(
        action="refuse",
        reason=f"{decision.action} refused: {reasons[0] if reasons else ''}",
        rank=decision.rank, gen=decision.gen, epoch=epoch, step=step,
        world_from=world, world_to=world, applied=False,
        evidence={"refused_action": decision.action,
                  "refusals": list(reasons),
                  **decision.evidence}))


def _apply_evict(supervisor, decision: ControlDecision, *, report, state,
                 epoch: int, step: int) -> Tuple[Any, ControlDecision]:
    world_from = supervisor.world_size
    # the canonical tag, not the free-text reason: the resize record's
    # `cause` is what the chaos verdict (and any dashboard) matches on
    state, applied, detail = supervisor.boundary_shrink(
        report, state, epoch=epoch, step=step,
        evicted_rank=decision.rank, cause="straggler_evict")
    if not applied:
        return state, _refusal(decision, [detail], epoch=epoch, step=step,
                               world=world_from)
    final = emit_decision(dataclasses.replace(
        decision, epoch=epoch, step=step, world_from=world_from,
        world_to=supervisor.world_size, applied=True))
    return state, final


def _apply_retune(supervisor, decision: ControlDecision, *, report, state,
                  epoch: int, step: int, gate) -> Tuple[Any, ControlDecision]:
    world = supervisor.world_size
    overrides = dict(decision.evidence.get("overrides", {}))
    if not overrides:
        return state, _refusal(decision, ["no overrides proposed"],
                               epoch=epoch, step=step, world=world)
    ok, refusals = gate(overrides)
    if not ok:
        return state, _refusal(decision, refusals, epoch=epoch, step=step,
                               world=world)
    state, applied, detail = supervisor.boundary_retune(
        report, state, epoch=epoch, step=step, overrides=overrides,
        cause=decision.reason)
    if not applied:
        return state, _refusal(decision, [detail], epoch=epoch, step=step,
                               world=world)
    final = emit_decision(dataclasses.replace(
        decision, epoch=epoch, step=step, world_from=world,
        world_to=supervisor.world_size, applied=True))
    return state, final
