"""The one typed record every control action flows through.

A :class:`ControlDecision` is the policy layer's unit of accountability:
whatever a loop decides — naming a straggler, evicting it, re-admitting
returned capacity, re-planning the wire config, or REFUSING a candidate
that failed its contract — the decision is emitted as one
``control_decision`` telemetry event (kind
:data:`~..telemetry.recorder.CONTROL_DECISION_KIND`, name = the action)
on the same stream as every other instrument. ``telemetry summary``
renders the chain, ``/metrics`` counts it as
``dpt_control_decisions_total{action}``, and the chaos autopilot verdict
reads it back — a control plane whose actions were not in the stream
would be indistinguishable from a flaky fleet.

Actions:

* ``detect`` — a policy named a persistently slow rank (informational;
  always precedes an evict).
* ``evict`` — the straggler is treated as a capacity loss: drain the
  segment, shrink via the elastic re-plan path.
* ``grow`` — previously evicted/preempted capacity was re-admitted (the
  Supervisor's boundary grow, observed and accounted by the autopilot).
* ``retune`` — the online tuner re-planned the training config at a
  segment boundary (only after its contract passed).
* ``refuse`` — a candidate action was rejected: contract findings, a
  config the matrix cannot even lower, or a re-plan surface that
  declined (shrink below the smallest viable world, unanchored
  checkpoint). Refusals are decisions too — a tuner that silently
  dropped a failing candidate would leave no audit trail.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from .. import telemetry as _telemetry
from ..telemetry.recorder import CONTROL_DECISION_KIND  # noqa: F401  (re-export)

DECISION_ACTIONS = ("detect", "evict", "grow", "retune", "refuse")


@dataclasses.dataclass(frozen=True)
class ControlDecision:
    """One gated control action: what, about whom, why, and whether it
    was actually applied. ``evidence`` carries the measurement that
    justified it (straggler rows, exposed-comm ratios, contract
    findings) — flattened into the telemetry event so the stream is the
    audit trail, not a pointer to one."""

    action: str
    reason: str
    rank: Optional[int] = None
    gen: Optional[int] = None
    epoch: Optional[int] = None
    step: Optional[int] = None
    world_from: Optional[int] = None
    world_to: Optional[int] = None
    applied: bool = False
    evidence: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.action not in DECISION_ACTIONS:
            raise ValueError(f"unknown control action {self.action!r} "
                             f"(choose from {DECISION_ACTIONS})")

    def fields(self) -> Dict[str, Any]:
        """The telemetry-event payload: every non-None scalar field plus
        the evidence dict, JSON-ready."""
        out: Dict[str, Any] = {"action": self.action, "reason": self.reason,
                               "applied": bool(self.applied)}
        for key in ("rank", "gen", "epoch", "step", "world_from",
                    "world_to"):
            val = getattr(self, key)
            if val is not None:
                out[key] = int(val)
        if self.evidence:
            out["evidence"] = dict(self.evidence)
        return out

    def as_dict(self) -> Dict[str, Any]:
        return self.fields()


def emit_decision(decision: ControlDecision) -> ControlDecision:
    """Put one decision on the telemetry stream (no-op when telemetry is
    unconfigured, like every module-level emit helper) and return it —
    callers chain ``decisions.append(emit_decision(d))``."""
    _telemetry.emit(CONTROL_DECISION_KIND, decision.action,
                    **decision.fields())
    return decision
