"""Straggler-eviction policy: persistence, not a single bad step.

`telemetry.aggregate.detect_stragglers` names (gen, rank, step, phase)
outliers; this policy answers the only question the control plane may
act on: is the SAME rank persistently slow — flagged at
``n_consecutive`` consecutive step labels — so that treating it as a
capacity loss (drain -> shrink -> re-admit on recovery) beats waiting it
out? One flagged step is weather (a GC pause, a cold page); N in a row
is a sick host.

Identity discipline: rank labels are only meaningful WITHIN one world
layout. After any elastic resize the surviving ranks renumber, so
:meth:`StragglerEvictionPolicy.note_resize` drops ALL accumulated
history — an old slow rank's record must never convict whichever new
rank inherited its number (the ISSUE 20 persistence-across-resize
satellite pins this).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..telemetry.aggregate import STRAGGLER_PHASES

# Consecutive flagged steps before a rank is named for eviction. 3 is the
# floor at which "persistent" is distinguishable from "unlucky twice" on
# the CPU-mesh step times the detector's abs floor already filters.
DEFAULT_N_CONSECUTIVE = 3


class StragglerEvictionPolicy:
    """Accumulate detector rows; convict on N consecutive flagged steps.

    ``observe_rows`` is idempotent per (gen, rank, step): the autopilot
    re-runs the detector over its whole buffered window at every segment
    boundary, so the same flag arriving twice must not double-count.
    ``verdict`` returns the worst persistent rank (longest flagged run,
    then highest factor) or None while nothing crosses the threshold.
    """

    def __init__(self, n_consecutive: int = DEFAULT_N_CONSECUTIVE,
                 phases: Tuple[str, ...] = STRAGGLER_PHASES):
        if n_consecutive < 1:
            raise ValueError("n_consecutive must be >= 1")
        self.n_consecutive = int(n_consecutive)
        self.phases = tuple(phases)
        # (gen, rank) -> {step -> worst row seen for that step}
        self._flags: Dict[Tuple[int, int], Dict[int, dict]] = {}

    def observe_rows(self, rows: List[dict]) -> None:
        """Merge one detector pass. Rows outside the configured phases
        are ignored (an eval-span outlier is not a training straggler)."""
        for row in rows:
            if row.get("phase") not in self.phases:
                continue
            key = (int(row.get("gen", 0)), int(row.get("rank", 0)))
            steps = self._flags.setdefault(key, {})
            step = int(row["step"])
            prev = steps.get(step)
            if prev is None or row.get("dur_s", 0.0) > prev.get("dur_s", 0.0):
                steps[step] = dict(row)

    def note_resize(self) -> None:
        """Rank identities just remapped (any elastic resize, either
        direction): forget everything. History from the old numbering
        must not convict a new rank."""
        self._flags.clear()

    def flagged_steps(self, gen: int, rank: int) -> List[int]:
        return sorted(self._flags.get((int(gen), int(rank)), ()))

    def verdict(self) -> Optional[dict]:
        """The persistent straggler, if any: ``{"gen", "rank", "steps",
        "evidence"}`` where ``steps`` is the qualifying consecutive run
        (>= n_consecutive) and ``evidence`` the worst row of that run
        (detector fields, device attribution when a capture covered
        it)."""
        best: Optional[dict] = None
        for (gen, rank), steps in self._flags.items():
            run = _longest_consecutive_run(sorted(steps))
            if len(run) < self.n_consecutive:
                continue
            worst = max((steps[s] for s in run),
                        key=lambda r: r.get("dur_s", 0.0))
            candidate = {"gen": gen, "rank": rank, "steps": run,
                         "evidence": worst}
            if best is None or (len(run), worst.get("factor", 0.0)) > (
                    len(best["steps"]), best["evidence"].get("factor", 0.0)):
                best = candidate
        return best


def _longest_consecutive_run(steps: List[int]) -> List[int]:
    """Longest run of consecutive integers in an ascending list (ties:
    the earliest run — the first sustained stall is the one that
    convicts)."""
    best: List[int] = []
    run: List[int] = []
    for s in steps:
        if run and s == run[-1] + 1:
            run.append(s)
        else:
            run = [s]
        if len(run) > len(best):
            best = list(run)
    return best
