"""The autopilot: observability plane in, gated decisions out.

One object closes the loop. It rides the telemetry recorder as an
OBSERVER (`Recorder.add_observer` — called outside the stream lock,
forbidden to emit; it only buffers), and the Supervisor calls
:meth:`Autopilot.on_segment_boundary` from its clean-segment path — the
same anchor elastic resizes use — so every decision lands where the run
is drained, checkpoint-anchored, and safe to re-plan.

Loop (1), straggler eviction: buffered ``data_wait``/``step_dispatch``
spans feed `telemetry.aggregate.detect_stragglers` at each boundary;
the rows feed a :class:`~.straggler.StragglerEvictionPolicy`; a verdict
(same rank, N consecutive flagged steps) emits a ``detect`` decision
and hands an ``evict`` to `control.apply_decision` — shrink via the
elastic path. While evicted capacity is out, detection is suspended (a
shrunken fleet re-convicting itself would thrash); when the Supervisor's
own boundary grow re-admits the capacity, the autopilot observes the
world change and emits the accounting ``grow`` decision, completing the
detect -> evict -> grow chain the chaos verdict reads back.

Loop (2), online tuning: ``device_profile`` windows (watchdog-armed
captures) feed a :class:`~.tuner.PerfTuner`; a proposal becomes a
``retune`` decision that `apply_decision` contract-gates before the
Supervisor applies it at this same boundary — or refuses with a logged
decision, and the run continues on the old config.

Identity hygiene: ANY world change (an eviction, a failure re-plan, a
grow) clears the policy's history and the span buffer — rank labels
renumber across resizes, and stale history must not convict whichever
new rank inherited a number (`StragglerEvictionPolicy.note_resize`).

Off by default, nothing when off: no Autopilot object, no observer, no
threads, no new events — the recorder stream and the lowered HLO are
byte-identical to a build without this package (the PR 8/13/14
discipline, pinned by tests/test_control.py).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from .. import telemetry as _telemetry
from ..telemetry.aggregate import StreamSegment, detect_stragglers
from ..telemetry.device import DEVICE_PROFILE_KIND
from .apply import apply_decision, contract_gate
from .decisions import ControlDecision, emit_decision
from .straggler import StragglerEvictionPolicy
from .tuner import PerfTuner

# Span-buffer bound: boundaries drain it on every resize and detection
# re-runs over the whole window, so this only guards a pathological
# never-resizing run from unbounded growth. 4096 events is hours of
# CPU-mesh steps.
MAX_BUFFERED_EVENTS = 4096


class Autopilot:
    """The control loop the Supervisor consults at segment boundaries.

    ``policy=None`` disables eviction, ``tuner=None`` disables retuning;
    the default is eviction-only (the chaos-proven loop). ``gate``
    defaults to the real contract gate; tests inject stubs to exercise
    the refusal path without lowering HLO.
    """

    def __init__(self, policy: Optional[StragglerEvictionPolicy] = None,
                 tuner: Optional[PerfTuner] = None, *,
                 evict: bool = True,
                 rel_factor: float = 5.0, abs_floor_s: float = 0.25,
                 gate=contract_gate):
        self.policy = (policy if policy is not None
                       else (StragglerEvictionPolicy() if evict else None))
        self.tuner = tuner
        self.rel_factor = float(rel_factor)
        self.abs_floor_s = float(abs_floor_s)
        self.gate = gate
        self.decisions: List[ControlDecision] = []
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._attached_to = None
        self._last_world: Optional[int] = None
        # world to watch for while evicted capacity is out (the pre-shrink
        # world); non-None suspends detection
        self._pending_readmit: Optional[int] = None
        self._evicted_rank: Optional[int] = None

    # -- recorder plumbing --------------------------------------------------

    def attach(self) -> "Autopilot":
        """Register the buffering observer on the configured recorder.
        Raises when telemetry is unconfigured: an autopilot without a
        stream would decide blind AND leave no audit trail."""
        rec = _telemetry.get()
        if rec is None:
            raise RuntimeError(
                "autopilot requires configured telemetry "
                "(telemetry.configure(...) / --telemetry-dir): its inputs "
                "and its decision log are both the stream")
        rec.add_observer(self._observe)
        self._attached_to = rec
        return self

    def detach(self) -> None:
        if self._attached_to is not None:
            try:
                self._attached_to.remove_observer(self._observe)
            finally:
                self._attached_to = None

    def _observe(self, ev: dict) -> None:
        # Recorder-observer contract: NEVER emit from here. Buffer the
        # straggler phases and feed profile windows to the tuner; drop
        # everything else on the floor.
        kind = ev.get("kind")
        interesting = (
            kind == DEVICE_PROFILE_KIND
            or (kind == "span" and self.policy is not None
                and ev.get("name") in self.policy.phases))
        if not interesting:
            return
        with self._lock:
            if kind == DEVICE_PROFILE_KIND and self.tuner is not None:
                self.tuner.observe(ev)
            if len(self._events) < MAX_BUFFERED_EVENTS:
                self._events.append(ev)

    def _drain(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def _clear(self) -> None:
        with self._lock:
            self._events.clear()

    # -- the boundary hook --------------------------------------------------

    def on_segment_boundary(self, *, supervisor, report, state,
                            epoch: int, step: int):
        """Called by the Supervisor at each clean segment boundary (its
        thread, never an observer's). Returns the (possibly resharded)
        state."""
        world = int(supervisor.world_size)
        if self._last_world is not None and world != self._last_world:
            # any resize — ours, a failure re-plan, a grow — remaps rank
            # identity: forget everything measured under the old numbering
            if self.policy is not None:
                self.policy.note_resize()
            self._clear()
            if (self._pending_readmit is not None
                    and world >= self._pending_readmit):
                self.decisions.append(emit_decision(ControlDecision(
                    action="grow",
                    reason="capacity returned; evicted share re-admitted "
                           "by the boundary grow",
                    rank=self._evicted_rank, epoch=epoch, step=step,
                    world_from=self._last_world, world_to=world,
                    applied=True)))
                self._pending_readmit = None
                self._evicted_rank = None
        self._last_world = world

        if self.policy is not None and self._pending_readmit is None:
            state = self._run_eviction(supervisor, report, state,
                                       epoch=epoch, step=step)
        if self.tuner is not None:
            state = self._run_tuner(supervisor, report, state,
                                    epoch=epoch, step=step)
        return state

    # -- loops --------------------------------------------------------------

    def _segment(self, events: List[dict]) -> StreamSegment:
        gen = int(events[0].get("gen", 0)) if events else 0
        rank = int(events[0].get("rank", 0)) if events else 0
        return StreamSegment(gen=gen, rank=rank, path="<live>",
                             anchor_ts=float(events[0].get("ts", 0.0))
                             if events else 0.0,
                             events=list(events))

    def _run_eviction(self, supervisor, report, state, *, epoch, step):
        events = self._drain()
        if not events:
            return state
        rows = detect_stragglers([self._segment(events)],
                                 phases=self.policy.phases,
                                 rel_factor=self.rel_factor,
                                 abs_floor_s=self.abs_floor_s)
        self.policy.observe_rows(rows)
        verdict = self.policy.verdict()
        if verdict is None:
            return state
        self.decisions.append(emit_decision(ControlDecision(
            action="detect",
            reason=(f"rank {verdict['rank']} persistently slow: "
                    f"{len(verdict['steps'])} consecutive flagged steps"),
            rank=verdict["rank"], gen=verdict["gen"], epoch=epoch,
            step=step, world_from=supervisor.world_size,
            evidence={"steps": verdict["steps"],
                      "worst": verdict["evidence"]})))
        evict = ControlDecision(
            action="evict",
            reason=(f"straggler_evict: rank {verdict['rank']} flagged at "
                    f"steps {verdict['steps']}"),
            rank=verdict["rank"], gen=verdict["gen"],
            evidence={"steps": verdict["steps"],
                      "worst": verdict["evidence"]})
        world_before = int(supervisor.world_size)
        state, final = apply_decision(supervisor, evict, report=report,
                                      state=state, epoch=epoch, step=step,
                                      gate=self.gate)
        self.decisions.append(final)
        if final.applied:
            self._pending_readmit = world_before
            self._evicted_rank = verdict["rank"]
            self.policy.note_resize()
            self._clear()
            self._last_world = int(supervisor.world_size)
        return state

    def _current_config(self, supervisor) -> Dict[str, Any]:
        cfg = getattr(getattr(supervisor, "trainer", None), "config", None)
        out: Dict[str, Any] = {}
        for key in ("wire_dtype", "bucket_cap_mb", "overlap_grad_sync",
                    "grad_accum"):
            val = getattr(cfg, key, None)
            if val is not None:
                out[key] = val
        return out

    def _run_tuner(self, supervisor, report, state, *, epoch, step):
        proposal = self.tuner.propose(self._current_config(supervisor))
        if proposal is None:
            return state
        retune = ControlDecision(
            action="retune",
            reason=("exposed-comm ratio "
                    f"{proposal['evidence']['mean_exposed_comm_ratio']} over "
                    f"{proposal['evidence']['windows']} windows >= "
                    f"{proposal['evidence']['threshold']}"),
            evidence={"overrides": proposal["overrides"],
                      **proposal["evidence"]})
        state, final = apply_decision(supervisor, retune, report=report,
                                      state=state, epoch=epoch, step=step,
                                      gate=self.gate)
        self.decisions.append(final)
        return state
