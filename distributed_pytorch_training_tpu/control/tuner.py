"""Online perf tuner: exposed-comm ratio -> a candidate wire re-plan.

The device-attribution plane (telemetry/device.py) already measures the
number that matters for the wire choice: ``exposed_comm_ratio`` — the
fraction of collective time the step FAILED to hide behind compute —
captured in watchdog-armed windows and emitted as ``device_profile``
events. This tuner closes the loop: accumulate the ratios, and when the
fleet is persistently comm-exposed on an exact fp32 wire, propose the
compressed-wire config (bucket cap + int8 multihop — the DynamiQ-style
choice PAPERS.md frames as the slow-interconnect remedy).

The tuner only PROPOSES. Nothing here touches the re-plan surface:
`control.apply_decision` runs the candidate through the ``analysis/``
contract matrix first (the ``control_replan`` contract with the
overrides applied) and refuses — with a logged decision — any candidate
that fails or cannot even lower. Applied re-plans land ONLY at segment
boundaries via ``Supervisor.boundary_retune``, anchored on a durable
checkpoint exactly like an elastic resize.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..telemetry.device import DEVICE_PROFILE_KIND

# Config keys a tuner candidate may override — the knobs the contract
# matrix actually checks. Anything else in an overrides dict is refused
# by the gate before it can reach a TrainConfig.
TUNABLE_KEYS = ("wire_dtype", "bucket_cap_mb", "overlap_grad_sync",
                "grad_accum")

# Default compressed-wire candidate: the bucketed DynamiQ multihop form
# the gsync_int8_mh contract pins. The tiny bucket cap mirrors the
# contract matrix's _CAP so the candidate engages multi-bucket behavior
# even on the contract model.
DEFAULT_CANDIDATE: Dict[str, Any] = {"wire_dtype": "int8_multihop",
                                     "bucket_cap_mb": 0.02}


class PerfTuner:
    """Accumulate ``device_profile`` windows; propose one re-plan.

    ``threshold`` is the mean exposed-comm ratio above which the fp32
    wire is judged interconnect-bound; ``min_windows`` is the number of
    captured windows required before the mean is credible (one window is
    weather). The tuner is one-shot by design: after a proposal —
    whether the gate applied or refused it — it stays quiet until
    :meth:`reset`, because re-proposing the same refused candidate every
    boundary would spam the decision log without new evidence.
    """

    def __init__(self, threshold: float = 0.3, min_windows: int = 2,
                 candidate: Optional[Dict[str, Any]] = None):
        if not 0.0 <= threshold <= 1.0:
            raise ValueError("threshold is a ratio in [0, 1]")
        if min_windows < 1:
            raise ValueError("min_windows must be >= 1")
        self.threshold = float(threshold)
        self.min_windows = int(min_windows)
        self.candidate = dict(candidate if candidate is not None
                              else DEFAULT_CANDIDATE)
        unknown = [k for k in self.candidate if k not in TUNABLE_KEYS]
        if unknown:
            raise ValueError(f"candidate overrides {unknown} are not "
                             f"tunable (knobs: {TUNABLE_KEYS})")
        self._ratios: List[float] = []
        self._proposed = False

    def observe(self, ev: Dict[str, Any]) -> None:
        """Feed one telemetry event; only ``device_profile`` events with
        an ``exposed_comm_ratio`` field count. Safe to call with the
        whole stream."""
        if ev.get("kind") != DEVICE_PROFILE_KIND:
            return
        ratio = ev.get("exposed_comm_ratio")
        if ratio is None:
            return
        self._ratios.append(float(ratio))

    @property
    def windows(self) -> int:
        return len(self._ratios)

    def mean_ratio(self) -> Optional[float]:
        if not self._ratios:
            return None
        return sum(self._ratios) / len(self._ratios)

    def propose(self, current_config: Optional[Dict[str, Any]] = None
                ) -> Optional[Dict[str, Any]]:
        """The candidate overrides, or None.

        Proposes iff: not already proposed, >= min_windows captured,
        mean ratio >= threshold, and the current wire (from
        ``current_config``, default exact fp32) is not already the
        candidate's. Returns ``{"overrides": ..., "evidence": ...}`` —
        the evidence rides the decision record verbatim."""
        if self._proposed or len(self._ratios) < self.min_windows:
            return None
        mean = self.mean_ratio()
        if mean is None or mean < self.threshold:
            return None
        current = dict(current_config or {})
        if current.get("wire_dtype", "fp32") == self.candidate.get(
                "wire_dtype", "fp32"):
            return None  # already on the proposed wire
        self._proposed = True
        return {
            "overrides": dict(self.candidate),
            "evidence": {
                "mean_exposed_comm_ratio": round(mean, 4),
                "windows": len(self._ratios),
                "threshold": self.threshold,
                "current_wire": current.get("wire_dtype", "fp32"),
            },
        }

    def reset(self) -> None:
        """Re-arm (new config epoch: a retune landed or was refused and
        the operator changed the candidate)."""
        self._ratios.clear()
        self._proposed = False
