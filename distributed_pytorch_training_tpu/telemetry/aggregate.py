"""Fleet aggregation (ISSUE 14): merge N telemetry streams into ONE view.

PR 8's telemetry is one stream read post-hoc; PRs 10-12 made runs
multi-process (fleet generations), elastic (mid-run world resizes) and
2-D sharded (per-axis wire tiers). This module is the cross-stream half:

* :func:`split_streams` — N JSONL paths -> per-``(gen, rank)``
  :class:`StreamSegment`\\ s. Segment-aware by necessity: fleet children
  of successive generations APPEND to the same ``telemetry_rank0.jsonl``
  (the recorder opens ``"a"``), so one file can hold several runs'
  events; every ``meta`` line starts a new segment, and each event's own
  ``gen``/``rank`` stamp (v2) resolves which run it belongs to. v1
  streams (no stamps) normalize to gen 0 / rank 0.
* :func:`aggregate_streams` — the fleet summary: per-(gen, rank)
  step-time/phase splits SIDE BY SIDE, wire-byte rollups by tier/axis
  (the DCN tier slots in as one more row, nothing here is tier-aware
  beyond grouping), anomaly rollup, and the straggler table.
* :func:`detect_stragglers` — per-step cross-rank attribution: for each
  (step, phase) the slowest stream is compared against its peers at the
  SAME step (or, when no peer ran that step — elastic runs overlap only
  partially — against the phase's own cross-fleet median), and a flagged
  straggler names the (gen, rank), the step, AND the phase that made it
  slow. A ``loader_stall`` chaos fault on one fleet child reads back as
  exactly that: data_wait, that child's gen/rank, that step.
* :func:`stitch_perfetto` — ONE Chrome trace-event timeline with a
  STABLE pid per (gen, rank) (sorted identity order, so re-exports are
  diffable), span tracks on tid 1 and gauge COUNTER tracks (``ph:"C"``)
  beside them.

Clock skew: streams come from different processes (and, at fleet scale,
different hosts), so wall clocks disagree. Every segment's own ``meta``
event is its anchor — cross-stream timelines and per-step comparisons use
``ts - anchor_ts`` (durations were always monotonic ``perf_counter``
pairs and need nothing). The merged timeline therefore OVERLAYS segments
at t=0, which is the comparison view the straggler story needs; absolute
wall time stays in each event's ``args``.

jax-free by design, like every reader in this package: fleet summaries
are produced by the orchestrator (which must never initialize a backend)
and read on machines with no accelerator stack.
"""

from __future__ import annotations

import dataclasses
import json
import os
import statistics
from collections import defaultdict
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

# the per-step phases the straggler detector attributes (the two spans
# the train loop emits per step, with their `step` field)
STRAGGLER_PHASES = ("data_wait", "step_dispatch")


@dataclasses.dataclass
class StreamSegment:
    """One recorder lifetime: the events between a ``meta`` line and the
    next (or EOF), keyed by the (gen, rank) identity stamped on them."""

    gen: int
    rank: int
    path: str
    anchor_ts: float            # the meta event's wall clock: t=0
    run_id: Optional[str] = None
    pid: Optional[int] = None
    events: List[dict] = dataclasses.field(default_factory=list)

    @property
    def key(self) -> Tuple[int, int]:
        return (self.gen, self.rank)


def _identity_of(ev: dict) -> Tuple[int, int]:
    """(gen, rank) of one event; v1 events (no stamps) read as (0, 0)."""
    try:
        return (int(ev.get("gen", 0)), int(ev.get("rank", 0)))
    except (TypeError, ValueError):
        return (0, 0)


def split_streams(paths: Iterable, *, missing: Optional[List[str]] = None
                  ) -> List[StreamSegment]:
    """Read N stream files into per-(gen, rank) segments. Unreadable or
    empty paths are recorded in ``missing`` (when given) instead of
    raising — one dead rank must not hide the rest of the fleet."""
    from .__main__ import read_stream

    segments: List[StreamSegment] = []
    current: Optional[StreamSegment] = None
    for raw_path in paths:
        path = str(raw_path)
        try:
            events, _bad = read_stream(path)
        except OSError:
            events = []
        if not events:
            if missing is not None:
                missing.append(path)
            continue
        current = None
        for ev in events:
            if ev.get("kind") == "meta":
                gen, rank = _identity_of(ev)
                current = StreamSegment(
                    gen=gen, rank=rank, path=path,
                    anchor_ts=float(ev.get("ts", 0.0)),
                    run_id=ev.get("run_id"), pid=ev.get("pid"))
                current.events.append(ev)
                segments.append(current)
                continue
            if current is None:
                # a header lost to truncation/rotation: synthesize an
                # anchor from the first event so the tail still reads
                gen, rank = _identity_of(ev)
                current = StreamSegment(
                    gen=gen, rank=rank, path=path,
                    anchor_ts=float(ev.get("ts", 0.0)))
                segments.append(current)
            current.events.append(ev)
    return segments


# ---------------------------------------------------------------------------
# straggler / divergence detection
# ---------------------------------------------------------------------------


def detect_stragglers(segments: List[StreamSegment],
                      phases: Tuple[str, ...] = STRAGGLER_PHASES,
                      rel_factor: float = 5.0,
                      abs_floor_s: float = 0.25) -> List[dict]:
    """Cross-rank per-step attribution: flag (gen, rank, step, phase)
    where one stream's span ran ``rel_factor`` x slower than its peers'
    median at the SAME step AND above ``abs_floor_s`` (microsecond noise
    at CPU-mesh step times must not read as divergence). Steps no peer
    ran — elastic fleets overlap only partially — fall back to the
    phase's own cross-fleet median, so a stall in a solo segment is still
    named. Each segment's FIRST ``step_dispatch`` is exempt: a relaunch's
    first dispatch is compile-dominated by construction (the watchdog's
    warm-up rule, applied cross-stream) and naming every generation's
    cold start a straggler would bury the real ones. Sorted worst-first
    by excess duration.

    Device attribution (ISSUE 15): when the flagged segment carries a
    ``device_profile`` event covering the flagged step (the window
    contains it, or the capture was anomaly-TRIGGERED by it —
    telemetry/device.covers_step), the straggler row gains a ``device``
    block: the captured split, the dominant collective op, and — when
    OTHER segments profiled too — the exposed-comm factor vs the fleet
    median ("rank 3 slow at step 12: exposed all-reduce 4.1x fleet
    median"). Span-based attribution is unchanged and remains the
    fallback when no capture overlapped."""
    # (phase, step) -> [(dur_s, segment)]
    by_step: Dict[Tuple[str, int], List[Tuple[float, StreamSegment]]] = \
        defaultdict(list)
    phase_all: Dict[str, List[float]] = defaultdict(list)
    profiles: Dict[Tuple[int, int], List[dict]] = defaultdict(list)
    for seg in segments:
        seen_dispatch = False
        for ev in seg.events:
            if ev.get("kind") == "device_profile":
                profiles[seg.key].append(ev)
                continue
            if ev.get("kind") != "span" or ev.get("name") not in phases:
                continue
            if ev["name"] == "step_dispatch" and not seen_dispatch:
                seen_dispatch = True   # the compile-carrying cold start
                continue
            dur_s = float(ev.get("dur_ms", 0.0)) / 1e3
            phase_all[ev["name"]].append(dur_s)
            step = ev.get("step")
            if step is None:
                continue
            by_step[(ev["name"], int(step))].append((dur_s, seg))

    out: List[dict] = []
    for (phase, step), entries in by_step.items():
        dur_s, seg = max(entries, key=lambda e: e[0])
        peers = [d for d, s in entries if s is not seg]
        if peers:
            baseline = statistics.median(peers)
            basis = "peers_at_step"
        else:
            others = [d for d in phase_all[phase]]
            if len(others) < 4:   # nothing credible to compare against
                continue
            baseline = statistics.median(others)
            basis = "phase_median"
        if dur_s > abs_floor_s and dur_s > rel_factor * max(baseline, 1e-9):
            row = {
                "gen": seg.gen, "rank": seg.rank, "step": step,
                "phase": phase,
                "dur_s": round(dur_s, 4),
                "baseline_s": round(baseline, 6),
                "factor": round(dur_s / max(baseline, 1e-9), 1),
                "basis": basis, "peers": len(peers),
            }
            device = _device_attribution(profiles, seg.key, step)
            if device is not None:
                row["device"] = device
            out.append(row)
    out.sort(key=lambda s: -(s["dur_s"] - s["baseline_s"]))
    return out


def _device_attribution(profiles: Dict[Tuple[int, int], List[dict]],
                        key: Tuple[int, int], step: int) -> Optional[dict]:
    """The straggler row's device block: the flagged segment's covering
    profile, plus the exposed-comm factor vs the fleet median of the
    OTHER segments' profiles (when any exist to compare against)."""
    from .device import covers_step, split_of_event

    mine = next((p for p in profiles.get(key, ())
                 if covers_step(p, step)), None)
    if mine is None:
        return None
    split = split_of_event(mine)
    by_op = mine.get("by_op_ms") or {}
    device = {
        "split_ms": {p: round(v, 3) for p, v in split.items()},
        "window_ms": round(float(mine.get("window_ms", 0.0)), 3),
        "exposed_comm_ratio": mine.get("exposed_comm_ratio"),
        "reason": mine.get("reason"),
        "trigger_step": mine.get("trigger_step"),
    }
    if by_op:
        device["dominant_op"] = max(by_op, key=lambda k: by_op[k])
    peer_exposed = [float(p.get("comm_exposed_ms", 0.0))
                    for k, plist in profiles.items() if k != key
                    for p in plist]
    if peer_exposed:
        med = statistics.median(peer_exposed)
        if med > 0:
            device["exposed_vs_fleet_median"] = round(
                split["comm_exposed"] / med, 1)
        # med == 0 (peers fully hidden their comm): a ratio would be
        # meaningless noise — the absolute split above is the evidence
    return device


# ---------------------------------------------------------------------------
# the fleet summary
# ---------------------------------------------------------------------------


def aggregate_streams(paths: Iterable, *, rel_factor: float = 5.0,
                      abs_floor_s: float = 0.25) -> dict:
    """Merge N stream FILES (across ranks AND generations) into one
    fleet summary — the path-taking wrapper over
    :func:`aggregate_segments` (callers that also stitch a trace split
    once and pass the segments to both, instead of re-parsing)."""
    missing: List[str] = []
    segments = split_streams(paths, missing=missing)
    return aggregate_segments(segments, missing=missing,
                              rel_factor=rel_factor,
                              abs_floor_s=abs_floor_s)


def aggregate_segments(segments: List[StreamSegment], *,
                       missing: Optional[List[str]] = None,
                       rel_factor: float = 5.0,
                       abs_floor_s: float = 0.25) -> dict:
    """The fleet summary body: per-(gen, rank) phase splits side by
    side, wire-byte rollups by (counter, tier, axis), anomaly rollup,
    stragglers."""
    from .__main__ import summarize

    missing = missing if missing is not None else []
    streams: List[dict] = []
    wire: Dict[Tuple[str, str, str], float] = defaultdict(float)
    anomalies: List[dict] = []
    total_steps = 0.0
    for seg in sorted(segments, key=lambda s: s.key):
        s = summarize(seg.events)
        total_steps += s["counters"].get("steps", 0.0)
        streams.append({
            "gen": seg.gen, "rank": seg.rank, "run_id": seg.run_id,
            "path": seg.path, "n_events": len(seg.events),
            "schema": s.get("schema"),
            "step_split_pct": s["step_split_pct"],
            "steps": s["counters"].get("steps", 0.0),
            "recorded_wall_ms": s["totals"]["recorded_wall_ms"],
            "accounted_span_ms": s["totals"]["accounted_span_ms"],
            "partial_epoch": s.get("partial_epoch"),
            "anomaly_count": len(s["anomalies"]),
            # the device-time split beside the wall-clock one (ISSUE 15)
            "device": s.get("device"),
        })
        for ev in seg.events:
            kind = ev.get("kind")
            if kind == "counter" and ("tier" in ev or "axis" in ev):
                key = (ev.get("name", "?"), str(ev.get("tier", "")),
                       str(ev.get("axis", "")))
                wire[key] += float(ev.get("value", 0.0))
            elif kind == "anomaly":
                anomalies.append({
                    "gen": seg.gen, "rank": seg.rank,
                    "name": ev.get("name", "?"),
                    **{k: v for k, v in ev.items()
                       if k not in ("v", "ts", "kind", "name", "gen",
                                    "rank")}})
    stragglers = detect_stragglers(segments, rel_factor=rel_factor,
                                   abs_floor_s=abs_floor_s)
    return {
        "kind": "fleet_summary",
        "n_streams": len(segments),
        "identities": sorted({seg.key for seg in segments}),
        "streams": streams,
        "total_steps": total_steps,
        "wire": [{"name": n, "tier": t, "axis": a, "total": round(v, 4)}
                 for (n, t, a), v in sorted(wire.items())],
        "anomalies": anomalies,
        "stragglers": stragglers,
        "missing_streams": missing,
    }


def print_fleet_summary(agg: dict) -> None:
    print(f"fleet: {agg['n_streams']} stream segment(s) across "
          f"{len(agg['identities'])} (gen, rank) identit(ies)")
    for s in agg["streams"]:
        split = " ".join(f"{n}={p:.1f}%" for n, p in
                         sorted(s["step_split_pct"].items(),
                                key=lambda kv: -kv[1]))
        partial = ""
        if s.get("partial_epoch"):
            partial = (f"  [PARTIAL EPOCH: "
                       f"{s['partial_epoch']['steps']} step(s)]")
        print(f"  gen={s['gen']} rank={s['rank']}: "
              f"{s['steps']:.0f} steps, wall "
              f"{s['recorded_wall_ms']:.0f}ms — {split}{partial}")
        if s.get("device"):
            d = s["device"]
            dev_split = " ".join(
                f"{n}={p:.1f}%" for n, p in
                sorted(d["split_pct"].items(), key=lambda kv: -kv[1]))
            print(f"    device ({d['profiles']} window(s), "
                  f"{d['window_ms']:.0f}ms): {dev_split} "
                  f"exposed_ratio={d['exposed_comm_ratio']:.3f}")
    for w in agg["wire"]:
        tier = f" tier={w['tier']}" if w["tier"] else ""
        axis = f" axis={w['axis']}" if w["axis"] else ""
        print(f"  wire: {w['name']}{tier}{axis} = {w['total']}")
    if agg["anomalies"]:
        print(f"  ANOMALIES ({len(agg['anomalies'])}):")
        for a in agg["anomalies"]:
            print(f"    gen={a['gen']} rank={a['rank']} {a['name']} "
                  + " ".join(f"{k}={v}" for k, v in a.items()
                             if k not in ("gen", "rank", "name")))
    if agg["stragglers"]:
        print(f"  STRAGGLERS ({len(agg['stragglers'])}):")
        for s in agg["stragglers"]:
            print(f"    gen={s['gen']} rank={s['rank']} step={s['step']} "
                  f"{s['phase']} {s['dur_s']:.3f}s "
                  f"({s['factor']}x {s['basis']})")
            if s.get("device"):
                d = s["device"]
                vs = (f" {d['exposed_vs_fleet_median']}x fleet median"
                      if "exposed_vs_fleet_median" in d else "")
                op = (f" {d['dominant_op']}" if "dominant_op" in d else "")
                print(f"      device: exposed{op} "
                      f"{d['split_ms']['comm_exposed']:.1f}ms{vs} "
                      f"(compute {d['split_ms']['compute']:.1f}ms, "
                      f"host gap {d['split_ms']['host_gap']:.1f}ms; "
                      f"capture: {d.get('reason', '?')})")
    for path in agg["missing_streams"]:
        print(f"  note: unreadable/empty stream skipped: {path}")


# ---------------------------------------------------------------------------
# trace stitching: N streams -> one Perfetto timeline
# ---------------------------------------------------------------------------


def stitch_perfetto(segments: List[StreamSegment]) -> dict:
    """One Chrome trace-event JSON over every segment: exactly one pid
    per (gen, rank) — STABLE (sorted identity order), named via metadata
    events — spans as ``ph:"X"`` on tid 1, gauges as counter tracks
    (``ph:"C"``), everything skew-normalized to its own segment's meta
    anchor so streams from skewed host clocks overlay comparably."""
    identities = sorted({seg.key for seg in segments})
    pid_of = {key: i + 1 for i, key in enumerate(identities)}
    trace: List[dict] = []
    for (gen, rank), pid in sorted(pid_of.items(), key=lambda kv: kv[1]):
        trace.append({"ph": "M", "pid": pid, "tid": 1,
                      "name": "process_name",
                      "args": {"name": f"gen{gen}/rank{rank}"}})
    for seg in segments:
        pid = pid_of[seg.key]
        for ev in seg.events:
            kind = ev.get("kind")
            if kind == "meta":
                continue
            rel_us = (float(ev.get("ts", seg.anchor_ts))
                      - seg.anchor_ts) * 1e6
            args = {k: v for k, v in ev.items()
                    if k not in ("v", "ts", "kind", "name", "t0",
                                 "dur_ms", "gen", "rank")}
            args["wall_ts"] = ev.get("ts")
            common = {"pid": pid, "tid": 1,
                      "cat": f"telemetry/{kind}",
                      "name": ev.get("name", "?"), "args": args}
            if kind == "span":
                t0 = float(ev.get("t0", ev.get("ts", seg.anchor_ts)))
                trace.append({**common, "ph": "X",
                              "ts": (t0 - seg.anchor_ts) * 1e6,
                              "dur": float(ev.get("dur_ms", 0.0)) * 1e3})
            elif kind == "gauge":
                try:
                    value = float(ev.get("value", 0.0))
                except (TypeError, ValueError):
                    continue
                trace.append({"ph": "C", "pid": pid,
                              "name": ev.get("name", "?"), "ts": rel_us,
                              "args": {"value": value}})
            elif kind == "device_profile":
                # the device split beside the host spans: one X event on
                # tid 2 spanning the captured window (the event's ts is
                # ingestion time — just after the window closed, so the
                # window is drawn ending there)
                window_us = float(ev.get("window_ms", 0.0)) * 1e3
                trace.append({**common, "tid": 2, "ph": "X",
                              "ts": rel_us - window_us,
                              "dur": window_us})
            else:
                trace.append({**common, "ph": "i", "s": "p",
                              "ts": rel_us})
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# StreamFollower: incremental JSONL reads (tail -f, the fleet's live tail)
# ---------------------------------------------------------------------------


class StreamFollower:
    """Poll a JSONL stream for new events, surviving rotation.

    Tracks a byte offset and the file's inode: a shrink or an inode
    change means the stream was rotated/replaced, and the follower
    restarts from the new file's beginning instead of wedging at a stale
    offset. Partial trailing lines (the writer mid-append) stay buffered
    until their newline lands. Missing files poll as empty — a follower
    may be armed before its child process first emits.

    ``start_at_end=True`` skips whatever the file holds AT ARM TIME (the
    fleet orchestrator's per-child watch: previous generations appended
    to the same file, and their events are not this child's progress).
    The snapshot is taken in the constructor, not at the first poll — a
    file created AFTER arming has no backlog, and everything the new
    child writes is seen from its first byte. A later rotation still
    restarts from byte 0: a fresh file is all new content."""

    def __init__(self, path, start_at_end: bool = False):
        self.path = Path(path)
        self._pos = 0
        self._ino: Optional[int] = None
        self._carry = b""
        self.n_malformed = 0
        if start_at_end:
            try:
                st = os.stat(self.path)
                self._pos = st.st_size
                self._ino = st.st_ino
            except OSError:
                pass   # nothing exists yet: nothing to skip

    def poll(self) -> List[dict]:
        try:
            st = os.stat(self.path)
        except OSError:
            return []
        if self._ino is not None and (st.st_ino != self._ino
                                      or st.st_size < self._pos):
            self._pos = 0          # rotated or truncated: start over
            self._carry = b""
        self._ino = st.st_ino
        if st.st_size <= self._pos:
            return []
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                chunk = f.read()
        except OSError:
            return []
        self._pos += len(chunk)
        data = self._carry + chunk
        head, sep, tail = data.rpartition(b"\n")
        if not sep:
            self._carry = data     # no complete line yet
            return []
        self._carry = tail
        events: List[dict] = []
        for line in head.split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line.decode("utf-8"))
                if not isinstance(ev, dict):
                    raise ValueError("not an object")
                events.append(ev)
            except (ValueError, UnicodeDecodeError):
                self.n_malformed += 1
        return events


def last_step_of(events: Iterable[dict], prior: int = -1,
                 gen: Optional[int] = None) -> int:
    """The largest `step` seen on a step_dispatch span (the step fence's
    observable) — the fleet orchestrator's live-progress probe. ``gen``
    restricts to events stamped with that generation: on the shared
    appended stream a previous generation's spans must not read as THIS
    child's progress (v1 events, unstamped, count only when gen is
    None or 0)."""
    best = prior
    for ev in events:
        if ev.get("kind") == "span" and ev.get("name") == "step_dispatch":
            if gen is not None and _identity_of(ev)[0] != gen:
                continue
            try:
                best = max(best, int(ev.get("step", -1)))
            except (TypeError, ValueError):
                continue
    return best
