"""``python -m distributed_pytorch_training_tpu.telemetry`` — read one
telemetry JSONL stream (``telemetry_rank0.jsonl``) and report.

Also installed as the ``telemetry`` console script (pyproject.toml).

Commands:
  summary <stream.jsonl> [--json]
      Per-phase step-time split (data_wait / step_dispatch / device_sync /
      save_blocked / eval / restore, the serving phases queue_wait /
      prefill / decode / drain, and the elastic phases elastic_replan /
      elastic_reshard; `compile` spans show in the spans table but are
      not summed — a lazy compile nests inside the span that triggered
      it), throughput, wire-byte totals, and
      anomaly counts — the "gradient sync share of step" table the
      reference promised, computed from the stream's OWN recorded totals
      (the split is checked against the recorded epoch seconds; the
      unaccounted remainder is printed, never hidden).
  tail <stream.jsonl> [-n N]
      Last N events, one per line.
  export <stream.jsonl> --perfetto -o trace.json
      Host spans as Chrome trace-event JSON (``ph:"X"`` complete events,
      wall-clock microseconds) — loads in Perfetto/chrome://tracing
      alongside the XLA trace captured by utils/profiling.StepProfiler.

Exit codes: 0 ok, 1 unreadable/empty stream, 2 usage error.

jax-free by design: postmortems are read on machines with no accelerator
stack (the same constraint as the recorder's).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import List, Optional, Tuple

from .recorder import ELASTIC_SPAN_NAMES, SERVING_SPAN_NAMES, SPAN_NAMES


def read_stream(path: str) -> Tuple[List[dict], int]:
    """(events, n_malformed). Malformed lines are counted, not fatal — a
    stream torn mid-line by a crash must still summarize."""
    events: List[dict] = []
    bad = 0
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
                if not isinstance(ev, dict):
                    raise ValueError("not an object")
                events.append(ev)
            except ValueError:
                bad += 1
    return events, bad


def summarize(events: List[dict]) -> dict:
    """The summary body: span totals, counter sums, gauge last-values,
    the step-time split, and the self-consistency line."""
    spans: dict = defaultdict(lambda: {"total_ms": 0.0, "count": 0,
                                       "max_ms": 0.0})
    counters: dict = defaultdict(float)
    gauges: dict = {}
    anomalies: List[dict] = []
    meta: Optional[dict] = None
    for ev in events:
        kind = ev.get("kind")
        name = ev.get("name", "?")
        if kind == "span":
            dur = float(ev.get("dur_ms", 0.0))
            s = spans[name]
            s["total_ms"] += dur
            s["count"] += 1
            s["max_ms"] = max(s["max_ms"], dur)
        elif kind == "counter":
            counters[name] += float(ev.get("value", 0.0))
        elif kind == "gauge":
            gauges[name] = ev.get("value")
        elif kind == "anomaly":
            anomalies.append(ev)
        elif kind == "meta" and meta is None:
            meta = ev

    # the step-time split over the canonical phases, against the stream's
    # own recorded wall total (the `epoch_time_s` counter the train loop
    # emits per epoch) — phases are measured independently of the total,
    # so the unaccounted remainder is an honesty check, not filler. Some
    # phases legitimately sit OUTSIDE the epoch wall (eval, epoch-boundary
    # save stalls), so when accounted spans exceed it the denominator is
    # the accounted total instead — percentages always close to 100.
    wall_ms = counters.get("epoch_time_s", 0.0) * 1e3
    accounted = {n: spans[n]["total_ms"]
                 for n in SPAN_NAMES + SERVING_SPAN_NAMES
                 + ELASTIC_SPAN_NAMES if n in spans}
    accounted_ms = sum(accounted.values())
    split = {}
    base = max(wall_ms, accounted_ms)
    if base > 0:
        split = {n: round(100.0 * v / base, 2)
                 for n, v in accounted.items()}
        if wall_ms > accounted_ms:
            split["unaccounted"] = round(
                100.0 * (wall_ms - accounted_ms) / base, 2)

    out = {
        "schema": (meta or {}).get("schema"),
        "run_id": (meta or {}).get("run_id"),
        "n_events": len(events),
        "spans": {n: {"total_ms": round(v["total_ms"], 3),
                      "count": v["count"],
                      "mean_ms": round(v["total_ms"] / v["count"], 4)
                      if v["count"] else 0.0,
                      "max_ms": round(v["max_ms"], 3)}
                  for n, v in sorted(spans.items())},
        "counters": {n: round(v, 4) for n, v in sorted(counters.items())},
        "gauges": dict(sorted(gauges.items())),
        "anomalies": [{"name": a.get("name"),
                       **{k: v for k, v in a.items()
                          if k not in ("v", "ts", "kind", "name")}}
                      for a in anomalies],
        "step_split_pct": split,
        "totals": {
            "recorded_wall_ms": round(wall_ms, 3),
            "accounted_span_ms": round(accounted_ms, 3),
            "unaccounted_ms": round(max(0.0, wall_ms - accounted_ms), 3)
            if wall_ms > 0 else None,
        },
    }
    if counters.get("epoch_time_s", 0.0) > 0 and "samples" in counters:
        out["throughput"] = {
            "samples": counters["samples"],
            "samples_per_sec": round(
                counters["samples"] / counters["epoch_time_s"], 2),
        }
    for key in ("wire_bytes_per_replica", "fsdp_gather_bytes",
                "tp_psum_bytes_per_replica", "exposed_comm_pct"):
        if key in counters:
            out.setdefault("wire", {})[key] = counters[key]
        elif key in gauges:
            out.setdefault("wire", {})[key] = gauges[key]
    return out


def to_perfetto(events: List[dict]) -> dict:
    """Chrome trace-event JSON: spans as complete ("X") events on one
    host-telemetry track, anomalies/events as instants — timestamps are
    wall-clock microseconds so the spans align with an XLA trace captured
    in the same run."""
    trace: List[dict] = []
    pid = None
    for ev in events:
        kind = ev.get("kind")
        if kind == "meta":
            pid = ev.get("pid", pid)
            continue
        args = {k: v for k, v in ev.items()
                if k not in ("v", "ts", "kind", "name", "t0", "dur_ms")}
        common = {"pid": ev.get("pid", pid) or 0, "tid": 1,
                  "cat": f"telemetry/{kind}", "name": ev.get("name", "?"),
                  "args": args}
        if kind == "span":
            t0 = float(ev.get("t0", ev.get("ts", 0.0)))
            trace.append({**common, "ph": "X", "ts": t0 * 1e6,
                          "dur": float(ev.get("dur_ms", 0.0)) * 1e3})
        else:
            trace.append({**common, "ph": "i", "s": "p",
                          "ts": float(ev.get("ts", 0.0)) * 1e6})
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def _print_summary(s: dict) -> None:
    print(f"run {s.get('run_id')} — {s['n_events']} events")
    if s["step_split_pct"]:
        print("step-time split (% of recorded wall):")
        for n, pct in sorted(s["step_split_pct"].items(),
                             key=lambda kv: -kv[1]):
            tot = s["spans"].get(n, {}).get("total_ms")
            extra = f"  ({tot:.1f} ms)" if tot is not None else ""
            print(f"  {n:16s} {pct:6.2f}%{extra}")
    t = s["totals"]
    if t["recorded_wall_ms"]:
        print(f"recorded wall: {t['recorded_wall_ms']:.1f} ms, spans "
              f"account for {t['accounted_span_ms']:.1f} ms")
    if "throughput" in s:
        print(f"throughput: {s['throughput']['samples_per_sec']:.2f} "
              f"samples/s over {s['throughput']['samples']:.0f} samples")
    if "wire" in s:
        for k, v in s["wire"].items():
            print(f"wire: {k} = {v}")
    if s["anomalies"]:
        print(f"ANOMALIES ({len(s['anomalies'])}):")
        for a in s["anomalies"]:
            print(f"  {a}")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="telemetry", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("command", choices=["summary", "tail", "export"])
    p.add_argument("stream", help="path to a telemetry JSONL stream")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("-n", type=int, default=20, help="tail: last N events")
    p.add_argument("--perfetto", action="store_true",
                   help="export: Chrome trace-event JSON")
    p.add_argument("-o", "--output", default=None,
                   help="export: output path (default: stdout)")
    args = p.parse_args(argv)

    if not Path(args.stream).is_file():
        print(f"telemetry: no such stream: {args.stream}", file=sys.stderr)
        return 1
    events, bad = read_stream(args.stream)
    if bad:
        print(f"telemetry: note: {bad} malformed line(s) skipped",
              file=sys.stderr)
    if not events:
        print("telemetry: stream holds no events", file=sys.stderr)
        return 1

    if args.command == "summary":
        s = summarize(events)
        if args.as_json:
            print(json.dumps(s, sort_keys=True))
        else:
            _print_summary(s)
        return 0
    if args.command == "tail":
        for ev in events[-args.n:]:
            print(json.dumps(ev, sort_keys=True))
        return 0
    # export
    if not args.perfetto:
        print("telemetry: export needs --perfetto (the only format so far)",
              file=sys.stderr)
        return 2
    body = json.dumps(to_perfetto(events))
    if args.output:
        Path(args.output).write_text(body)
        print(f"telemetry: wrote {args.output}", file=sys.stderr)
    else:
        print(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
