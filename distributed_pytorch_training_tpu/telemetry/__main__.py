"""``python -m distributed_pytorch_training_tpu.telemetry`` — read one
telemetry JSONL stream (``telemetry_rank0.jsonl``) and report.

Also installed as the ``telemetry`` console script (pyproject.toml).

Commands:
  summary <stream.jsonl> [--json]
      Per-phase step-time split (data_wait / step_dispatch / device_sync /
      save_blocked / eval / restore, the serving phases queue_wait /
      prefill / decode / drain, and the elastic phases elastic_replan /
      elastic_reshard; `compile` spans show in the spans table but are
      not summed — a lazy compile nests inside the span that triggered
      it), throughput, wire-byte totals, and
      anomaly counts — the "gradient sync share of step" table the
      reference promised, computed from the stream's OWN recorded totals
      (the split is checked against the recorded epoch seconds; the
      unaccounted remainder is printed, never hidden). A crash-truncated
      stream — per-step spans with no enclosing ``epoch_time_s`` total —
      reports those steps as an explicit PARTIAL EPOCH block instead of
      folding them into a misleading split.
  aggregate <stream.jsonl> [<stream.jsonl> ...] [--json]
      The FLEET summary (telemetry/aggregate.py): merge N per-rank
      streams (across ranks AND fleet generations; generations appended
      into one file split at their meta headers) into per-(gen, rank)
      phase splits side by side, wire rollups by tier/axis, anomaly
      rollup, and the cross-rank straggler table (slowest rank, with the
      phase and step that made it slow).
  tail <stream.jsonl> [-n N] [-f [--poll-s S] [--follow-timeout S]]
      Last N events, one per line. With ``-f``, keep polling the file for
      new events (surviving rotation to a new stream file) — the
      watch-a-live-run mode that needs no HTTP endpoint.
  export <stream.jsonl> [<stream.jsonl> ...] --perfetto -o trace.json
      Host spans as Chrome trace-event JSON — loads in Perfetto/
      chrome://tracing alongside the XLA trace captured by
      utils/profiling.StepProfiler. One stream exports on the wall
      clock; multiple streams STITCH into one timeline with a stable
      pid per (gen, rank) and gauge counter tracks, skew-normalized to
      each stream's own meta anchor.

Exit codes: 0 ok, 1 unreadable/empty stream, 2 usage error.

jax-free by design: postmortems are read on machines with no accelerator
stack (the same constraint as the recorder's).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import defaultdict
from pathlib import Path
from typing import List, Optional, Tuple

from .recorder import (
    CONTROL_DECISION_KIND,
    CONTROL_SPAN_NAMES,
    ELASTIC_SPAN_NAMES,
    SERVING_SPAN_NAMES,
    SPAN_NAMES,
)

# The per-step phases: spans that belong INSIDE an epoch's recorded wall.
# Trailing instances with no epoch_time_s after them are a crash-truncated
# partial epoch (the summary's explicit PARTIAL block, not split filler).
IN_EPOCH_SPAN_NAMES = ("data_wait", "step_dispatch", "device_sync")


def read_stream(path: str) -> Tuple[List[dict], int]:
    """(events, n_malformed). Malformed lines are counted, not fatal — a
    stream torn mid-line by a crash must still summarize."""
    events: List[dict] = []
    bad = 0
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
                if not isinstance(ev, dict):
                    raise ValueError("not an object")
                events.append(ev)
            except ValueError:
                bad += 1
    return events, bad


def summarize(events: List[dict]) -> dict:
    """The summary body: span totals, counter sums, gauge last-values,
    the step-time split, and the self-consistency line.

    Crash truncation (ISSUE 14 satellite): per-step spans are folded into
    the split only once their enclosing ``epoch_time_s`` total arrives. A
    mid-epoch crash (or a new ``meta`` header — an appended relaunch)
    leaves trailing in-epoch spans with NO such total; they are reported
    as an explicit ``partial_epoch`` block instead of being mixed into
    the completed epochs' percentages, where they used to force the
    adaptive denominator and claim a self-consistent 100% split over an
    epoch that never finished."""
    spans: dict = defaultdict(lambda: {"total_ms": 0.0, "count": 0,
                                       "max_ms": 0.0})
    counters: dict = defaultdict(float)
    gauges: dict = {}
    anomalies: List[dict] = []
    device_profiles: List[dict] = []
    control_decisions: List[dict] = []
    meta: Optional[dict] = None
    # in-epoch spans seen since the last epoch_time_s counter: folded into
    # the accounted split by that counter's arrival, or into the PARTIAL
    # block by a meta boundary / end of stream
    pending_ms: dict = defaultdict(float)
    pending_steps = 0
    partial_ms: dict = defaultdict(float)
    partial_steps = 0

    def _fold_pending_into_partial():
        nonlocal pending_ms, pending_steps, partial_steps
        for n, v in pending_ms.items():
            partial_ms[n] += v
        partial_steps += pending_steps
        pending_ms = defaultdict(float)
        pending_steps = 0

    for ev in events:
        kind = ev.get("kind")
        name = ev.get("name", "?")
        if kind == "span":
            dur = float(ev.get("dur_ms", 0.0))
            s = spans[name]
            s["total_ms"] += dur
            s["count"] += 1
            s["max_ms"] = max(s["max_ms"], dur)
            if name in IN_EPOCH_SPAN_NAMES:
                pending_ms[name] += dur
                if name == "step_dispatch":
                    pending_steps += 1
        elif kind == "counter":
            counters[name] += float(ev.get("value", 0.0))
            if name == "epoch_time_s":
                # the enclosing total arrived: the pending spans belong to
                # a COMPLETED epoch
                pending_ms = defaultdict(float)
                pending_steps = 0
        elif kind == "gauge":
            gauges[name] = ev.get("value")
        elif kind == "anomaly":
            anomalies.append(ev)
        elif kind == "device_profile":
            device_profiles.append(ev)
        elif kind == CONTROL_DECISION_KIND:
            control_decisions.append(ev)
        elif kind == "meta":
            # a relaunch appended to the same stream: whatever the
            # previous run left pending was truncated, not completed
            _fold_pending_into_partial()
            if meta is None:
                meta = ev
    _fold_pending_into_partial()

    # the step-time split over the canonical phases, against the stream's
    # own recorded wall total (the `epoch_time_s` counter the train loop
    # emits per epoch) — phases are measured independently of the total,
    # so the unaccounted remainder is an honesty check, not filler. Some
    # phases legitimately sit OUTSIDE the epoch wall (eval, epoch-boundary
    # save stalls), so when accounted spans exceed it the denominator is
    # the accounted total instead — percentages always close to 100.
    # Partial-epoch span time is EXCLUDED here (reported in its own
    # block); the spans table above still shows every span.
    wall_ms = counters.get("epoch_time_s", 0.0) * 1e3
    accounted = {n: spans[n]["total_ms"] - partial_ms.get(n, 0.0)
                 for n in SPAN_NAMES + SERVING_SPAN_NAMES
                 + ELASTIC_SPAN_NAMES + CONTROL_SPAN_NAMES if n in spans}
    accounted = {n: v for n, v in accounted.items() if v > 0.0}
    accounted_ms = sum(accounted.values())
    split = {}
    base = max(wall_ms, accounted_ms)
    if base > 0:
        split = {n: round(100.0 * v / base, 2)
                 for n, v in accounted.items()}
        if wall_ms > accounted_ms:
            split["unaccounted"] = round(
                100.0 * (wall_ms - accounted_ms) / base, 2)

    # device-time attribution (ISSUE 15): the profiled windows' device
    # split, rendered BESIDE the wall-clock split — summed over every
    # device_profile event on the stream (the on-demand/anomaly captures
    # plus the static window), with the per-window step ranges kept so a
    # reader can line a window up against the straggler table
    device = None
    if device_profiles:
        from .device import DEVICE_PHASES, split_of_event

        split_ms = {p: 0.0 for p in DEVICE_PHASES}
        window_ms = coll_ms = exposed_ms = 0.0
        by_op: dict = defaultdict(float)
        windows = []
        for ev in device_profiles:
            for phase, ms in split_of_event(ev).items():
                split_ms[phase] += ms
            window_ms += float(ev.get("window_ms", 0.0))
            exposed_ms += float(ev.get("comm_exposed_ms", 0.0))
            coll_ms += (float(ev.get("comm_exposed_ms", 0.0))
                        + float(ev.get("comm_hidden_ms", 0.0)))
            for op, ms in (ev.get("by_op_ms") or {}).items():
                by_op[op] += float(ms)
            windows.append({k: ev.get(k) for k in
                            ("start_step", "stop_step", "steps", "reason",
                             "trigger_step", "measured_mfu_pct")
                            if ev.get(k) is not None})
        device = {
            "profiles": len(device_profiles),
            "window_ms": round(window_ms, 3),
            "split_ms": {p: round(v, 3) for p, v in split_ms.items()},
            "split_pct": {p: round(100.0 * v / window_ms, 2)
                          for p, v in split_ms.items()} if window_ms
            else {},
            "exposed_comm_ratio": round(exposed_ms / coll_ms, 4)
            if coll_ms else 0.0,
            "by_op_ms": {op: round(v, 3)
                         for op, v in sorted(by_op.items())},
            "windows": windows,
        }

    # control-plane decisions (ISSUE 20): the audit trail the autopilot
    # leaves on the stream — every record kept in order so the summary
    # shows the full detect -> evict -> grow / retune -> refuse chain
    control = None
    if control_decisions:
        by_action: dict = defaultdict(int)
        for ev in control_decisions:
            by_action[str(ev.get("name", "?"))] += 1
        control = {
            "total": len(control_decisions),
            "by_action": dict(sorted(by_action.items())),
            "chain": [{("action" if k == "name" else k): ev.get(k)
                       for k in ("name", "rank", "epoch", "step",
                                 "world_from", "world_to", "applied",
                                 "reason")
                       if ev.get(k) is not None}
                      for ev in control_decisions],
        }

    partial_total = sum(partial_ms.values())
    partial_epoch = None
    if partial_steps or partial_total > 0.0:
        partial_epoch = {
            "steps": partial_steps,
            "span_ms": {n: round(v, 3)
                        for n, v in sorted(partial_ms.items())},
            "total_ms": round(partial_total, 3),
        }

    out = {
        "schema": (meta or {}).get("schema"),
        "run_id": (meta or {}).get("run_id"),
        "n_events": len(events),
        "spans": {n: {"total_ms": round(v["total_ms"], 3),
                      "count": v["count"],
                      "mean_ms": round(v["total_ms"] / v["count"], 4)
                      if v["count"] else 0.0,
                      "max_ms": round(v["max_ms"], 3)}
                  for n, v in sorted(spans.items())},
        "counters": {n: round(v, 4) for n, v in sorted(counters.items())},
        "gauges": dict(sorted(gauges.items())),
        "anomalies": [{"name": a.get("name"),
                       **{k: v for k, v in a.items()
                          if k not in ("v", "ts", "kind", "name")}}
                      for a in anomalies],
        "step_split_pct": split,
        "device": device,
        "control_decisions": control,
        "partial_epoch": partial_epoch,
        "totals": {
            "recorded_wall_ms": round(wall_ms, 3),
            "accounted_span_ms": round(accounted_ms, 3),
            "unaccounted_ms": round(max(0.0, wall_ms - accounted_ms), 3)
            if wall_ms > 0 else None,
        },
    }
    if counters.get("epoch_time_s", 0.0) > 0 and "samples" in counters:
        out["throughput"] = {
            "samples": counters["samples"],
            "samples_per_sec": round(
                counters["samples"] / counters["epoch_time_s"], 2),
        }
    for key in ("wire_bytes_per_replica", "fsdp_gather_bytes",
                "tp_psum_bytes_per_replica", "exposed_comm_pct"):
        if key in counters:
            out.setdefault("wire", {})[key] = counters[key]
        elif key in gauges:
            out.setdefault("wire", {})[key] = gauges[key]
    return out


def to_perfetto(events: List[dict]) -> dict:
    """Chrome trace-event JSON: spans as complete ("X") events on one
    host-telemetry track, anomalies/events as instants — timestamps are
    wall-clock microseconds so the spans align with an XLA trace captured
    in the same run."""
    trace: List[dict] = []
    pid = None
    for ev in events:
        kind = ev.get("kind")
        if kind == "meta":
            pid = ev.get("pid", pid)
            continue
        args = {k: v for k, v in ev.items()
                if k not in ("v", "ts", "kind", "name", "t0", "dur_ms")}
        common = {"pid": ev.get("pid", pid) or 0, "tid": 1,
                  "cat": f"telemetry/{kind}", "name": ev.get("name", "?"),
                  "args": args}
        if kind == "span":
            t0 = float(ev.get("t0", ev.get("ts", 0.0)))
            trace.append({**common, "ph": "X", "ts": t0 * 1e6,
                          "dur": float(ev.get("dur_ms", 0.0)) * 1e3})
        else:
            trace.append({**common, "ph": "i", "s": "p",
                          "ts": float(ev.get("ts", 0.0)) * 1e6})
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def _print_summary(s: dict) -> None:
    print(f"run {s.get('run_id')} — {s['n_events']} events")
    if s["step_split_pct"]:
        print("step-time split (% of recorded wall):")
        for n, pct in sorted(s["step_split_pct"].items(),
                             key=lambda kv: -kv[1]):
            tot = s["spans"].get(n, {}).get("total_ms")
            extra = f"  ({tot:.1f} ms)" if tot is not None else ""
            print(f"  {n:16s} {pct:6.2f}%{extra}")
    t = s["totals"]
    if t["recorded_wall_ms"]:
        print(f"recorded wall: {t['recorded_wall_ms']:.1f} ms, spans "
              f"account for {t['accounted_span_ms']:.1f} ms")
    if "throughput" in s:
        print(f"throughput: {s['throughput']['samples_per_sec']:.2f} "
              f"samples/s over {s['throughput']['samples']:.0f} samples")
    if "wire" in s:
        for k, v in s["wire"].items():
            print(f"wire: {k} = {v}")
    if s.get("device"):
        d = s["device"]
        print(f"device-time split ({d['profiles']} profiled window(s), "
              f"{d['window_ms']:.1f} ms of device window):")
        for phase, pct in sorted(d["split_pct"].items(),
                                 key=lambda kv: -kv[1]):
            print(f"  {phase:16s} {pct:6.2f}%  "
                  f"({d['split_ms'][phase]:.1f} ms)")
        print(f"  exposed-comm ratio: {d['exposed_comm_ratio']:.3f}")
        for op, ms in d["by_op_ms"].items():
            print(f"  collective: {op} = {ms:.1f} ms")
        for w in d["windows"]:
            rng = (f"steps {w.get('start_step')}-{w.get('stop_step')}"
                   if w.get("start_step") is not None else "untracked")
            trig = (f", trigger step {w['trigger_step']}"
                    if w.get("trigger_step") is not None else "")
            mfu = (f", measured MFU {w['measured_mfu_pct']:.1f}%"
                   if w.get("measured_mfu_pct") is not None else "")
            print(f"  window: {rng} ({w.get('reason', '?')}{trig}{mfu})")
    if s.get("control_decisions"):
        c = s["control_decisions"]
        acts = ", ".join(f"{a}={n}" for a, n in c["by_action"].items())
        print(f"control decisions ({c['total']}): {acts}")
        for d in c["chain"]:
            who = f" rank {d['rank']}" if d.get("rank") is not None else ""
            at = (f" @epoch {d['epoch']} step {d['step']}"
                  if d.get("step") is not None else "")
            world = (f" world {d['world_from']}->{d['world_to']}"
                     if d.get("world_to") is not None else "")
            applied = " [applied]" if d.get("applied") else ""
            print(f"  {d.get('action'):7s}{who}{at}{world}{applied}: "
                  f"{d.get('reason', '')}")
    if s.get("partial_epoch"):
        pe = s["partial_epoch"]
        phases = ", ".join(f"{n} {v:.1f}ms"
                           for n, v in pe["span_ms"].items())
        print(f"PARTIAL EPOCH (crash-truncated — no enclosing epoch "
              f"total): {pe['steps']} step(s), {pe['total_ms']:.1f} ms "
              f"({phases}) excluded from the split above")
    if s["anomalies"]:
        print(f"ANOMALIES ({len(s['anomalies'])}):")
        for a in s["anomalies"]:
            print(f"  {a}")


def _follow(stream: str, n: int, poll_s: float,
            timeout_s: Optional[float]) -> int:
    """``tail -f``: print the last N events, then poll the file for new
    ones — surviving rotation to a new stream file (the follower resets
    on inode change/truncation). Ctrl-C (or ``--follow-timeout``, the
    scriptable bound) ends the watch cleanly."""
    from .aggregate import StreamFollower

    follower = StreamFollower(stream)
    backlog = follower.poll()
    for ev in backlog[-n:]:
        print(json.dumps(ev, sort_keys=True))
    sys.stdout.flush()
    deadline = (time.monotonic() + timeout_s
                if timeout_s is not None else None)
    try:
        while deadline is None or time.monotonic() < deadline:
            for ev in follower.poll():
                print(json.dumps(ev, sort_keys=True))
            sys.stdout.flush()
            time.sleep(poll_s)
    except KeyboardInterrupt:
        pass
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="telemetry", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("command",
                   choices=["summary", "aggregate", "tail", "export"])
    p.add_argument("streams", nargs="+",
                   help="telemetry JSONL stream path(s) — aggregate/"
                        "export merge several; summary/tail take one")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("-n", type=int, default=20, help="tail: last N events")
    p.add_argument("-f", "--follow", action="store_true",
                   help="tail: keep polling for new events (survives "
                        "stream rotation)")
    p.add_argument("--poll-s", type=float, default=0.5,
                   help="tail -f: poll interval seconds")
    p.add_argument("--follow-timeout", type=float, default=None,
                   help="tail -f: stop after this many seconds "
                        "(default: until Ctrl-C)")
    p.add_argument("--perfetto", action="store_true",
                   help="export: Chrome trace-event JSON")
    p.add_argument("-o", "--output", default=None,
                   help="export/aggregate: output path (default: stdout)")
    args = p.parse_args(argv)

    if args.command == "aggregate":
        from .aggregate import aggregate_streams, print_fleet_summary

        agg = aggregate_streams(args.streams)
        if agg["n_streams"] == 0:
            print("telemetry: no readable stream among "
                  f"{args.streams}", file=sys.stderr)
            return 1
        if args.output:
            # -o always writes the machine-readable body, whatever the
            # stdout format — a silently-ignored output path would strand
            # every script that reads it
            Path(args.output).write_text(json.dumps(agg, sort_keys=True))
            print(f"telemetry: wrote {args.output}", file=sys.stderr)
        if args.as_json:
            if not args.output:
                print(json.dumps(agg, sort_keys=True))
        else:
            print_fleet_summary(agg)
        return 0

    if args.command in ("summary", "tail") and len(args.streams) != 1:
        print(f"telemetry: {args.command} takes exactly one stream "
              "(aggregate merges several)", file=sys.stderr)
        return 2
    stream = args.streams[0]

    if args.command == "tail" and args.follow:
        # the follower tolerates a not-yet-created stream; no upfront check
        return _follow(stream, args.n, args.poll_s, args.follow_timeout)

    if args.command == "export" and len(args.streams) > 1:
        if not args.perfetto:
            print("telemetry: export needs --perfetto (the only format "
                  "so far)", file=sys.stderr)
            return 2
        from .aggregate import split_streams, stitch_perfetto

        segments = split_streams(args.streams)
        if not segments:
            print("telemetry: no readable stream among "
                  f"{args.streams}", file=sys.stderr)
            return 1
        body = json.dumps(stitch_perfetto(segments))
        if args.output:
            Path(args.output).write_text(body)
            print(f"telemetry: wrote {args.output}", file=sys.stderr)
        else:
            print(body)
        return 0

    if not Path(stream).is_file():
        print(f"telemetry: no such stream: {stream}", file=sys.stderr)
        return 1
    events, bad = read_stream(stream)
    if bad:
        print(f"telemetry: note: {bad} malformed line(s) skipped",
              file=sys.stderr)
    if not events:
        print("telemetry: stream holds no events", file=sys.stderr)
        return 1

    if args.command == "summary":
        s = summarize(events)
        if args.as_json:
            print(json.dumps(s, sort_keys=True))
        else:
            _print_summary(s)
        return 0
    if args.command == "tail":
        for ev in events[-args.n:]:
            print(json.dumps(ev, sort_keys=True))
        return 0
    # export
    if not args.perfetto:
        print("telemetry: export needs --perfetto (the only format so far)",
              file=sys.stderr)
        return 2
    body = json.dumps(to_perfetto(events))
    if args.output:
        Path(args.output).write_text(body)
        print(f"telemetry: wrote {args.output}", file=sys.stderr)
    else:
        print(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
