"""Live metrics surface (ISSUE 14): a stdlib-only background HTTP thread.

``/metrics`` serves Prometheus text-format gauges/counters/histograms
aggregated from the SAME event stream the recorder writes — the server
registers an observer on the :class:`~.recorder.Recorder` and folds each
event into thread-safe counters as it is emitted, so the scrape handler
never touches the JSONL and never blocks an emit:

* ``dpt_steps_total`` / ``dpt_last_step`` — the step fence, observed
  through ``step_dispatch`` spans;
* ``dpt_epoch`` — the last completed epoch (``epoch_time_s`` counters);
* ``dpt_phase_seconds`` — one histogram per canonical phase
  (data_wait / step_dispatch / ... / prefill / decode), fixed buckets;
* ``dpt_wire_bytes_total{name,tier,axis}`` — the per-tier wire counters
  (grad_sync's emit_wire_accounting rows; the DCN tier is one more
  label value, not new code);
* ``dpt_anomalies_total{name}`` — watchdog detections;
* ``dpt_gauge{name}`` — every gauge last-value (world_size, capacity,
  queue depth, EF norm);
* ``dpt_last_progress_age_seconds`` — seconds since the step fence last
  ADVANCED (a new high-water `step`, a `steps` counter, or a serving
  prefill/decode span).

``/healthz`` is the progress-fence liveness probe: 200 while the last
step advance is younger than ``stale_after_s`` (the server's start time
seeds the fence, so a compiling run gets its grace), 503 once the fence
stops advancing — a wedged dispatch, a dead loader, a hung collective
all flip it without any in-band cooperation from the training loop.

Costs, by construction: OFF means this module is never imported by the
hot path and zero threads exist (train.py/serving gate on a nonzero
port). ON means one listener thread + per-event dict updates on the
host side only — nothing here can touch traced code, so the telemetry
on/off HLO-identity pin extends to the live surface unchanged.

jax-free and stdlib-only, like every module in this package.
"""

from __future__ import annotations

import http.server
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.locktrace import named_lock
from .device import DEVICE_PROFILE_KIND, split_of_event
from .recorder import (
    CONTROL_DECISION_KIND,
    CONTROL_SPAN_NAMES,
    ELASTIC_SPAN_NAMES,
    Recorder,
    SCHEMA_VERSION,
    SERVING_SPAN_NAMES,
    SPAN_NAMES,
)

METRICS_PORT_ENV = "DPT_METRICS_PORT"
METRICS_STALE_S_ENV = "DPT_METRICS_STALE_S"

_PHASES = (SPAN_NAMES + SERVING_SPAN_NAMES + ELASTIC_SPAN_NAMES
           + CONTROL_SPAN_NAMES)

# seconds; the +Inf bucket is implicit. Spans range from ~100us CPU-mesh
# dispatches to multi-second compiles/stalls.
_BUCKETS_S = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
              1.0, 2.5, 5.0, 10.0, 30.0)

# step_dispatch feeds the fence only when its `step` ADVANCES (or is
# unstamped); the serving phases always count — see _MetricsState.observe.
_PROGRESS_SPANS = ("step_dispatch", "prefill", "decode")


def resolve_metrics_port(cli_port: Optional[int], rank: int = 0) -> int:
    """The effective port: an explicit CLI value wins, else the
    ``DPT_METRICS_PORT`` env (the fleet orchestrator's stamp), else off.
    A nonzero base is offset by the rank so co-hosted ranks under
    ``--telemetry-all-ranks`` each get their own listener. 0 = off."""
    base = cli_port
    if base is None:
        try:
            base = int(os.environ.get(METRICS_PORT_ENV, "0"))
        except ValueError:
            base = 0
    base = int(base)
    return base + int(rank) if base > 0 else 0


def _escape_label(value: Any) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


class _MetricsState:
    """The scrape-side aggregate, fed one event at a time. ``identity``
    carries the serving (gen, rank, schema, backend) — the satellite that
    lets a federated scrape trace every series back to the rank that
    produced it (``dpt_build_info`` + the /healthz body fields)."""

    def __init__(self, identity: Optional[Dict[str, Any]] = None):
        self._lock = named_lock("_MetricsState._lock")
        self._t0 = time.monotonic()
        self.identity = {"gen": 0, "rank": 0,
                         "schema_version": SCHEMA_VERSION, "backend": "",
                         **(identity or {})}
        self.events_total = 0        # guarded-by: _lock
        self.steps_total = 0         # guarded-by: _lock
        self.last_step = -1          # guarded-by: _lock
        self.epoch = -1              # guarded-by: _lock
        self.last_progress = self._t0   # guarded-by: _lock
        # phase -> (bucket counts, sum_s, count)
        self.phases: Dict[str, Tuple[List[int], float, int]] = {}  # guarded-by: _lock
        self.wire: Dict[Tuple[str, str, str], float] = {}          # guarded-by: _lock
        self.anomalies: Dict[str, int] = {}                        # guarded-by: _lock
        self.gauges: Dict[str, float] = {}                         # guarded-by: _lock
        # device-time attribution (ISSUE 15): per-phase device seconds +
        # the latest exposed-comm ratio, fed by device_profile events
        self.device_seconds: Dict[str, float] = {}                 # guarded-by: _lock
        self.device_profiles = 0                                   # guarded-by: _lock
        self.exposed_comm_ratio: Optional[float] = None            # guarded-by: _lock
        # control-plane decisions (ISSUE 20): action -> count, fed by
        # control_decision events (name = the action)
        self.control_decisions: Dict[str, int] = {}                # guarded-by: _lock

    # -- the observer ---------------------------------------------------

    def observe(self, ev: dict) -> None:
        kind = ev.get("kind")
        name = ev.get("name", "?")
        with self._lock:
            self.events_total += 1
            if kind == "span":
                dur_s = float(ev.get("dur_ms", 0.0)) / 1e3
                if name in _PHASES:
                    buckets, total, count = self.phases.get(
                        name, ([0] * (len(_BUCKETS_S) + 1), 0.0, 0))
                    for i, le in enumerate(_BUCKETS_S):
                        if dur_s <= le:
                            buckets[i] += 1
                            break
                    else:
                        buckets[-1] += 1
                    self.phases[name] = (buckets, total + dur_s, count + 1)
                if name == "step_dispatch":
                    self.steps_total += 1
                    step = ev.get("step")
                    if step is None:
                        # an unstamped dispatch carries no fence to
                        # compare — count it as progress
                        self.last_progress = time.monotonic()
                    elif isinstance(step, (int, float)) \
                            and step > self.last_step:
                        self.last_step = int(step)
                        self.last_progress = time.monotonic()
                    # a re-dispatch of an already-seen step (a restart
                    # loop replaying from a checkpoint) is NOT progress:
                    # the fence must ADVANCE to keep /healthz green
                elif name in ("prefill", "decode"):
                    # serving progress: every served phase counts
                    self.last_progress = time.monotonic()
            elif kind == "counter":
                if name == "epoch_time_s":
                    epoch = ev.get("epoch")
                    if isinstance(epoch, (int, float)):
                        self.epoch = max(self.epoch, int(epoch))
                elif name == "steps":
                    self.last_progress = time.monotonic()
                if "tier" in ev or "axis" in ev:
                    key = (name, str(ev.get("tier", "")),
                           str(ev.get("axis", "")))
                    self.wire[key] = (self.wire.get(key, 0.0)
                                      + float(ev.get("value", 0.0)))
            elif kind == "anomaly":
                self.anomalies[name] = self.anomalies.get(name, 0) + 1
            elif kind == "gauge":
                try:
                    self.gauges[name] = float(ev.get("value", 0.0))
                except (TypeError, ValueError):
                    pass
            elif kind == CONTROL_DECISION_KIND:
                self.control_decisions[name] = (
                    self.control_decisions.get(name, 0) + 1)
            elif kind == DEVICE_PROFILE_KIND:
                for phase, ms in split_of_event(ev).items():
                    self.device_seconds[phase] = (
                        self.device_seconds.get(phase, 0.0) + ms / 1e3)
                self.device_profiles += 1
                try:
                    self.exposed_comm_ratio = float(
                        ev.get("exposed_comm_ratio", 0.0))
                except (TypeError, ValueError):
                    pass

    # -- the scrape views -----------------------------------------------

    def render(self) -> str:
        with self._lock:
            age = time.monotonic() - self.last_progress
            ident = ",".join(
                f'{k}="{_escape_label(v)}"'
                for k, v in (("gen", self.identity["gen"]),
                             ("rank", self.identity["rank"]),
                             ("schema_version",
                              self.identity["schema_version"]),
                             ("backend", self.identity["backend"])))
            lines = [
                "# TYPE dpt_build_info gauge",
                f"dpt_build_info{{{ident}}} 1",
                "# TYPE dpt_events_total counter",
                f"dpt_events_total {self.events_total}",
                "# TYPE dpt_steps_total counter",
                f"dpt_steps_total {self.steps_total}",
                "# TYPE dpt_last_step gauge",
                f"dpt_last_step {self.last_step}",
                "# TYPE dpt_epoch gauge",
                f"dpt_epoch {self.epoch}",
                "# TYPE dpt_last_progress_age_seconds gauge",
                f"dpt_last_progress_age_seconds {age:.3f}",
            ]
            if self.phases:
                lines.append("# TYPE dpt_phase_seconds histogram")
                for phase in sorted(self.phases):
                    buckets, total, count = self.phases[phase]
                    cum = 0
                    label = _escape_label(phase)
                    for le, n in zip(_BUCKETS_S, buckets):
                        cum += n
                        lines.append(
                            f'dpt_phase_seconds_bucket{{phase="{label}",'
                            f'le="{le:g}"}} {cum}')
                    cum += buckets[-1]
                    lines.append(
                        f'dpt_phase_seconds_bucket{{phase="{label}",'
                        f'le="+Inf"}} {cum}')
                    lines.append(f'dpt_phase_seconds_sum{{phase="{label}"}}'
                                 f' {total:.6f}')
                    lines.append(f'dpt_phase_seconds_count{{phase='
                                 f'"{label}"}} {count}')
            if self.wire:
                lines.append("# TYPE dpt_wire_bytes_total counter")
                for (name, tier, axis), v in sorted(self.wire.items()):
                    lines.append(
                        f'dpt_wire_bytes_total{{name="{_escape_label(name)}'
                        f'",tier="{_escape_label(tier)}",axis='
                        f'"{_escape_label(axis)}"}} {v:g}')
            if self.anomalies:
                lines.append("# TYPE dpt_anomalies_total counter")
                for name, n in sorted(self.anomalies.items()):
                    lines.append(f'dpt_anomalies_total{{name='
                                 f'"{_escape_label(name)}"}} {n}')
            if self.gauges:
                lines.append("# TYPE dpt_gauge gauge")
                for name, v in sorted(self.gauges.items()):
                    lines.append(
                        f'dpt_gauge{{name="{_escape_label(name)}"}} {v:g}')
            if self.control_decisions:
                lines.append("# TYPE dpt_control_decisions_total counter")
                for action, n in sorted(self.control_decisions.items()):
                    lines.append(
                        f'dpt_control_decisions_total{{action='
                        f'"{_escape_label(action)}"}} {n}')
            if self.device_profiles:
                lines.append("# TYPE dpt_device_profiles_total counter")
                lines.append(
                    f"dpt_device_profiles_total {self.device_profiles}")
                lines.append("# TYPE dpt_device_seconds counter")
                for phase, secs in sorted(self.device_seconds.items()):
                    lines.append(
                        f'dpt_device_seconds{{phase="{_escape_label(phase)}'
                        f'"}} {secs:.6f}')
                if self.exposed_comm_ratio is not None:
                    lines.append("# TYPE dpt_exposed_comm_ratio gauge")
                    lines.append(f"dpt_exposed_comm_ratio "
                                 f"{self.exposed_comm_ratio:g}")
            return "\n".join(lines) + "\n"

    def health(self, stale_after_s: float) -> Tuple[bool, dict]:
        with self._lock:
            age = time.monotonic() - self.last_progress
            healthy = age < stale_after_s
            return healthy, {
                "healthy": healthy,
                "last_progress_age_s": round(age, 3),
                "stale_after_s": stale_after_s,
                "last_step": self.last_step,
                "steps_total": self.steps_total,
                # serving identity (ISSUE 15 satellite): a federated probe
                # can trace this answer back to the rank that produced it
                "gen": self.identity["gen"],
                "rank": self.identity["rank"],
                "schema_version": self.identity["schema_version"],
                "backend": self.identity["backend"],
            }


class _Handler(http.server.BaseHTTPRequestHandler):
    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        server: "_Server" = self.server  # type: ignore[assignment]
        if self.path.split("?")[0] == "/metrics":
            self._reply(200, server.state.render().encode("utf-8"),
                        "text/plain; version=0.0.4; charset=utf-8")
        elif self.path.split("?")[0] == "/healthz":
            healthy, detail = server.state.health(server.stale_after_s)
            self._reply(200 if healthy else 503,
                        (json.dumps(detail, sort_keys=True) + "\n")
                        .encode("utf-8"), "application/json")
        else:
            self._reply(404, b"telemetry metrics: /metrics or /healthz\n",
                        "text/plain")

    def do_POST(self):  # noqa: N802 — the on-demand profiling trigger
        """``POST /profile?steps=K`` (ISSUE 15): arm a K-step trace
        capture on the running process. 202 armed; 409 profiler busy
        (refuse-not-clobber); 400 bad steps; 404 when this process has
        no profiler wired (metrics on a run without the capture plane —
        the supervised loop, or a server outside train.py/serving)."""
        path, _, query = self.path.partition("?")
        if path != "/profile":
            self._reply(404, b'{"error": "POST /profile?steps=K"}\n',
                        "application/json")
            return
        server: "_Server" = self.server  # type: ignore[assignment]
        owner = server.owner
        handler = getattr(owner, "profile_handler", None)
        if handler is None:
            self._reply(404, b'{"error": "no profiler wired on this '
                             b'process"}\n', "application/json")
            return
        params = dict(p.partition("=")[::2] for p in query.split("&") if p)
        try:
            steps = int(params.get("steps", "2"))
            if steps < 1:
                raise ValueError
        except ValueError:
            self._reply(400, b'{"error": "steps must be a positive '
                             b'integer"}\n', "application/json")
            return
        try:
            armed = bool(handler(steps))
        except Exception:  # noqa: BLE001 — the trigger never crashes
            armed = False  # the serving thread
        if armed:
            body = json.dumps({"armed": True, "steps": steps}) + "\n"
            self._reply(202, body.encode("utf-8"), "application/json")
        else:
            self._reply(409, b'{"error": "profiler busy (a window is '
                             b'armed or in flight)"}\n',
                        "application/json")

    def log_message(self, fmt, *args):  # scrapes must not spam stdout
        return


class _Server(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, state: _MetricsState, stale_after_s: float,
                 owner: Optional["MetricsServer"] = None):
        super().__init__(addr, _Handler)
        self.state = state
        self.stale_after_s = stale_after_s
        self.owner = owner


class MetricsServer:
    """The background `/metrics` + `/healthz` listener.

    ``port=0`` binds an ephemeral port (tests); :meth:`start` returns the
    bound port. ``recorder`` is the stream to observe (its observer is
    removed again on :meth:`stop`; its gen/rank stamp the serving
    identity). ``stale_after_s`` is the healthz fence: default from
    ``DPT_METRICS_STALE_S``, else 300s — generous because a first-step
    compile is legitimate silence. ``backend`` labels
    ``dpt_build_info`` (this module stays jax-free: the caller names its
    backend). ``profile_handler`` (settable after start — train.py wires
    it once the profiler exists) is the ``POST /profile`` target:
    ``handler(steps) -> bool`` (armed)."""

    def __init__(self, port: int, recorder: Optional[Recorder] = None,
                 host: str = "0.0.0.0",
                 stale_after_s: Optional[float] = None,
                 backend: str = "",
                 profile_handler: Optional[Any] = None):
        if stale_after_s is None:
            try:
                stale_after_s = float(
                    os.environ.get(METRICS_STALE_S_ENV, "300"))
            except ValueError:
                stale_after_s = 300.0
        self.state = _MetricsState(identity={
            "gen": getattr(recorder, "gen", 0),
            "rank": getattr(recorder, "rank", 0),
            "backend": backend})
        self._host = host
        self._want_port = int(port)
        self._recorder = recorder
        self.stale_after_s = float(stale_after_s)
        self.profile_handler = profile_handler
        self._httpd: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    def start(self) -> int:
        if self._httpd is not None:
            return self.port  # type: ignore[return-value]
        self._httpd = _Server((self._host, self._want_port), self.state,
                              self.stale_after_s, owner=self)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name=f"dpt-metrics-{self.port}", daemon=True)
        self._thread.start()
        if self._recorder is not None:
            self._recorder.add_observer(self.state.observe)
        return self.port  # type: ignore[return-value]

    def stop(self) -> None:
        if self._recorder is not None:
            self._recorder.remove_observer(self.state.observe)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# module-global lifecycle (the train.py / serving wiring): one server per
# process, started only when a port resolves nonzero — off means this
# function is the only thing that ran, and it started nothing.
# ---------------------------------------------------------------------------

_SERVER: Optional[MetricsServer] = None


def start_metrics_server(port: int, recorder: Optional[Recorder] = None,
                         **kwargs: Any) -> Optional[MetricsServer]:
    """Start (or replace) the process-global metrics server. ``port <= 0``
    is a no-op returning None — the off path creates zero threads. A bind
    failure (the port is taken) also returns None, with a stderr note:
    the live surface shares the recorder's contract — a broken
    observability convenience must never take the training run down."""
    import sys

    global _SERVER
    if port <= 0:
        return None
    stop_metrics_server()
    server = MetricsServer(port, recorder=recorder, **kwargs)
    try:
        server.start()
    except OSError as e:
        print(f"telemetry: /metrics server could not bind port {port} "
              f"({e}) — continuing without the live surface",
              file=sys.stderr, flush=True)
        return None
    _SERVER = server
    return _SERVER


def stop_metrics_server() -> None:
    global _SERVER
    if _SERVER is not None:
        _SERVER.stop()
        _SERVER = None


def get_metrics_server() -> Optional[MetricsServer]:
    return _SERVER


# ---------------------------------------------------------------------------
# Federation (ISSUE 15): ONE /metrics endpoint over the per-rank ports.
# ---------------------------------------------------------------------------


def scrape_metrics(port: int, timeout_s: float = 0.8,
                   host: str = "127.0.0.1") -> Optional[str]:
    """One best-effort /metrics scrape of a local listener, or None
    (a target mid-compile simply has no listener yet; not an error).
    THE scrape helper — the federation proxy and the fleet
    orchestrator's smoke both route through it, so a future fix
    (retries, remote hosts, wider exception set) lands once."""
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://{host}:{int(port)}/metrics",
                timeout=timeout_s) as resp:
            return resp.read().decode("utf-8", errors="replace")
    except (urllib.error.URLError, OSError, ValueError):
        return None


_IDENTITY_RE = None  # compiled lazily (keeps the import section stdlib-thin)


def _parse_identity(body: str) -> Optional[Tuple[str, str]]:
    """(gen, rank) from a scraped page's ``dpt_build_info`` line — the
    self-describing satellite: the proxy never has to be told which
    identity sits behind a port."""
    global _IDENTITY_RE
    if _IDENTITY_RE is None:
        import re
        _IDENTITY_RE = re.compile(
            r'^dpt_build_info\{[^}]*gen="([^"]*)"[^}]*rank="([^"]*)"')
    for line in body.splitlines():
        m = _IDENTITY_RE.match(line)
        if m:
            return m.group(1), m.group(2)
    return None


def _relabel_line(line: str, gen: str, rank: str) -> Optional[str]:
    """One Prometheus sample line with ``gen``/``rank`` labels injected
    (None for comment/blank lines — the merger re-derives TYPE lines).
    Lines already carrying a gen label (dpt_build_info) pass through."""
    line = line.rstrip()
    if not line or line.startswith("#"):
        return None
    if 'gen="' in line.split("}")[0]:
        return line
    name, brace, rest = line.partition("{")
    if brace:
        return f'{name}{{gen="{gen}",rank="{rank}",{rest}'
    name, _, value = line.partition(" ")
    return f'{name}{{gen="{gen}",rank="{rank}"}} {value}'


class FederationServer:
    """The fan-in proxy: scrape N per-rank ``/metrics`` ports, merge into
    ONE Prometheus page with every series ``gen``/``rank``-labelled.

    ``targets`` is a list of ports (or a callable returning one — the
    orchestrator's live-children feed). Identities are read from each
    target's own ``dpt_build_info`` line, so the proxy needs no mapping.
    Pages are CACHED per identity: a child that exited (a finished fleet
    generation) keeps its last page in the merge, marked
    ``dpt_federation_up{gen,rank} 0`` — the final federated page carries
    every generation that ever answered, which is the fleet story the
    ROADMAP's missing-proxy item asked for. ``refresh_s`` arms a
    background poll (the orchestrator's mode: children live shorter than
    the gap between external scrapes); without it every GET scrapes
    inline. stdlib-only, jax-free, like everything in this package."""

    def __init__(self, port: int, targets, host: str = "0.0.0.0",
                 timeout_s: float = 0.8,
                 refresh_s: Optional[float] = None):
        self._want_port = int(port)
        self._host = host
        self._targets = targets if callable(targets) \
            else (lambda t=list(targets): t)
        self.timeout_s = float(timeout_s)
        self.refresh_s = refresh_s
        self._lock = named_lock("FederationServer._lock")
        # identity -> {"body": str, "up": bool, "port": int}; scrapes
        # happen OUTSIDE the lock (refresh), only the cache swap is under
        self._cache: Dict[Tuple[str, str], Dict[str, Any]] = {}  # guarded-by: _lock
        self._httpd: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None
        self._refresher: Optional[threading.Thread] = None
        self._stop_refresh = threading.Event()
        # the handler duck-types against _MetricsState: render()/health()
        self.state = self
        self.stale_after_s = 0.0

    # -- scraping ---------------------------------------------------------

    def _scrape(self, port: int) -> Optional[str]:
        return scrape_metrics(port, timeout_s=self.timeout_s)

    def refresh(self) -> int:
        """Scrape every current target once; returns how many answered.
        Identities that did not answer (exited children) stay cached,
        marked down."""
        answered = 0
        live: set = set()
        for port in list(self._targets()):
            body = self._scrape(int(port))
            if body is None:
                continue
            answered += 1
            identity = _parse_identity(body) or ("?", str(port))
            live.add(identity)
            with self._lock:
                self._cache[identity] = {"body": body, "up": True,
                                         "port": int(port)}
        with self._lock:
            for identity, entry in self._cache.items():
                if identity not in live:
                    entry["up"] = False
        return answered

    # -- the merged page (duck-typed _MetricsState surface) ---------------

    def render(self) -> str:
        if self.refresh_s is None:
            self.refresh()   # inline mode: every GET is a fresh fan-out
        with self._lock:
            cache = {k: dict(v) for k, v in self._cache.items()}
        types: Dict[str, str] = {}
        samples: List[str] = []
        up_lines: List[str] = []
        for (gen, rank) in sorted(cache):
            entry = cache[(gen, rank)]
            up_lines.append(
                f'dpt_federation_up{{gen="{_escape_label(gen)}",rank='
                f'"{_escape_label(rank)}"}} {1 if entry["up"] else 0}')
            for line in entry["body"].splitlines():
                if line.startswith("# TYPE "):
                    parts = line.split()
                    if len(parts) == 4:
                        types.setdefault(parts[2], parts[3])
                    continue
                out = _relabel_line(line, gen, rank)
                if out is not None:
                    samples.append(out)
        lines = ["# TYPE dpt_federation_targets gauge",
                 f"dpt_federation_targets {len(cache)}",
                 "# TYPE dpt_federation_up gauge", *up_lines]
        for name, kind in sorted(types.items()):
            lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)
        return "\n".join(lines) + "\n"

    def health(self, stale_after_s: float) -> Tuple[bool, dict]:
        if self.refresh_s is None:
            self.refresh()
        with self._lock:
            detail = {
                "healthy": any(e["up"] for e in self._cache.values()),
                "targets": {
                    f"gen{g}/rank{r}": {"up": e["up"], "port": e["port"]}
                    for (g, r), e in sorted(self._cache.items())},
            }
        return bool(detail["healthy"]), detail

    # -- lifecycle --------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    def start(self) -> int:
        if self._httpd is not None:
            return self.port  # type: ignore[return-value]
        self._httpd = _Server((self._host, self._want_port), self,
                              self.stale_after_s, owner=None)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name=f"dpt-metrics-federation-{self.port}", daemon=True)
        self._thread.start()
        if self.refresh_s is not None:
            self._stop_refresh.clear()
            self._refresher = threading.Thread(
                target=self._refresh_loop, name="dpt-federation-refresh",
                daemon=True)
            self._refresher.start()
        return self.port  # type: ignore[return-value]

    def _refresh_loop(self) -> None:
        while not self._stop_refresh.wait(self.refresh_s):
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 — the poll must outlive any
                pass           # one bad scrape

    def stop(self) -> None:
        self._stop_refresh.set()
        if self._refresher is not None:
            self._refresher.join(timeout=5.0)
            self._refresher = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
