"""Live metrics surface (ISSUE 14): a stdlib-only background HTTP thread.

``/metrics`` serves Prometheus text-format gauges/counters/histograms
aggregated from the SAME event stream the recorder writes — the server
registers an observer on the :class:`~.recorder.Recorder` and folds each
event into thread-safe counters as it is emitted, so the scrape handler
never touches the JSONL and never blocks an emit:

* ``dpt_steps_total`` / ``dpt_last_step`` — the step fence, observed
  through ``step_dispatch`` spans;
* ``dpt_epoch`` — the last completed epoch (``epoch_time_s`` counters);
* ``dpt_phase_seconds`` — one histogram per canonical phase
  (data_wait / step_dispatch / ... / prefill / decode), fixed buckets;
* ``dpt_wire_bytes_total{name,tier,axis}`` — the per-tier wire counters
  (grad_sync's emit_wire_accounting rows; the DCN tier is one more
  label value, not new code);
* ``dpt_anomalies_total{name}`` — watchdog detections;
* ``dpt_gauge{name}`` — every gauge last-value (world_size, capacity,
  queue depth, EF norm);
* ``dpt_last_progress_age_seconds`` — seconds since the step fence last
  ADVANCED (a new high-water `step`, a `steps` counter, or a serving
  prefill/decode span).

``/healthz`` is the progress-fence liveness probe: 200 while the last
step advance is younger than ``stale_after_s`` (the server's start time
seeds the fence, so a compiling run gets its grace), 503 once the fence
stops advancing — a wedged dispatch, a dead loader, a hung collective
all flip it without any in-band cooperation from the training loop.

Costs, by construction: OFF means this module is never imported by the
hot path and zero threads exist (train.py/serving gate on a nonzero
port). ON means one listener thread + per-event dict updates on the
host side only — nothing here can touch traced code, so the telemetry
on/off HLO-identity pin extends to the live surface unchanged.

jax-free and stdlib-only, like every module in this package.
"""

from __future__ import annotations

import http.server
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .recorder import (
    ELASTIC_SPAN_NAMES,
    Recorder,
    SERVING_SPAN_NAMES,
    SPAN_NAMES,
)

METRICS_PORT_ENV = "DPT_METRICS_PORT"
METRICS_STALE_S_ENV = "DPT_METRICS_STALE_S"

_PHASES = SPAN_NAMES + SERVING_SPAN_NAMES + ELASTIC_SPAN_NAMES

# seconds; the +Inf bucket is implicit. Spans range from ~100us CPU-mesh
# dispatches to multi-second compiles/stalls.
_BUCKETS_S = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
              1.0, 2.5, 5.0, 10.0, 30.0)

# step_dispatch feeds the fence only when its `step` ADVANCES (or is
# unstamped); the serving phases always count — see _MetricsState.observe.
_PROGRESS_SPANS = ("step_dispatch", "prefill", "decode")


def resolve_metrics_port(cli_port: Optional[int], rank: int = 0) -> int:
    """The effective port: an explicit CLI value wins, else the
    ``DPT_METRICS_PORT`` env (the fleet orchestrator's stamp), else off.
    A nonzero base is offset by the rank so co-hosted ranks under
    ``--telemetry-all-ranks`` each get their own listener. 0 = off."""
    base = cli_port
    if base is None:
        try:
            base = int(os.environ.get(METRICS_PORT_ENV, "0"))
        except ValueError:
            base = 0
    base = int(base)
    return base + int(rank) if base > 0 else 0


def _escape_label(value: Any) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


class _MetricsState:
    """The scrape-side aggregate, fed one event at a time."""

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.events_total = 0
        self.steps_total = 0
        self.last_step = -1
        self.epoch = -1
        self.last_progress = self._t0
        # phase -> (bucket counts, sum_s, count)
        self.phases: Dict[str, Tuple[List[int], float, int]] = {}
        self.wire: Dict[Tuple[str, str, str], float] = {}
        self.anomalies: Dict[str, int] = {}
        self.gauges: Dict[str, float] = {}

    # -- the observer ---------------------------------------------------

    def observe(self, ev: dict) -> None:
        kind = ev.get("kind")
        name = ev.get("name", "?")
        with self._lock:
            self.events_total += 1
            if kind == "span":
                dur_s = float(ev.get("dur_ms", 0.0)) / 1e3
                if name in _PHASES:
                    buckets, total, count = self.phases.get(
                        name, ([0] * (len(_BUCKETS_S) + 1), 0.0, 0))
                    for i, le in enumerate(_BUCKETS_S):
                        if dur_s <= le:
                            buckets[i] += 1
                            break
                    else:
                        buckets[-1] += 1
                    self.phases[name] = (buckets, total + dur_s, count + 1)
                if name == "step_dispatch":
                    self.steps_total += 1
                    step = ev.get("step")
                    if step is None:
                        # an unstamped dispatch carries no fence to
                        # compare — count it as progress
                        self.last_progress = time.monotonic()
                    elif isinstance(step, (int, float)) \
                            and step > self.last_step:
                        self.last_step = int(step)
                        self.last_progress = time.monotonic()
                    # a re-dispatch of an already-seen step (a restart
                    # loop replaying from a checkpoint) is NOT progress:
                    # the fence must ADVANCE to keep /healthz green
                elif name in ("prefill", "decode"):
                    # serving progress: every served phase counts
                    self.last_progress = time.monotonic()
            elif kind == "counter":
                if name == "epoch_time_s":
                    epoch = ev.get("epoch")
                    if isinstance(epoch, (int, float)):
                        self.epoch = max(self.epoch, int(epoch))
                elif name == "steps":
                    self.last_progress = time.monotonic()
                if "tier" in ev or "axis" in ev:
                    key = (name, str(ev.get("tier", "")),
                           str(ev.get("axis", "")))
                    self.wire[key] = (self.wire.get(key, 0.0)
                                      + float(ev.get("value", 0.0)))
            elif kind == "anomaly":
                self.anomalies[name] = self.anomalies.get(name, 0) + 1
            elif kind == "gauge":
                try:
                    self.gauges[name] = float(ev.get("value", 0.0))
                except (TypeError, ValueError):
                    pass

    # -- the scrape views -----------------------------------------------

    def render(self) -> str:
        with self._lock:
            age = time.monotonic() - self.last_progress
            lines = [
                "# TYPE dpt_events_total counter",
                f"dpt_events_total {self.events_total}",
                "# TYPE dpt_steps_total counter",
                f"dpt_steps_total {self.steps_total}",
                "# TYPE dpt_last_step gauge",
                f"dpt_last_step {self.last_step}",
                "# TYPE dpt_epoch gauge",
                f"dpt_epoch {self.epoch}",
                "# TYPE dpt_last_progress_age_seconds gauge",
                f"dpt_last_progress_age_seconds {age:.3f}",
            ]
            if self.phases:
                lines.append("# TYPE dpt_phase_seconds histogram")
                for phase in sorted(self.phases):
                    buckets, total, count = self.phases[phase]
                    cum = 0
                    label = _escape_label(phase)
                    for le, n in zip(_BUCKETS_S, buckets):
                        cum += n
                        lines.append(
                            f'dpt_phase_seconds_bucket{{phase="{label}",'
                            f'le="{le:g}"}} {cum}')
                    cum += buckets[-1]
                    lines.append(
                        f'dpt_phase_seconds_bucket{{phase="{label}",'
                        f'le="+Inf"}} {cum}')
                    lines.append(f'dpt_phase_seconds_sum{{phase="{label}"}}'
                                 f' {total:.6f}')
                    lines.append(f'dpt_phase_seconds_count{{phase='
                                 f'"{label}"}} {count}')
            if self.wire:
                lines.append("# TYPE dpt_wire_bytes_total counter")
                for (name, tier, axis), v in sorted(self.wire.items()):
                    lines.append(
                        f'dpt_wire_bytes_total{{name="{_escape_label(name)}'
                        f'",tier="{_escape_label(tier)}",axis='
                        f'"{_escape_label(axis)}"}} {v:g}')
            if self.anomalies:
                lines.append("# TYPE dpt_anomalies_total counter")
                for name, n in sorted(self.anomalies.items()):
                    lines.append(f'dpt_anomalies_total{{name='
                                 f'"{_escape_label(name)}"}} {n}')
            if self.gauges:
                lines.append("# TYPE dpt_gauge gauge")
                for name, v in sorted(self.gauges.items()):
                    lines.append(
                        f'dpt_gauge{{name="{_escape_label(name)}"}} {v:g}')
            return "\n".join(lines) + "\n"

    def health(self, stale_after_s: float) -> Tuple[bool, dict]:
        with self._lock:
            age = time.monotonic() - self.last_progress
            healthy = age < stale_after_s
            return healthy, {
                "healthy": healthy,
                "last_progress_age_s": round(age, 3),
                "stale_after_s": stale_after_s,
                "last_step": self.last_step,
                "steps_total": self.steps_total,
            }


class _Handler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        server: "_Server" = self.server  # type: ignore[assignment]
        if self.path.split("?")[0] == "/metrics":
            body = server.state.render().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
        elif self.path.split("?")[0] == "/healthz":
            healthy, detail = server.state.health(server.stale_after_s)
            body = (json.dumps(detail, sort_keys=True) + "\n") \
                .encode("utf-8")
            self.send_response(200 if healthy else 503)
            self.send_header("Content-Type", "application/json")
        else:
            body = b"telemetry metrics: /metrics or /healthz\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stdout
        return


class _Server(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, state: _MetricsState, stale_after_s: float):
        super().__init__(addr, _Handler)
        self.state = state
        self.stale_after_s = stale_after_s


class MetricsServer:
    """The background `/metrics` + `/healthz` listener.

    ``port=0`` binds an ephemeral port (tests); :meth:`start` returns the
    bound port. ``recorder`` is the stream to observe (its observer is
    removed again on :meth:`stop`). ``stale_after_s`` is the healthz
    fence: default from ``DPT_METRICS_STALE_S``, else 300s — generous
    because a first-step compile is legitimate silence."""

    def __init__(self, port: int, recorder: Optional[Recorder] = None,
                 host: str = "0.0.0.0",
                 stale_after_s: Optional[float] = None):
        if stale_after_s is None:
            try:
                stale_after_s = float(
                    os.environ.get(METRICS_STALE_S_ENV, "300"))
            except ValueError:
                stale_after_s = 300.0
        self.state = _MetricsState()
        self._host = host
        self._want_port = int(port)
        self._recorder = recorder
        self.stale_after_s = float(stale_after_s)
        self._httpd: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    def start(self) -> int:
        if self._httpd is not None:
            return self.port  # type: ignore[return-value]
        self._httpd = _Server((self._host, self._want_port), self.state,
                              self.stale_after_s)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name=f"dpt-metrics-{self.port}", daemon=True)
        self._thread.start()
        if self._recorder is not None:
            self._recorder.add_observer(self.state.observe)
        return self.port  # type: ignore[return-value]

    def stop(self) -> None:
        if self._recorder is not None:
            self._recorder.remove_observer(self.state.observe)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# module-global lifecycle (the train.py / serving wiring): one server per
# process, started only when a port resolves nonzero — off means this
# function is the only thing that ran, and it started nothing.
# ---------------------------------------------------------------------------

_SERVER: Optional[MetricsServer] = None


def start_metrics_server(port: int, recorder: Optional[Recorder] = None,
                         **kwargs: Any) -> Optional[MetricsServer]:
    """Start (or replace) the process-global metrics server. ``port <= 0``
    is a no-op returning None — the off path creates zero threads. A bind
    failure (the port is taken) also returns None, with a stderr note:
    the live surface shares the recorder's contract — a broken
    observability convenience must never take the training run down."""
    import sys

    global _SERVER
    if port <= 0:
        return None
    stop_metrics_server()
    server = MetricsServer(port, recorder=recorder, **kwargs)
    try:
        server.start()
    except OSError as e:
        print(f"telemetry: /metrics server could not bind port {port} "
              f"({e}) — continuing without the live surface",
              file=sys.stderr, flush=True)
        return None
    _SERVER = server
    return _SERVER


def stop_metrics_server() -> None:
    global _SERVER
    if _SERVER is not None:
        _SERVER.stop()
        _SERVER = None


def get_metrics_server() -> Optional[MetricsServer]:
    return _SERVER
