"""Anomaly watchdog: detections fed off the same host-side stream.

Three detectors, each emitting a structured ``anomaly`` event into the
recorder (and, with ``abort=True``, raising :class:`AnomalyAbort` — which
under the restart Supervisor is a restartable failure like any other, so
"abort" means checkpoint-restore-replay, not data loss):

* **non-finite loss** — fed at print boundaries (the loop's only host
  fetch; the watchdog must not add device syncs);
* **step-time spike** — host wall per step vs a rolling median. Honest
  scope: with async dispatch the host observes device time only through
  donation backpressure once the pipeline fills, so the detector warms up
  (``min_samples``) before judging and compares against the rolling
  median, not the mean (compile steps would poison a mean forever);
* **loader stall** — data-wait exceeding both an absolute floor and a
  multiple of its own rolling median (the chaos ``loader_stall`` fault's
  signature).

The watchdog holds no device state and is jax-free.
"""

from __future__ import annotations

import collections
import math
import statistics
from typing import Deque, Optional

from . import recorder as _recorder


class AnomalyAbort(RuntimeError):
    """Raised by an ``abort=True`` watchdog on detection — under the
    Supervisor this is a restartable step failure (restore + replay)."""


class AnomalyWatchdog:
    """Rolling-median anomaly detection over per-step host timings.

    ``spike_factor``: a step slower than factor x median (after
    ``min_samples`` warm-up steps) is a ``step_time_spike``.
    ``stall_factor`` / ``stall_min_s``: a data wait above BOTH
    ``stall_min_s`` and factor x its median is a ``loader_stall``.
    ``abort``: raise :class:`AnomalyAbort` on detection (default: observe
    only). Detections are also counted on the instance for tests/reports.
    """

    def __init__(self, spike_factor: float = 5.0, min_samples: int = 20,
                 stall_factor: float = 10.0, stall_min_s: float = 1.0,
                 window: int = 128, abort: bool = False):
        if spike_factor <= 1.0 or stall_factor <= 1.0:
            raise ValueError("spike/stall factors must be > 1")
        self.spike_factor = spike_factor
        self.min_samples = max(2, min_samples)
        self.stall_factor = stall_factor
        self.stall_min_s = stall_min_s
        self.abort = abort
        self._step_s: Deque[float] = collections.deque(maxlen=window)
        self._wait_s: Deque[float] = collections.deque(maxlen=window)
        self.anomalies: list = []

    # -- detections --------------------------------------------------------

    def _fire(self, name: str, **fields) -> None:
        self.anomalies.append((name, fields))
        _recorder.emit("anomaly", name, **fields)
        if self.abort:
            raise AnomalyAbort(
                f"anomaly watchdog: {name} "
                + " ".join(f"{k}={v}" for k, v in fields.items()))

    def observe_step(self, step: int, step_s: float,
                     data_wait_s: Optional[float] = None) -> None:
        """Feed one step's host wall time (+ its data wait). Samples are
        recorded AFTER the check so a spike never judges itself normal.

        Attribution: the stall detector runs FIRST and the spike detector
        judges the BUSY time (step minus data wait) — a step made slow by
        its loader is a loader_stall, never additionally a
        step_time_spike (the stall's shadow would otherwise fire first
        under abort=True and misname the cause)."""
        busy_s = max(0.0, step_s - (data_wait_s or 0.0))
        if data_wait_s is not None and len(self._wait_s) >= self.min_samples:
            med_w = statistics.median(self._wait_s)
            if data_wait_s > self.stall_min_s and \
                    data_wait_s > self.stall_factor * max(med_w, 1e-9):
                # record the samples before a potential abort-raise so a
                # replayed step re-enters a warmed-up detector
                self._step_s.append(busy_s)
                self._wait_s.append(data_wait_s)
                self._fire("loader_stall", step=step,
                           data_wait_s=round(data_wait_s, 4),
                           median_wait_s=round(med_w, 6))
                return
        if len(self._step_s) >= self.min_samples:
            med = statistics.median(self._step_s)
            if med > 0 and busy_s > self.spike_factor * med:
                self._step_s.append(busy_s)
                if data_wait_s is not None:
                    self._wait_s.append(data_wait_s)
                self._fire("step_time_spike", step=step,
                           step_s=round(busy_s, 4),
                           median_s=round(med, 4),
                           factor=round(busy_s / med, 2))
                return
        self._step_s.append(busy_s)
        if data_wait_s is not None:
            self._wait_s.append(data_wait_s)

    def observe_loss(self, step: int, loss: float) -> None:
        """Feed a host-fetched loss (print boundaries — never a new sync)."""
        if not math.isfinite(loss):
            self._fire("non_finite_loss", step=step, loss=str(loss))
