"""Anomaly watchdog: detections fed off the same host-side stream.

Three detectors, each emitting a structured ``anomaly`` event into the
recorder (and, with ``abort=True``, raising :class:`AnomalyAbort` — which
under the restart Supervisor is a restartable failure like any other, so
"abort" means checkpoint-restore-replay, not data loss):

* **non-finite loss** — fed at print boundaries (the loop's only host
  fetch; the watchdog must not add device syncs);
* **step-time spike** — host wall per step vs a rolling median. Honest
  scope: with async dispatch the host observes device time only through
  donation backpressure once the pipeline fills, so the detector warms up
  (``min_samples``) before judging and compares against the rolling
  median, not the mean (compile steps would poison a mean forever);
* **loader stall** — data-wait exceeding both an absolute floor and a
  multiple of its own rolling median (the chaos ``loader_stall`` fault's
  signature).

Anomaly-triggered capture (ISSUE 15): with a ``capture_hook`` installed
(train.py wires it to ``StepProfiler.request_capture``), a step-time
spike or loader stall ARMS a short on-demand trace capture the moment it
is detected — the straggling behaviour is recorded while it is still
happening instead of being unreproducible after the fact. The hook fires
on detection regardless of the abort flag (and BEFORE an abort raise),
is contained (a failing hook never takes the run down), and arming is
refuse-not-clobber when the profiler is busy — so the hook has no
``--telemetry-abort``-like side effects on control flow.

The watchdog holds no device state and is jax-free. The detector knobs
read env overrides via :func:`kwargs_from_env` (``DPT_WATCHDOG_*``) so
an orchestrator can tune warm-up/floors on children it cannot pass
flags to (the fleet's capture story needs a short warm-up on short
runs).
"""

from __future__ import annotations

import collections
import math
import os
import statistics
from typing import Callable, Deque, Optional

from . import recorder as _recorder

# env-name -> (ctor kwarg, cast): the orchestrator-facing tuning surface
WATCHDOG_ENV_KNOBS = {
    "DPT_WATCHDOG_MIN_SAMPLES": ("min_samples", int),
    "DPT_WATCHDOG_SPIKE_FACTOR": ("spike_factor", float),
    "DPT_WATCHDOG_STALL_FACTOR": ("stall_factor", float),
    "DPT_WATCHDOG_STALL_MIN_S": ("stall_min_s", float),
    "DPT_WATCHDOG_STALL_ABS_S": ("stall_abs_s", float),
}


def kwargs_from_env() -> dict:
    """AnomalyWatchdog constructor overrides from ``DPT_WATCHDOG_*`` env
    (unset/unparseable names are simply absent — defaults hold)."""
    out = {}
    for env, (kwarg, cast) in WATCHDOG_ENV_KNOBS.items():
        raw = os.environ.get(env)
        if raw is None:
            continue
        try:
            out[kwarg] = cast(raw)
        except ValueError:
            pass
    return out


class AnomalyAbort(RuntimeError):
    """Raised by an ``abort=True`` watchdog on detection — under the
    Supervisor this is a restartable step failure (restore + replay)."""


class AnomalyWatchdog:
    """Rolling-median anomaly detection over per-step host timings.

    ``spike_factor``: a step slower than factor x median (after
    ``min_samples`` warm-up steps) is a ``step_time_spike``.
    ``stall_factor`` / ``stall_min_s``: a data wait above BOTH
    ``stall_min_s`` and factor x its median is a ``loader_stall``.
    ``stall_abs_s`` (default None = off): an UNCONDITIONAL absolute
    stall bound — a data wait above it is a ``loader_stall`` with no
    warm-up and no median (a stall on the FIRST post-resume step is
    otherwise invisible: the rolling median has nothing to compare
    against; the fleet's anomaly-capture story needs exactly that step).
    The caller owns the bound's sanity — None keeps the PR 8 semantics
    bit-for-bit.
    ``abort``: raise :class:`AnomalyAbort` on detection (default: observe
    only). ``capture_hook(name, step)``: arm an on-demand trace capture
    on a timing anomaly (spike/stall — not the non-finite-loss detector,
    whose damage a device trace cannot show). Detections are also
    counted on the instance for tests/reports.
    """

    def __init__(self, spike_factor: float = 5.0, min_samples: int = 20,
                 stall_factor: float = 10.0, stall_min_s: float = 1.0,
                 window: int = 128, abort: bool = False,
                 capture_hook: Optional[Callable[[str, int],
                                                 object]] = None,
                 stall_abs_s: Optional[float] = None):
        if spike_factor <= 1.0 or stall_factor <= 1.0:
            raise ValueError("spike/stall factors must be > 1")
        if stall_abs_s is not None and stall_abs_s <= 0:
            raise ValueError("stall_abs_s must be > 0 (or None = off)")
        self.spike_factor = spike_factor
        self.min_samples = max(2, min_samples)
        self.stall_factor = stall_factor
        self.stall_min_s = stall_min_s
        self.stall_abs_s = stall_abs_s
        self.abort = abort
        self.capture_hook = capture_hook
        self._step_s: Deque[float] = collections.deque(maxlen=window)
        self._wait_s: Deque[float] = collections.deque(maxlen=window)
        self.anomalies: list = []

    # -- detections --------------------------------------------------------

    # the timing anomalies a device trace can explain; non_finite_loss is
    # a numerics problem, not a schedule one — no capture armed for it
    _CAPTURE_ANOMALIES = ("step_time_spike", "loader_stall")

    def _fire(self, name: str, **fields) -> None:
        self.anomalies.append((name, fields))
        _recorder.emit("anomaly", name, **fields)
        if self.capture_hook is not None and name in self._CAPTURE_ANOMALIES:
            # BEFORE a potential abort-raise: the capture of the
            # anomalous behaviour is the point, and it must arm whether
            # or not the abort hook then turns this into a restart
            try:
                self.capture_hook(name, fields.get("step", -1))
            except Exception:  # noqa: BLE001 — observability never takes
                pass           # the run down
        if self.abort:
            raise AnomalyAbort(
                f"anomaly watchdog: {name} "
                + " ".join(f"{k}={v}" for k, v in fields.items()))

    def observe_step(self, step: int, step_s: float,
                     data_wait_s: Optional[float] = None) -> None:
        """Feed one step's host wall time (+ its data wait). Samples are
        recorded AFTER the check so a spike never judges itself normal.

        Attribution: the stall detector runs FIRST and the spike detector
        judges the BUSY time (step minus data wait) — a step made slow by
        its loader is a loader_stall, never additionally a
        step_time_spike (the stall's shadow would otherwise fire first
        under abort=True and misname the cause)."""
        busy_s = max(0.0, step_s - (data_wait_s or 0.0))
        if data_wait_s is not None and self.stall_abs_s is not None \
                and data_wait_s > self.stall_abs_s:
            # the unconditional absolute bound: no warm-up, no median —
            # samples still recorded first so a replayed step re-enters
            # a warmed-up detector (the relative path's convention)
            self._step_s.append(busy_s)
            self._wait_s.append(data_wait_s)
            self._fire("loader_stall", step=step,
                       data_wait_s=round(data_wait_s, 4),
                       absolute_bound_s=self.stall_abs_s)
            return
        if data_wait_s is not None and len(self._wait_s) >= self.min_samples:
            med_w = statistics.median(self._wait_s)
            if data_wait_s > self.stall_min_s and \
                    data_wait_s > self.stall_factor * max(med_w, 1e-9):
                # record the samples before a potential abort-raise so a
                # replayed step re-enters a warmed-up detector
                self._step_s.append(busy_s)
                self._wait_s.append(data_wait_s)
                self._fire("loader_stall", step=step,
                           data_wait_s=round(data_wait_s, 4),
                           median_wait_s=round(med_w, 6))
                return
        if len(self._step_s) >= self.min_samples:
            med = statistics.median(self._step_s)
            if med > 0 and busy_s > self.spike_factor * med:
                self._step_s.append(busy_s)
                if data_wait_s is not None:
                    self._wait_s.append(data_wait_s)
                self._fire("step_time_spike", step=step,
                           step_s=round(busy_s, 4),
                           median_s=round(med, 4),
                           factor=round(busy_s / med, 2))
                return
        self._step_s.append(busy_s)
        if data_wait_s is not None:
            self._wait_s.append(data_wait_s)

    def observe_loss(self, step: int, loss: float) -> None:
        """Feed a host-fetched loss (print boundaries — never a new sync)."""
        if not math.isfinite(loss):
            self._fire("non_finite_loss", step=step, loss=str(loss))
