"""Flight recorder: the crash-surviving postmortem artifact.

On any abnormal exit the ring buffer's last N events + the exit cause are
written to ``flight_<ts>_<seq>.json`` in the telemetry directory —
explicitly fsync'd, so it survives the process dying immediately after.
Every rc=70 / rc!=0 path in the stack flushes one:

* ``resilience/heartbeat.py`` — the Deathwatch lethal probe, right before
  ``hard_exit`` (cause names the dead relay ports);
* ``resilience/supervisor.py`` — every restart (cause = the caught step/
  save failure, so an injected ``crash@step=3`` reads back verbatim),
  torn-checkpoint skips, the preemption (SIGTERM) drain, relay-death
  abort, and retry exhaustion;
* ``train.py`` — unhandled exceptions, via the explicit ``except
  BaseException`` clause in ``main()`` (NOT :func:`install_excepthook`:
  the flush must run BEFORE ``finally: telemetry.reset()`` closes the
  recorder, and ``sys.excepthook`` fires after the function's finally
  blocks — the hook would find no recorder and write an empty flight).
  ``install_excepthook`` exists for entry points with no such wrapper
  (one-off scripts driving the library directly); never combine both in
  one process or a crash writes two flights.

A flight flush is best-effort by contract: it runs on paths that are
already dying, so it must never raise, never import jax, and never block
unboundedly (one open/write/fsync/rename).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import time
from pathlib import Path
from typing import Any, Dict, Optional

from . import recorder as _recorder

_SEQ = itertools.count()

# Fleet context (ISSUE 12): the cross-process orchestrator
# (resilience/fleet.py) stamps every child it launches with its launch
# generation and rank. A postmortem that cannot say WHICH launch of a
# relaunch sequence died is half a postmortem — the context rides in the
# flight's cause (and as structured fields), read straight from the env
# so no plumbing crosses the library. The names moved to recorder.py
# (ISSUE 14: the recorder stamps the same identity on every stream
# event); re-exported here for the orchestrator's historical import.
FLEET_GENERATION_ENV = _recorder.FLEET_GENERATION_ENV
FLEET_RANK_ENV = _recorder.FLEET_RANK_ENV


def _fleet_context() -> dict:
    ctx = {}
    gen = os.environ.get(FLEET_GENERATION_ENV)
    rank = os.environ.get(FLEET_RANK_ENV)
    if gen is not None:
        ctx["fleet_generation"] = gen
    if rank is not None:
        ctx["fleet_rank"] = rank
    return ctx


def flush_flight(cause: str, detail: str = "", rc: Optional[int] = None,
                 directory: Optional[str] = None,
                 extra: Optional[Dict[str, Any]] = None) -> Optional[Path]:
    """Write ``flight_<ms>_<seq>.json`` with the exit cause + the ring's
    tail. Returns the path, or None when there is nowhere to write (no
    recorder configured and no explicit ``directory``). Never raises."""
    try:
        rec = _recorder.get()
        out_dir = Path(directory) if directory is not None else (
            rec.directory if rec is not None else None)
        if out_dir is None:
            return None
        fleet = _fleet_context()
        if fleet:
            # a fleet-launched child names its launch generation + rank in
            # the cause itself (the first thing anyone reads), so a
            # relaunch sequence's postmortems are attributable at a glance
            cause = (f"{cause} [fleet gen="
                     f"{fleet.get('fleet_generation', '?')} rank="
                     f"{fleet.get('fleet_rank', '?')}]")
        events = rec.tail(rec.ring.maxlen) if rec is not None else []
        body = {
            "schema": _recorder.SCHEMA_VERSION,
            "kind": "flight",
            "cause": cause,
            "detail": detail,
            "rc": rc,
            "ts": time.time(),
            "pid": os.getpid(),
            "run_id": rec.run_id if rec is not None else None,
            "n_events": len(events),
            "events": events,
            **fleet,
        }
        if extra:
            body.update(extra)
        out_dir.mkdir(parents=True, exist_ok=True)
        path = out_dir / (f"flight_{int(time.time() * 1000)}_"
                          f"{next(_SEQ)}.json")
        tmp = path.with_suffix(".json.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(body, f, sort_keys=True, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: never a half-written flight
        if rec is not None:
            # the exit record also lands in the JSONL stream (tail loss
            # there is exactly what the flight file compensates for)
            rec.emit("exit", "flight", cause=cause, detail=detail, rc=rc,
                     flight_path=str(path))
            rec.flush()
        return path
    except Exception:  # noqa: BLE001 — a dying process owes no cleanup here
        return None


def install_excepthook() -> None:
    """Chain a flight flush into ``sys.excepthook``: an unhandled exception
    (train.py's crash path) leaves a postmortem before the traceback
    prints. Idempotent; SystemExit/KeyboardInterrupt never reach the hook
    (Python's contract), so clean exits stay flight-free."""
    prev = sys.excepthook
    if getattr(prev, "_telemetry_flight_hook", False):
        return

    def hook(exc_type, exc, tb):
        flush_flight(cause=f"{exc_type.__name__}: {exc}",
                     detail="unhandled exception", rc=1)
        prev(exc_type, exc, tb)

    hook._telemetry_flight_hook = True
    sys.excepthook = hook
