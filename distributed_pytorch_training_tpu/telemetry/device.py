"""Device-split ingestion (ISSUE 15): captured traces -> typed telemetry.

PR 13's observability plane sees the run only through host-side spans — it
can say WHICH rank was slow and in WHICH phase, but not what the device was
doing. This module closes that gap: whenever utils/profiling.StepProfiler
finishes a capture (the static ``--profile-steps`` window, a ``POST
/profile`` on-demand window, or an anomaly-triggered one), the trace is
parsed through the experiments/trace_analysis machinery
(:func:`~..experiments.trace_analysis.device_time_split` — the
``comm_overlap_split`` interval algebra plus the collective census' op
normalization) into ONE ``device_profile`` event on the stream:

* per-phase device milliseconds — ``compute`` / ``comm_hidden`` /
  ``comm_exposed`` / ``host_gap`` — whose sum is the captured window (the
  self-consistency the acceptance test pins);
* per-collective-op rollups (``by_op_ms``: all-reduce vs all-gather vs
  reduce-scatter time);
* ``exposed_comm_ratio`` — exposed / total collective time, the number
  that decides whether compressed gradient sync paid off (DynamiQ's
  headline metric, now a runtime series instead of a bench.py-only one);
* measured MFU when the caller provides a FLOPs reference (train.py wires
  the Trainer's analytic per-step FLOPs + chip peak).

The event is gen/rank-stamped like every other (the recorder does that),
so ``telemetry aggregate``'s straggler detector can device-attribute a
flagged rank when a capture overlapped the flagged step, and the live
``/metrics`` observer folds it into ``dpt_device_seconds{phase=...}`` /
``dpt_exposed_comm_ratio`` without extra wiring.

Ingestion is observability: every failure path here logs and returns —
a torn trace, a missing capture, a parse error must never take the
training run down. Imports of the trace parser are lazy so this module
(and the telemetry package) stays importable on jax-free readers.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from . import recorder as _recorder

# The event kind + the four phase keys (readers — summary, aggregate,
# metrics_http — key on these; one definition).
DEVICE_PROFILE_KIND = "device_profile"
DEVICE_PHASES = ("compute", "comm_hidden", "comm_exposed", "host_gap")

# type of the optional MFU reference: () -> (flops_per_step, peak_flops_total)
MfuRef = Callable[[], Optional[Tuple[float, float]]]


def analyze_capture(trace_dir: str) -> Optional[Dict[str, Any]]:
    """Parse one captured trace directory into the device split, or None
    (logged) when no trace exists / parsing fails."""
    try:
        from ..experiments.trace_analysis import device_time_split

        return device_time_split(trace_dir)
    except FileNotFoundError:
        # legitimate: process != 0, or a capture window that closed
        # before the profiler flushed — nothing to ingest
        return None
    except Exception as e:  # noqa: BLE001 — ingestion is observability
        print(f"telemetry: device-split parse of {trace_dir} failed: {e}",
              flush=True)
        return None


def profile_event_fields(split: Dict[str, Any], info: Dict[str, Any],
                         mfu_ref: Optional[MfuRef] = None
                         ) -> Dict[str, Any]:
    """The ``device_profile`` event body from one parsed split + the
    profiler's window info (start/stop step, reason, trigger)."""
    window_ms = split["window_us"] / 1e3
    coll_ms = split["collective_us"] / 1e3
    fields: Dict[str, Any] = {
        "start_step": info.get("start_step"),
        "stop_step": info.get("stop_step"),
        "steps": info.get("steps"),
        "reason": info.get("reason", "?"),
        "trigger_step": info.get("trigger_step"),
        "window_ms": round(window_ms, 4),
        "compute_ms": round(split["compute_us"] / 1e3, 4),
        "comm_hidden_ms": round(split["comm_hidden_us"] / 1e3, 4),
        "comm_exposed_ms": round(split["comm_exposed_us"] / 1e3, 4),
        "host_gap_ms": round(split["host_gap_us"] / 1e3, 4),
        "exposed_comm_ratio": round(
            split["comm_exposed_us"] / split["collective_us"], 4)
        if split["collective_us"] else 0.0,
        "comm_share_pct": round(100.0 * coll_ms / window_ms, 2)
        if window_ms else 0.0,
        "by_op_ms": {k: round(v / 1e3, 4)
                     for k, v in split["by_op"].items()},
        "n_device_lanes": split["n_device_lanes"],
    }
    steps = info.get("steps")
    if mfu_ref is not None and steps and window_ms > 0:
        try:
            ref = mfu_ref()
        except Exception:  # noqa: BLE001 — the reference is a nicety
            ref = None
        if ref:
            flops_per_step, peak_total = ref
            if flops_per_step and peak_total:
                fields["measured_mfu_pct"] = round(
                    100.0 * flops_per_step * steps
                    / (peak_total * window_ms / 1e3), 2)
    return fields


def ingest_capture(trace_dir: str, info: Dict[str, Any],
                   mfu_ref: Optional[MfuRef] = None
                   ) -> Optional[Dict[str, Any]]:
    """Parse + emit one capture. Returns the emitted fields (tests), or
    None when there was nothing to ingest. Never raises."""
    split = analyze_capture(trace_dir)
    if split is None:
        return None
    fields = profile_event_fields(split, info, mfu_ref=mfu_ref)
    fields["trace_dir"] = str(trace_dir)
    _recorder.emit(DEVICE_PROFILE_KIND, "device_profile", **fields)
    return fields


def make_ingestor(mfu_ref: Optional[MfuRef] = None
                  ) -> Callable[[str, Dict[str, Any]], None]:
    """The ``StepProfiler(on_capture=...)`` callback: close over the
    optional MFU reference (train.py passes a lazy Trainer read — the
    reference is set after the profiler is constructed)."""

    def _ingest(trace_dir: str, info: Dict[str, Any]) -> None:
        ingest_capture(trace_dir, info, mfu_ref=mfu_ref)

    return _ingest


def split_of_event(ev: Dict[str, Any]) -> Dict[str, float]:
    """{phase: ms} of one ``device_profile`` event (reader helper —
    summary/aggregate/metrics all bucket through this one mapping)."""
    return {"compute": float(ev.get("compute_ms", 0.0)),
            "comm_hidden": float(ev.get("comm_hidden_ms", 0.0)),
            "comm_exposed": float(ev.get("comm_exposed_ms", 0.0)),
            "host_gap": float(ev.get("host_gap_ms", 0.0))}


def covers_step(ev: Dict[str, Any], step: int) -> bool:
    """Does this profile attribute the given step? True when the window
    [start_step, stop_step) contains it OR the capture was TRIGGERED by
    the anomaly at that step (an anomaly-armed window records the steps
    immediately after its trigger — that capture is the device-side
    evidence for the triggering step, and refusing to associate them
    would strand exactly the trace the trigger existed to record)."""
    if ev.get("trigger_step") == step:
        return True
    start, stop = ev.get("start_step"), ev.get("stop_step")
    try:
        return start is not None and stop is not None \
            and int(start) <= int(step) < int(stop)
    except (TypeError, ValueError):
        return False
