"""telemetry/ — unified structured run telemetry with a crash-surviving
flight recorder (ISSUE 8).

The reference promises "gradient sync profiling and scaling experiments"
(README.md:23,:35) but ships scattered one-off instruments; here every
instrument feeds ONE stream:

* :class:`~.recorder.Recorder` — process-local typed events (host-side
  spans, counters, gauges, anomalies) appended to a schema-versioned JSONL
  (``telemetry_rank0.jsonl``, fsync'd on a cadence) AND kept in a bounded
  in-memory ring buffer;
* the **flight recorder** (:mod:`.flight`) — on any abnormal exit
  (Deathwatch lethal probe, Supervisor retry/abort, chaos crash/sigterm,
  unhandled exception) the ring's last N events + the exit cause are
  flushed to ``flight_<ts>.json``, so every rc=70 / rc!=0 leaves a
  postmortem artifact even when the JSONL's tail was lost;
* the **anomaly watchdog** (:mod:`.watchdog`) — non-finite loss,
  step-time spikes vs a rolling median, loader-stall detection, each an
  ``anomaly`` event with an optional abort hook (off by default);
* the ``telemetry`` CLI (:mod:`.__main__`) — ``summary`` (per-phase time
  split + throughput + wire-byte totals, with crash-truncated partial
  epochs reported explicitly), ``tail`` (``-f`` follows a live stream
  through rotation), ``aggregate`` (the fleet summary), and
  ``export --perfetto`` (host spans as Chrome trace-event JSON that loads
  alongside an XLA trace in Perfetto; multiple streams stitch into one
  timeline with a stable pid per (gen, rank));
* the **fleet plane** (ISSUE 14): per-rank streams
  (``telemetry_rank<R>.jsonl``, rank 0 by default, every rank under the
  ``--telemetry-all-ranks`` opt-in; every event stamped with its
  gen/rank identity), cross-stream aggregation with a straggler
  detector that rank- AND phase-attributes divergence
  (:mod:`.aggregate`), and a stdlib-only live ``/metrics`` +
  ``/healthz`` HTTP surface fed by an observer on the recorder
  (:mod:`.metrics_http`; zero threads when off).

Design constraints (enforced, not aspirational):

* **Host-side only.** Instrumentation lives around dispatched steps, never
  inside traced code — the ``telemetry-emit-outside-traced`` AST rule
  (analysis/ast_rules.py) forbids Recorder calls in jit/shard_map bodies,
  and a tier-1 test pins that the lowered HLO of a telemetry-on and
  telemetry-off run is IDENTICAL (PARITY.md: telemetry adds surfaces,
  never changes training numerics).
* **Zero cost when unconfigured.** The module-level emit helpers check one
  global and return; no file, no ring, no timestamps.
* **No jax at module scope.** The flight recorder must be callable from
  resilience/heartbeat.py (which refuses to initialize a backend) and
  from the bench driver before any backend exists.
"""

from __future__ import annotations

from .recorder import (  # noqa: F401
    ALL_RANKS_ENV,
    CONTROL_DECISION_KIND,
    FLEET_GENERATION_ENV,
    FLEET_RANK_ENV,
    REGISTERED_SPAN_NAMES,
    SCHEMA_VERSION,
    NullSpan,
    Recorder,
    all_ranks_enabled,
    configure,
    counter,
    emit,
    gauge,
    generation_identity,
    get,
    is_configured,
    rank_identity,
    reset,
    should_stream,
    span,
    span_event,
    stream_filename,
)
from .flight import flush_flight, install_excepthook  # noqa: F401
from .watchdog import AnomalyAbort, AnomalyWatchdog  # noqa: F401

# The live-surface names resolve lazily (PEP 562): metrics_http's cost
# contract is that the OFF path never even imports it — the recorder,
# flight recorder, and every jax-free CLI reader import this package
# without paying for http.server, and the first actual use (train.py's
# port wiring, a test) triggers the real import.
_METRICS_EXPORTS = frozenset({
    "METRICS_PORT_ENV", "MetricsServer", "FederationServer",
    "get_metrics_server", "resolve_metrics_port",
    "start_metrics_server", "stop_metrics_server",
})


def __getattr__(name: str):
    if name in _METRICS_EXPORTS:
        from . import metrics_http

        return getattr(metrics_http, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
