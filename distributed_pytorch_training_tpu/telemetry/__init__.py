"""telemetry/ — unified structured run telemetry with a crash-surviving
flight recorder (ISSUE 8).

The reference promises "gradient sync profiling and scaling experiments"
(README.md:23,:35) but ships scattered one-off instruments; here every
instrument feeds ONE stream:

* :class:`~.recorder.Recorder` — process-local typed events (host-side
  spans, counters, gauges, anomalies) appended to a schema-versioned JSONL
  (``telemetry_rank0.jsonl``, fsync'd on a cadence) AND kept in a bounded
  in-memory ring buffer;
* the **flight recorder** (:mod:`.flight`) — on any abnormal exit
  (Deathwatch lethal probe, Supervisor retry/abort, chaos crash/sigterm,
  unhandled exception) the ring's last N events + the exit cause are
  flushed to ``flight_<ts>.json``, so every rc=70 / rc!=0 leaves a
  postmortem artifact even when the JSONL's tail was lost;
* the **anomaly watchdog** (:mod:`.watchdog`) — non-finite loss,
  step-time spikes vs a rolling median, loader-stall detection, each an
  ``anomaly`` event with an optional abort hook (off by default);
* the ``telemetry`` CLI (:mod:`.__main__`) — ``summary`` (per-phase time
  split + throughput + wire-byte totals), ``tail``, and
  ``export --perfetto`` (host spans as Chrome trace-event JSON that loads
  alongside an XLA trace in Perfetto).

Design constraints (enforced, not aspirational):

* **Host-side only.** Instrumentation lives around dispatched steps, never
  inside traced code — the ``telemetry-emit-outside-traced`` AST rule
  (analysis/ast_rules.py) forbids Recorder calls in jit/shard_map bodies,
  and a tier-1 test pins that the lowered HLO of a telemetry-on and
  telemetry-off run is IDENTICAL (PARITY.md: telemetry adds surfaces,
  never changes training numerics).
* **Zero cost when unconfigured.** The module-level emit helpers check one
  global and return; no file, no ring, no timestamps.
* **No jax at module scope.** The flight recorder must be callable from
  resilience/heartbeat.py (which refuses to initialize a backend) and
  from the bench driver before any backend exists.
"""

from __future__ import annotations

from .recorder import (  # noqa: F401
    SCHEMA_VERSION,
    NullSpan,
    Recorder,
    configure,
    counter,
    emit,
    gauge,
    get,
    is_configured,
    reset,
    span,
    span_event,
)
from .flight import flush_flight, install_excepthook  # noqa: F401
from .watchdog import AnomalyAbort, AnomalyWatchdog  # noqa: F401
