"""Recorder: the process-local typed event stream.

Event model (``SCHEMA_VERSION`` stamps every line; the first line of every
JSONL is a ``meta`` event carrying the run context):

=========  ==============================================================
kind       meaning / required fields
=========  ==============================================================
meta       stream header: schema, run_id, pid, argv hint
span       one timed host-side region: ``name``, ``t0`` (wall seconds at
           entry), ``dur_ms``. Canonical names: ``data_wait``,
           ``step_dispatch``, ``device_sync``, ``eval``, ``save_blocked``,
           ``restore`` — free-form names are legal, the canonical set is
           what ``telemetry summary`` buckets into the step-time split.
counter    monotonic count/total: ``name``, ``value`` (summed by summary)
gauge      instantaneous level: ``name``, ``value`` (last-wins)
anomaly    watchdog detection: ``name`` + detection detail
event      anything else worth a timestamped line (probe failures,
           restarts, preemptions)
exit       the flight recorder's cause record (also the flight file body)
=========  ==============================================================

Durability: every emit appends one JSON line; the file handle is flushed
per line and ``os.fsync``'d on a cadence (``fsync_every_s``) plus at
``flush()``/``close()`` — a crash loses at most the last cadence window of
OS-buffered lines, and the flight recorder's explicitly-fsync'd
``flight_*.json`` carries the ring's tail regardless.

This module imports neither jax nor anything from the package that does:
arming telemetry must never initialize a backend (the heartbeat
constraint), and the CLI must read streams on machines with no accelerator
stack at all. Process-0 gating is therefore the CALLER's job — train.py
gates on :func:`should_stream` (rank 0 always; other ranks only under the
``--telemetry-all-ranks`` / ``DPT_TELEMETRY_ALL_RANKS`` opt-in, so the
default run's disk cost is one stream) and names the file
:func:`stream_filename` (``telemetry_rank<R>.jsonl``).

Rank identity (ISSUE 14): a recorder knows WHICH stream it is. The fleet
orchestrator (resilience/fleet.py) stamps ``DPT_FLEET_GENERATION`` /
``DPT_FLEET_RANK`` into every child's env; outside a fleet the caller
passes the jax process index as the fallback (this module stays jax-free,
so it can only receive it). Every event carries ``gen``/``rank`` fields —
that is the v2 schema change — so N streams merge attributably
(telemetry/aggregate.py) even when generations share one appended file.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Deque, Dict, List, Optional

from ..utils.locktrace import named_lock

# v2 (ISSUE 14): every event (meta included) carries `gen`/`rank`. Readers
# accept v1 streams — a missing gen/rank reads as 0/0 (the aggregator's
# normalization), and `summarize` never keyed on the version.
SCHEMA_VERSION = 2

# The fleet-context env names (the orchestrator is the writer, this module
# and the flight recorder are the readers — one definition, re-exported by
# telemetry/flight.py for the orchestrator's import).
FLEET_GENERATION_ENV = "DPT_FLEET_GENERATION"
FLEET_RANK_ENV = "DPT_FLEET_RANK"

# Non-zero-rank streaming opt-in: rank 0 always streams; other ranks only
# when this env (or the --telemetry-all-ranks flag feeding it) says so —
# the default run writes exactly one telemetry_rank0.jsonl, unchanged.
ALL_RANKS_ENV = "DPT_TELEMETRY_ALL_RANKS"

# Canonical span names `telemetry summary` buckets into the step-time
# split. Free-form names are legal; these are the contract.
SPAN_NAMES = ("data_wait", "step_dispatch", "device_sync", "eval",
              "save_blocked", "restore")

# The serving phases (serving/): how long a request queued, the prefill
# and decode dispatch walls, and the shutdown drain. `telemetry summary`
# buckets these exactly like the training phases — a serving stream's
# latency story decomposes instead of lumping into "unaccounted".
# The continuous-batching path (ISSUE 17) adds two host-side phases:
# `slot_wait` (popped from the queue -> admitted into a slot — the
# pool/page-pressure share of latency, distinct from queue_wait's
# load share) and `router_dispatch` (the multi-replica router's pick +
# submit wall, including health probes). The speculative path (ISSUE 19)
# adds three more: `draft_decode` (draft prefill + propose-round
# dispatch), `spec_verify` (the K+1-window target forward), and
# `prefill_skip` (a prefix-resident admission that dispatched NO
# prefill — its near-zero wall IS the TTFT win, and its count is the
# zero-dispatch census the skip test pins).
SERVING_SPAN_NAMES = ("queue_wait", "prefill", "decode", "drain",
                      "slot_wait", "router_dispatch", "draft_decode",
                      "spec_verify", "prefill_skip")

# The elastic phases (ISSUEs 11 + 12): mesh re-planning after a replica
# death, the checkpoint reshard (N -> M re-slice), the grow-side live
# reshard when preempted capacity returns (`elastic_grow`), and the
# Supervisor's segment-boundary capacity polls (`capacity_watch`).
# Bucketed by `telemetry summary` like every other canonical phase
# instead of lumping into "unaccounted". The `compile` span (the serving
# engine's per-program AOT instrument — with the persistent compile cache
# on it collapses to cache-load time, the restart-downtime win) is
# deliberately NOT in this accounting list: a lazy compile runs INSIDE
# the prefill/decode/step_dispatch span that triggered it, so summing it
# as its own phase would double-count the same wall time; it stays
# visible in the summary's spans table under its own name.
ELASTIC_SPAN_NAMES = ("elastic_replan", "elastic_reshard", "elastic_grow",
                      "capacity_watch")

# Registered-but-unaccounted span names: visible in the spans table, never
# summed into the step-time split (the `compile` double-count rationale
# above). Together the five tuples are THE span-name registry — the
# `span-names-registered` AST rule (analysis/ast_rules.py) flags any
# in-repo emission whose literal name is not in it, because `telemetry
# summary` silently buckets unknown names into "unaccounted": a typo'd
# span name would vanish from the split instead of failing loudly.
AUX_SPAN_NAMES = ("compile",)

# The control-plane phases (ISSUE 20): `control_apply` wraps one
# `control.apply_decision` — the sole sanctioned entry from policy to the
# Supervisor's re-plan surface — and `control_retune` wraps the
# Supervisor's segment-boundary config re-plan (the online tuner's
# apply). Like `compile`, these run INSIDE the segment wall they act on,
# so they are registered-but-unaccounted: visible in the spans table,
# never summed into the step-time split.
CONTROL_SPAN_NAMES = ("control_apply", "control_retune")

REGISTERED_SPAN_NAMES = (SPAN_NAMES + SERVING_SPAN_NAMES
                         + ELASTIC_SPAN_NAMES + AUX_SPAN_NAMES
                         + CONTROL_SPAN_NAMES)

# Event kind of one ControlDecision record (control/decisions.py): the
# policy layer's typed decisions ride the same stream as every other
# instrument — `telemetry summary` renders them, metrics_http counts
# them as `dpt_control_decisions_total{action}`. Defined here (not in
# control/) so the jax-free telemetry readers never import the policy
# layer.
CONTROL_DECISION_KIND = "control_decision"


# ---------------------------------------------------------------------------
# Rank identity (ISSUE 14): which stream is this process?
# ---------------------------------------------------------------------------


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def generation_identity() -> int:
    """The fleet launch generation (``DPT_FLEET_GENERATION``), 0 outside a
    fleet — gen 0 IS the un-orchestrated run's identity, not a sentinel."""
    return _env_int(FLEET_GENERATION_ENV, 0)


def rank_identity(process_index: Optional[int] = None) -> int:
    """The stream rank: the fleet env stamp wins (``DPT_FLEET_RANK``),
    else the caller-provided jax process index (this module cannot import
    jax to ask), else 0."""
    env_rank = os.environ.get(FLEET_RANK_ENV)
    if env_rank is not None:
        try:
            return int(env_rank)
        except ValueError:
            pass
    return int(process_index) if process_index is not None else 0


def stream_filename(rank: int = 0) -> str:
    """``telemetry_rank<R>.jsonl`` — rank 0 keeps the historical name, so
    every existing reader/doc/test path stays valid."""
    return f"telemetry_rank{int(rank)}.jsonl"


def all_ranks_enabled(flag: bool = False) -> bool:
    """The non-zero-rank streaming opt-in: an explicit CLI flag OR a
    truthy ``DPT_TELEMETRY_ALL_RANKS`` (the fleet orchestrator's way to
    arm children it cannot pass flags to)."""
    if flag:
        return True
    raw = os.environ.get(ALL_RANKS_ENV, "").strip().lower()
    return raw not in ("", "0", "false", "no", "off")


def should_stream(rank: int, all_ranks: bool = False) -> bool:
    """Rank 0 always streams; other ranks only under the opt-in — the
    default run's disk cost (one JSONL) is unchanged by construction."""
    return rank == 0 or all_ranks_enabled(all_ranks)


class Recorder:
    """Append-only JSONL + bounded ring buffer of typed events.

    ``path=None`` keeps a ring-only recorder (tests; flight-only use).
    All emit paths are thread-safe: the checkpoint writer thread, the
    loader producer thread, and the deathwatch thread all emit into the
    same stream as the main loop.
    """

    def __init__(self, path: Optional[str] = None, ring_size: int = 512,
                 fsync_every_s: float = 2.0, run_id: Optional[str] = None,
                 meta: Optional[Dict[str, Any]] = None,
                 gen: Optional[int] = None, rank: Optional[int] = None):
        self.path = Path(path) if path is not None else None
        self.ring: Deque[dict] = collections.deque(maxlen=max(1, ring_size))  # guarded-by: _lock
        self.run_id = run_id or f"run-{os.getpid()}-{int(time.time())}"
        # stream identity (v2): env stamps win, explicit args override —
        # stamped on EVERY event so merged/append-shared files stay
        # attributable line by line
        self.gen = int(gen) if gen is not None else generation_identity()
        self.rank = int(rank) if rank is not None else rank_identity()
        self._fsync_every_s = fsync_every_s
        self._last_fsync = time.monotonic()   # guarded-by: _lock
        self._lock = named_lock("Recorder._lock")
        self._fh = None                       # guarded-by: _lock
        # observers (telemetry/metrics_http.py): called with each event
        # AFTER it is recorded, outside the stream lock (an observer
        # taking its own lock must never be able to deadlock an emit).
        # Empty on every run without a live surface — one list check.
        self._observers: List[Callable[[dict], None]] = []  # guarded-by: _lock
        self.n_events = 0                     # guarded-by: _lock
        if self.path is not None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        self.emit("meta", "stream", schema=SCHEMA_VERSION,
                  run_id=self.run_id, pid=os.getpid(),
                  **(meta or {}))

    # -- core ------------------------------------------------------------

    def emit(self, kind: str, name: str, **fields: Any) -> dict:
        """Append one event to the ring (always) and the JSONL (if open)."""
        ev = {"v": SCHEMA_VERSION, "ts": time.time(), "kind": kind,
              "name": name, "gen": self.gen, "rank": self.rank}
        ev.update(fields)
        with self._lock:
            self.ring.append(ev)
            self.n_events += 1
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(ev, sort_keys=True,
                                              default=str) + "\n")
                    self._fh.flush()
                    now = time.monotonic()
                    if now - self._last_fsync >= self._fsync_every_s:
                        os.fsync(self._fh.fileno())
                        self._last_fsync = now
                except (OSError, ValueError):
                    # a full/readonly disk (or a handle closed under us)
                    # must never take the training run down with it
                    pass
            observers = list(self._observers) if self._observers else None
        if observers:
            for obs in observers:
                try:
                    obs(ev)
                except Exception:  # noqa: BLE001 — a broken live surface
                    pass           # must never take the run down with it
        return ev

    # -- observers (the live /metrics surface) ----------------------------

    def add_observer(self, fn: Callable[[dict], None]) -> None:
        """Register a per-event callback (metrics_http's state feed).
        Observers run outside the stream lock and MUST NOT emit."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)

    def remove_observer(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)

    # -- typed helpers ----------------------------------------------------

    def span_event(self, name: str, dur_s: float, **attrs: Any) -> dict:
        """A span whose duration the CALLER measured (the hot-loop form:
        one perf_counter pair at the call site, no context-manager
        overhead). ``t0`` is reconstructed as now - dur."""
        return self.emit("span", name, t0=time.time() - dur_s,
                         dur_ms=round(dur_s * 1e3, 4), **attrs)

    def span(self, name: str, **attrs: Any) -> "_Span":
        return _Span(self, name, attrs)

    def counter(self, name: str, value: float, **attrs: Any) -> dict:
        return self.emit("counter", name, value=value, **attrs)

    def gauge(self, name: str, value: float, **attrs: Any) -> dict:
        return self.emit("gauge", name, value=value, **attrs)

    def anomaly(self, name: str, **fields: Any) -> dict:
        return self.emit("anomaly", name, **fields)

    # -- lifecycle ---------------------------------------------------------

    def tail(self, n: int = 50) -> List[dict]:
        with self._lock:
            return list(self.ring)[-n:]

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    self._last_fsync = time.monotonic()
                except (OSError, ValueError):
                    pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError):
                    pass
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    @property
    def directory(self) -> Optional[Path]:
        """Where flight files land (the JSONL's directory), or None for a
        ring-only recorder (flights then need an explicit directory)."""
        return self.path.parent if self.path is not None else None


class _Span:
    """Context manager measuring one host-side region with perf_counter
    (monotonic — an NTP step mid-span cannot corrupt the duration; the
    event's wall ``t0`` is for cross-log alignment only)."""

    def __init__(self, recorder: Recorder, name: str, attrs: Dict[str, Any]):
        self._rec = recorder
        self._name = name
        self._attrs = attrs
        self._t0_wall = 0.0
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        self._t0_wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        dur = time.perf_counter() - self._t0
        self._rec.emit("span", self._name, t0=self._t0_wall,
                       dur_ms=round(dur * 1e3, 4),
                       **({"error": f"{exc_type.__name__}"}
                          if exc_type is not None else {}),
                       **self._attrs)


class NullSpan:
    """The unconfigured path's span: enters and exits for free."""

    def __enter__(self) -> "NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_SPAN = NullSpan()

# ---------------------------------------------------------------------------
# The process-global recorder: one stream per process, installed by the
# entry point (train.py / bench.py / the chaos CLI), consumed by every
# instrumented layer through the no-op-when-unconfigured helpers below.
# ---------------------------------------------------------------------------

_RECORDER: Optional[Recorder] = None


def configure(path: Optional[str] = None, **kwargs: Any) -> Recorder:
    """Install the process-global recorder (closing any previous one)."""
    global _RECORDER
    if _RECORDER is not None:
        _RECORDER.close()
    _RECORDER = Recorder(path, **kwargs)
    return _RECORDER


def reset() -> None:
    """Drop the global recorder (tests; end-of-run cleanup)."""
    global _RECORDER
    if _RECORDER is not None:
        _RECORDER.close()
    _RECORDER = None


def get() -> Optional[Recorder]:
    return _RECORDER


def is_configured() -> bool:
    return _RECORDER is not None


def emit(kind: str, name: str, **fields: Any) -> None:
    if _RECORDER is not None:
        _RECORDER.emit(kind, name, **fields)


def span(name: str, **attrs: Any):
    """Context-manager span on the global recorder; free when off."""
    if _RECORDER is None:
        return _NULL_SPAN
    return _RECORDER.span(name, **attrs)


def span_event(name: str, dur_s: float, **attrs: Any) -> None:
    if _RECORDER is not None:
        _RECORDER.span_event(name, dur_s, **attrs)


def counter(name: str, value: float, **attrs: Any) -> None:
    if _RECORDER is not None:
        _RECORDER.counter(name, value, **attrs)


def gauge(name: str, value: float, **attrs: Any) -> None:
    if _RECORDER is not None:
        _RECORDER.gauge(name, value, **attrs)
