"""Mixture-of-Experts MLP with expert parallelism over the mesh ``expert`` axis.

No analogue in the reference (ResNet-only; SURVEY.md §2c "EP: absent — note as
extension"); this is the extension, built the TPU way: token-choice top-k
routing with fixed capacity per expert, so every shape is static and the
expert matmuls are einsums XLA tiles onto the MXU. With the stacked expert
weights sharded ``P("expert", ...)``, XLA lowers the dispatch/return to
all-to-alls over the ``expert`` mesh axis — expert parallelism falls out of
layout, exactly like gradient sync falls out of batch sharding.

Two dispatch formulations behind one interface (``dispatch_mode``):

* ``"sorted"`` (default) — argsort assignments by expert id (stable,
  first-choice-major, so priority matches the k-round semantics), compute
  each assignment's rank within its expert segment, drop ranks >= capacity,
  then scatter-add tokens into the (E*C, d) expert buffer and gather-combine
  back. Memory is O(S*k) index vectors + the (E, C, d) buffers — no
  (B, S, E, C) tensor, so 32+ experts and S=4096 fit on one chip.
* ``"einsum"`` — the original dense one-hot dispatch/combine tensors
  ((B, S, E, C): linear in tokens but carrying the S x E x C blowup). Kept
  as the parity oracle; preferable only for tiny expert counts.

Load balancing: the standard Switch-Transformer auxiliary loss
(num_experts * Σ_e fraction_tokens_e * fraction_router_prob_e), sown into the
``"losses"`` collection; `MoeLanguageModelingTask` adds it to the CE loss.
Tokens overflowing an expert's capacity are dropped (their combine weight is
zero) — the residual path carries them unchanged, the standard behavior.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import EXPERT
from ..parallel.sharding import PartitionRules
from .layers import VocabPaddingMixin
from .registry import register_model
from jax.sharding import PartitionSpec as P

Dtype = Any


class MoeMlp(nn.Module):
    """Top-k token-choice MoE feed-forward (drop-in for MlpBlock)."""

    num_experts: int
    hidden_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    activation: Callable = nn.gelu
    router_noise: float = 0.0  # jitter std during training, 0 = off
    dispatch_mode: str = "sorted"  # "sorted" (scalable) | "einsum" (oracle)

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        # GShard-style GROUP-WISE dispatch: each batch row is a routing group
        # with its own capacity ceil(S*k/E * cf). Capacity scales with top_k:
        # k assignments are made per token, so total slots must cover S*k
        # routing decisions, not S.
        b, s, d = x.shape
        e = self.num_experts
        cap = max(1, int(np.ceil(s * self.top_k / e * self.capacity_factor)))

        router = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                          param_dtype=self.param_dtype, name="router")
        logits = router(x.astype(jnp.float32))  # (B, S, E), fp32 softmax
        if self.router_noise and not deterministic:
            key = self.make_rng("dropout")
            logits = logits + self.router_noise * jax.random.normal(
                key, logits.shape)
        probs = jax.nn.softmax(logits, axis=-1)

        wi = self.param("wi", nn.initializers.lecun_normal(batch_axis=(0,)),
                        (e, d, self.hidden_dim), self.param_dtype)
        wo = self.param("wo", nn.initializers.lecun_normal(batch_axis=(0,)),
                        (e, self.hidden_dim, d), self.param_dtype)

        if self.dispatch_mode == "sorted":
            xin, combine_fn, frac_tokens = self._dispatch_sorted(
                x, probs, b, s, d, e, cap)
        else:
            xin, combine_fn, frac_tokens = self._dispatch_einsum(
                x, probs, b, s, d, e, cap)

        # --- auxiliary load-balancing loss (Switch eq. 4, over all tokens) -
        frac_probs = probs.reshape(-1, e).mean(0)
        aux = e * jnp.sum(frac_tokens * frac_probs) / self.top_k
        self.sow("losses", "moe_aux", aux)

        # --- expert computation (stacked weights, EP via sharding) ---------
        h = self.activation(jnp.einsum("becd,edh->bech", xin,
                                       wi.astype(self.dtype)))
        out = jnp.einsum("bech,ehd->becd", h, wo.astype(self.dtype))
        return combine_fn(out)

    def _topk(self, probs, b, s, e):
        """(expert_ids, gates) per assignment, flattened FIRST-CHOICE-MAJOR
        (all k=0 assignments before any k=1), matching the round-robin
        priority of the einsum oracle's k-round loop."""
        gates, choice = jax.lax.top_k(probs, self.top_k)  # (B, S, K)
        eids = choice.transpose(0, 2, 1).reshape(b, self.top_k * s)
        gvals = gates.transpose(0, 2, 1).reshape(b, self.top_k * s)
        return eids.astype(jnp.int32), gvals

    def _dispatch_sorted(self, x, probs, b, s, d, e, cap):
        """Sort-based dispatch: rank each assignment within its expert via a
        stable argsort, drop ranks >= capacity, scatter tokens into the
        (E*C, d) buffer. No (B, S, E, C) tensor anywhere (VERDICT r3 #8)."""
        n = self.top_k * s
        eids, gates = self._topk(probs, b, s, e)  # (B, N)

        # rank of each assignment within its expert segment
        sort_idx = jnp.argsort(eids, axis=-1, stable=True)  # (B, N)
        sorted_e = jnp.take_along_axis(eids, sort_idx, axis=-1)
        counts = jnp.sum(jax.nn.one_hot(eids, e, dtype=jnp.int32), axis=1)
        starts = jnp.concatenate(
            [jnp.zeros((b, 1), jnp.int32),
             jnp.cumsum(counts, axis=-1)[:, :-1]], axis=-1)  # (B, E)
        ranks_sorted = (jnp.arange(n, dtype=jnp.int32)[None, :]
                        - jnp.take_along_axis(starts, sorted_e, axis=-1))
        inv = jnp.argsort(sort_idx, axis=-1, stable=True)
        ranks = jnp.take_along_axis(ranks_sorted, inv, axis=-1)  # (B, N)

        kept = ranks < cap
        # overflow assignments land in a sacrificial bin at E*cap
        dest = jnp.where(kept, eids * cap + ranks, e * cap)  # (B, N)

        tok = jnp.arange(n, dtype=jnp.int32) % s  # k-major: token of slot n
        x_gath = x.astype(self.dtype)[:, tok]  # (B, N, d)
        brow = jnp.arange(b, dtype=jnp.int32)[:, None]
        xin_flat = jnp.zeros((b, e * cap + 1, d), self.dtype
                             ).at[brow, dest].add(x_gath)
        xin = xin_flat[:, :e * cap].reshape(b, e, cap, d)

        kept_onehot = (jax.nn.one_hot(eids, e, dtype=jnp.float32)
                       * kept[..., None].astype(jnp.float32))
        frac_tokens = kept_onehot.sum(1).mean(0) / s  # == mean over (B*S)

        def combine_fn(out):  # out: (B, E, C, d)
            out_flat = jnp.concatenate(
                [out.reshape(b, e * cap, d),
                 jnp.zeros((b, 1, d), out.dtype)], axis=1)
            y_n = out_flat[brow, dest]  # (B, N, d); overflow bin reads zeros
            y_n = y_n * gates[..., None].astype(self.dtype)
            return y_n.reshape(b, self.top_k, s, d).sum(1)

        return xin, combine_fn, frac_tokens

    def _dispatch_einsum(self, x, probs, b, s, d, e, cap):
        """The original dense one-hot formulation — (B, S, E, C) dispatch/
        combine tensors. Parity oracle for the sorted path; carries the
        S x E x C memory bill, so use it only at small E."""
        combine = jnp.zeros((b, s, e, cap), jnp.float32)
        fill = jnp.zeros((b, e), jnp.int32)  # slots taken, per group
        remaining = probs
        total_dispatch = jnp.zeros((b, s, e), jnp.float32)
        for _ in range(self.top_k):
            choice = jnp.argmax(remaining, axis=-1)  # (B, S)
            onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # (B, S, E)
            gate = (probs * onehot).sum(-1)  # (B, S)
            # position of each token within its expert's buffer (per group):
            pos = (jnp.cumsum(onehot, axis=1) - 1.0) + fill[:, None, :]
            pos_tok = (pos * onehot).sum(-1).astype(jnp.int32)  # (B, S)
            keep = pos_tok < cap
            slot = jax.nn.one_hot(pos_tok, cap, dtype=jnp.float32)  # (B, S, C)
            disp = onehot * keep[..., None]  # (B, S, E)
            combine = combine + (gate[..., None, None] * disp[..., None]
                                 * slot[..., None, :])
            total_dispatch = total_dispatch + disp
            fill = fill + disp.sum(1).astype(jnp.int32)
            remaining = remaining * (1.0 - onehot)  # mask chosen expert

        frac_tokens = total_dispatch.reshape(-1, e).mean(0)
        dispatch = (combine > 0).astype(self.dtype)  # (B, S, E, C)
        xin = jnp.einsum("bsec,bsd->becd", dispatch,
                         x.astype(self.dtype))  # (B, E, C, d)

        def combine_fn(out):
            return jnp.einsum("bsec,becd->bsd", combine.astype(self.dtype),
                              out)

        return xin, combine_fn, frac_tokens


def moe_rules() -> PartitionRules:
    """Expert-parallel rules: stacked expert weights split over ``expert``;
    the router stays replicated (it is tiny and every token needs it)."""
    return PartitionRules([
        (r"moe/wi", P(EXPERT, None, None)),
        (r"moe/wo", P(EXPERT, None, None)),
    ])


class MoeTransformerBlock(nn.Module):
    """Pre-LN block with the MoE feed-forward in place of the dense MLP."""

    num_heads: int
    head_dim: int
    num_experts: int
    mlp_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    dropout_rate: float = 0.0
    layernorm_epsilon: float = 1e-5
    attention_fn: Optional[Callable] = None
    router_noise: float = 0.0
    dispatch_mode: str = "sorted"

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        from .layers import MultiHeadAttention, dot_product_attention

        ln_kw = dict(epsilon=self.layernorm_epsilon, dtype=self.dtype,
                     param_dtype=self.param_dtype)
        y = nn.LayerNorm(**ln_kw, name="ln1")(x)
        y = MultiHeadAttention(
            num_heads=self.num_heads, head_dim=self.head_dim,
            dtype=self.dtype, param_dtype=self.param_dtype,
            dropout_rate=self.dropout_rate,
            attention_fn=self.attention_fn or dot_product_attention,
            name="attn")(y, mask=mask, deterministic=deterministic)
        x = x + y
        y = nn.LayerNorm(**ln_kw, name="ln2")(x)
        y = MoeMlp(num_experts=self.num_experts, hidden_dim=self.mlp_dim,
                   top_k=self.top_k, capacity_factor=self.capacity_factor,
                   dtype=self.dtype, param_dtype=self.param_dtype,
                   router_noise=self.router_noise,
                   dispatch_mode=self.dispatch_mode,
                   name="moe")(y, deterministic=deterministic)
        return x + y


class GPT2MoELMHead(VocabPaddingMixin, nn.Module):
    """GPT-2-style causal LM with MoE feed-forwards on alternating layers
    (the Switch/GShard layout: dense and MoE blocks interleave)."""

    vocab_size: int = 50257
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2  # layer i is MoE iff i % moe_every == moe_every - 1
    max_position: int = 1024
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    layernorm_epsilon: float = 1e-5
    attention_fn: Optional[Callable] = None
    router_noise: float = 0.0
    dispatch_mode: str = "sorted"
    # jax.checkpoint the DENSE blocks only: MoE blocks sow the router
    # aux-loss into the "losses" collection, which remat would complicate;
    # half the layers is still half the activation memory.
    remat: bool = False
    # Megatron-style vocab padding for TP (see models/gpt2.py). 0 = exact.
    pad_vocab_to_multiple_of: int = 0

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, train: bool = False):
        from .layers import TransformerBlock, causal_mask, dot_product_attention

        b, s = input_ids.shape
        wte = nn.Embed(self.padded_vocab, self.hidden_dim, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       embedding_init=nn.initializers.normal(stddev=0.02),
                       name="wte")
        x = wte(input_ids)
        x = x + nn.Embed(self.max_position, self.hidden_dim, dtype=self.dtype,
                         param_dtype=self.param_dtype,
                         embedding_init=nn.initializers.normal(stddev=0.01),
                         name="wpe")(jnp.arange(s)[None, :])

        attn_fn = self.attention_fn or dot_product_attention
        uses_kernel = attn_fn is not dot_product_attention
        # kernel paths own causal structure — they get only the padding
        # mask (flash applies it blockwise); einsum gets causal & padding
        if uses_kernel:
            mask = (attention_mask[:, None, None, :].astype(bool)
                    if attention_mask is not None else None)
        else:
            mask = causal_mask(s)
            if attention_mask is not None:
                mask = mask & attention_mask[:, None, None, :].astype(bool)

        head_dim = self.hidden_dim // self.num_heads
        for i in range(self.depth):
            if i % self.moe_every == self.moe_every - 1:
                x = MoeTransformerBlock(
                    num_heads=self.num_heads, head_dim=head_dim,
                    num_experts=self.num_experts,
                    mlp_dim=4 * self.hidden_dim, top_k=self.top_k,
                    capacity_factor=self.capacity_factor, dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    layernorm_epsilon=self.layernorm_epsilon,
                    attention_fn=self.attention_fn,
                    router_noise=self.router_noise,
                    dispatch_mode=self.dispatch_mode,
                    name=f"block{i}")(x, mask=mask, deterministic=not train)
            else:
                dense_cls = (nn.remat(TransformerBlock) if self.remat
                             else TransformerBlock)
                x = dense_cls(
                    num_heads=self.num_heads, head_dim=head_dim,
                    mlp_dim=4 * self.hidden_dim, dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    layernorm_epsilon=self.layernorm_epsilon,
                    attention_fn=attn_fn,
                    name=f"block{i}")(x, mask=mask, deterministic=not train)

        x = nn.LayerNorm(epsilon=self.layernorm_epsilon, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_f")(x)
        from .layers import mask_vocab_padding

        return mask_vocab_padding(wte.attend(x).astype(jnp.float32),
                                  self.vocab_size)

    @staticmethod
    def partition_rules() -> PartitionRules:
        from .layers import tp_fsdp_rules

        return moe_rules() + tp_fsdp_rules()


@register_model("gpt2_moe")
def gpt2_moe(**kw) -> GPT2MoELMHead:
    """GPT-2-small-sized MoE LM (8 experts, top-2, MoE every other layer)."""
    return GPT2MoELMHead(**kw)
