"""Pipelined GPT-2: the LM driven through GPipe pipeline parallelism.

The reference has no pipeline (pure DDP, SURVEY.md §2c "PP: absent"); this is
the model-level integration of `parallel/pipeline.py` — a real transformer LM
whose blocks execute as pipeline stages over the mesh ``pipe`` axis, trained
with a real optimizer through the same Trainer/Task stack as every other
model (`--mesh pipe=N` in train.py).

Design (TPU-native, not a module-per-stage port):
* all ``depth`` TransformerBlocks share one structure, so their params are
  STACKED: each leaf has shape (n_stages, layers_per_stage, ...) with the
  leading axis sharded over ``pipe`` (partition_rules). One program, SPMD.
* embeddings / final LN / tied LM head live outside the pipeline and stay
  replicated (they are the smallest params; stage-0/stage-last placement is
  a further optimization).
* the forward is `pipeline_apply` (lax.scan over ticks + lax.ppermute ring);
  its autodiff produces the reverse schedule, so jax.grad of the loss just
  works — no hand-written backward schedule.

Matches the param-tree naming of models/gpt2.py `GPT2LMHead` (wte, wpe,
block ln1/attn/ln2/mlp, ln_f) so stacked-vs-sequential parity is directly
testable (tests/test_pipeline.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import PIPE
from ..parallel.pipeline import pipeline_apply
from ..parallel.sharding import PartitionRules
from jax.sharding import PartitionSpec as P
from .layers import TransformerBlock, causal_mask


@dataclasses.dataclass(frozen=True)  # hashable: apply is a jit-static field
class GPT2PipeLMHead:
    """GPT-2 with blocks executed as a GPipe pipeline over ``mesh['pipe']``.

    Not an nn.Module: the pipeline needs explicit control of the stacked
    param layout, so this is a thin model object exposing the same
    ``init(rng, ids, train)`` / ``apply(variables, ids, ...)`` surface the
    Trainer consumes.
    """

    mesh: Any
    num_microbatches: int = 2
    vocab_size: int = 50257
    hidden_dim: int = 1024
    depth: int = 24
    num_heads: int = 16
    max_position: int = 1024
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    layernorm_epsilon: float = 1e-5
    remat: bool = False  # jax.checkpoint each stage layer (HBM for FLOPs)

    def _block(self) -> TransformerBlock:
        return TransformerBlock(
            num_heads=self.num_heads,
            head_dim=self.hidden_dim // self.num_heads,
            mlp_dim=4 * self.hidden_dim,
            dtype=self.dtype, param_dtype=self.param_dtype,
            layernorm_epsilon=self.layernorm_epsilon)

    @property
    def n_stages(self) -> int:
        return self.mesh.shape[PIPE]

    # -- flax-compatible surface ------------------------------------------

    def init(self, rng: jax.Array, input_ids, train: bool = False) -> dict:
        del train
        if self.depth % self.n_stages:
            raise ValueError(f"depth {self.depth} not divisible into "
                             f"{self.n_stages} pipeline stages")
        k_wte, k_wpe, k_blocks = jax.random.split(rng, 3)
        d = self.hidden_dim
        wte = (0.02 * jax.random.normal(k_wte, (self.vocab_size, d))
               ).astype(self.param_dtype)
        wpe = (0.01 * jax.random.normal(k_wpe, (self.max_position, d))
               ).astype(self.param_dtype)

        block = self._block()
        sample = jnp.zeros((1, int(np.shape(input_ids)[-1]), d), self.dtype)
        keys = jax.random.split(k_blocks, self.depth)

        def init_one(key):
            return block.init(key, sample, mask=None, deterministic=True
                              )["params"]

        stacked = jax.vmap(init_one)(keys)  # leaves (depth, ...)
        # stage-major: (n_stages, depth/n_stages, ...) — axis 0 rides `pipe`
        stage_params = jax.tree_util.tree_map(
            lambda leaf: leaf.reshape(self.n_stages,
                                      self.depth // self.n_stages,
                                      *leaf.shape[1:]),
            stacked)
        params = {
            "wte": {"embedding": wte},
            "wpe": {"embedding": wpe},
            "blocks": stage_params,
            "ln_f": {"scale": jnp.ones((d,), self.param_dtype),
                     "bias": jnp.zeros((d,), self.param_dtype)},
        }
        return {"params": params}

    def apply(self, variables: dict, input_ids, train: bool = False,
              mutable: Optional[Any] = None, rngs: Optional[dict] = None):
        del rngs  # no dropout in the pipelined variant (rate 0)
        params = variables["params"]
        b, s = input_ids.shape
        x = jnp.take(params["wte"]["embedding"], input_ids, axis=0)
        x = x + params["wpe"]["embedding"][:s]
        x = x.astype(self.dtype)

        mask = causal_mask(s)
        block = self._block()

        def apply_layer(layer_params, h):
            return block.apply({"params": layer_params}, h, mask=mask,
                               deterministic=True)

        if self.remat:
            apply_layer = jax.checkpoint(apply_layer)
        x = pipeline_apply(apply_layer, params["blocks"], x, self.mesh,
                           self.num_microbatches)

        # final LN + tied head (fp32 logits, like GPT2LMHead)
        xf = x.astype(jnp.float32)
        mean = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        xn = (xf - mean) * jax.lax.rsqrt(var + self.layernorm_epsilon)
        xn = (xn * params["ln_f"]["scale"].astype(jnp.float32)
              + params["ln_f"]["bias"].astype(jnp.float32))
        logits = xn @ params["wte"]["embedding"].astype(jnp.float32).T
        if mutable is not None:
            return logits, {}
        return logits

    @staticmethod
    def partition_rules() -> PartitionRules:
        """Stage-stacked block leaves ride ``pipe`` on their leading axis
        (specs shorter than the leaf rank replicate the remaining dims);
        embeddings/LN replicate. The same table shards the optimizer
        moments, so each stage holds only its own layers' Adam state."""
        return PartitionRules([
            (r"blocks/", P(PIPE)),
        ])
