"""ViT-B/16 — the "ViT-B/16 / ImageNet bf16 (AMP-path parity)" config
(BASELINE.json:10). Torchvision-equivalent architecture (what the reference's
stack would provide): 16x16 conv patch embed, CLS token, learned positional
embeddings, 12 pre-LN blocks of width 768 / 12 heads / MLP 3072, LN + linear
head. torchvision vit_b_16(num_classes=1000) has 86,567,656 params — the
parity check in tests/test_models.py."""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from ..parallel.sharding import PartitionRules
from .layers import TransformerBlock, dot_product_attention, tp_fsdp_rules
from .registry import register_model


class ViT(nn.Module):
    num_classes: int = 1000
    patch_size: int = 16
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    layernorm_epsilon: float = 1e-6
    attention_fn: Callable = dot_product_attention
    remat: bool = False  # jax.checkpoint each block: HBM for recompute FLOPs

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        n = x.shape[0]
        x = nn.Conv(self.hidden_dim, (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name="patch_embed")(x)
        x = x.reshape(n, -1, self.hidden_dim)  # (N, S, D)

        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, self.hidden_dim), self.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (n, 1, self.hidden_dim)
                                              ).astype(self.dtype), x], axis=1)
        pos = self.param("pos_embedding",
                         nn.initializers.normal(stddev=0.02),
                         (1, x.shape[1], self.hidden_dim), self.param_dtype)
        x = x + pos.astype(self.dtype)

        block_cls = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                head_dim=self.hidden_dim // self.num_heads,
                mlp_dim=self.mlp_dim, dtype=self.dtype,
                param_dtype=self.param_dtype,
                dropout_rate=self.dropout_rate,
                layernorm_epsilon=self.layernorm_epsilon,
                attention_fn=self.attention_fn,
                name=f"block{i}",
            )(x, deterministic=not train)

        x = nn.LayerNorm(epsilon=self.layernorm_epsilon, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_final")(x)
        cls_out = x[:, 0]
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="head")(cls_out)
        return logits.astype(jnp.float32)

    @staticmethod
    def partition_rules() -> PartitionRules:
        return tp_fsdp_rules()


@register_model("vit_b16")
def vit_b16(num_classes: int = 1000, **kw) -> ViT:
    return ViT(num_classes=num_classes, **kw)
