"""Shared transformer building blocks (attention, MLP, embeddings).

No analogue exists in the reference (ResNet-only, /root/reference/train_ddp.py:154);
these serve the ViT/BERT/GPT-2 configs (BASELINE.json:9-12) that the
reference's dependency stack (torchvision/transformers model zoos) would
provide on GPU.

TP design (megatron-style over the mesh's ``model`` axis, SURVEY.md §2c):
* qkv projection kernels partitioned on the *output* (head) dim,
* attention-out and MLP-down kernels partitioned on the *input* dim,
so each device holds a head/neuron slice and XLA inserts exactly one
all-reduce per residual join. The rules live in `tp_fsdp_rules()`
(one table covers TP, FSDP, and their composition; trivial axes are inert).

The attention inner product is pluggable (`attention_fn`) so the Pallas
flash/ring kernels in `ops/` can replace the XLA einsum path per-config.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.collectives import copy_to_tp, reduce_from_tp
from ..parallel.mesh import FSDP, MODEL
from ..parallel.sharding import PartitionRules
from jax.sharding import PartitionSpec as P

Dtype = Any


class RowParallelDense(nn.Module):
    """Megatron row-parallel linear for the EXPLICIT TP forward (inside a
    shard_map with the ``model`` axis bound): the kernel's contracting
    (input) dims are a per-shard slice, the partial product is psum'd over
    the TP axis (`reduce_from_tp` — THE one forward psum per residual
    join), and the bias — a full, model-replicated parameter — is added
    AFTER the psum so it lands exactly once. Param paths match the GSPMD
    module's (``<name>/kernel``, ``<name>/bias``): the same checkpoint tree,
    just with the kernel holding this shard's rows."""

    features: int
    tp_axis: str
    n_contract_dims: int = 1  # trailing input dims contracted (DenseGeneral axis)
    use_bias: bool = True
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        nd = self.n_contract_dims
        contract_shape = x.shape[-nd:]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(
                in_axis=tuple(range(nd)), out_axis=-1),
            contract_shape + (self.features,), self.param_dtype)
        y = jax.lax.dot_general(
            x.astype(self.dtype), kernel.astype(self.dtype),
            ((tuple(range(x.ndim - nd, x.ndim)), tuple(range(nd))),
             ((), ())))
        y = reduce_from_tp(y, self.tp_axis)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y


def dot_product_attention(
    q: jnp.ndarray,  # (B, S, H, D)
    k: jnp.ndarray,  # (B, T, H, D)
    v: jnp.ndarray,  # (B, T, H, D)
    mask: Optional[jnp.ndarray] = None,  # broadcastable to (B, H, S, T), True=attend
    dtype: Dtype = jnp.float32,
) -> jnp.ndarray:
    """Reference XLA attention: softmax(QK^T/sqrt(d))V. Softmax in fp32 for
    bf16 stability; output cast back to `dtype`."""
    d = q.shape[-1]
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
    logits = logits / np.sqrt(d).astype(np.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bhst,bthd->bshd", weights, v)


def decode_dot_product_attention(
    q: jnp.ndarray,  # (B, 1, H, D) — the single new token
    k: jnp.ndarray,  # (B, T, H, D) — the KV cache
    v: jnp.ndarray,  # (B, T, H, D)
    mask: Optional[jnp.ndarray] = None,  # (B, 1, 1, T), True=attend
    dtype: Dtype = jnp.float32,
) -> jnp.ndarray:
    """`dot_product_attention` for the one-token decode step, formulated so
    its fp32 output is BITWISE-equal to the corresponding row of the full
    forward on the CPU mesh (the serving parity pin, PARITY.md).

    Same math, one deliberate difference: the weights x V contraction runs
    through an explicit `lax.dot_general` with (B, H) batch dims. The
    einsum form ``bhst,bthd->bshd`` lowers to a GEMV for s=1 whose
    accumulation order differs from the s=S GEMM's — ~1e-7-level
    reassociation noise that would break the decode-vs-full bitwise parity
    contract. The dot_general form accumulates like the GEMM row does
    (pinned empirically by tests/test_serving.py; the QK^T einsum and the
    softmax are already row-stable at s=1, so they stay as-is)."""
    d = q.shape[-1]
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
    logits = logits / np.sqrt(d).astype(np.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1).astype(dtype)  # (B, H, 1, T)
    out = jax.lax.dot_general(
        weights, v.transpose(0, 2, 1, 3),
        (((3,), (2,)), ((0, 1), (0, 1))))  # (B, H, 1, D)
    return out.transpose(0, 2, 1, 3)


class MultiHeadAttention(nn.Module):
    """Self-attention with fused qkv projection.

    `attention_fn(q, k, v, mask, dtype)` defaults to the XLA einsum path;
    swap in `ops.flash_attention` / `ops.ring_attention` for long context.

    Explicit TP (``tp_size`` > 1, inside a shard_map binding ``tp_axis``):
    megatron column/row split — the qkv projection holds this shard's
    ``num_heads / tp_size`` heads (column-parallel, `copy_to_tp` at its
    input so the backward sums the per-shard cotangents), attention runs on
    the local heads, and the out projection is `RowParallelDense` (one
    forward psum per residual join, bias added once after it). Param tree
    paths are unchanged; kernel/bias SHAPES hold the local slice, exactly
    the `tp_fsdp_rules()` model-axis dims — the passive GSPMD constraints
    read as the explicit layout contract.

    KV cache (serving/): ``cache=(k, v)`` of shape (B, T, H, D) engages the
    incremental-decoding path and the call returns ``(out, new_cache)``.
    Two cache writes exist:

    * prefill (``cache_positions=None``, S > 1 legal): the fresh k/v land
      in slots [0, S) and attention runs over the FRESH k/v with the
      caller's (causal) mask — exactly the no-cache computation, so
      prefill logits are the eval forward's logits bit-for-bit, with the
      cache fill as a side output.
    * decode (``cache_positions`` = per-row write index, S == 1): the new
      token's k/v land at each row's own position (a where-scatter, so
      rows at different prompt lengths advance independently with no
      recompile) and attention runs over the UPDATED cache under the
      caller's per-row validity mask.

    With ``cache=None`` the path is byte-identical to the pre-cache module
    (pinned by tests/test_serving.py's lowering test).
    """

    num_heads: int
    head_dim: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    dropout_rate: float = 0.0
    use_bias: bool = True
    attention_fn: Callable = dot_product_attention
    tp_size: int = 1
    tp_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True,
                 cache=None, cache_positions=None):
        features = self.num_heads * self.head_dim
        dense = functools.partial(nn.DenseGeneral, dtype=self.dtype,
                                  param_dtype=self.param_dtype,
                                  use_bias=self.use_bias)
        if self.tp_size > 1:
            return self._tp_call(x, mask, deterministic, cache, dense)
        qkv = dense(features=(3, self.num_heads, self.head_dim), name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        new_cache = None
        y = None
        if cache is not None:
            if self.attention_fn is not dot_product_attention:
                raise ValueError(
                    "KV-cache decoding needs the XLA attention path — the "
                    "kernel attention_fns own their causal structure and "
                    "take no cache (serve with --attention xla)")
            ck, cv = cache
            if cache_positions is None:
                # prefill: the S fresh rows fill slots [0, S); attention
                # runs over the FRESH k/v below (the eval computation)
                new_cache = (
                    jax.lax.dynamic_update_slice(
                        ck, k.astype(ck.dtype), (0, 0, 0, 0)),
                    jax.lax.dynamic_update_slice(
                        cv, v.astype(cv.dtype), (0, 0, 0, 0)))
            else:
                # decode: per-row scatter at each row's own position, then
                # attend over the updated cache (q is the single new token)
                hit = (jnp.arange(ck.shape[1])[None, :]
                       == cache_positions[:, None])[:, :, None, None]
                ck = jnp.where(hit, k.astype(ck.dtype), ck)
                cv = jnp.where(hit, v.astype(cv.dtype), cv)
                new_cache = (ck, cv)
                y = decode_dot_product_attention(q, ck, cv, mask=mask,
                                                 dtype=self.dtype)
        if y is None:
            y = self.attention_fn(q, k, v, mask=mask, dtype=self.dtype)
        if self.dropout_rate and not deterministic:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=False)
        out = nn.DenseGeneral(features=x.shape[-1], axis=(-2, -1),
                              dtype=self.dtype, param_dtype=self.param_dtype,
                              use_bias=self.use_bias, name="out")(y)
        return out if cache is None else (out, new_cache)

    def _tp_call(self, x, mask, deterministic, cache, dense):
        """The explicit-TP attention body (tp_size > 1): local head slice,
        one forward psum at the out projection."""
        if cache is not None:
            raise ValueError(
                "explicit TP attention has no KV-cache path — serve TP "
                "checkpoints via the GSPMD rules (--mesh model=N without "
                "--fsdp-explicit on the serving side)")
        if self.dropout_rate and not deterministic:
            raise ValueError(
                "explicit TP runs the dropout RNG stream replicated over "
                "the model axis; per-shard head slices would draw "
                "correlated masks — train explicit TP with dropout 0")
        if self.num_heads % self.tp_size:
            raise ValueError(
                f"num_heads={self.num_heads} not divisible by "
                f"tp_size={self.tp_size}")
        heads_local = self.num_heads // self.tp_size
        x = copy_to_tp(x, self.tp_axis)
        qkv = dense(features=(3, heads_local, self.head_dim),
                    name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        y = self.attention_fn(q, k, v, mask=mask, dtype=self.dtype)
        return RowParallelDense(
            features=x.shape[-1], tp_axis=self.tp_axis, n_contract_dims=2,
            use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype, name="out")(y)


class MlpBlock(nn.Module):
    """Transformer MLP. Explicit TP (``tp_size`` > 1): fc1 is
    column-parallel (this shard's ``hidden_dim / tp_size`` neurons, with
    its bias slice), fc2 is `RowParallelDense` — one forward psum per
    residual join, full bias added once after it."""

    hidden_dim: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    dropout_rate: float = 0.0
    activation: Callable = nn.gelu
    tp_size: int = 1
    tp_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        d = x.shape[-1]
        if self.tp_size > 1:
            if self.dropout_rate and not deterministic:
                raise ValueError(
                    "explicit TP runs the dropout RNG stream replicated "
                    "over the model axis; per-shard neuron slices would "
                    "draw correlated masks — train explicit TP with "
                    "dropout 0")
            if self.hidden_dim % self.tp_size:
                raise ValueError(
                    f"hidden_dim={self.hidden_dim} not divisible by "
                    f"tp_size={self.tp_size}")
            x = copy_to_tp(x, self.tp_axis)
            h = nn.Dense(self.hidden_dim // self.tp_size, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="fc1")(x)
            h = self.activation(h)
            return RowParallelDense(
                features=d, tp_axis=self.tp_axis, dtype=self.dtype,
                param_dtype=self.param_dtype, name="fc2")(h)
        h = nn.Dense(self.hidden_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="fc1")(x)
        h = self.activation(h)
        if self.dropout_rate and not deterministic:
            h = nn.Dropout(self.dropout_rate)(h, deterministic=False)
        out = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype,
                       name="fc2")(h)
        return out


class TransformerBlock(nn.Module):
    """Pre-LN transformer block (ViT/GPT-2 style; BERT overrides to post-LN)."""

    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    dropout_rate: float = 0.0
    layernorm_epsilon: float = 1e-5
    attention_fn: Callable = dot_product_attention
    tp_size: int = 1
    tp_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True,
                 cache=None, cache_positions=None):
        ln = functools.partial(nn.LayerNorm, epsilon=self.layernorm_epsilon,
                               dtype=self.dtype, param_dtype=self.param_dtype)
        y = ln(name="ln1")(x)
        y = MultiHeadAttention(
            num_heads=self.num_heads, head_dim=self.head_dim, dtype=self.dtype,
            param_dtype=self.param_dtype, dropout_rate=self.dropout_rate,
            attention_fn=self.attention_fn, name="attn",
            tp_size=self.tp_size, tp_axis=self.tp_axis,
        )(y, mask=mask, deterministic=deterministic, cache=cache,
          cache_positions=cache_positions)
        new_cache = None
        if cache is not None:
            y, new_cache = y
        x = x + y
        y = ln(name="ln2")(x)
        y = MlpBlock(hidden_dim=self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype,
                     dropout_rate=self.dropout_rate, name="mlp",
                     tp_size=self.tp_size, tp_axis=self.tp_axis,
                     )(y, deterministic=deterministic)
        return x + y if cache is None else (x + y, new_cache)


def padded_vocab_size(vocab_size: int, multiple: int) -> int:
    """Megatron-style vocab padding: the smallest multiple of `multiple`
    >= vocab_size. GPT-2's 50257 is indivisible by any TP degree, so the
    (vocab, d) embedding — the model's largest tensor — could never shard
    over the `model` axis without this (it would silently replicate, see
    parallel/sharding.feasible_spec). 0 or 1 disables padding."""
    if multiple <= 1:
        return vocab_size
    return -(-vocab_size // multiple) * multiple


class VocabPaddingMixin:
    """Shared accessors for Megatron-style vocab padding. Models declare the
    ``pad_vocab_to_multiple_of: int = 0`` field themselves (flax's dataclass
    transform requires fields on the Module subclass); this mixin supplies
    the derived quantities so the padding formula lives in one place."""

    @property
    def padded_vocab(self) -> int:
        return padded_vocab_size(self.vocab_size, self.pad_vocab_to_multiple_of)

    @property
    def vocab_pad_params(self) -> int:
        """Extra params introduced by vocab padding (for HF-exact reporting)."""
        return (self.padded_vocab - self.vocab_size) * self.hidden_dim


def mask_vocab_padding(logits: jnp.ndarray, vocab_size: int) -> jnp.ndarray:
    """Neutralize padded vocab columns: set their logits to the dtype min so
    softmax assigns them exactly zero probability (exp underflows to 0.0)
    and argmax never selects them. With that, CE loss / token accuracy over
    a padded head are bit-identical to the unpadded head."""
    padded = logits.shape[-1]
    if padded == vocab_size:
        return logits
    keep = jnp.arange(padded) < vocab_size
    return jnp.where(keep, logits, jnp.finfo(logits.dtype).min)


def causal_mask(seq_len: int) -> jnp.ndarray:
    """(1, 1, S, S) lower-triangular True=attend mask."""
    return jnp.tril(jnp.ones((seq_len, seq_len), bool))[None, None]


def padding_mask(attention_mask: jnp.ndarray) -> jnp.ndarray:
    """(B, T) 1=real token -> (B, 1, 1, T) attend mask."""
    return attention_mask[:, None, None, :].astype(bool)


def tp_fsdp_rules() -> PartitionRules:
    """The combined layout table every transformer here ships: megatron TP
    over ``model`` on the head/neuron dim + ZeRO-style FSDP over ``fsdp`` on
    the complementary (d_model) dim of the same kernels (SURVEY.md §2c; the
    promise at parallel/mesh.py `fsdp` axis).

    One table serves every mesh: an axis of size 1 contributes nothing, so
    pure DP (both axes 1) reproduces the DDP replicated layout, ``--mesh
    model=N`` is pure TP, ``--mesh fsdp=N`` is pure FSDP, and ``--mesh
    fsdp=M,model=N`` is 2-D parameter sharding.

    The EXPLICIT TP x FSDP step (ISSUE 13) reads this same table as its
    layout contract: `parallel.sharding.tp_split_dims` takes each leaf's
    model-axis dim from these specs, and the tp_size>1 module forms above
    compute with exactly those slices — the passive GSPMD constraints and
    the explicit layout cannot disagree.

    Because `shard_pytree` applies the same table to the optimizer state,
    the AdamW/SGD moments are sharded identically — the ZeRO-2/3 memory win.
    The batch is sharded over (data, fsdp) jointly (sharding.batch_spec), so
    fsdp devices also do data-parallel work; XLA inserts the per-layer
    all-gather (params) and reduce-scatter (grads) that a hand-written FSDP
    wrapper would schedule manually.
    """
    return PartitionRules([
        (r"attn/qkv/kernel", P(FSDP, None, MODEL, None)),
        (r"attn/qkv/bias", P(None, MODEL, None)),
        (r"attn/out/kernel", P(MODEL, None, FSDP)),
        (r"mlp/fc1/kernel", P(FSDP, MODEL)),
        (r"mlp/fc1/bias", P(MODEL)),
        (r"mlp/fc2/kernel", P(MODEL, FSDP)),
        (r"(token_embedding|wte)/embedding", P(MODEL, FSDP)),
        (r"(position_embedding|wpe)/embedding", P(None, FSDP)),
        (r"patch_embed/kernel", P(None, None, None, FSDP)),
        (r"(head|fc|mlm_dense)/kernel", P(FSDP, None)),
    ])
