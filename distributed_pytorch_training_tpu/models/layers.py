"""Shared transformer building blocks (attention, MLP, embeddings).

No analogue exists in the reference (ResNet-only, /root/reference/train_ddp.py:154);
these serve the ViT/BERT/GPT-2 configs (BASELINE.json:9-12) that the
reference's dependency stack (torchvision/transformers model zoos) would
provide on GPU.

TP design (megatron-style over the mesh's ``model`` axis, SURVEY.md §2c):
* qkv projection kernels partitioned on the *output* (head) dim,
* attention-out and MLP-down kernels partitioned on the *input* dim,
so each device holds a head/neuron slice and XLA inserts exactly one
all-reduce per residual join. The rules live in `tp_fsdp_rules()`
(one table covers TP, FSDP, and their composition; trivial axes are inert).

The attention inner product is pluggable (`attention_fn`) so the Pallas
flash/ring kernels in `ops/` can replace the XLA einsum path per-config.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.collectives import copy_to_tp, reduce_from_tp
from ..parallel.mesh import FSDP, MODEL
from ..parallel.sharding import PartitionRules
from jax.sharding import PartitionSpec as P

Dtype = Any


class RowParallelDense(nn.Module):
    """Megatron row-parallel linear for the EXPLICIT TP forward (inside a
    shard_map with the ``model`` axis bound): the kernel's contracting
    (input) dims are a per-shard slice, the partial product is psum'd over
    the TP axis (`reduce_from_tp` — THE one forward psum per residual
    join), and the bias — a full, model-replicated parameter — is added
    AFTER the psum so it lands exactly once. Param paths match the GSPMD
    module's (``<name>/kernel``, ``<name>/bias``): the same checkpoint tree,
    just with the kernel holding this shard's rows."""

    features: int
    tp_axis: str
    n_contract_dims: int = 1  # trailing input dims contracted (DenseGeneral axis)
    use_bias: bool = True
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        nd = self.n_contract_dims
        contract_shape = x.shape[-nd:]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(
                in_axis=tuple(range(nd)), out_axis=-1),
            contract_shape + (self.features,), self.param_dtype)
        y = jax.lax.dot_general(
            x.astype(self.dtype), kernel.astype(self.dtype),
            ((tuple(range(x.ndim - nd, x.ndim)), tuple(range(nd))),
             ((), ())))
        y = reduce_from_tp(y, self.tp_axis)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y


def dot_product_attention(
    q: jnp.ndarray,  # (B, S, H, D)
    k: jnp.ndarray,  # (B, T, H, D)
    v: jnp.ndarray,  # (B, T, H, D)
    mask: Optional[jnp.ndarray] = None,  # broadcastable to (B, H, S, T), True=attend
    dtype: Dtype = jnp.float32,
) -> jnp.ndarray:
    """Reference XLA attention: softmax(QK^T/sqrt(d))V. Softmax in fp32 for
    bf16 stability; output cast back to `dtype`."""
    d = q.shape[-1]
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
    logits = logits / np.sqrt(d).astype(np.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bhst,bthd->bshd", weights, v)


def decode_dot_product_attention(
    q: jnp.ndarray,  # (B, S, H, D) — S=1 decode, S=K+1 verify window
    k: jnp.ndarray,  # (B, T, H, D) — the KV cache
    v: jnp.ndarray,  # (B, T, H, D)
    mask: Optional[jnp.ndarray] = None,  # (B, 1, S, T), True=attend
    dtype: Dtype = jnp.float32,
) -> jnp.ndarray:
    """`dot_product_attention` for the cached decode step, formulated so
    its fp32 output rows are BITWISE-equal to the corresponding rows of
    the full forward on the CPU mesh (the serving parity pin, PARITY.md).

    Same math, one deliberate difference: the weights x V contraction runs
    through an explicit `lax.dot_general` with (B, H) batch dims. The
    einsum form ``bhst,bthd->bshd`` lowers to a GEMV for s=1 whose
    accumulation order differs from the s=S GEMM's — ~1e-7-level
    reassociation noise that would break the decode-vs-full bitwise parity
    contract. The dot_general form accumulates like the GEMM row does
    (pinned empirically by tests/test_serving.py; the QK^T einsum and the
    softmax are already row-stable at s=1, so they stay as-is).

    The same formulation serves the speculative VERIFY window (S = K+1
    query rows per slot, serving/speculative.py): every op is
    row-independent over the query axis, so window row ``j`` under its own
    causal mask is bitwise the s=1 decode step at that position — the
    acceptance comparison compares exact tokens, never float
    intermediates."""
    d = q.shape[-1]
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
    logits = logits / np.sqrt(d).astype(np.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1).astype(dtype)  # (B, H, 1, T)
    out = jax.lax.dot_general(
        weights, v.transpose(0, 2, 1, 3),
        (((3,), (2,)), ((0, 1), (0, 1))))  # (B, H, 1, D)
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Paged KV cache substrate (fleet-scale serving, ISSUE 17)
#
# The dense per-request cache above allocates (rows, bucket + max_new, H, D)
# per block whether a slot is live or not — the HBM ceiling at long
# max_new_tokens. The paged form stores k/v in a POOL of fixed-size pages
# (L, n_pages, page_size, H, D), stacked over every block so one gather /
# one scatter serves the whole model; each serving slot owns a row of a
# page TABLE
# mapping its logical positions onto pool pages. The compiled decode step
# gathers a slot's pages into the SAME dense (rows, T, H, D) view the
# bitwise-pinned decode attention consumes, so fp32 paged decode inherits
# the dense path's exactness proof verbatim: trailing/garbage positions are
# masked to the fp32 min, their softmax weight underflows to exactly 0.0,
# and adding 0.0 in the fp32 contraction is exact. int8 pages quantize each
# (position, head) row over D through the gradient-wire codec grid
# (``grad_sync._quantize_int8_rows`` — codes + one fp32 scale per row), a
# bounded, deterministic, replica-identical perturbation (PARITY.md).
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class PagedKV:
    """The model's paged KV pool, stacked across ALL blocks.

    ``k``/``v`` are (L, n_pages, page_size, H, D) in the model dtype — one
    leading layer axis over every transformer block — or int8 codes when
    quantized, in which case ``k_scale``/``v_scale`` hold one fp32 scale
    per (layer, page, position, head) row (the wire codec's per-row grid
    over D). The stack is a performance contract, not a convenience: every
    block's pages share one page table, so the decode step's read half is
    ONE gather and its write half ONE scatter, instead of 2 x depth tiny
    ops each paying their own dispatch (measured ~6 ms/step of pure
    overhead on the 8-device CPU mesh at depth 4).

    Page 0 is the SCRATCH page by convention (serving/paged.py): freed or
    unallocated table entries point at it, so a gather is always in-bounds
    and masked positions stay finite (0.0 x finite = 0.0 exactly; a NaN
    would poison the masked softmax row)."""

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_paged_kv(depth: int, n_pages: int, page_size: int, num_heads: int,
                  head_dim: int, dtype: Dtype = jnp.float32,
                  quantized: bool = False) -> PagedKV:
    """Zero-filled paged pool for ALL ``depth`` blocks (stacked axis 0)."""
    shape = (depth, n_pages, page_size, num_heads, head_dim)
    if quantized:
        return PagedKV(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32))
    # k and v must be DISTINCT buffers: the serving step donates the whole
    # pool, and XLA rejects donating one buffer twice
    return PagedKV(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def _dequant_pages(codes: jnp.ndarray, scales: jnp.ndarray,
                   dtype: Dtype) -> jnp.ndarray:
    return (codes.astype(jnp.float32) * scales[..., None]).astype(dtype)


def _quant_rows(x: jnp.ndarray, fused: Optional[bool] = None
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """int8-quantize (..., D) through the gradient-wire codec grid: one
    scale per leading row over the trailing D axis — THE same absmax /
    ``max(amax, 1e-30) * (1/127)`` / round/clip grid the wire uses, so the
    KV-page error model is the wire codec's one-shot bound. ``fused``
    threads the PR 6 tri-state (None = auto, True = Pallas fused kernel,
    False = XLA-composed reference) exactly like the wire's
    ``_quantize_int8_rows`` — the fused kernel is bit-identical by the
    PR 6 exactness model, so the page bytes do not depend on the flag."""
    from ..parallel.grad_sync import _quantize_int8_rows

    lead = x.shape[:-1]
    q, scales = _quantize_int8_rows(
        x.astype(jnp.float32).reshape(-1, x.shape[-1]), fused=fused)
    return q.reshape(x.shape), scales.reshape(lead)


def gather_paged_kv(pkv: PagedKV, page_table: jnp.ndarray,
                    dtype: Dtype = jnp.float32
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-slot dense view of the whole pool: ``page_table`` (rows, P)
    int32 -> (L, rows, P * page_size, H, D) k and v in ``dtype``
    (dequantized when the pool is int8) — ONE gather covering every layer.
    Per-layer slices of the result feed the bitwise-pinned
    `decode_dot_product_attention` unchanged; positions beyond a slot's
    write frontier carry scratch/stale (finite) values the caller's mask
    zeroes exactly."""
    rows, pages = page_table.shape
    depth, _, ps = pkv.k.shape[:3]

    def dense(codes, scales):
        g = codes[:, page_table]              # (L, rows, P, ps, H, D)
        g = g.reshape(depth, rows, pages * ps, *g.shape[4:])
        if scales is not None:
            s = scales[:, page_table].reshape(depth, rows, pages * ps, -1)
            return _dequant_pages(g, s, dtype)
        return g.astype(dtype)

    return dense(pkv.k, pkv.k_scale), dense(pkv.v, pkv.v_scale)


def scatter_paged_rows(pkv: PagedKV, page_table: jnp.ndarray,
                       positions: jnp.ndarray, k_rows: jnp.ndarray,
                       v_rows: jnp.ndarray, active: jnp.ndarray,
                       fused: Optional[bool] = None) -> PagedKV:
    """Write ONE fresh (H, D) k/v row per slot per layer — ``k_rows`` /
    ``v_rows`` are (L, rows, H, D) — at that slot's own position: the paged
    decode step's write half, ONE scatter covering every layer.
    ``positions`` (rows,) int32, ``active`` (rows,) bool: inactive rows are
    dropped by pointing their write at an out-of-range page
    (``mode="drop"``), so finished/free slots never touch the pool (the
    token-granular join/leave substrate). ``fused`` is the int8 codec's
    PR 6 tri-state (`_quant_rows`)."""
    n_pages, ps = pkv.k.shape[1], pkv.k.shape[2]
    rows = positions.shape[0]
    page = page_table[jnp.arange(rows), positions // ps]
    page = jnp.where(active, page, n_pages)         # drop inactive writes
    off = positions % ps

    def put(store, scale_store, fresh):
        if scale_store is not None:
            q, s = _quant_rows(fresh, fused=fused)
            return (store.at[:, page, off].set(q, mode="drop"),
                    scale_store.at[:, page, off].set(s, mode="drop"))
        return (store.at[:, page, off].set(fresh.astype(store.dtype),
                                           mode="drop"), None)

    k, ks = put(pkv.k, pkv.k_scale, k_rows)
    v, vs = put(pkv.v, pkv.v_scale, v_rows)
    return PagedKV(k=k, v=v, k_scale=ks, v_scale=vs)


def scatter_paged_window(pkv: PagedKV, page_table: jnp.ndarray,
                         positions: jnp.ndarray, k_rows: jnp.ndarray,
                         v_rows: jnp.ndarray, active: jnp.ndarray,
                         fused: Optional[bool] = None) -> PagedKV:
    """`scatter_paged_rows` generalized to an S-position window per slot:
    ``positions`` / ``active`` are (rows, S) and ``k_rows`` / ``v_rows``
    (L, rows, S, H, D) — the speculative VERIFY step's write half (target
    k/v for the whole K+1 window) and the draft engine's propose-round
    commit, still ONE scatter covering every layer. Inactive (row, offset)
    pairs — dead slots, positions past the slot's page span — are dropped
    exactly like the one-row form; the caller masks out-of-range window
    positions BEFORE the page lookup here clips them, so a clipped index
    can never alias a live page."""
    n_pages, ps = pkv.k.shape[1], pkv.k.shape[2]
    rows = positions.shape[0]
    page = page_table[jnp.arange(rows)[:, None], positions // ps]  # (rows, S)
    page = jnp.where(active, page, n_pages)         # drop inactive writes
    off = positions % ps

    def put(store, scale_store, fresh):
        if scale_store is not None:
            q, s = _quant_rows(fresh, fused=fused)
            return (store.at[:, page, off].set(q, mode="drop"),
                    scale_store.at[:, page, off].set(s, mode="drop"))
        return (store.at[:, page, off].set(fresh.astype(store.dtype),
                                           mode="drop"), None)

    k, ks = put(pkv.k, pkv.k_scale, k_rows)
    v, vs = put(pkv.v, pkv.v_scale, v_rows)
    return PagedKV(k=k, v=v, k_scale=ks, v_scale=vs)


def scatter_paged_prefill(pkv: PagedKV, page_row: jnp.ndarray,
                          k_seqs: jnp.ndarray, v_seqs: jnp.ndarray,
                          length: jnp.ndarray,
                          fused: Optional[bool] = None) -> PagedKV:
    """Write one slot's prompt k/v — ``k_seqs`` / ``v_seqs`` (L, S, H, D),
    every layer at once — into its pages, positions [0, length) only: the
    paged prefill's write half. ``page_row`` (P,) is the slot's page-table
    row; positions past ``length`` (bucket padding) are dropped, so a
    shared prefix page is only ever rewritten with its own bytes
    (identical params + identical tokens -> identical k/v, bitwise — the
    prefix-sharing safety argument)."""
    n_pages, ps = pkv.k.shape[1], pkv.k.shape[2]
    s = k_seqs.shape[1]
    idx = jnp.arange(s)
    page = jnp.where(idx < length, page_row[idx // ps], n_pages)
    off = idx % ps

    def put(store, scale_store, fresh):
        if scale_store is not None:
            q, sc = _quant_rows(fresh, fused=fused)
            return (store.at[:, page, off].set(q, mode="drop"),
                    scale_store.at[:, page, off].set(sc, mode="drop"))
        return (store.at[:, page, off].set(fresh.astype(store.dtype),
                                           mode="drop"), None)

    k, ks = put(pkv.k, pkv.k_scale, k_seqs)
    v, vs = put(pkv.v, pkv.v_scale, v_seqs)
    return PagedKV(k=k, v=v, k_scale=ks, v_scale=vs)


def paged_kv_bytes(pool) -> int:
    """At-rest bytes of a paged pool (every block's codes + scales for
    int8 pools, raw elements otherwise) — the serving analogue of
    grad_sync's wire accounting, compared against `dense_kv_bytes`."""
    import jax

    return int(sum(arr.size * arr.dtype.itemsize
                   for arr in jax.tree_util.tree_leaves(pool)))


def dense_kv_bytes(rows: int, cache_len: int, num_heads: int, head_dim: int,
                   depth: int, itemsize: int = 4) -> int:
    """The dense engine's at-rest KV bytes at the same config — the
    baseline the >= 3x int8-paged HBM cut is measured against."""
    return 2 * depth * rows * cache_len * num_heads * head_dim * itemsize


class MultiHeadAttention(nn.Module):
    """Self-attention with fused qkv projection.

    `attention_fn(q, k, v, mask, dtype)` defaults to the XLA einsum path;
    swap in `ops.flash_attention` / `ops.ring_attention` for long context.

    Explicit TP (``tp_size`` > 1, inside a shard_map binding ``tp_axis``):
    megatron column/row split — the qkv projection holds this shard's
    ``num_heads / tp_size`` heads (column-parallel, `copy_to_tp` at its
    input so the backward sums the per-shard cotangents), attention runs on
    the local heads, and the out projection is `RowParallelDense` (one
    forward psum per residual join, bias added once after it). Param tree
    paths are unchanged; kernel/bias SHAPES hold the local slice, exactly
    the `tp_fsdp_rules()` model-axis dims — the passive GSPMD constraints
    read as the explicit layout contract.

    KV cache (serving/): ``cache=(k, v)`` of shape (B, T, H, D) engages the
    incremental-decoding path and the call returns ``(out, new_cache)``.
    Two cache writes exist:

    * prefill (``cache_positions=None``, S > 1 legal): the fresh k/v land
      in slots [0, S) and attention runs over the FRESH k/v with the
      caller's (causal) mask — exactly the no-cache computation, so
      prefill logits are the eval forward's logits bit-for-bit, with the
      cache fill as a side output.
    * decode (``cache_positions`` = per-row write index, S == 1): the new
      token's k/v land at each row's own position (a where-scatter, so
      rows at different prompt lengths advance independently with no
      recompile) and attention runs over the UPDATED cache under the
      caller's per-row validity mask.

    With ``cache=None`` the path is byte-identical to the pre-cache module
    (pinned by tests/test_serving.py's lowering test).
    """

    num_heads: int
    head_dim: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    dropout_rate: float = 0.0
    use_bias: bool = True
    attention_fn: Callable = dot_product_attention
    tp_size: int = 1
    tp_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True,
                 cache=None, cache_positions=None):
        features = self.num_heads * self.head_dim
        dense = functools.partial(nn.DenseGeneral, dtype=self.dtype,
                                  param_dtype=self.param_dtype,
                                  use_bias=self.use_bias)
        if self.tp_size > 1:
            return self._tp_call(x, mask, deterministic, cache, dense)
        qkv = dense(features=(3, self.num_heads, self.head_dim), name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        new_cache = None
        y = None
        if cache is not None:
            if self.attention_fn is not dot_product_attention:
                raise ValueError(
                    "KV-cache decoding needs the XLA attention path — the "
                    "kernel attention_fns own their causal structure and "
                    "take no cache (serve with --attention xla)")
            ck, cv = cache
            if cache_positions is None:
                # prefill: the S fresh rows fill slots [0, S); attention
                # runs over the FRESH k/v below (the eval computation)
                new_cache = (
                    jax.lax.dynamic_update_slice(
                        ck, k.astype(ck.dtype), (0, 0, 0, 0)),
                    jax.lax.dynamic_update_slice(
                        cv, v.astype(cv.dtype), (0, 0, 0, 0)))
            else:
                # decode: per-row scatter at each row's own position, then
                # attend over the updated cache. S == 1 is the classic
                # one-token step; S > 1 is the speculative VERIFY window
                # (serving/speculative.py) — window token j lands at
                # position + j BEFORE attention, and the caller's per-row
                # causal mask hides the not-yet-committed later rows, so
                # window row j is bitwise the s=1 step at that position.
                s_q = q.shape[1]
                if s_q == 1:
                    hit = (jnp.arange(ck.shape[1])[None, :]
                           == cache_positions[:, None])[:, :, None, None]
                    ck = jnp.where(hit, k.astype(ck.dtype), ck)
                    cv = jnp.where(hit, v.astype(cv.dtype), cv)
                else:
                    for j in range(s_q):
                        hit = (jnp.arange(ck.shape[1])[None, :]
                               == (cache_positions + j)[:, None]
                               )[:, :, None, None]
                        ck = jnp.where(hit, k[:, j:j + 1].astype(ck.dtype),
                                       ck)
                        cv = jnp.where(hit, v[:, j:j + 1].astype(cv.dtype),
                                       cv)
                new_cache = (ck, cv)
                y = decode_dot_product_attention(q, ck, cv, mask=mask,
                                                 dtype=self.dtype)
        if y is None:
            y = self.attention_fn(q, k, v, mask=mask, dtype=self.dtype)
        if self.dropout_rate and not deterministic:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=False)
        out = nn.DenseGeneral(features=x.shape[-1], axis=(-2, -1),
                              dtype=self.dtype, param_dtype=self.param_dtype,
                              use_bias=self.use_bias, name="out")(y)
        return out if cache is None else (out, new_cache)

    def _tp_call(self, x, mask, deterministic, cache, dense):
        """The explicit-TP attention body (tp_size > 1): local head slice,
        one forward psum at the out projection."""
        if cache is not None:
            raise ValueError(
                "explicit TP attention has no KV-cache path — serve TP "
                "checkpoints via the GSPMD rules (--mesh model=N without "
                "--fsdp-explicit on the serving side)")
        if self.dropout_rate and not deterministic:
            raise ValueError(
                "explicit TP runs the dropout RNG stream replicated over "
                "the model axis; per-shard head slices would draw "
                "correlated masks — train explicit TP with dropout 0")
        if self.num_heads % self.tp_size:
            raise ValueError(
                f"num_heads={self.num_heads} not divisible by "
                f"tp_size={self.tp_size}")
        heads_local = self.num_heads // self.tp_size
        x = copy_to_tp(x, self.tp_axis)
        qkv = dense(features=(3, heads_local, self.head_dim),
                    name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        y = self.attention_fn(q, k, v, mask=mask, dtype=self.dtype)
        return RowParallelDense(
            features=x.shape[-1], tp_axis=self.tp_axis, n_contract_dims=2,
            use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype, name="out")(y)


class MlpBlock(nn.Module):
    """Transformer MLP. Explicit TP (``tp_size`` > 1): fc1 is
    column-parallel (this shard's ``hidden_dim / tp_size`` neurons, with
    its bias slice), fc2 is `RowParallelDense` — one forward psum per
    residual join, full bias added once after it."""

    hidden_dim: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    dropout_rate: float = 0.0
    activation: Callable = nn.gelu
    tp_size: int = 1
    tp_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        d = x.shape[-1]
        if self.tp_size > 1:
            if self.dropout_rate and not deterministic:
                raise ValueError(
                    "explicit TP runs the dropout RNG stream replicated "
                    "over the model axis; per-shard neuron slices would "
                    "draw correlated masks — train explicit TP with "
                    "dropout 0")
            if self.hidden_dim % self.tp_size:
                raise ValueError(
                    f"hidden_dim={self.hidden_dim} not divisible by "
                    f"tp_size={self.tp_size}")
            x = copy_to_tp(x, self.tp_axis)
            h = nn.Dense(self.hidden_dim // self.tp_size, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="fc1")(x)
            h = self.activation(h)
            return RowParallelDense(
                features=d, tp_axis=self.tp_axis, dtype=self.dtype,
                param_dtype=self.param_dtype, name="fc2")(h)
        h = nn.Dense(self.hidden_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="fc1")(x)
        h = self.activation(h)
        if self.dropout_rate and not deterministic:
            h = nn.Dropout(self.dropout_rate)(h, deterministic=False)
        out = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype,
                       name="fc2")(h)
        return out


class TransformerBlock(nn.Module):
    """Pre-LN transformer block (ViT/GPT-2 style; BERT overrides to post-LN)."""

    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    dropout_rate: float = 0.0
    layernorm_epsilon: float = 1e-5
    attention_fn: Callable = dot_product_attention
    tp_size: int = 1
    tp_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True,
                 cache=None, cache_positions=None):
        ln = functools.partial(nn.LayerNorm, epsilon=self.layernorm_epsilon,
                               dtype=self.dtype, param_dtype=self.param_dtype)
        y = ln(name="ln1")(x)
        y = MultiHeadAttention(
            num_heads=self.num_heads, head_dim=self.head_dim, dtype=self.dtype,
            param_dtype=self.param_dtype, dropout_rate=self.dropout_rate,
            attention_fn=self.attention_fn, name="attn",
            tp_size=self.tp_size, tp_axis=self.tp_axis,
        )(y, mask=mask, deterministic=deterministic, cache=cache,
          cache_positions=cache_positions)
        new_cache = None
        if cache is not None:
            y, new_cache = y
        x = x + y
        y = ln(name="ln2")(x)
        y = MlpBlock(hidden_dim=self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype,
                     dropout_rate=self.dropout_rate, name="mlp",
                     tp_size=self.tp_size, tp_axis=self.tp_axis,
                     )(y, deterministic=deterministic)
        return x + y if cache is None else (x + y, new_cache)


def padded_vocab_size(vocab_size: int, multiple: int) -> int:
    """Megatron-style vocab padding: the smallest multiple of `multiple`
    >= vocab_size. GPT-2's 50257 is indivisible by any TP degree, so the
    (vocab, d) embedding — the model's largest tensor — could never shard
    over the `model` axis without this (it would silently replicate, see
    parallel/sharding.feasible_spec). 0 or 1 disables padding."""
    if multiple <= 1:
        return vocab_size
    return -(-vocab_size // multiple) * multiple


class VocabPaddingMixin:
    """Shared accessors for Megatron-style vocab padding. Models declare the
    ``pad_vocab_to_multiple_of: int = 0`` field themselves (flax's dataclass
    transform requires fields on the Module subclass); this mixin supplies
    the derived quantities so the padding formula lives in one place."""

    @property
    def padded_vocab(self) -> int:
        return padded_vocab_size(self.vocab_size, self.pad_vocab_to_multiple_of)

    @property
    def vocab_pad_params(self) -> int:
        """Extra params introduced by vocab padding (for HF-exact reporting)."""
        return (self.padded_vocab - self.vocab_size) * self.hidden_dim


def mask_vocab_padding(logits: jnp.ndarray, vocab_size: int) -> jnp.ndarray:
    """Neutralize padded vocab columns: set their logits to the dtype min so
    softmax assigns them exactly zero probability (exp underflows to 0.0)
    and argmax never selects them. With that, CE loss / token accuracy over
    a padded head are bit-identical to the unpadded head."""
    padded = logits.shape[-1]
    if padded == vocab_size:
        return logits
    keep = jnp.arange(padded) < vocab_size
    return jnp.where(keep, logits, jnp.finfo(logits.dtype).min)


def causal_mask(seq_len: int) -> jnp.ndarray:
    """(1, 1, S, S) lower-triangular True=attend mask."""
    return jnp.tril(jnp.ones((seq_len, seq_len), bool))[None, None]


def padding_mask(attention_mask: jnp.ndarray) -> jnp.ndarray:
    """(B, T) 1=real token -> (B, 1, 1, T) attend mask."""
    return attention_mask[:, None, None, :].astype(bool)


def tp_fsdp_rules() -> PartitionRules:
    """The combined layout table every transformer here ships: megatron TP
    over ``model`` on the head/neuron dim + ZeRO-style FSDP over ``fsdp`` on
    the complementary (d_model) dim of the same kernels (SURVEY.md §2c; the
    promise at parallel/mesh.py `fsdp` axis).

    One table serves every mesh: an axis of size 1 contributes nothing, so
    pure DP (both axes 1) reproduces the DDP replicated layout, ``--mesh
    model=N`` is pure TP, ``--mesh fsdp=N`` is pure FSDP, and ``--mesh
    fsdp=M,model=N`` is 2-D parameter sharding.

    The EXPLICIT TP x FSDP step (ISSUE 13) reads this same table as its
    layout contract: `parallel.sharding.tp_split_dims` takes each leaf's
    model-axis dim from these specs, and the tp_size>1 module forms above
    compute with exactly those slices — the passive GSPMD constraints and
    the explicit layout cannot disagree.

    Because `shard_pytree` applies the same table to the optimizer state,
    the AdamW/SGD moments are sharded identically — the ZeRO-2/3 memory win.
    The batch is sharded over (data, fsdp) jointly (sharding.batch_spec), so
    fsdp devices also do data-parallel work; XLA inserts the per-layer
    all-gather (params) and reduce-scatter (grads) that a hand-written FSDP
    wrapper would schedule manually.
    """
    return PartitionRules([
        (r"attn/qkv/kernel", P(FSDP, None, MODEL, None)),
        (r"attn/qkv/bias", P(None, MODEL, None)),
        (r"attn/out/kernel", P(MODEL, None, FSDP)),
        (r"mlp/fc1/kernel", P(FSDP, MODEL)),
        (r"mlp/fc1/bias", P(MODEL)),
        (r"mlp/fc2/kernel", P(MODEL, FSDP)),
        (r"(token_embedding|wte)/embedding", P(MODEL, FSDP)),
        (r"(position_embedding|wpe)/embedding", P(None, FSDP)),
        (r"patch_embed/kernel", P(None, None, None, FSDP)),
        (r"(head|fc|mlm_dense)/kernel", P(FSDP, None)),
    ])
