"""GPT-2 355M (medium) — the "GPT-2 355M multi-host v4-32 pod (scaling
experiment)" flagship config (BASELINE.json:12).

HF-equivalent architecture: learned token + position embeddings, 24 pre-LN
blocks (1024 wide, 16 heads, MLP 4096, GELU), final LN, LM head tied to the
token embedding. Parity anchor: HF ``GPT2LMHeadModel(gpt2-medium)`` has
354,823,168 params — checked in tests/test_models.py.

Long-context: the attention implementation is pluggable; pass
``ops.ring_attention.make_ring_attention(mesh)`` to shard the sequence over
the mesh ``seq`` axis (context parallelism, SURVEY.md §5).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.collectives import (
    TpShardedLogits,
    copy_to_tp,
    reduce_from_tp,
)
from ..parallel.sharding import PartitionRules
from .layers import (
    TransformerBlock,
    VocabPaddingMixin,
    causal_mask,
    dot_product_attention,
    mask_vocab_padding,
    tp_fsdp_rules,
)
from .registry import register_model


class GPT2LMHead(VocabPaddingMixin, nn.Module):
    vocab_size: int = 50257
    hidden_dim: int = 1024
    depth: int = 24
    num_heads: int = 16
    max_position: int = 1024
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    layernorm_epsilon: float = 1e-5
    attention_fn: Callable = dot_product_attention
    remat: bool = False  # jax.checkpoint each block: HBM for recompute FLOPs
    # Megatron-style vocab padding for TP (VERDICT r4 weak #4): pad the
    # embedding rows to a multiple so the (vocab, d) table — the largest
    # param — shards over the `model` axis instead of degrading to
    # replication. Padded logit columns are masked to the fp32 min, so the
    # loss is identical to the unpadded head. 0 = exact HF shapes.
    pad_vocab_to_multiple_of: int = 0
    # Explicit tensor parallelism (ISSUE 13): tp_size > 1 runs the
    # megatron column/row-split forward with `tp_axis` bound by the
    # enclosing shard_map (training/loop.py's explicit TP x FSDP step).
    # When the padded vocab divides by tp_size, the (vocab, d) embedding —
    # the largest tensor — is vocab-split too: lookups psum the per-shard
    # partial rows, and the tied head returns its LOCAL logit columns as a
    # `TpShardedLogits` — the task layer computes Megatron's
    # parallel-vocab cross-entropy from two (B, S)-sized model-axis stats
    # instead of gathering the (B, S, vocab) logits. Indivisible vocab
    # degrades the embedding to model-replicated with a warning — the
    # blocks still split.
    tp_size: int = 1
    tp_axis: Optional[str] = None

    @property
    def tp_vocab(self) -> bool:
        """Whether the explicit-TP forward vocab-splits the embedding."""
        return self.tp_size > 1 and self.padded_vocab % self.tp_size == 0

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, train: bool = False,
                 cache=None, cache_positions=None):
        """Causal LM forward. Three modes, selected by ``cache``:

        * ``cache=None`` (training/eval): the original forward, byte-
          identical HLO to the pre-cache module (the lowering pin in
          tests/test_serving.py) — the cache plumbing contributes ZERO ops
          when off.
        * prefill (``cache`` given, ``cache_positions=None``): the same
          causal forward over the (padded) prompt, additionally returning
          the per-block (k, v) caches filled at slots [0, S). Attention
          runs over the fresh k/v, so prefill logits ARE the eval
          forward's logits bit-for-bit (PARITY.md "Serving shares
          training numerics").
        * decode (``cache`` + ``cache_positions`` (B,) int32): S new
          tokens per row starting at that row's own position — per-row
          cache scatter, per-row position embedding, attention over cache
          slots ``<= position + j`` for window row j. Returns
          (B, S, vocab) logits for the NEXT token at each window offset.
          S == 1 is the classic decode step; S == K+1 is the speculative
          verify window (serving/speculative.py), whose row j is bitwise
          the s=1 step at that position. Rows at different prompt lengths
          decode in one batch with no recompile (the positions are traced
          values).

        With a cache the return value is ``(logits, new_cache)`` where
        ``new_cache`` matches `init_cache`'s structure.
        """
        b, s = input_ids.shape
        decoding = cache is not None and cache_positions is not None
        tp = self.tp_size
        if tp > 1 and cache is not None:
            raise ValueError(
                "explicit TP has no KV-cache path — serve TP checkpoints "
                "via the GSPMD rules (models/layers.py MultiHeadAttention "
                "documents the restriction)")
        vocab_rows = (self.padded_vocab // tp if self.tp_vocab
                      else self.padded_vocab)
        wte = nn.Embed(vocab_rows, self.hidden_dim, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       embedding_init=nn.initializers.normal(stddev=0.02),
                       name="wte")
        if self.tp_vocab:
            # vocab-parallel lookup: this shard owns rows
            # [shard * rows, (shard+1) * rows); out-of-range ids contribute
            # exact zeros and the per-shard partials psum to the full
            # embedding row (`reduce_from_tp`: backward is identity, so
            # each shard's table gets exactly its own rows' cotangents)
            shard = jax.lax.axis_index(self.tp_axis)
            local_ids = input_ids - shard * vocab_rows
            valid = (local_ids >= 0) & (local_ids < vocab_rows)
            rows = wte(jnp.clip(local_ids, 0, vocab_rows - 1))
            x = reduce_from_tp(
                jnp.where(valid[..., None], rows, 0.0), self.tp_axis)
        else:
            x = wte(input_ids)
        # Decode position ids: s == 1 is the classic one-token step; s > 1
        # is the speculative verify window — row j sits at absolute
        # position cache_positions + j (clipped into the wpe table: the
        # overflow rows past a slot's page span are write-dropped and
        # never sampled, they only need to stay finite).
        if decoding and s == 1:
            pos_ids = cache_positions[:, None]
        elif decoding:
            pos_ids = jnp.minimum(
                cache_positions[:, None] + jnp.arange(s)[None, :],
                self.max_position - 1)
        else:
            pos_ids = jnp.arange(s)[None, :]
        x = x + nn.Embed(self.max_position, self.hidden_dim, dtype=self.dtype,
                         param_dtype=self.param_dtype,
                         embedding_init=nn.initializers.normal(stddev=0.01),
                         name="wpe")(pos_ids)

        # Kernel attention paths (flash/ring) own the causal structure, so
        # they get ONLY the padding mask (flash applies it inside the
        # blocks; ring/ulysses raise — their adapters need the XLA path).
        # The XLA einsum path takes the combined causal & padding mask.
        # Decode attends over the cache: slot j is visible iff j <= this
        # row's position (later slots are unwritten or prefill pad — both
        # must stay invisible).
        uses_kernel = self.attention_fn is not dot_product_attention
        if decoding and s == 1:
            t = cache[0][0].shape[1]
            mask = (jnp.arange(t)[None, :]
                    <= cache_positions[:, None])[:, None, None, :]
        elif decoding:
            # verify window: row j of the window attends cache slots
            # <= cache_positions + j — each row's visibility is exactly
            # the s=1 decode step's at that position, so the masked-out
            # later window rows (scattered but not yet committed) weigh
            # exactly 0.0 in its softmax (the bitwise argument).
            t = cache[0][0].shape[1]
            win = cache_positions[:, None] + jnp.arange(s)[None, :]
            mask = (jnp.arange(t)[None, None, :]
                    <= win[:, :, None])[:, None, :, :]
        elif uses_kernel:
            mask = (attention_mask[:, None, None, :].astype(bool)
                    if attention_mask is not None else None)
        else:
            mask = causal_mask(s)
            if attention_mask is not None:
                mask = mask & attention_mask[:, None, None, :].astype(bool)

        new_cache = []
        block_cls = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        for i in range(self.depth):
            block = block_cls(
                num_heads=self.num_heads,
                head_dim=self.hidden_dim // self.num_heads,
                mlp_dim=4 * self.hidden_dim, dtype=self.dtype,
                param_dtype=self.param_dtype,
                dropout_rate=self.dropout_rate,
                layernorm_epsilon=self.layernorm_epsilon,
                attention_fn=self.attention_fn,
                tp_size=tp, tp_axis=self.tp_axis,
                name=f"block{i}",
            )
            if cache is None:
                x = block(x, mask=mask, deterministic=not train)
            else:
                x, c = block(x, mask=mask, deterministic=not train,
                             cache=cache[i], cache_positions=cache_positions)
                new_cache.append(c)

        x = nn.LayerNorm(epsilon=self.layernorm_epsilon, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_f")(x)
        if self.tp_vocab:
            # vocab-parallel tied head, Megatron parallel-vocab CE form:
            # the local logit columns STAY sharded — no vocab-scale
            # model-axis gather; the loss layer psums two (B, S)-sized
            # stats instead (collectives.tp_parallel_cross_entropy).
            # `copy_to_tp` at the matmul input so ln_f and the residual
            # stream see the full summed cotangent. Padded columns are
            # masked per shard (global column = shard * rows + j), so the
            # sharded head is column-for-column the masked gathered one.
            local = wte.attend(copy_to_tp(x, self.tp_axis)).astype(
                jnp.float32)
            cols = (jax.lax.axis_index(self.tp_axis) * vocab_rows
                    + jnp.arange(vocab_rows))
            local = jnp.where(cols < self.vocab_size, local,
                              jnp.finfo(jnp.float32).min)
            return TpShardedLogits(local, self.tp_axis, vocab_rows,
                                   self.vocab_size)
        logits = wte.attend(x)  # tied LM head (HF ties wte <-> lm_head)
        logits = mask_vocab_padding(logits.astype(jnp.float32),
                                    self.vocab_size)
        return logits if cache is None else (logits, tuple(new_cache))

    def init_cache(self, batch: int, max_len: int):
        """Zero-filled per-block (k, v) cache: ``depth`` pairs of
        (batch, max_len, heads, head_dim) arrays in the compute dtype.
        ``max_len`` = prompt bucket + max new tokens (serving/engine.py)."""
        z = jnp.zeros((batch, max_len, self.num_heads,
                       self.hidden_dim // self.num_heads), self.dtype)
        return tuple((z, z) for _ in range(self.depth))

    def init_paged_pool(self, n_pages: int, page_size: int,
                        quantized: bool = False):
        """Zero-filled paged KV pool: ONE `layers.PagedKV` stacked over all
        ``depth`` blocks — (depth, n_pages, page_size, heads, head_dim)
        pages (int8 codes + per-row fp32 scales when ``quantized`` — the
        wire-codec grid). The paged serving engine
        (serving/continuous.py) gathers per-slot pages into the SAME dense
        cache shape `init_cache` produces, so the decode forward above
        runs unchanged — paging is a storage layout, not a numerics change
        (PARITY.md)."""
        from .layers import init_paged_kv

        return init_paged_kv(self.depth, n_pages, page_size,
                             self.num_heads,
                             self.hidden_dim // self.num_heads,
                             dtype=self.dtype, quantized=quantized)

    @staticmethod
    def partition_rules() -> PartitionRules:
        return tp_fsdp_rules()


@register_model("gpt2_355m")
def gpt2_355m(**kw) -> GPT2LMHead:
    """GPT-2 medium (355M). Config values are defaults — callers (tests,
    dry-runs) may override any of them."""
    cfg = dict(hidden_dim=1024, depth=24, num_heads=16)
    cfg.update(kw)
    return GPT2LMHead(**cfg)


@register_model("gpt2_124m")
def gpt2_124m(**kw) -> GPT2LMHead:
    """GPT-2 small — CPU-testable sibling of the 355M flagship."""
    cfg = dict(hidden_dim=768, depth=12, num_heads=12)
    cfg.update(kw)
    return GPT2LMHead(**cfg)
