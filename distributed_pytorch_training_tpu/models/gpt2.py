"""GPT-2 355M (medium) — the "GPT-2 355M multi-host v4-32 pod (scaling
experiment)" flagship config (BASELINE.json:12).

HF-equivalent architecture: learned token + position embeddings, 24 pre-LN
blocks (1024 wide, 16 heads, MLP 4096, GELU), final LN, LM head tied to the
token embedding. Parity anchor: HF ``GPT2LMHeadModel(gpt2-medium)`` has
354,823,168 params — checked in tests/test_models.py.

Long-context: the attention implementation is pluggable; pass
``ops.ring_attention.make_ring_attention(mesh)`` to shard the sequence over
the mesh ``seq`` axis (context parallelism, SURVEY.md §5).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..parallel.sharding import PartitionRules
from .layers import (
    TransformerBlock,
    VocabPaddingMixin,
    causal_mask,
    dot_product_attention,
    mask_vocab_padding,
    tp_fsdp_rules,
)
from .registry import register_model


class GPT2LMHead(VocabPaddingMixin, nn.Module):
    vocab_size: int = 50257
    hidden_dim: int = 1024
    depth: int = 24
    num_heads: int = 16
    max_position: int = 1024
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    layernorm_epsilon: float = 1e-5
    attention_fn: Callable = dot_product_attention
    remat: bool = False  # jax.checkpoint each block: HBM for recompute FLOPs
    # Megatron-style vocab padding for TP (VERDICT r4 weak #4): pad the
    # embedding rows to a multiple so the (vocab, d) table — the largest
    # param — shards over the `model` axis instead of degrading to
    # replication. Padded logit columns are masked to the fp32 min, so the
    # loss is identical to the unpadded head. 0 = exact HF shapes.
    pad_vocab_to_multiple_of: int = 0

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, train: bool = False):
        b, s = input_ids.shape
        wte = nn.Embed(self.padded_vocab, self.hidden_dim, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       embedding_init=nn.initializers.normal(stddev=0.02),
                       name="wte")
        x = wte(input_ids)
        pos_ids = jnp.arange(s)[None, :]
        x = x + nn.Embed(self.max_position, self.hidden_dim, dtype=self.dtype,
                         param_dtype=self.param_dtype,
                         embedding_init=nn.initializers.normal(stddev=0.01),
                         name="wpe")(pos_ids)

        # Kernel attention paths (flash/ring) own the causal structure, so
        # they get ONLY the padding mask (flash applies it inside the
        # blocks; ring/ulysses raise — their adapters need the XLA path).
        # The XLA einsum path takes the combined causal & padding mask.
        uses_kernel = self.attention_fn is not dot_product_attention
        if uses_kernel:
            mask = (attention_mask[:, None, None, :].astype(bool)
                    if attention_mask is not None else None)
        else:
            mask = causal_mask(s)
            if attention_mask is not None:
                mask = mask & attention_mask[:, None, None, :].astype(bool)

        block_cls = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                head_dim=self.hidden_dim // self.num_heads,
                mlp_dim=4 * self.hidden_dim, dtype=self.dtype,
                param_dtype=self.param_dtype,
                dropout_rate=self.dropout_rate,
                layernorm_epsilon=self.layernorm_epsilon,
                attention_fn=self.attention_fn,
                name=f"block{i}",
            )(x, mask=mask, deterministic=not train)

        x = nn.LayerNorm(epsilon=self.layernorm_epsilon, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_f")(x)
        logits = wte.attend(x)  # tied LM head (HF GPT-2 ties wte <-> lm_head)
        return mask_vocab_padding(logits.astype(jnp.float32), self.vocab_size)

    @staticmethod
    def partition_rules() -> PartitionRules:
        return tp_fsdp_rules()


@register_model("gpt2_355m")
def gpt2_355m(**kw) -> GPT2LMHead:
    """GPT-2 medium (355M). Config values are defaults — callers (tests,
    dry-runs) may override any of them."""
    cfg = dict(hidden_dim=1024, depth=24, num_heads=16)
    cfg.update(kw)
    return GPT2LMHead(**cfg)


@register_model("gpt2_124m")
def gpt2_124m(**kw) -> GPT2LMHead:
    """GPT-2 small — CPU-testable sibling of the 355M flagship."""
    cfg = dict(hidden_dim=768, depth=12, num_heads=12)
    cfg.update(kw)
    return GPT2LMHead(**cfg)
