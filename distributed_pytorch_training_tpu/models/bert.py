"""BERT-base for masked-LM — the "BERT-base MLM seq-len 512 (grad-sync
profiling run)" config (BASELINE.json:11).

HuggingFace-equivalent architecture (what the reference's dependency stack
would provide): token + position + type embeddings with post-embedding LN,
12 post-LN encoder blocks (768 wide, 12 heads, MLP 3072, GELU), and the MLM
head (dense 768 + GELU + LN, decoder tied to the token embedding + vocab
bias). Parity anchor: HF ``BertForMaskedLM(bert-base-uncased)`` totals
109,514,298 trainable params incl. the tied embedding counted once — checked
in tests/test_models.py.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..parallel.sharding import PartitionRules
from .layers import (
    MlpBlock,
    MultiHeadAttention,
    VocabPaddingMixin,
    dot_product_attention,
    mask_vocab_padding,
    padding_mask,
    tp_fsdp_rules,
)
from .registry import register_model


class BertBlock(nn.Module):
    """Post-LN encoder block (BERT ordering: sublayer -> residual -> LN)."""

    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    dropout_rate: float = 0.0
    layernorm_epsilon: float = 1e-12
    attention_fn: Callable = dot_product_attention

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        ln = functools.partial(nn.LayerNorm, epsilon=self.layernorm_epsilon,
                               dtype=self.dtype, param_dtype=self.param_dtype)
        y = MultiHeadAttention(
            num_heads=self.num_heads, head_dim=self.head_dim,
            dtype=self.dtype, param_dtype=self.param_dtype,
            dropout_rate=self.dropout_rate, attention_fn=self.attention_fn,
            name="attn")(x, mask=mask, deterministic=deterministic)
        x = ln(name="ln1")(x + y)
        y = MlpBlock(hidden_dim=self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype,
                     dropout_rate=self.dropout_rate, name="mlp",
                     )(x, deterministic=deterministic)
        return ln(name="ln2")(x + y)


class BertForMaskedLM(VocabPaddingMixin, nn.Module):
    vocab_size: int = 30522
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    layernorm_epsilon: float = 1e-12
    attention_fn: Callable = dot_product_attention
    remat: bool = False  # jax.checkpoint each block: HBM for recompute FLOPs
    # Megatron-style vocab padding for TP (see models/gpt2.py): lets the
    # token embedding shard over `model`; padded columns masked out of the
    # logits. 0 = exact HF shapes.
    pad_vocab_to_multiple_of: int = 0

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 train: bool = False):
        b, s = input_ids.shape
        tok = nn.Embed(self.padded_vocab, self.hidden_dim,
                       dtype=self.dtype, param_dtype=self.param_dtype,
                       name="token_embedding")
        x = tok(input_ids)
        pos_ids = jnp.arange(s)[None, :]
        x = x + nn.Embed(self.max_position, self.hidden_dim, dtype=self.dtype,
                         param_dtype=self.param_dtype,
                         name="position_embedding")(pos_ids)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + nn.Embed(self.type_vocab_size, self.hidden_dim,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         name="type_embedding")(token_type_ids)
        x = nn.LayerNorm(epsilon=self.layernorm_epsilon, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="embed_ln")(x)

        mask = padding_mask(attention_mask) if attention_mask is not None else None
        block_cls = nn.remat(BertBlock) if self.remat else BertBlock
        for i in range(self.depth):
            x = block_cls(num_heads=self.num_heads,
                          head_dim=self.hidden_dim // self.num_heads,
                          mlp_dim=self.mlp_dim, dtype=self.dtype,
                          param_dtype=self.param_dtype,
                          dropout_rate=self.dropout_rate,
                          layernorm_epsilon=self.layernorm_epsilon,
                          attention_fn=self.attention_fn,
                          name=f"block{i}")(x, mask=mask,
                                            deterministic=not train)

        # MLM head: transform + decode with tied embedding (HF equivalence).
        h = nn.Dense(self.hidden_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="mlm_dense")(x)
        h = nn.gelu(h)
        h = nn.LayerNorm(epsilon=self.layernorm_epsilon, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="mlm_ln")(h)
        logits = tok.attend(h)  # tied decoder: (B, S, padded vocab)
        # Bias stays at the HF-exact (vocab,) shape (it is replicated — no
        # sharding need); pad with zeros to match the padded logit width.
        bias = self.param("mlm_bias", nn.initializers.zeros,
                          (self.vocab_size,), self.param_dtype)
        if self.padded_vocab != self.vocab_size:
            bias = jnp.pad(bias, (0, self.padded_vocab - self.vocab_size))
        return mask_vocab_padding((logits + bias).astype(jnp.float32),
                                  self.vocab_size)

    @staticmethod
    def partition_rules() -> PartitionRules:
        return tp_fsdp_rules()


@register_model("bert_base")
def bert_base(**kw) -> BertForMaskedLM:
    return BertForMaskedLM(**kw)
