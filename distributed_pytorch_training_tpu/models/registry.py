"""Model registry: name -> constructor (the `build_model` factory surface,
/root/reference/train_ddp.py:153-156, generalized to the BASELINE config
matrix)."""

from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register_model(name: str):
    def deco(fn: Callable):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_model(name: str, **kwargs):
    """Instantiate a registered model (e.g. ``get_model("resnet18",
    num_classes=10)`` ≙ ref :154)."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def list_models():
    return sorted(_REGISTRY)
