"""Model zoo — TPU-native replacements for the torchvision/transformers models
the reference leans on (/root/reference/train_ddp.py:154 and BASELINE.json:6-12):
ResNet-18/50, ViT-B/16, BERT-base (MLM), GPT-2 355M.

All models are flax.linen modules with:
* `dtype` (compute) vs `param_dtype` (storage) split — the bf16 mixed-precision
  path (the reference's `--amp`, train_ddp.py:203-209, without a GradScaler:
  bf16 keeps fp32's exponent range);
* a `partition_rules()` classmethod giving TP/FSDP PartitionSpecs for the
  mesh axes defined in `parallel.mesh`.
"""

from .registry import get_model, list_models, register_model  # noqa: F401
from . import resnet  # noqa: F401  (registers resnet18/resnet50)
from . import vit  # noqa: F401  (registers vit_b16)
from . import bert  # noqa: F401  (registers bert_base)
from . import gpt2  # noqa: F401  (registers gpt2_355m/gpt2_124m)
from . import moe  # noqa: F401  (registers gpt2_moe)
