"""ResNet-18/50 in flax.linen — TPU-native equivalent of
``torchvision.models.resnet18(num_classes=10)`` (/root/reference/train_ddp.py:154).

Behavioral parity notes:
* Standard ImageNet stem (7x7/2 conv + 3x3/2 maxpool) by default — the
  reference feeds 32x32 CIFAR images through the unmodified torchvision
  architecture, so that is the parity default; ``cifar_stem=True`` gives the
  3x3/1 stem commonly used for CIFAR accuracy.
* BatchNorm epsilon 1e-5, EMA retention 0.9 (torch momentum=0.1).
* He/fan-out conv init, zero-init of the final BN scale in each residual
  branch (torchvision's ``zero_init_residual`` is False by default — we also
  default False).
* NHWC layout (TPU-native; torchvision is NCHW) — layout is an internal
  choice, the API contract is images in, logits out.

TPU notes: under jit with a data-sharded batch, BatchNorm statistics are
computed over the *global* batch (SyncBN semantics) — stronger than DDP's
per-device BN; XLA fuses the required psums into the step.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..parallel.sharding import PartitionRules
from .registry import register_model

conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class BasicBlock(nn.Module):
    """2x 3x3 conv residual block (ResNet-18/34)."""

    features: int
    strides: int = 1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    zero_init_residual: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype)
        conv = functools.partial(
            nn.Conv, use_bias=False, kernel_init=conv_init,
            dtype=self.dtype, param_dtype=self.param_dtype)

        residual = x
        y = conv(self.features, (3, 3), (self.strides, self.strides), name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), name="conv2")(y)
        scale_init = (nn.initializers.zeros if self.zero_init_residual
                      else nn.initializers.ones)
        y = norm(name="bn2", scale_init=scale_init)(y)

        if residual.shape != y.shape:
            residual = conv(self.features, (1, 1), (self.strides, self.strides),
                            name="downsample_conv")(residual)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 residual block with 4x expansion (ResNet-50+)."""

    features: int
    strides: int = 1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    zero_init_residual: bool = False
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype)
        conv = functools.partial(
            nn.Conv, use_bias=False, kernel_init=conv_init,
            dtype=self.dtype, param_dtype=self.param_dtype)

        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), (self.strides, self.strides), name="conv2")(y)
        y = norm(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.features * self.expansion, (1, 1), name="conv3")(y)
        scale_init = (nn.initializers.zeros if self.zero_init_residual
                      else nn.initializers.ones)
        y = norm(name="bn3", scale_init=scale_init)(y)

        if residual.shape != y.shape:
            residual = conv(self.features * self.expansion, (1, 1),
                            (self.strides, self.strides), name="downsample_conv")(residual)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Images (N,H,W,C float, already normalized) -> logits (N,num_classes)."""

    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int = 1000
    num_filters: int = 64
    cifar_stem: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    zero_init_residual: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype)
        conv = functools.partial(
            nn.Conv, use_bias=False, kernel_init=conv_init,
            dtype=self.dtype, param_dtype=self.param_dtype)

        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="stem_conv")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = self.block_cls(
                    features=self.num_filters * 2 ** stage,
                    strides=strides,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    zero_init_residual=self.zero_init_residual,
                    name=f"stage{stage + 1}_block{block}",
                )(x, train=train)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="fc")(x)
        return x.astype(jnp.float32)  # logits/loss in fp32 even under bf16

    @staticmethod
    def partition_rules() -> PartitionRules:
        """Pure-DP layout (every param replicated — the DDP layout). ResNets
        are small; FSDP rules can shard the fc layer if ever needed."""
        return PartitionRules()


@register_model("resnet18")
def resnet18(num_classes: int = 10, **kw) -> ResNet:
    """≙ torchvision.models.resnet18(num_classes=10), ref :154."""
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock,
                  num_classes=num_classes, **kw)


@register_model("resnet50")
def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    """BASELINE.json:9 — ResNet-50/ImageNet data-parallel config."""
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck,
                  num_classes=num_classes, **kw)
