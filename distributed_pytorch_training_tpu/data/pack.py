"""Pack a class-folder image tree into the framework's on-disk layout.

The torchvision-style ImageFolder tree the reference ecosystem uses
(`train/<class>/*.JPEG`, ref train_ddp.py:103-119's dataset ancestry) is a
host-decode-bound format: JPEG decode per sample per epoch, millions of tiny
files. The TPU-friendly layout is one packed uint8 `.npy` per split —
memory-mapped at load (datasets.load_imagenet), O(1) row access, batch
assembly via the native prefetcher's parallel row memcpy, augmentation on
device. Decode and resize happen ONCE, here, offline:

    python -m distributed_pytorch_training_tpu.data.pack \
        --src /data/imagenet/train --out ./data/imagenet --split train \
        --size 224

writes `train_images.npy` (N, 224, 224, 3) uint8, `train_labels.npy`
(N,) int64, and `classes.json` (sorted class-dir names -> index, the
torchvision class_to_idx convention). Images are resized so the short side
is `size` then center-cropped — the standard eval-style geometry; training
randomness (crop jitter + flip) stays on device (data/augment.py), where it
is fused into the forward pass.

The writer streams through np.lib.format.open_memmap, so packing a 150 GB
split needs no resident RAM either.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}


def _resize_center_crop(img, size: int) -> np.ndarray:
    """PIL image -> (size, size, 3) uint8: short-side resize + center crop."""
    w, h = img.size
    scale = size / min(w, h)
    img = img.resize((max(size, round(w * scale)),
                      max(size, round(h * scale))))
    w, h = img.size
    left, top = (w - size) // 2, (h - size) // 2
    img = img.crop((left, top, left + size, top + size))
    arr = np.asarray(img.convert("RGB"), dtype=np.uint8)
    return arr


def list_class_folders(src: Path) -> List[Tuple[str, List[Path]]]:
    """[(class_name, [image paths...])] — class dirs sorted by name (the
    torchvision class_to_idx rule, so indices match an ImageFolder run)."""
    out = []
    for cls_dir in sorted(p for p in src.iterdir() if p.is_dir()):
        files = sorted(p for p in cls_dir.rglob("*")
                       if p.suffix.lower() in IMAGE_EXTS)
        if files:
            out.append((cls_dir.name, files))
    return out


def pack_images(src: str, out: str, split: str, size: int = 224,
                classes: Optional[Sequence[str]] = None,
                log=print) -> Tuple[Path, Path]:
    """Pack `{src}/<class>/*.jpg` into `{out}/{split}_images.npy` +
    `{split}_labels.npy` (+ classes.json when packing the train split).
    `classes` pins the class->index map (pass the train split's order when
    packing val, so label spaces agree even if val misses a class)."""
    from PIL import Image

    src_p, out_p = Path(src), Path(out)
    folders = list_class_folders(src_p)
    if not folders:
        raise ValueError(f"no class folders with images under {src_p}")
    if classes is None:
        classes = [name for name, _ in folders]
    cls_to_idx = {c: i for i, c in enumerate(classes)}
    unknown = [name for name, _ in folders if name not in cls_to_idx]
    if unknown:
        raise ValueError(f"classes {unknown} not in the provided class map")

    n = sum(len(files) for _, files in folders)
    out_p.mkdir(parents=True, exist_ok=True)
    img_path = out_p / f"{split}_images.npy"
    lab_path = out_p / f"{split}_labels.npy"
    # stream into a disk-backed memmap: RAM stays O(1) regardless of N
    images = np.lib.format.open_memmap(
        img_path, mode="w+", dtype=np.uint8, shape=(n, size, size, 3))
    labels = np.empty(n, np.int64)
    i = 0
    for name, files in folders:
        for f in files:
            with Image.open(f) as im:
                images[i] = _resize_center_crop(im, size)
            labels[i] = cls_to_idx[name]
            i += 1
        log(f"pack: {split}: {name} done ({i}/{n})")
    images.flush()
    np.save(lab_path, labels)
    if classes is not None:
        (out_p / "classes.json").write_text(json.dumps(list(classes)))
    log(f"pack: wrote {img_path} {images.shape} + {lab_path}")
    return img_path, lab_path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--src", required=True,
                   help="class-folder tree (ImageFolder layout)")
    p.add_argument("--out", required=True,
                   help="output dir (becomes --data-dir/imagenet)")
    p.add_argument("--split", default="train", choices=["train", "val"])
    p.add_argument("--size", default=224, type=int)
    p.add_argument("--classes-from", default=None,
                   help="classes.json from a previous (train) pack, to pin "
                        "the class->index map for the val split")
    args = p.parse_args(argv)
    classes = None
    if args.classes_from:
        classes = json.loads(Path(args.classes_from).read_text())
    pack_images(args.src, args.out, args.split, args.size, classes)
    return 0


if __name__ == "__main__":
    sys.exit(main())
