"""Pack ragged data into the framework's static device shapes.

Two packers live here, one per direction of the data path:

* **Offline image packing** (`pack_images`, the module CLI): a
  torchvision-style ImageFolder tree into one packed uint8 `.npy` per
  split — decode/resize once, memory-mapped row access forever after.
* **Online sequence packing** (`bucket_for` / `pack_token_rows` /
  `unpack_token_rows`): a RAGGED batch of token sequences (serving
  requests, variable prompt lengths) into ONE static (rows, bucket)
  int32 matrix plus per-row lengths/weights. The bucket ladder is the
  compile-once contract: XLA compiles one program per (rows, bucket)
  shape, and every request thereafter reuses it — never a
  shape-of-the-request recompile. The serving engine
  (serving/batching.py) drains its request queue through these.

The torchvision-style ImageFolder tree the reference ecosystem uses
(`train/<class>/*.JPEG`, ref train_ddp.py:103-119's dataset ancestry) is a
host-decode-bound format: JPEG decode per sample per epoch, millions of tiny
files. The TPU-friendly layout is one packed uint8 `.npy` per split —
memory-mapped at load (datasets.load_imagenet), O(1) row access, batch
assembly via the native prefetcher's parallel row memcpy, augmentation on
device. Decode and resize happen ONCE, here, offline:

    python -m distributed_pytorch_training_tpu.data.pack \
        --src /data/imagenet/train --out ./data/imagenet --split train \
        --size 224

writes `train_images.npy` (N, 224, 224, 3) uint8, `train_labels.npy`
(N,) int64, and `classes.json` (sorted class-dir names -> index, the
torchvision class_to_idx convention). Images are resized so the short side
is `size` then center-cropped — the standard eval-style geometry; training
randomness (crop jitter + flip) stays on device (data/augment.py), where it
is fused into the forward pass.

The writer streams through np.lib.format.open_memmap, so packing a 150 GB
split needs no resident RAM either.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}


# ---------------------------------------------------------------------------
# Online sequence packing: ragged request batches -> static bucket shapes
# ---------------------------------------------------------------------------


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """The smallest bucket >= ``length`` from the (sorted-ascending) bucket
    ladder. One compiled program exists per bucket, so this choice decides
    which executable a request rides — and the padding it pays (at most to
    the next rung). A length above the top rung raises: silently truncating
    a request would serve logits for a prompt nobody sent."""
    if length <= 0:
        raise ValueError(f"sequence length must be >= 1, got {length}")
    for b in sorted(buckets):
        if length <= b:
            return int(b)
    raise ValueError(
        f"sequence length {length} exceeds the largest bucket "
        f"{max(buckets)} — add a rung to the bucket ladder or reject the "
        "request upstream")


def pack_token_rows(
    seqs: Sequence[np.ndarray], bucket: int, rows: int, pad_id: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack ragged token sequences into one static (rows, bucket) batch.

    Returns ``(ids, lengths, weight)``: ``ids`` int32 right-padded with
    ``pad_id`` (right-padding, NOT left: positions 0..len-1 keep the same
    position embeddings as the training/eval forward, which is what makes
    fp32 served logits bitwise-comparable to the eval forward), ``lengths``
    int32 per-row real lengths (0 for the padded filler rows beyond
    ``len(seqs)``), and ``weight`` fp32 1.0/0.0 per row (the loader
    convention: filler rows carry weight 0, so any metric path ignores
    them). Each request is its OWN row — requests are never concatenated
    into a shared row, so cross-request attention cannot exist by
    construction; trailing pad positions are masked by the causal
    structure (no real position ever attends forward into pad).
    """
    if len(seqs) > rows:
        raise ValueError(f"{len(seqs)} sequences do not fit {rows} rows")
    ids = np.full((rows, bucket), pad_id, np.int32)
    lengths = np.zeros(rows, np.int32)
    weight = np.zeros(rows, np.float32)
    for i, s in enumerate(seqs):
        s = np.asarray(s)
        if s.ndim != 1:
            raise ValueError(f"sequence {i} is not 1-D (shape {s.shape})")
        if len(s) > bucket:
            raise ValueError(
                f"sequence {i} ({len(s)} tokens) exceeds bucket {bucket} — "
                "route it through bucket_for first")
        ids[i, : len(s)] = s
        lengths[i] = len(s)
        weight[i] = 1.0
    return ids, lengths, weight


def unpack_token_rows(outputs: np.ndarray, lengths: np.ndarray,
                      n_real: int) -> List[np.ndarray]:
    """Invert `pack_token_rows` on a per-position output (rows, bucket, ...):
    per-request arrays with every pad position dropped — the round-trip
    contract the serving tests pin. ``n_real`` cuts the filler rows."""
    out = []
    for i in range(int(n_real)):
        out.append(np.asarray(outputs[i][: int(lengths[i])]))
    return out


def prompt_page_hashes(tokens: Sequence[int], page_size: int) -> List[str]:
    """Content hashes of the FULLY prompt-covered KV pages of a prompt:
    hash ``i`` digests ``tokens[0 : (i+1) * page_size]`` — the cumulative
    prefix, not the lone page, because a KV page's contents depend on every
    earlier token through attention's causal structure. Only pages wholly
    inside the prompt get a hash (a partially-filled tail page also
    receives DECODE writes, so it can never be shared). Two prompts with
    equal hashes have bitwise-identical k/v for those pages under the same
    weights — the prefix-sharing contract serving/paged.py's pool keys on.
    """
    import hashlib

    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    toks = np.asarray(tokens, np.int64)
    out: List[str] = []
    for end in range(page_size, len(toks) + 1, page_size):
        out.append(hashlib.sha1(toks[:end].tobytes()).hexdigest())
    return out


def _resize_center_crop(img, size: int) -> np.ndarray:
    """PIL image -> (size, size, 3) uint8: short-side resize + center crop."""
    w, h = img.size
    scale = size / min(w, h)
    img = img.resize((max(size, round(w * scale)),
                      max(size, round(h * scale))))
    w, h = img.size
    left, top = (w - size) // 2, (h - size) // 2
    img = img.crop((left, top, left + size, top + size))
    arr = np.asarray(img.convert("RGB"), dtype=np.uint8)
    return arr


def list_class_folders(src: Path) -> List[Tuple[str, List[Path]]]:
    """[(class_name, [image paths...])] — class dirs sorted by name (the
    torchvision class_to_idx rule, so indices match an ImageFolder run)."""
    out = []
    for cls_dir in sorted(p for p in src.iterdir() if p.is_dir()):
        files = sorted(p for p in cls_dir.rglob("*")
                       if p.suffix.lower() in IMAGE_EXTS)
        if files:
            out.append((cls_dir.name, files))
    return out


def pack_images(src: str, out: str, split: str, size: int = 224,
                classes: Optional[Sequence[str]] = None,
                log=print) -> Tuple[Path, Path]:
    """Pack `{src}/<class>/*.jpg` into `{out}/{split}_images.npy` +
    `{split}_labels.npy` (+ classes.json when packing the train split).
    `classes` pins the class->index map (pass the train split's order when
    packing val, so label spaces agree even if val misses a class)."""
    from PIL import Image

    src_p, out_p = Path(src), Path(out)
    folders = list_class_folders(src_p)
    if not folders:
        raise ValueError(f"no class folders with images under {src_p}")
    if classes is None:
        classes = [name for name, _ in folders]
    cls_to_idx = {c: i for i, c in enumerate(classes)}
    unknown = [name for name, _ in folders if name not in cls_to_idx]
    if unknown:
        raise ValueError(f"classes {unknown} not in the provided class map")

    n = sum(len(files) for _, files in folders)
    out_p.mkdir(parents=True, exist_ok=True)
    img_path = out_p / f"{split}_images.npy"
    lab_path = out_p / f"{split}_labels.npy"
    # stream into a disk-backed memmap: RAM stays O(1) regardless of N
    images = np.lib.format.open_memmap(
        img_path, mode="w+", dtype=np.uint8, shape=(n, size, size, 3))
    labels = np.empty(n, np.int64)
    i = 0
    for name, files in folders:
        for f in files:
            with Image.open(f) as im:
                images[i] = _resize_center_crop(im, size)
            labels[i] = cls_to_idx[name]
            i += 1
        log(f"pack: {split}: {name} done ({i}/{n})")
    images.flush()
    np.save(lab_path, labels)
    if classes is not None:
        (out_p / "classes.json").write_text(json.dumps(list(classes)))
    log(f"pack: wrote {img_path} {images.shape} + {lab_path}")
    return img_path, lab_path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--src", required=True,
                   help="class-folder tree (ImageFolder layout)")
    p.add_argument("--out", required=True,
                   help="output dir (becomes --data-dir/imagenet)")
    p.add_argument("--split", default="train", choices=["train", "val"])
    p.add_argument("--size", default=224, type=int)
    p.add_argument("--classes-from", default=None,
                   help="classes.json from a previous (train) pack, to pin "
                        "the class->index map for the val split")
    args = p.parse_args(argv)
    classes = None
    if args.classes_from:
        classes = json.loads(Path(args.classes_from).read_text())
    pack_images(args.src, args.out, args.split, args.size, classes)
    return 0


if __name__ == "__main__":
    sys.exit(main())
