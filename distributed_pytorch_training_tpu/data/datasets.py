"""Datasets: CIFAR-10 from disk, synthetic generators for every config.

The reference downloads CIFAR-10 via torchvision with a rank-0-only download
plus barrier (/root/reference/train_ddp.py:103-112). This environment has no
network egress, so the TPU pipeline reads the standard CIFAR-10 python-pickle
layout from disk when present and otherwise generates a deterministic
synthetic stand-in with identical shapes/dtypes — which is also what the
ImageNet-scale benchmark configs (BASELINE.json:8-10) use, since ImageNet
cannot ship with a repo either.
"""

from __future__ import annotations

import dataclasses
import pickle
import tarfile
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

# Reference normalization constants (train_ddp.py:86-89).
CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)
CIFAR10_STD = (0.2470, 0.2435, 0.2616)
# Standard ImageNet stats (torchvision defaults the reference would use for
# the ResNet-50/ViT configs, BASELINE.json:9-10).
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


@dataclasses.dataclass
class ArrayDataset:
    """In-memory dataset: images NHWC uint8, integer labels."""

    images: np.ndarray  # (N, H, W, C) uint8
    labels: np.ndarray  # (N,) int32
    num_classes: int
    name: str = "dataset"
    synthetic: bool = False

    def __post_init__(self):
        assert self.images.ndim == 4 and self.images.dtype == np.uint8
        assert len(self.images) == len(self.labels)
        self.labels = self.labels.astype(np.int32)

    def __len__(self) -> int:
        return len(self.images)


def _cifar_batches_dir(data_dir: Path) -> Optional[Path]:
    for cand in (data_dir / "cifar-10-batches-py", data_dir):
        if (cand / "data_batch_1").exists():
            return cand
    tar = data_dir / "cifar-10-python.tar.gz"
    if tar.exists():
        with tarfile.open(tar) as tf:
            tf.extractall(data_dir)
        cand = data_dir / "cifar-10-batches-py"
        if (cand / "data_batch_1").exists():
            return cand
    return None


def load_cifar10(data_dir: str, train: bool) -> Optional[ArrayDataset]:
    """Read the standard CIFAR-10 python pickle layout (what torchvision's
    download produces, ref :103-108). Returns None if absent on disk."""
    root = _cifar_batches_dir(Path(data_dir))
    if root is None:
        return None
    files = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    xs, ys = [], []
    for fname in files:
        with open(root / fname, "rb") as f:
            entry = pickle.load(f, encoding="latin1")
        xs.append(np.asarray(entry["data"], np.uint8))
        ys.append(np.asarray(entry.get("labels", entry.get("fine_labels")), np.int32))
    # CHW-planar records -> NHWC, decoded by the native runtime when present
    # (the torchvision C++ image-op role, SURVEY.md §2b).
    from ..native import chw_to_hwc_u8

    images = chw_to_hwc_u8(np.concatenate(xs), 3, 32, 32)
    return ArrayDataset(images, np.concatenate(ys), num_classes=10,
                        name="cifar10", synthetic=False)


def synthetic_image_dataset(
    n: int,
    hw: Tuple[int, int] = (32, 32),
    num_classes: int = 10,
    seed: int = 0,
    name: str = "synthetic",
) -> ArrayDataset:
    """Deterministic synthetic image classification data.

    Class-conditional means keep the learning problem non-trivial, so
    integration tests can assert decreasing loss (SURVEY.md §4).
    """
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int32)
    # Class-conditional means come from a FIXED seed so train and val splits
    # (different `seed`s) describe the same classification problem; only
    # labels/noise vary per split.
    class_means = np.random.RandomState(1234).randint(
        40, 216, size=(num_classes, 1, 1, 3))
    noise = rng.randint(-40, 40, size=(n, *hw, 3))
    images = np.clip(class_means[labels] + noise, 0, 255).astype(np.uint8)
    return ArrayDataset(images, labels, num_classes=num_classes,
                        name=name, synthetic=True)


_SYNTH_SIZES = {  # (train_n, eval_n) kept CPU-friendly; benches override
    "cifar10": (50_000, 10_000),
    "imagenet": (10_000, 1_000),
}


def get_dataset(
    name: str,
    data_dir: str = "./data",
    train: bool = True,
    synthetic: bool = False,
    synthetic_size: Optional[int] = None,
    seed: int = 0,
    download: bool = False,
) -> ArrayDataset:
    """Dataset factory (maps get_dataloaders' dataset construction, ref
    :103-119). ``download=True`` fetches+verifies the archive when absent
    (the torchvision ``download=(rank==0)`` role, ref :106 — pass True only
    on process 0 and barrier, as train.py does). Falls back to synthetic
    data when the real set is absent — loudly, via the `.synthetic` flag."""
    name = name.lower()
    if name == "cifar10":
        if not synthetic:
            if download:
                from .download import ensure_cifar10

                ensure_cifar10(data_dir, download=True)
            ds = load_cifar10(data_dir, train)
            if ds is not None:
                return ds
        n = synthetic_size or _SYNTH_SIZES["cifar10"][0 if train else 1]
        return synthetic_image_dataset(n, (32, 32), 10, seed=seed + (0 if train else 1),
                                       name="cifar10-synthetic")
    if name == "imagenet":
        if not synthetic:
            ds = load_imagenet(data_dir, train)
            if ds is not None:
                return ds
        n = synthetic_size or _SYNTH_SIZES["imagenet"][0 if train else 1]
        return synthetic_image_dataset(n, (224, 224), 1000, seed=seed + (0 if train else 1),
                                       name="imagenet-synthetic")
    raise ValueError(f"unknown dataset {name!r} (cifar10, imagenet)")


def load_imagenet(data_dir: str, train: bool) -> Optional[ArrayDataset]:
    """Packed-layout ImageNet (or any image corpus): memory-mapped
    `{split}_images.npy` (N, H, W, 3) uint8 + `{split}_labels.npy` under
    `{data_dir}/imagenet/`, as written by ``python -m
    distributed_pytorch_training_tpu.data.pack`` from a class-folder JPEG
    tree (the torchvision ImageFolder layout the reference-style pipeline
    reads, ref :103-119 analogue).

    The memmap is the TPU-friendly design: O(1) row access with no JPEG
    decode in the hot loop — the native prefetcher's row gather pages in
    exactly the batch rows, so a 150 GB train split needs no resident RAM.
    Returns None when the packed files are absent (caller falls back to
    synthetic, loudly)."""
    import json

    split = "train" if train else "val"
    base = Path(data_dir) / "imagenet"
    img_p, lab_p = base / f"{split}_images.npy", base / f"{split}_labels.npy"
    if not (img_p.exists() and lab_p.exists()):
        return None
    images = np.load(img_p, mmap_mode="r")
    labels = np.load(lab_p)
    classes_p = base / "classes.json"
    if classes_p.exists():
        num_classes = len(json.loads(classes_p.read_text()))
    else:
        num_classes = int(labels.max()) + 1
    return ArrayDataset(images, labels, num_classes=num_classes,
                        name=f"imagenet-{split}", synthetic=False)
