"""Dataset fetching: checksum-verified download + extract, rank-0 gated.

The reference's data layer downloads CIFAR-10 through torchvision with
``download=(rank == 0)`` and holds every other rank at a barrier until the
files exist (/root/reference/train_ddp.py:103-112). This module is the
TPU-native equivalent of that capability: a stdlib-only fetcher with

* atomic writes (``.part`` tempfile + rename — a crashed download can never
  be mistaken for a finished one),
* mandatory-when-given SHA-256 verification (torchvision checks MD5; a
  checksum mismatch deletes the file and raises, it is never "kept anyway"),
* bounded retries with backoff for transient network errors,
* idempotence (existing file with matching checksum -> no network touched),

plus ``ensure_cifar10`` mapping the exact torchvision contract. Process
gating stays where the reference put it: the CALLER downloads on process 0
and barriers (train.py does this around ``_load_datasets``); this module is
process-agnostic.

Zero-egress environments: everything here is exercised in tests against a
loopback HTTP server (tests/test_download.py); real fetches simply raise
after retries, and `get_dataset` falls back to synthetic data loudly.
"""

from __future__ import annotations

import hashlib
import http.client
import shutil
import tarfile
import time
import urllib.error
import urllib.request
from pathlib import Path
from typing import Optional

# The canonical CIFAR-10 python-pickle archive the reference's stack fetches
# (torchvision's cifar.py url/tgz_md5 pair, here with SHA-256).
CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_SHA256 = (
    "6d958be074577803d12ecdefd02955f39262c83c16fe9348329d7fe0b5c001ce")


class ChecksumError(RuntimeError):
    """Downloaded bytes do not match the expected digest."""


def sha256_file(path: Path, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def fetch(url: str, dest: str, sha256: Optional[str] = None, *,
          retries: int = 3, timeout: float = 60.0,
          backoff: float = 2.0) -> Path:
    """Download `url` to `dest` (a file path), verified and atomic.

    Returns immediately (no network) when `dest` already exists and matches
    `sha256`. On digest mismatch the bad file is removed and ChecksumError
    raised — callers can never train on a truncated archive.
    """
    dest_path = Path(dest)
    dest_path.parent.mkdir(parents=True, exist_ok=True)

    if dest_path.exists():
        if sha256 is None or sha256_file(dest_path) == sha256:
            return dest_path
        dest_path.unlink()  # stale/corrupt cache: refetch

    part = dest_path.with_suffix(dest_path.suffix + ".part")
    last: Optional[Exception] = None
    for attempt in range(1, retries + 1):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    open(part, "wb") as out:
                shutil.copyfileobj(r, out)
        # HTTPException covers IncompleteRead — a connection dropped
        # mid-body — which is neither a URLError nor an OSError.
        except (urllib.error.URLError, OSError,
                http.client.HTTPException) as e:
            last = e
            part.unlink(missing_ok=True)
            if attempt < retries:
                time.sleep(min(backoff ** attempt, 30) if backoff else 0)
            continue
        # Verify INSIDE the retry loop: a dropped connection can also
        # surface as a silently short body (no exception at all — observed
        # with Content-Length mismatch), which only the digest catches.
        # Transient truncation therefore retries; a persistently wrong file
        # exhausts the attempts and raises ChecksumError.
        got = sha256_file(part) if sha256 is not None else None
        if sha256 is None or got == sha256:
            # atomic: readers see absent or complete, never partial
            part.replace(dest_path)
            return dest_path
        part.unlink()
        last = ChecksumError(
            f"{url}: SHA-256 mismatch: expected {sha256}, got {got}")
        if attempt < retries:
            time.sleep(min(backoff ** attempt, 30) if backoff else 0)
    if isinstance(last, ChecksumError):
        raise last
    raise RuntimeError(
        f"download failed after {retries} attempts: {url}: {last}")


def fetch_and_extract(url: str, data_dir: str,
                      sha256: Optional[str] = None,
                      filename: Optional[str] = None,
                      **fetch_kwargs) -> Path:
    """Fetch a .tar/.tar.gz archive into `data_dir` and extract it there.

    Returns the archive path. Extraction uses the stdlib 'data' filter
    (no path traversal out of data_dir). Extra kwargs go to `fetch`.
    """
    data_dir_p = Path(data_dir)
    name = filename or url.rsplit("/", 1)[-1]
    archive = fetch(url, str(data_dir_p / name), sha256, **fetch_kwargs)
    with tarfile.open(archive) as tf:
        try:
            tf.extractall(data_dir_p, filter="data")
        except TypeError:  # older tarfile without filter=
            tf.extractall(data_dir_p)
    return archive


def ensure_cifar10(data_dir: str, download: bool = False,
                   url: Optional[str] = None,
                   sha256: Optional[str] = None) -> bool:
    """The torchvision ``CIFAR10(root, download=...)`` contract
    (ref :103-108): True iff the batch files are usable on return.

    Already on disk -> True (no network). Absent and ``download`` -> fetch +
    verify + extract -> True. Absent and not ``download`` -> False (the
    caller decides between erroring and synthetic fallback).
    """
    from .datasets import _cifar_batches_dir

    if _cifar_batches_dir(Path(data_dir)) is not None:
        return True
    if not download:
        return False
    # read the module constants at call time so tests/configs can repoint
    # the source (e.g. an internal mirror) by assignment
    fetch_and_extract(url or CIFAR10_URL, data_dir,
                      sha256 if sha256 is not None else CIFAR10_SHA256)
    return _cifar_batches_dir(Path(data_dir)) is not None
