"""Device-side augmentation — the transform pipeline, jit-fused.

Reference transforms (/root/reference/train_ddp.py:91-101): RandomCrop(32,
padding=4) + RandomHorizontalFlip + ToTensor + Normalize for train; ToTensor +
Normalize for eval. torchvision runs these per-sample in DataLoader worker
processes on the host; here they are vectorized jax ops executed on the TPU as
part of the compiled step, where XLA fuses them into the input side of the
forward pass (no host CPU augmentation bottleneck, no extra H2D traffic —
uint8 crosses the wire, float math happens on device).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def normalize_images(
    images: jnp.ndarray,
    mean: Sequence[float],
    std: Sequence[float],
    dtype: jnp.dtype = jnp.float32,
) -> jnp.ndarray:
    """uint8 NHWC -> normalized float (ToTensor + Normalize, ref :94-95,
    :86-89). `dtype` is the compute dtype (bf16 under mixed precision)."""
    x = images.astype(jnp.float32) / 255.0
    mean = jnp.asarray(mean, jnp.float32).reshape(1, 1, 1, -1)
    std = jnp.asarray(std, jnp.float32).reshape(1, 1, 1, -1)
    return ((x - mean) / std).astype(dtype)


def random_crop_flip(
    images: jnp.ndarray,
    key: jax.Array,
    padding: int = 4,
    flip_prob: float = 0.5,
) -> jnp.ndarray:
    """RandomCrop(H, padding) + RandomHorizontalFlip, vectorized over the
    batch (ref :92-93). Input NHWC (any numeric dtype); output same shape.

    Implementation notes for XLA: per-sample crop offsets become one
    `dynamic_slice` per sample under `vmap` — static output shapes, fully
    fusable, no data-dependent control flow.
    """
    n, h, w, c = images.shape
    key_crop_h, key_crop_w, key_flip = jax.random.split(key, 3)
    padded = jnp.pad(
        images,
        ((0, 0), (padding, padding), (padding, padding), (0, 0)),
        mode="constant",
    )
    off_h = jax.random.randint(key_crop_h, (n,), 0, 2 * padding + 1)
    off_w = jax.random.randint(key_crop_w, (n,), 0, 2 * padding + 1)

    # Per-sample crop as ONE batched gather (advanced indexing), not a
    # vmap'd dynamic_slice: compile time stays O(1) in batch size (the
    # slice form made XLA compile minutes-long programs at batch >= 2048).
    rows = off_h[:, None] + jnp.arange(h)[None, :]           # (N, h)
    cols = off_w[:, None] + jnp.arange(w)[None, :]           # (N, w)
    cropped = padded[jnp.arange(n)[:, None, None],
                     rows[:, :, None], cols[:, None, :]]     # (N, h, w, C)
    flip = jax.random.bernoulli(key_flip, flip_prob, (n, 1, 1, 1))
    return jnp.where(flip, cropped[:, :, ::-1, :], cropped)
