"""Device-side augmentation — the transform pipeline, jit-fused.

Reference transforms (/root/reference/train_ddp.py:91-101): RandomCrop(32,
padding=4) + RandomHorizontalFlip + ToTensor + Normalize for train; ToTensor +
Normalize for eval. torchvision runs these per-sample in DataLoader worker
processes on the host; here they are vectorized jax ops executed on the TPU as
part of the compiled step, where XLA fuses them into the input side of the
forward pass (no host CPU augmentation bottleneck, no extra H2D traffic —
uint8 crosses the wire, float math happens on device).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def normalize_images(
    images: jnp.ndarray,
    mean: Sequence[float],
    std: Sequence[float],
    dtype: jnp.dtype = jnp.float32,
) -> jnp.ndarray:
    """uint8 NHWC -> normalized float (ToTensor + Normalize, ref :94-95,
    :86-89). `dtype` is the compute dtype (bf16 under mixed precision)."""
    x = images.astype(jnp.float32) / 255.0
    mean = jnp.asarray(mean, jnp.float32).reshape(1, 1, 1, -1)
    std = jnp.asarray(std, jnp.float32).reshape(1, 1, 1, -1)
    return ((x - mean) / std).astype(dtype)


def random_crop_flip(
    images: jnp.ndarray,
    key: jax.Array,
    padding: int = 4,
    flip_prob: float = 0.5,
) -> jnp.ndarray:
    """RandomCrop(H, padding) + RandomHorizontalFlip, vectorized over the
    batch (ref :92-93). Input NHWC (any numeric dtype); output same shape.

    Implementation notes for XLA/TPU: the per-sample crop is expressed as two
    batched one-hot matmuls (row select, then column select), NOT a gather.
    A batched 3-index gather here compiles to a u8[N*H*W, C] kernel whose
    C-wide minor dimension wastes 125 of 128 vector lanes — measured 16 ms of
    a 21 ms ResNet-18 step at batch 2048 on a v5e chip, ~70% of step time.
    The one-hot selection rides the MXU instead (<0.1 ms) and is *bit-exact*:
    every output element is dot(one_hot_row, values) with exactly one nonzero
    0/1 weight, so no rounding occurs for uint8/int inputs even in a bf16
    pass (0..255 are exactly representable: 8 significand bits). The flip is
    folded into the column-selection indices (reversed per flipped sample),
    so crop+flip is still just the two matmuls. Wider dtypes select through a
    float32 HIGHEST pass: exact for integer values up to 2^24. Two caveats vs
    a gather: integers beyond 2^24 round, and a non-finite pixel (inf/NaN
    sentinel) contaminates its whole row/column of the contraction — feed
    finite pixel data.
    """
    n, h, w, c = images.shape
    key_crop_h, key_crop_w, key_flip = jax.random.split(key, 3)
    padded = jnp.pad(
        images,
        ((0, 0), (padding, padding), (padding, padding), (0, 0)),
        mode="constant",
    )
    off_h = jax.random.randint(key_crop_h, (n,), 0, 2 * padding + 1)
    off_w = jax.random.randint(key_crop_w, (n,), 0, 2 * padding + 1)
    flip = jax.random.bernoulli(key_flip, flip_prob, (n,))

    # bf16 pass only for dtypes whose values it represents exactly (8-bit
    # ints: 0..255 fit in bf16's 8 significand bits; bf16 itself). Wider
    # ints / other floats select in float32 under HIGHEST so e.g. uint16
    # sensor values survive bit-exact (exact up to 2^24).
    if images.dtype in (jnp.uint8, jnp.int8, jnp.bfloat16):
        sel_dtype, precision = jnp.bfloat16, jax.lax.Precision.DEFAULT
    else:
        sel_dtype, precision = jnp.float32, jax.lax.Precision.HIGHEST

    hp, wp = h + 2 * padding, w + 2 * padding
    rows = jax.nn.one_hot(off_h[:, None] + jnp.arange(h), hp,
                          dtype=sel_dtype)                   # (N, h, HP)
    # Horizontal flip ≙ selecting columns in reverse order: applied on the
    # (N, w) index array, free on the (N, h, w, C) images.
    col_idx = jnp.where(flip[:, None],
                        off_w[:, None] + (w - 1) - jnp.arange(w),
                        off_w[:, None] + jnp.arange(w))
    cols = jax.nn.one_hot(col_idx, wp, dtype=sel_dtype)      # (N, w, WP)

    x = jnp.einsum("nhp,npwc->nhwc", rows, padded.astype(sel_dtype),
                   precision=precision)
    x = jnp.einsum("nwp,nhpc->nhwc", cols, x, precision=precision)
    return x.astype(images.dtype)
