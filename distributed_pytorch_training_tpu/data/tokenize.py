"""Tokenize raw text into the packed-token layout the LM configs train on.

`data/text.py` reads `{data_dir}/{family}_{split}.npy` — a flat array of
token ids chunked to sequences at load. This tool writes those files from
raw text:

    python -m distributed_pytorch_training_tpu.data.tokenize \
        --tokenizer gpt2 --out ./data corpus1.txt corpus2.txt

* ``--tokenizer gpt2`` / ``bert-base-uncased`` / any HF name: uses the
  `transformers` fast tokenizer (GPT-2's public BPE vocab). Requires the
  tokenizer files locally (HF cache) or network access — on a zero-egress
  box, pre-seed the cache or use the fallback below.
* ``--tokenizer bytes``: the dependency-free byte-level fallback — UTF-8
  bytes are the token ids (vocab 256, a strict subset of both LM vocabs, so
  the stock gpt2/bert models train on it unchanged; perplexities are
  byte-level, not BPE-level).

Output: ``{out}/{family}_train.npy`` and ``{family}_val.npy`` (uint16 when
the vocab fits, else uint32), split ``--val-fraction`` from the tail —
loaded and chunked by data.text.get_token_dataset, which then reports
``synthetic=False`` (the r3 verdict's missing real-data LM path).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Iterable, List

import numpy as np


def encode_bytes(texts: Iterable[str]) -> np.ndarray:
    """Byte-level fallback: UTF-8 bytes as token ids (vocab 256)."""
    chunks = [np.frombuffer(t.encode("utf-8"), dtype=np.uint8)
              for t in texts]
    return np.concatenate(chunks).astype(np.uint16) if chunks else \
        np.zeros(0, np.uint16)


def encode_hf(texts: Iterable[str], tokenizer_name: str) -> np.ndarray:
    """HF fast-tokenizer path (gpt2 BPE / bert WordPiece / any name)."""
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(tokenizer_name)
    ids: List[int] = []
    for t in texts:
        ids.extend(tok(t, add_special_tokens=False)["input_ids"])
    arr = np.asarray(ids, np.int64)
    if arr.size and arr.max() >= 2 ** 16:
        return arr.astype(np.uint32)
    return arr.astype(np.uint16)


def tokenize_files(paths: Iterable[str], tokenizer: str, out_dir: str,
                   family: str, val_fraction: float = 0.1,
                   log=print) -> None:
    texts = [Path(p).read_text(encoding="utf-8", errors="replace")
             for p in paths]
    if tokenizer == "bytes":
        flat = encode_bytes(texts)
    else:
        flat = encode_hf(texts, tokenizer)
    if flat.size == 0:
        raise ValueError("no tokens produced — empty input files?")
    n_val = int(len(flat) * val_fraction)
    train, val = (flat[:-n_val], flat[-n_val:]) if n_val else (flat, flat[:0])
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    np.save(out / f"{family}_train.npy", train)
    np.save(out / f"{family}_val.npy", val)
    log(f"tokenize: {len(flat):,} tokens ({tokenizer}, dtype {flat.dtype}) "
        f"-> {out}/{family}_train.npy ({len(train):,}) + "
        f"{family}_val.npy ({len(val):,})")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("files", nargs="+", help="raw UTF-8 text files")
    p.add_argument("--tokenizer", default="gpt2",
                   help="HF tokenizer name, or 'bytes' for the "
                        "dependency-free byte-level fallback")
    p.add_argument("--out", default="./data")
    p.add_argument("--family", default="gpt2", choices=["gpt2", "bert"],
                   help="output filename prefix (matches --model family)")
    p.add_argument("--val-fraction", default=0.1, type=float)
    args = p.parse_args(argv)
    tokenize_files(args.files, args.tokenizer, args.out, args.family,
                   args.val_fraction)
    return 0


if __name__ == "__main__":
    sys.exit(main())
