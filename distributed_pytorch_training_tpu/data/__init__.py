"""Input pipeline — TPU-native equivalent of the reference's L2 layer
(/root/reference/train_ddp.py:81-150: torchvision CIFAR-10 + transforms +
DistributedSampler + DataLoader workers).

Design: the host side stays cheap (uint8 arrays, index shuffling, thread
prefetch); normalization and augmentation run **on device inside the jitted
step** where they fuse into the forward pass — the TPU answer to torchvision
transform pipelines and `pin_memory` H2D overlap (ref :131-148).
"""

from .datasets import (  # noqa: F401
    CIFAR10_MEAN,
    CIFAR10_STD,
    IMAGENET_MEAN,
    IMAGENET_STD,
    ArrayDataset,
    get_dataset,
    load_cifar10,
    synthetic_image_dataset,
)
from .augment import normalize_images, random_crop_flip  # noqa: F401
from .download import ensure_cifar10, fetch, fetch_and_extract  # noqa: F401
from .loader import ShardedLoader  # noqa: F401
from .sampler import ShardedSampler  # noqa: F401
