"""Text/LM data pipeline — token datasets for the BERT-MLM and GPT-2 configs
(BASELINE.json:11-12). No analogue in the reference (vision-only); this is
the text-side counterpart of datasets.py.

Zero-egress: corpora are synthetic token streams with Zipfian unigram
statistics (so losses have realistic scale) or token arrays loaded from disk
(.npy / .bin of uint16/uint32 token ids — the standard packed-LM layout).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from .. import native
from ..parallel.mesh import batch_shard_count
from ..parallel.sharding import shard_batch
from .sampler import ShardedSampler


@dataclasses.dataclass
class TokenDataset:
    """Packed token ids (N, seq_len) int32, already chunked to sequences."""

    tokens: np.ndarray  # (N, S) int32
    vocab_size: int
    name: str = "tokens"
    synthetic: bool = False

    def __post_init__(self):
        assert self.tokens.ndim == 2
        self.tokens = self.tokens.astype(np.int32)

    def __len__(self) -> int:
        return len(self.tokens)

    @property
    def seq_len(self) -> int:
        return self.tokens.shape[1]


def synthetic_token_dataset(
    n: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    name: str = "synthetic-tokens",
) -> TokenDataset:
    """Zipfian token sequences — deterministic, loss-scale-realistic."""
    rng = np.random.RandomState(seed)
    # Zipf over the vocab (clipped to vocab_size); ids shuffled so frequent
    # tokens are spread over the id space like a real BPE vocab.
    raw = rng.zipf(1.3, size=(n, seq_len))
    ids = np.minimum(raw, vocab_size) - 1
    perm = np.random.RandomState(1234).permutation(vocab_size)
    return TokenDataset(perm[ids], vocab_size, name=name, synthetic=True)


def load_token_file(path: str, seq_len: int, vocab_size: int) -> TokenDataset:
    """Load a packed token file (.npy, or flat .bin of uint16 — the
    nanoGPT-style layout) and chunk into (N, seq_len). Written by
    ``python -m distributed_pytorch_training_tpu.data.tokenize`` (GPT-2 BPE
    via transformers, or the dependency-free byte-level fallback).

    .npy loads memory-mapped; the int32 conversion below materializes the
    (truncated) token matrix — at GPT-2 scales (billions of tokens) swap
    the model input pipeline to uint16 gathers before worrying here."""
    p = Path(path)
    if p.suffix == ".npy":
        flat = np.load(p, mmap_mode="r").ravel()
    else:
        flat = np.fromfile(p, dtype=np.uint16).astype(np.int64)
    n = len(flat) // seq_len
    return TokenDataset(flat[: n * seq_len].reshape(n, seq_len).astype(np.int32),
                        vocab_size, name=p.stem, synthetic=False)


def get_token_dataset(
    name: str,
    seq_len: int,
    data_dir: str = "./data",
    train: bool = True,
    synthetic_size: Optional[int] = None,
    seed: int = 0,
) -> TokenDataset:
    """Factory keyed by config name: 'bert' (vocab 30522), 'gpt2' (50257)."""
    vocabs = {"bert": 30522, "gpt2": 50257}
    if name not in vocabs:
        raise ValueError(f"unknown text dataset {name!r} ({sorted(vocabs)})")
    vocab = vocabs[name]
    fname = Path(data_dir) / f"{name}_{'train' if train else 'val'}.npy"
    if fname.exists():
        return load_token_file(str(fname), seq_len, vocab)
    n = synthetic_size or (4096 if train else 512)
    return TokenDataset(
        synthetic_token_dataset(n, seq_len, vocab,
                                seed=seed + (0 if train else 1)).tokens,
        vocab, name=f"{name}-synthetic", synthetic=True)


class TokenLoader:
    """Mesh-sharded LM batches: {"input_ids": (B, S) int32, "weight": (B,)}.

    Same sharding/padding semantics as data.loader.ShardedLoader; token
    masking (MLM) and next-token shifting are device-side task concerns
    (training/tasks.py), not loader concerns. ``fault_hook`` is the same
    resilience/faults.py injection point ShardedLoader carries (the
    ``loader_stall`` chaos fault — the ROADMAP-carried constraint): called
    with the in-epoch step index before that step's batch is produced;
    None on every un-instrumented run, zero hot-path cost.
    """

    def __init__(self, dataset: TokenDataset, mesh: Mesh,
                 per_device_batch: int, shuffle: bool, seed: int = 42,
                 drop_last: bool = False, fault_hook=None):
        self.fault_hook = fault_hook
        self.dataset = dataset
        self.mesh = mesh
        self.global_batch = per_device_batch * batch_shard_count(mesh)
        self.sampler = ShardedSampler(
            n=len(dataset), global_batch=self.global_batch, shuffle=shuffle,
            seed=seed, drop_last=drop_last,
            process_index=jax.process_index(),
            process_count=jax.process_count())

    def __len__(self) -> int:
        return self.sampler.steps_per_epoch()

    def epoch(self, epoch: int,
              start_step: int = 0) -> Iterator[Dict[str, jax.Array]]:
        for k, (idx, w) in enumerate(
                self.sampler.iter_epoch(epoch, start_step)):
            if self.fault_hook is not None:
                self.fault_hook(start_step + k)
            yield shard_batch({
                # native byte-wise row gather (works for int32 rows too)
                "input_ids": native.gather_rows(self.dataset.tokens, idx),
                "weight": w,
            }, self.mesh)
