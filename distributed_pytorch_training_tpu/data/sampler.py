"""Sharded sampling: the DistributedSampler contract, jit-shaped.

Reference semantics (/root/reference/train_ddp.py:121-139):
* `DistributedSampler(shuffle=True)` — a global permutation seeded by
  `seed + epoch` (`set_epoch`, ref :185), partitioned across ranks.
* `drop_last=False` (ref :139) — the last incomplete batch still trains.

The TPU twist: jit wants static shapes, so a short last batch would trigger
recompilation. Instead the permutation is padded up to a whole number of
global batches and a per-sample weight array marks padding with 0 (SURVEY.md
§7 "hard parts (a)"). Loss and metrics are weight-aware, so they match the
variable-batch semantics exactly. Padding slots hold *wrap-around repeats of
the shuffled permutation* (the same trick torch's DistributedSampler uses to
even out ranks), so batch-statistic layers (BatchNorm) see real, varied
samples — only the loss/metric contribution of the repeats is masked out.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Tuple

import numpy as np

from .. import native


@dataclasses.dataclass
class ShardedSampler:
    """Deterministic epoch sharding of `n` samples into fixed-size global
    batches, sliced per process.

    Parameters mirror the reference: `global_batch` = per-device batch x
    batch-shard count (ref :27 per-GPU semantic), `shuffle` + `seed` feed the
    per-epoch permutation (ref :122-127, :185), `drop_last` (ref :139).
    `process_index`/`process_count` generalize `rank`/`num_replicas`.
    """

    n: int
    global_batch: int
    shuffle: bool = True
    seed: int = 42
    drop_last: bool = False
    process_index: int = 0
    process_count: int = 1

    def __post_init__(self):
        if self.global_batch % self.process_count:
            raise ValueError(
                f"global batch {self.global_batch} not divisible by "
                f"{self.process_count} processes"
            )
        self.local_batch = self.global_batch // self.process_count

    def steps_per_epoch(self) -> int:
        if self.drop_last:
            return self.n // self.global_batch
        return -(-self.n // self.global_batch)  # ceil

    def epoch_indices(self, epoch: int) -> Tuple[np.ndarray, np.ndarray]:
        """(indices, weights) for this process, shaped
        (steps, local_batch); weights are 0.0 on padding slots.

        The permutation is identical on every process (same seed+epoch rule
        as `set_epoch`, ref :185) so shards are disjoint and exhaustive.
        """
        if self.shuffle:
            # Native splitmix64 Fisher-Yates (native/, with a bit-identical
            # Python mirror) — every host derives the same order from
            # seed+epoch whether or not it has a C++ toolchain.
            order = native.permutation(self.seed + epoch, self.n)
        else:
            order = np.arange(self.n)
        steps = self.steps_per_epoch()
        usable = steps * self.global_batch
        if self.drop_last:
            order = order[:usable]
            weights = np.ones(usable, np.float32)
        else:
            pad = usable - self.n
            weights = np.concatenate([np.ones(self.n, np.float32),
                                      np.zeros(pad, np.float32)])
            # wrap-around padding with real samples (DistributedSampler-style)
            reps = np.resize(order, pad) if pad else order[:0]
            order = np.concatenate([order, reps])
        order = order.reshape(steps, self.process_count, self.local_batch)
        weights = weights.reshape(steps, self.process_count, self.local_batch)
        return order[:, self.process_index], weights[:, self.process_index]

    def iter_epoch(self, epoch: int,
                   start_step: int = 0) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """`start_step` resumes mid-epoch: the permutation is deterministic
        in (seed, epoch), so skipping the first batches reproduces the
        uninterrupted trajectory exactly (step-granular preemption resume)."""
        idx, w = self.epoch_indices(epoch)
        for step in range(start_step, idx.shape[0]):
            yield idx[step], w[step]
