"""ShardedLoader: host-side batching + background prefetch + device placement.

The TPU-native replacement for `DataLoader(num_workers, pin_memory=True)` +
`DistributedSampler` (/root/reference/train_ddp.py:131-148):

* gather/slice of uint8 arrays is cheap NumPy — no worker processes needed at
  CIFAR scale; a background thread keeps `prefetch` batches in flight so host
  batching overlaps device compute (the `pin_memory`/`non_blocking` role,
  ref :137, :198-199);
* each process builds only its local shard; `shard_batch` assembles the
  global device array over the mesh (the DistributedSampler role, :122-127);
* every batch carries a `weight` mask so the padded final batch reproduces
  `drop_last=False` (ref :139) under static jit shapes.

Batches are dicts: {"image": uint8 (B,H,W,C), "label": int32 (B,), "weight":
float32 (B,)} — normalization/augmentation happen on device (see augment.py).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from .. import native, telemetry
from ..parallel.mesh import batch_shard_count
from ..parallel.sharding import shard_batch
from .datasets import ArrayDataset
from .sampler import ShardedSampler


class ShardedLoader:
    """Iterate global, mesh-sharded batches of an ArrayDataset."""

    def __init__(
        self,
        dataset: ArrayDataset,
        mesh: Mesh,
        per_device_batch: int,
        shuffle: bool,
        seed: int = 42,
        drop_last: bool = False,
        prefetch: int = 2,
        fault_hook=None,
    ):
        # resilience/faults.py injection point: called with the in-epoch
        # step index before that step's batch is produced (loader_stall
        # chaos). None on every un-instrumented run — zero hot-path cost.
        self.fault_hook = fault_hook
        self.dataset = dataset
        self.mesh = mesh
        self.global_batch = per_device_batch * batch_shard_count(mesh)
        self.sampler = ShardedSampler(
            n=len(dataset),
            global_batch=self.global_batch,
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
        )
        self.prefetch = max(1, prefetch)

    def __len__(self) -> int:
        return self.sampler.steps_per_epoch()

    def _host_batches(self, epoch: int,
                      start_step: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        images, labels = self.dataset.images, self.dataset.labels
        for k, (idx, w) in enumerate(
                self.sampler.iter_epoch(epoch, start_step)):
            if self.fault_hook is not None:
                self.fault_hook(start_step + k)
            yield {
                "image": native.gather_rows(images, idx),
                "label": labels[idx],
                "weight": w,
            }

    def _native_epoch(self, epoch: int, start_step: int = 0
                      ) -> Optional[Iterator[Dict[str, jax.Array]]]:
        """Epoch served by the C++ prefetcher (native/): batch assembly runs
        in native threads off the GIL, `prefetch` buffers deep. Returns None
        when the native library is unavailable (no toolchain / disabled)."""
        if not native.is_available():
            return None
        idx, w = self.sampler.epoch_indices(epoch)
        idx, w = idx[start_step:], w[start_step:]

        def gen():
            pf = native.NativePrefetcher(
                self.dataset.images, self.dataset.labels, idx, w,
                depth=self.prefetch)
            try:
                for k, (img, lab, weight) in enumerate(pf):
                    if self.fault_hook is not None:
                        self.fault_hook(start_step + k)
                    yield shard_batch(
                        {"image": img, "label": lab, "weight": weight},
                        self.mesh)
            finally:
                pf.close()

        return gen()

    def epoch(self, epoch: int,
              start_step: int = 0) -> Iterator[Dict[str, jax.Array]]:
        """Sharded device batches for one epoch. `epoch` seeds the reshuffle
        (the `set_epoch` contract, ref :184-185); `start_step` skips the
        first batches at the SAMPLER (no wasted assembly) for step-granular
        preemption resume."""
        it = self._native_epoch(epoch, start_step)
        if it is not None:
            return it
        return self._python_epoch(epoch, start_step)

    def _python_epoch(self, epoch: int,
                      start_step: int = 0) -> Iterator[Dict[str, jax.Array]]:
        """Pure-Python fallback: background thread + queue prefetch."""
        # producer/consumer share NO locked state: the queue is its own
        # synchronization, `stop` is a monotonic Event, and `err` is
        # published before the sentinel (the q.put/q.get pair is the
        # happens-before edge the consumer reads err[0] through); the
        # blocking q.get below runs with no lock held
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        stop = threading.Event()
        err: list[BaseException] = []

        def producer():
            try:
                for batch in self._host_batches(epoch, start_step):
                    while not stop.is_set():
                        try:
                            q.put(batch, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # surfaced in the consumer
                err.append(e)
            finally:
                # The sentinel MUST land or the consumer blocks forever on
                # q.get(); retry with the same stop-aware loop as batches
                # (the queue may legitimately be full while the consumer is
                # still draining).
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                # prefetch health: depth 0 at consume time means the
                # producer is behind (the loader-stall signature the
                # anomaly watchdog sees as a data_wait spike)
                telemetry.gauge("loader_queue_depth", q.qsize())
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield shard_batch(item, self.mesh)
        finally:
            # Consumer abandoned the epoch (break/exception/GeneratorExit):
            # unblock and retire the producer instead of leaking it.
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)
