"""distributed_pytorch_training_tpu — a TPU-native distributed training framework.

A ground-up JAX / XLA / Pallas re-design of the capabilities of the reference
repo ``yamiel-abreu/distributed-pytorch-training`` (a torch.distributed / NCCL
DDP training script, /root/reference/train_ddp.py). This is NOT a port: where
the reference uses one-process-per-GPU + NCCL + a DDP gradient-hook reducer,
this framework uses one-process-per-host, a `jax.sharding.Mesh` over TPU chips,
pure jitted train steps with `NamedSharding`, and XLA-inserted collectives over
ICI/DCN.

Subpackages
-----------
runtime    process/device runtime (maps train_ddp.py:49-73)
parallel   mesh, collectives, sharding rules (maps train_ddp.py:159-167, 303-311)
data       input pipeline (maps train_ddp.py:81-150)
models     model zoo: ResNet-18/50, ViT-B/16, BERT-base, GPT-2 (maps :153-156)
ops        Pallas TPU kernels (ring/flash attention, fused ops)
training   train/eval loops, optimizers, checkpointing (maps :170-300, 314-390)
utils      config, metrics, logging, profiling (maps :19-46, 224-262, 348-384)
"""

__version__ = "0.1.0"

from . import parallel, runtime  # noqa: F401
