// dpt_native — C++ host-side data runtime for the TPU training framework.
//
// Role: the native work PyTorch's C++ DataLoader core + torchvision image ops
// perform for the reference (/root/reference/train_ddp.py:131-148 — worker
// processes, pinned buffers, prefetch; SURVEY.md §2b "DataLoader worker
// processes"). On TPU the device-side pipeline is XLA; the host side — record
// decode, batch assembly, prefetch — is genuinely CPU work and lives here,
// off the GIL, with a thread pool and a bounded ring buffer.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
//
// Components:
//   * chw->hwc u8 record decode (the CIFAR python-pickle layout stores 3072-
//     byte CHW planes; devices want NHWC interleave)  — parallel over records
//   * row gather (batch assembly from a shuffled index set) — parallel memcpy
//   * splitmix64-seeded Fisher-Yates permutation (deterministic host shuffle)
//   * Prefetcher: producer thread + thread-pool gather filling a bounded ring
//     of reusable batch buffers; consumer pops in order. This is the
//     DataLoader(num_workers>0) equivalent: batch t+depth assembles while the
//     device runs step t.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

int32_t dpt_version() { return 1; }

// ---------------------------------------------------------------- thread fan
// One-shot fan-out for the standalone entry points (called once per epoch /
// dataset load, where thread spawn cost is immaterial).
static void parallel_for(int64_t n, int threads,
                         const std::function<void(int64_t, int64_t)>& fn) {
  if (threads <= 1 || n < 2) {
    fn(0, n);
    return;
  }
  int t = std::min<int64_t>(threads, n);
  std::vector<std::thread> pool;
  pool.reserve(t);
  int64_t chunk = (n + t - 1) / t;
  for (int i = 0; i < t; ++i) {
    int64_t lo = i * chunk, hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back([&fn, lo, hi] { fn(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

// Persistent worker pool for the per-batch hot loop (the Prefetcher): threads
// live for the pool's lifetime; `run` fans a [0, n) range out as chunks, the
// caller participates, and returns when every chunk is done.
class Pool {
 public:
  explicit Pool(int workers) {
    for (int i = 0; i < workers; ++i)
      threads_.emplace_back([this] { loop(); });
  }
  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_task_.notify_all();
    for (auto& t : threads_) t.join();
  }

  void run(int64_t n, const std::function<void(int64_t, int64_t)>& fn) {
    if (threads_.empty() || n < 2) {
      fn(0, n);
      return;
    }
    int64_t parts = std::min<int64_t>((int64_t)threads_.size() + 1, n);
    {
      std::lock_guard<std::mutex> lk(mu_);
      fn_ = &fn;
      total_ = n;
      chunk_ = (n + parts - 1) / parts;
      next_ = 0;
      inflight_ = 0;
    }
    cv_task_.notify_all();
    work();  // caller participates
    std::unique_lock<std::mutex> lk(mu_);
    cv_done_.wait(lk, [this] { return next_ >= total_ && inflight_ == 0; });
    fn_ = nullptr;
  }

 private:
  void work() {
    for (;;) {
      int64_t lo, hi;
      const std::function<void(int64_t, int64_t)>* fn;
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (fn_ == nullptr || next_ >= total_) return;
        lo = next_;
        hi = std::min(total_, lo + chunk_);
        next_ = hi;
        ++inflight_;
        fn = fn_;
      }
      (*fn)(lo, hi);
      {
        std::lock_guard<std::mutex> lk(mu_);
        --inflight_;
        if (next_ >= total_ && inflight_ == 0) cv_done_.notify_all();
      }
    }
  }

  void loop() {
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_task_.wait(lk, [this] {
          return stop_ || (fn_ != nullptr && next_ < total_);
        });
        if (stop_) return;
      }
      work();
    }
  }

  std::vector<std::thread> threads_;
  std::mutex mu_;
  std::condition_variable cv_task_, cv_done_;
  const std::function<void(int64_t, int64_t)>* fn_ = nullptr;
  int64_t total_ = 0, chunk_ = 0, next_ = 0, inflight_ = 0;
  bool stop_ = false;
};

// ------------------------------------------------------------------- decode
// src: (n, c*hw) planar records; dst: (n, hw*c) interleaved.
void dpt_chw_to_hwc_u8(const uint8_t* src, uint8_t* dst, int64_t n, int64_t c,
                       int64_t hw, int32_t threads) {
  parallel_for(n, threads, [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* rec = src + i * c * hw;
      uint8_t* out = dst + i * c * hw;
      for (int64_t p = 0; p < hw; ++p)
        for (int64_t ch = 0; ch < c; ++ch) out[p * c + ch] = rec[ch * hw + p];
    }
  });
}

// ------------------------------------------------------------------- gather
void dpt_gather_rows_u8(const uint8_t* src, const int64_t* idx, uint8_t* dst,
                        int64_t batch, int64_t row_bytes, int32_t threads) {
  parallel_for(batch, threads, [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i)
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
  });
}

// -------------------------------------------------------------- permutation
static inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// Unbiased Fisher-Yates via rejection-free Lemire reduction is overkill here;
// modulo bias at n << 2^64 is negligible for shuffling, but do Lemire anyway.
static inline uint64_t bounded(uint64_t& s, uint64_t n) {
  __uint128_t m = (__uint128_t)splitmix64(s) * n;
  return (uint64_t)(m >> 64);
}

void dpt_permutation(uint64_t seed, int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  uint64_t s = seed ^ 0xda3e39cb94b95bdbULL;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = (int64_t)bounded(s, (uint64_t)i + 1);
    std::swap(out[i], out[j]);
  }
}

// ---------------------------------------------------------------- prefetcher
struct Slot {
  std::vector<uint8_t> img;
  std::vector<int32_t> lab;
  std::vector<float> w;
  int64_t step = -1;
  bool ready = false;
};

struct Prefetcher {
  const uint8_t* images;
  const int32_t* labels;
  int64_t row_bytes, steps, batch;
  std::vector<int64_t> indices;  // (steps*batch), owned copy
  std::vector<float> weights;    // (steps*batch), owned copy
  int threads;
  std::unique_ptr<Pool> pool;  // persistent: no thread churn per batch

  std::vector<Slot> slots;
  std::mutex mu;
  std::condition_variable cv_prod, cv_cons;
  int64_t next_consume = 0;
  std::atomic<bool> stop{false};
  std::thread producer;

  void run() {
    for (int64_t t = 0; t < steps && !stop.load(); ++t) {
      Slot& s = slots[t % slots.size()];
      {
        std::unique_lock<std::mutex> lk(mu);
        // wait until the slot's previous occupant (step t-depth) is consumed
        cv_prod.wait(lk, [&] {
          return stop.load() || t - next_consume < (int64_t)slots.size();
        });
        if (stop.load()) return;
      }
      const int64_t* idx = indices.data() + t * batch;
      uint8_t* img_out = s.img.data();
      pool->run(batch, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i)
          std::memcpy(img_out + i * row_bytes, images + idx[i] * row_bytes,
                      row_bytes);
      });
      for (int64_t i = 0; i < batch; ++i) s.lab[i] = labels[idx[i]];
      std::memcpy(s.w.data(), weights.data() + t * batch,
                  batch * sizeof(float));
      {
        std::lock_guard<std::mutex> lk(mu);
        s.step = t;
        s.ready = true;
      }
      cv_cons.notify_all();
    }
  }
};

void* dpt_prefetch_create(const uint8_t* images, const int32_t* labels,
                          int64_t row_bytes, const int64_t* indices,
                          const float* weights, int64_t steps, int64_t batch,
                          int32_t depth, int32_t threads) {
  auto* p = new Prefetcher;
  p->images = images;
  p->labels = labels;
  p->row_bytes = row_bytes;
  p->steps = steps;
  p->batch = batch;
  p->indices.assign(indices, indices + steps * batch);
  p->weights.assign(weights, weights + steps * batch);
  p->threads = std::max(1, threads);
  p->pool.reset(new Pool(p->threads - 1));
  depth = std::max(1, depth);
  p->slots.resize(depth);
  for (auto& s : p->slots) {
    s.img.resize(batch * row_bytes);
    s.lab.resize(batch);
    s.w.resize(batch);
  }
  p->producer = std::thread([p] { p->run(); });
  return p;
}

// Blocks for the next in-order batch; copies into caller buffers. Returns the
// step index, or -1 when the epoch is exhausted.
int64_t dpt_prefetch_next(void* handle, uint8_t* out_img, int32_t* out_lab,
                          float* out_w) {
  auto* p = static_cast<Prefetcher*>(handle);
  int64_t t = p->next_consume;
  if (t >= p->steps) return -1;
  Slot& s = p->slots[t % p->slots.size()];
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_cons.wait(lk, [&] {
      return p->stop.load() || (s.ready && s.step == t);
    });
    if (p->stop.load()) return -1;
    std::memcpy(out_img, s.img.data(), p->batch * p->row_bytes);
    std::memcpy(out_lab, s.lab.data(), p->batch * sizeof(int32_t));
    std::memcpy(out_w, s.w.data(), p->batch * sizeof(float));
    s.ready = false;
    p->next_consume = t + 1;
  }
  p->cv_prod.notify_all();
  return t;
}

void dpt_prefetch_destroy(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  p->stop.store(true);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    for (auto& s : p->slots) s.ready = false;  // unblock nothing-to-consume
  }
  p->cv_prod.notify_all();
  p->cv_cons.notify_all();
  if (p->producer.joinable()) p->producer.join();
  delete p;
}

}  // extern "C"
