"""ctypes bindings for the C++ host data runtime (`src/dpt_native.cpp`).

The native library is the TPU-side stand-in for the C++ machinery the
reference gets from its dependency stack — DataLoader worker prefetch and
image-op decode (/root/reference/train_ddp.py:131-148; SURVEY.md §2b). It is
built lazily with g++ on first use and cached next to the sources; every
entry point has a NumPy fallback so the framework keeps working where no
toolchain exists (`is_available()` reports which path is live).

Set ``DPT_TPU_NATIVE=0`` to force the NumPy fallbacks (used by parity tests).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_SRC = Path(__file__).parent / "src" / "dpt_native.cpp"
_LIB_DIR = Path(__file__).parent / "lib"
_LIB = _LIB_DIR / "libdpt_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    """Compile the shared library if missing or older than its source."""
    try:
        if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
            return True
        _LIB_DIR.mkdir(parents=True, exist_ok=True)
        # Build to a temp name, then atomic-rename: concurrent processes
        # (multi-host launch) race benignly.
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB_DIR)
        os.close(fd)
        try:
            cmd = [
                "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
                str(_SRC), "-o", tmp,
            ]
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=120)
            if res.returncode != 0:
                return False
            os.replace(tmp, _LIB)
            return True
        finally:
            Path(tmp).unlink(missing_ok=True)
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DPT_TPU_NATIVE", "1") == "0":
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(str(_LIB))
        except OSError:
            return None

        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32, i64, u64 = ctypes.c_int32, ctypes.c_int64, ctypes.c_uint64

        lib.dpt_version.restype = i32
        lib.dpt_chw_to_hwc_u8.argtypes = [u8p, u8p, i64, i64, i64, i32]
        lib.dpt_gather_rows_u8.argtypes = [u8p, i64p, u8p, i64, i64, i32]
        lib.dpt_permutation.argtypes = [u64, i64, i64p]
        lib.dpt_prefetch_create.argtypes = [u8p, i32p, i64, i64p, f32p,
                                            i64, i64, i32, i32]
        lib.dpt_prefetch_create.restype = ctypes.c_void_p
        lib.dpt_prefetch_next.argtypes = [ctypes.c_void_p, u8p, i32p, f32p]
        lib.dpt_prefetch_next.restype = i64
        lib.dpt_prefetch_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def is_available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


_THREADS = max(1, min(8, (os.cpu_count() or 1)))


def chw_to_hwc_u8(records: np.ndarray, c: int, h: int, w: int) -> np.ndarray:
    """(N, c*h*w) planar uint8 records -> (N, h, w, c) interleaved images.

    The per-record decode torchvision's C++ ops do for the reference's
    CIFAR pickle batches (ref :103-108)."""
    records = np.ascontiguousarray(records, np.uint8)
    n = records.shape[0]
    lib = _load()
    if lib is None:
        return (records.reshape(n, c, h, w).transpose(0, 2, 3, 1)
                .copy())
    out = np.empty((n, h, w, c), np.uint8)
    lib.dpt_chw_to_hwc_u8(_ptr(records, ctypes.c_uint8),
                          _ptr(out, ctypes.c_uint8),
                          n, c, h * w, _THREADS)
    return out


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Batch assembly: rows of `src` at `idx` (NumPy fancy-index equivalent,
    parallel memcpy off the GIL). Any contiguous dtype — the copy is
    byte-wise, so int32 token rows work the same as uint8 image rows."""
    src = np.ascontiguousarray(src)
    lib = _load()
    # Only trivially-copyable numeric rows take the native memcpy path
    # (object arrays hold PyObject pointers — memcpy would skip refcounting).
    if lib is None or src.dtype.kind not in "biufc":
        return src[idx]
    idx = np.ascontiguousarray(idx, np.int64)
    if len(idx) and (idx.min() < 0 or idx.max() >= len(src)):
        # The C side is a raw memcpy with no bounds check; keep NumPy's
        # loud failure instead of reading out-of-bounds host memory.
        raise IndexError(
            f"gather_rows indices out of range [0, {len(src)}): "
            f"min={idx.min()}, max={idx.max()}")
    row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.itemsize
    out = np.empty((len(idx), *src.shape[1:]), src.dtype)
    # byte-pointer cast is dtype-agnostic: row_bytes covers the full row
    lib.dpt_gather_rows_u8(_ptr(src, ctypes.c_uint8),
                           _ptr(idx, ctypes.c_int64),
                           _ptr(out, ctypes.c_uint8),
                           len(idx), row_bytes, _THREADS)
    return out


_M64 = 2 ** 64 - 1


def _permutation_py(seed: int, n: int) -> np.ndarray:
    """Pure-Python mirror of dpt_permutation — SAME splitmix64 Fisher-Yates
    stream, so toolchain-less hosts shuffle identically to native hosts
    (cross-host shard consistency depends on this)."""
    s = (seed ^ 0xDA3E39CB94B95BDB) & _M64

    def splitmix64():
        nonlocal s
        s = (s + 0x9E3779B97F4A7C15) & _M64
        z = s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
        return z ^ (z >> 31)

    out = np.arange(n, dtype=np.int64)
    for i in range(n - 1, 0, -1):
        j = (splitmix64() * (i + 1)) >> 64  # Lemire bounded, as in C++
        out[i], out[j] = out[j], out[i]
    return out


def permutation(seed: int, n: int) -> np.ndarray:
    """Deterministic Fisher-Yates permutation (splitmix64 stream). Native and
    Python paths produce the identical permutation for a given seed."""
    lib = _load()
    if lib is None:
        return _permutation_py(seed, n)
    out = np.empty(n, np.int64)
    lib.dpt_permutation(seed & _M64, n, _ptr(out, ctypes.c_int64))
    return out


class NativePrefetcher:
    """Bounded-ring background batch assembly over a fixed epoch plan.

    Wraps the C++ Prefetcher: producer thread + thread-pool gather fill
    `depth` reusable buffers; `__iter__` yields fresh (image, label, weight)
    arrays in step order. The DataLoader(num_workers) role, ref :136."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 indices: np.ndarray, weights: np.ndarray,
                 depth: int = 3, threads: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        if images.dtype != np.uint8 or images.ndim < 2:
            raise TypeError(
                f"NativePrefetcher serves uint8 image batches, got "
                f"dtype={images.dtype} ndim={images.ndim}")
        steps, batch = indices.shape
        if indices.size and (indices.min() < 0
                             or indices.max() >= len(images)):
            raise IndexError(
                f"prefetch indices out of range [0, {len(images)}): "
                f"min={indices.min()}, max={indices.max()}")
        self._lib = lib
        # keep references so the buffers outlive the C++ pointers
        self._images = np.ascontiguousarray(images)
        self._labels = np.ascontiguousarray(labels, np.int32)
        self._indices = np.ascontiguousarray(indices, np.int64)
        self._weights = np.ascontiguousarray(weights, np.float32)
        self.steps = int(steps)
        self.batch = int(batch)
        self.item_shape = images.shape[1:]
        self._row_bytes = (int(np.prod(self.item_shape, dtype=np.int64))
                           * self._images.itemsize)
        self._handle = lib.dpt_prefetch_create(
            _ptr(self._images, ctypes.c_uint8),
            _ptr(self._labels, ctypes.c_int32),
            self._row_bytes,
            _ptr(self._indices, ctypes.c_int64),
            _ptr(self._weights, ctypes.c_float),
            self.steps, self.batch, depth, threads or _THREADS)
        if not self._handle:
            raise RuntimeError("dpt_prefetch_create failed")

    def next(self) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        if self._handle is None:
            return None
        img = np.empty((self.batch, *self.item_shape), np.uint8)
        lab = np.empty(self.batch, np.int32)
        w = np.empty(self.batch, np.float32)
        t = self._lib.dpt_prefetch_next(
            self._handle, _ptr(img, ctypes.c_uint8),
            _ptr(lab, ctypes.c_int32), _ptr(w, ctypes.c_float))
        if t < 0:
            return None
        return img, lab, w

    def __iter__(self):
        try:
            while True:
                item = self.next()
                if item is None:
                    return
                yield item
        finally:
            self.close()

    def close(self):
        if self._handle is not None:
            self._lib.dpt_prefetch_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
