"""AST lint engine: source-level parallelism contracts, checked on real
syntax trees instead of regexes.

The predecessor (tests/test_compat_lint.py's regex) fired on *mentions* of
the shard_map entry points inside docstrings and string literals — prose
about the rule tripped the rule. An `ast` visitor only sees real imports,
attribute accesses, and calls, so the false-positive class is structural,
not patched around.

Every rule reports `Finding`s with file:line locations. Suppression is
per-line: append ``# analysis: disable=<rule-name>`` (or ``disable=all``)
to the offending line — meant for experiment branches that knowingly break
a contract, and visible in review precisely because it sits on the line.

The engine is dependency-free by design (no jax import): linting the repo
must never require initializing a backend. The axis-name registry is
therefore a literal copy of `parallel/mesh.py`'s AXIS_ORDER; a tier-1 test
asserts the two stay identical.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .contracts import Finding, rule

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
PKG_ROOT = Path(__file__).resolve().parent.parent

# The one allowed home of the raw shard_map entry points (the version-compat
# shim — ROADMAP "jax version skew").
SHARD_MAP_SHIM = "parallel/collectives.py"

# Mirror of parallel/mesh.py AXIS_NAMES (kept import-free; test-pinned).
AXIS_NAMES = frozenset({"data", "fsdp", "model", "seq", "pipe", "expert",
                        "slice"})

# Collective-call names whose axis argument must come from the registry.
_AXIS_CALLS = frozenset({
    "psum", "pmean", "pmax", "psum_scatter", "all_gather", "all_to_all",
    "ppermute", "ppermute_ring", "axis_index", "axis_size",
})

_DISABLE_RE = re.compile(r"#\s*analysis:\s*disable=([\w\-,\s]+)")


@dataclasses.dataclass
class FileContext:
    """One parsed source file, shared across the rules that visit it."""

    path: Path
    relpath: str
    tree: ast.Module
    lines: List[str]
    # alias maps built once per file (imports are module-level in this repo)
    modules: Dict[str, str] = dataclasses.field(default_factory=dict)
    members: Dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def parse(cls, path: Path, repo: Path = REPO_ROOT) -> "FileContext":
        src = path.read_text()
        try:
            rel = path.resolve().relative_to(repo).as_posix()
        except ValueError:  # outside the repo (synthetic test files)
            rel = path.as_posix()
        ctx = cls(path=path, relpath=rel,
                  tree=ast.parse(src, filename=str(path)),
                  lines=src.splitlines())
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    ctx.modules[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        ctx.members[a.asname or a.name] = \
                            f"{node.module}.{a.name}"
        return ctx

    def loc(self, node: ast.AST) -> str:
        return f"{self.relpath}:{getattr(node, 'lineno', 0)}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted path of a Name/Attribute chain with import aliases
        expanded: `np.random.rand` -> "numpy.random.rand" under
        `import numpy as np`; `shard_map` -> its from-import source."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = node.id
        if head in self.members:
            parts.append(self.members[head])
        elif head in self.modules:
            parts.append(self.modules[head])
        else:
            parts.append(head)
        return ".".join(reversed(parts))

    def suppressed(self, finding: Finding) -> bool:
        try:
            lineno = int(finding.location.rsplit(":", 1)[1])
            line = self.lines[lineno - 1]
        except (IndexError, ValueError):
            return False
        m = _DISABLE_RE.search(line)
        if not m:
            return False
        names = {n.strip() for n in m.group(1).split(",")}
        return "all" in names or finding.rule in names


# ---------------------------------------------------------------------------
# Shared traced-function discovery (rules 2 and 3)
# ---------------------------------------------------------------------------

_JIT_NAMES = ("jax.jit", "jax.pmap")


def _is_jit_name(resolved: Optional[str]) -> bool:
    return resolved in _JIT_NAMES


def _is_shard_map_name(resolved: Optional[str]) -> bool:
    return bool(resolved) and resolved.split(".")[-1] == "shard_map"


def traced_function_names(ctx: FileContext) -> Set[str]:
    """Names of functions this file hands to jax.jit / shard_map (by call
    argument or decorator) — their bodies, including nested defs, run under
    tracing. A per-file heuristic: good enough because the repo's traced
    entry points are always wrapped in the module that defines them."""
    traced: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            fn = ctx.resolve(node.func)
            if (_is_jit_name(fn) or _is_shard_map_name(fn)) and node.args:
                target = node.args[0]
                if isinstance(target, ast.Name):
                    traced.add(target.id)
                elif isinstance(target, ast.Attribute):
                    traced.add(target.attr)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                fn = ctx.resolve(base)
                if _is_jit_name(fn) or _is_shard_map_name(fn):
                    traced.add(node.name)
                # @partial(jax.jit, ...) / @functools.partial(shard_map, ...)
                if isinstance(dec, ast.Call) and fn and \
                        fn.split(".")[-1] == "partial" and dec.args:
                    inner = ctx.resolve(dec.args[0])
                    if _is_jit_name(inner) or _is_shard_map_name(inner):
                        traced.add(node.name)
    return traced


def _traced_defs(ctx: FileContext, extra_names: Iterable[str] = ()
                 ) -> List[ast.FunctionDef]:
    names = traced_function_names(ctx) | set(extra_names)
    return [n for n in ast.walk(ctx.tree)
            if isinstance(n, ast.FunctionDef) and n.name in names]


# ---------------------------------------------------------------------------
# Rule 1: shard_map only via the compat shim
# ---------------------------------------------------------------------------


@rule("shard-map-shim-only", "ast",
      "shard_map is used only through the parallel/collectives.py shim",
      "the raw entry point moved (jax.experimental.shard_map -> "
      "jax.shard_map) and its replication flag was renamed (check_rep -> "
      "check_vma) across the jax versions this code runs under; a direct "
      "use works on ONE version and breaks on the next (ROADMAP 'jax "
      "version skew'). Unlike the old regex lint, mentions in docstrings "
      "and strings do not count — only real imports, attribute accesses, "
      "and kwargs.")
def check_shard_map_shim(ctx: FileContext) -> List[Finding]:
    if ctx.relpath.endswith(SHARD_MAP_SHIM):
        return []
    name = "shard-map-shim-only"
    out: List[Finding] = []
    # `jax.experimental.shard_map.shard_map` is ONE use: ast.walk visits
    # the outer Attribute before its inner chain, so flag the outer node
    # and skip its descendants (else the same line reports twice).
    inner_seen: Set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("jax.experimental.shard_map"):
                    out.append(Finding(
                        name, f"direct import of {a.name} (import "
                        "`shard_map` from "
                        "distributed_pytorch_training_tpu.parallel)",
                        ctx.loc(node)))
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            if node.module == "jax.experimental.shard_map" or (
                    node.module in ("jax", "jax.experimental")
                    and any(a.name == "shard_map" for a in node.names)):
                out.append(Finding(
                    name, f"direct shard_map import from {node.module} "
                    "(use the parallel/collectives.py shim)",
                    ctx.loc(node)))
        elif isinstance(node, ast.Attribute):
            if id(node) in inner_seen:
                continue
            resolved = ctx.resolve(node) or ""
            if resolved in ("jax.shard_map",
                            "jax.experimental.shard_map") or \
                    resolved.startswith("jax.experimental.shard_map."):
                out.append(Finding(
                    name, f"direct use of {resolved} (use the "
                    "parallel/collectives.py shim)", ctx.loc(node)))
                inner_seen.update(
                    id(sub) for sub in ast.walk(node) if sub is not node)
        elif isinstance(node, ast.Call):
            fn = ctx.resolve(node.func)
            if _is_shard_map_name(fn):
                bad = [k.arg for k in node.keywords
                       if k.arg in ("check_rep", "check_vma")]
                if bad:
                    out.append(Finding(
                        name, f"shard_map called with {bad} — the shim "
                        "owns the replication-check flag (its NAME is the "
                        "version skew)", ctx.loc(node)))
    return out


# ---------------------------------------------------------------------------
# Rule 2: no impure host calls inside traced bodies
# ---------------------------------------------------------------------------

# Impure prefixes: calls whose result differs run-to-run. Pure numpy shape
# math (np.prod(np.shape(x))) is trace-time constant folding and stays
# legal; np.random/stdlib random/time bake ONE trace-time draw into the
# compiled program silently — the program replays it forever.
_IMPURE_PREFIXES = ("time.", "random.", "numpy.random.")


@rule("no-impure-calls-in-traced", "ast",
      "no time/random/np.random calls inside jit/shard_map-traced bodies",
      "an impure host call inside a traced body executes ONCE at trace "
      "time and its result is baked into the compiled program as a "
      "constant — every step replays the same 'random' draw or timestamp, "
      "silently. (Pure numpy shape math is trace-time constant folding "
      "and is allowed.)")
def check_impure_in_traced(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[int] = set()
    for fndef in _traced_defs(ctx):
        for node in ast.walk(fndef):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            resolved = ctx.resolve(node.func)
            if not resolved:
                continue
            if any(resolved == p[:-1] or resolved.startswith(p)
                   for p in _IMPURE_PREFIXES):
                out.append(Finding(
                    "no-impure-calls-in-traced",
                    f"{resolved}() inside traced function "
                    f"`{fndef.name}` — executes once at trace time, baked "
                    "into the program as a constant (use jax.random / "
                    "device-side state)", ctx.loc(node)))
    return out


# ---------------------------------------------------------------------------
# Rule 3: no device syncs in training/loop.py step paths
# ---------------------------------------------------------------------------

_SYNC_CALLS = ("jax.device_get", "jax.block_until_ready")


def _scan_sync_calls(ctx: FileContext, fndefs, rule_name: str,
                     scope_desc: str, cost: str) -> List[Finding]:
    """The shared sync-call detector behind `no-host-sync-in-step` and
    `no-host-sync-in-decode`: `.item()` / `_SYNC_CALLS` / `float()`/`int()`
    on non-constants inside the given function defs. ONE detector — a
    future extension (e.g. catching `np.asarray` fetches) lands in both
    rules by construction instead of drifting between copies.
    ``scope_desc`` names the scanned region in messages ("step path" /
    "decode loop"); ``cost`` names what one sync costs ("per-step" /
    "per-token")."""
    out: List[Finding] = []
    seen: Set[int] = set()
    for fndef in fndefs:
        for node in ast.walk(fndef):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                out.append(Finding(
                    rule_name, f".item() inside {scope_desc} "
                    f"`{fndef.name}` — a {cost} device sync",
                    ctx.loc(node)))
                continue
            resolved = ctx.resolve(node.func)
            if resolved in _SYNC_CALLS:
                out.append(Finding(
                    rule_name, f"{resolved}() inside {scope_desc} "
                    f"`{fndef.name}` — a {cost} device sync",
                    ctx.loc(node)))
            elif resolved in ("float", "int") and node.args and \
                    not isinstance(node.args[0], ast.Constant):
                out.append(Finding(
                    rule_name, f"{resolved}() on a device value inside "
                    f"{scope_desc} `{fndef.name}` — forces a host fetch",
                    ctx.loc(node)))
    return out


@rule("no-host-sync-in-step", "ast",
      "no .item()/float()/device_get syncs inside training/loop.py step "
      "paths",
      "the reference's per-step .item() was its throughput bottleneck "
      "(train_ddp.py:217); the loop design fetches only at print "
      "boundaries. A sync creeping back into a step function stalls the "
      "device once per step — invisible in tests, ruinous at scale.")
def check_host_sync_in_step(ctx: FileContext) -> List[Finding]:
    if not ctx.relpath.endswith("training/loop.py"):
        return []
    step_names = {n.name for n in ast.walk(ctx.tree)
                  if isinstance(n, ast.FunctionDef)
                  and (n.name.endswith("_step") or
                       n.name.endswith("_step_impl"))}
    return _scan_sync_calls(ctx, _traced_defs(ctx, extra_names=step_names),
                            "no-host-sync-in-step", "step path", "per-step")


# The serving decode hot loops' homes and function names (serving/engine.py
# `generate`, serving/continuous.py `_step_decode_loop` — the continuous
# scheduler's shared-pool sibling — plus anything a refactor names
# *_decode_loop). One host fetch per BATCH is the design (after the last
# step, in serve_tokens / _complete_finished); a fetch inside the loop
# stalls the device once per generated TOKEN, for EVERY slot in the pool.
_DECODE_LOOP_FILES = ("serving/engine.py", "serving/continuous.py")


def _is_decode_loop_name(name: str) -> bool:
    return name == "generate" or name.endswith("_decode_loop")


@rule("no-host-sync-in-decode", "ast",
      "no .item()/float()/device_get syncs inside the serving decode loops "
      "(serving/engine.py generate, serving/continuous.py "
      "_step_decode_loop)",
      "the decode loop runs one compiled step per generated token with "
      "every chained value (token, positions, cache) staying on device; "
      "a host fetch creeping in serializes the device per TOKEN — the "
      "training loop's .item() anti-pattern, multiplied by max_new_tokens "
      "per request.")
def check_host_sync_in_decode(ctx: FileContext) -> List[Finding]:
    if not any(ctx.relpath.endswith(f) for f in _DECODE_LOOP_FILES):
        return []
    loops = [n for n in ast.walk(ctx.tree)
             if isinstance(n, ast.FunctionDef)
             and _is_decode_loop_name(n.name)]
    return _scan_sync_calls(ctx, loops, "no-host-sync-in-decode",
                            "decode loop", "per-token")


# ---------------------------------------------------------------------------
# Rule 4: axis-name literals only from the mesh registry
# ---------------------------------------------------------------------------


def _literal_strings(node: ast.AST) -> List[Tuple[str, ast.AST]]:
    """String constants in an axis-argument position: the constant itself
    or the elements of a tuple/list of constants."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, node)]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [(e.value, e) for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


@rule("axis-name-registry", "ast",
      "mesh axis names appear as string literals only in parallel/mesh.py",
      "every axis literal outside the registry is a rename hazard: "
      "collectives psum over 'data' while the mesh was built with the "
      "constants, and a registry change silently strands the literal — "
      "the axis typo failure mode _axes_present guards at runtime, "
      "caught at lint time instead.")
def check_axis_name_registry(ctx: FileContext) -> List[Finding]:
    if ctx.relpath.endswith("parallel/mesh.py"):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve(node.func) or ""
        base = resolved.split(".")[-1]
        candidates: List[Tuple[str, ast.AST]] = []
        if base in ("PartitionSpec", "P"):
            for arg in node.args:
                candidates += _literal_strings(arg)
        elif base in _AXIS_CALLS and len(node.args) >= 2:
            candidates += _literal_strings(node.args[1])
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                candidates += _literal_strings(kw.value)
        for value, lit in candidates:
            if value in AXIS_NAMES:
                out.append(Finding(
                    "axis-name-registry",
                    f"axis name {value!r} as a string literal in "
                    f"{base}(...) — import the constant from "
                    "parallel/mesh.py (DATA/FSDP/MODEL/SEQ/PIPE/EXPERT "
                    "or BATCH_AXES)", ctx.loc(lit)))
    return out


# ---------------------------------------------------------------------------
# Rule 5: os._exit only in resilience/heartbeat.py
# ---------------------------------------------------------------------------

# The one sanctioned home of the abrupt-exit primitive (hard_exit): the
# deathwatch abort (a clean teardown through a dead socket IS the hang
# being escaped) and preemption's hard deadline route through it.
# Matched on exact trailing path COMPONENTS, not a string suffix — a
# future `myresilience/heartbeat.py` must not inherit the exemption.
OS_EXIT_HOME = ("resilience", "heartbeat.py")


@rule("no-bare-os-exit", "ast",
      "os._exit appears only in resilience/heartbeat.py (hard_exit)",
      "an abrupt exit while this process holds the server-side TPU grant "
      "wedges the chip for every later process (observed live: a "
      "claim-holder killed without teardown left the device pool stuck "
      "for hours). The legitimate abrupt exits — the relay deathwatch, "
      "preemption's zombie-prevention deadline — live behind "
      "resilience/heartbeat.py's hard_exit, which documents when an "
      "abrupt exit is allowed and what cleanup it owes first; a bare "
      "os._exit anywhere else is a new stuck-grant hazard.")
def check_no_bare_os_exit(ctx: FileContext) -> List[Finding]:
    if tuple(ctx.relpath.replace("\\", "/").split("/")[-2:]) == OS_EXIT_HOME:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        # flag the ATTRIBUTE access, not just calls: `ex = os._exit` then
        # `ex(1)` is the same hazard with one extra hop
        if isinstance(node, ast.Attribute) or isinstance(node, ast.Name):
            resolved = ctx.resolve(node)
            if resolved == "os._exit":
                out.append(Finding(
                    "no-bare-os-exit",
                    "os._exit outside resilience/heartbeat.py — abrupt "
                    "claim-holder death wedges the server-side TPU grant; "
                    "use resilience.heartbeat.hard_exit (or the preemption "
                    "guard's deadline) so the exit is accounted for",
                    ctx.loc(node)))
    return out


# ---------------------------------------------------------------------------
# Rule: jax.profiler session entry points only via utils/profiling.py
# ---------------------------------------------------------------------------

# The one sanctioned home of the raw jax profiler session primitives
# (StepProfiler + trace_session own the process-wide session guard).
# Matched on exact trailing path COMPONENTS like OS_EXIT_HOME — a future
# `myutils/profiling.py` must not inherit the exemption.
PROFILER_HOME = ("utils", "profiling.py")

_PROFILER_SESSION_NAMES = ("jax.profiler.start_trace",
                           "jax.profiler.stop_trace")


@rule("profiler-session-via-stepprofiler-only", "ast",
      "jax.profiler.start_trace/stop_trace appear only in "
      "utils/profiling.py",
      "jax holds ONE profiler session per process: a second start_trace "
      "while one is open raises from deep inside jax, and a leaked open "
      "session silently fails every later capture — with ISSUE 15's "
      "on-demand and anomaly-triggered captures, windows can now open at "
      "RUNTIME from the HTTP thread and the watchdog, so every session "
      "entry must route through utils/profiling.py's process-wide guard "
      "(StepProfiler / trace_session), which refuses-and-counts "
      "(`profiler_busy`) instead of crashing. A bare start_trace "
      "anywhere else reintroduces the clobber.")
def check_profiler_session_home(ctx: FileContext) -> List[Finding]:
    if tuple(ctx.relpath.replace("\\", "/").split("/")[-2:]) \
            == PROFILER_HOME:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        # flag the reference itself (Name or Attribute), not just calls:
        # `st = jax.profiler.start_trace` then `st(d)` is the same hazard
        if isinstance(node, (ast.Attribute, ast.Name)):
            resolved = ctx.resolve(node)
            if resolved in _PROFILER_SESSION_NAMES:
                out.append(Finding(
                    "profiler-session-via-stepprofiler-only",
                    f"{resolved} outside utils/profiling.py — raw "
                    "session entry points bypass the process-wide "
                    "session guard (a concurrent on-demand capture would "
                    "clobber it); use utils.profiling.StepProfiler or "
                    "trace_session", ctx.loc(node)))
    return out


# ---------------------------------------------------------------------------

# The one sanctioned home of raw Pallas kernels: the package's ops/
# directory (flash/ring/ulysses attention, the fused int8 quantize codecs).
# Matched on exact trailing path components like OS_EXIT_HOME — a future
# `somewhere_else/ops/` must not inherit the exemption.
PALLAS_HOME = ("distributed_pytorch_training_tpu", "ops")

_PALLAS_CALL_NAMES = (
    "jax.experimental.pallas.pallas_call",
    "jax.experimental.pallas.tpu.pallas_call",
)


@rule("pallas-call-in-ops-only", "ast",
      "pl.pallas_call appears only under distributed_pytorch_training_tpu/"
      "ops/",
      "a Pallas kernel carries per-backend obligations the rest of the "
      "codebase must not re-derive ad hoc: a TPU gate with an interpreter-"
      "mode fallback (the XLA-composed path stays the CPU/tier-1 "
      "reference), a cost estimate, a bit-exactness or tolerance contract "
      "pinned by tests, and VMEM block-shape rules. ops/ is where those "
      "conventions live (flash_backend_supported, "
      "quantize_backend_supported); a pallas_call inlined elsewhere ships "
      "an ungated kernel that breaks the first time tier-1 runs on CPU.")
def check_pallas_call_in_ops(ctx: FileContext) -> List[Finding]:
    parts = tuple(ctx.relpath.replace("\\", "/").split("/"))
    if parts[-3:-1] == PALLAS_HOME:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        # flag the reference itself (Name or Attribute), not just calls:
        # `k = pl.pallas_call(...)` via an alias is the same kernel escape
        if isinstance(node, (ast.Attribute, ast.Name)):
            resolved = ctx.resolve(node)
            if resolved in _PALLAS_CALL_NAMES:
                out.append(Finding(
                    "pallas-call-in-ops-only",
                    "pl.pallas_call outside distributed_pytorch_training_"
                    "tpu/ops/ — raw kernels live in ops/ behind a backend "
                    "gate + interpreter fallback (the "
                    "flash_backend_supported convention); export a gated "
                    "wrapper from ops/ instead",
                    ctx.loc(node)))
    return out


# ---------------------------------------------------------------------------
# Rule 7: no telemetry emission inside traced bodies
# ---------------------------------------------------------------------------

# The telemetry package's module name (any import path component match:
# absolute `distributed_pytorch_training_tpu.telemetry`, relative
# `..telemetry`, `from .. import telemetry`).
_TELEMETRY_MODULE = "telemetry"


def _telemetry_bindings(ctx: FileContext
                        ) -> Tuple[Set[str], Set[str], Set[str]]:
    """(module aliases, member names, dotted prefixes) this file bound to
    the telemetry package. Walked here directly (not via ctx.members)
    because the repo imports telemetry RELATIVELY (``from .. import
    telemetry``), which the shared alias maps skip by design.

    An UNALIASED ``import pkg.telemetry`` binds only the ROOT name
    ``pkg`` — flagging every call rooted at ``pkg`` would false-positive
    on ``pkg.parallel.psum(...)``, so that form is tracked as the full
    dotted prefix (``pkg.telemetry``) and matched against the call's raw
    attribute chain instead."""
    mods: Set[str] = set()
    members: Set[str] = set()
    dotted: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                parts = a.name.split(".")
                if _TELEMETRY_MODULE not in parts:
                    continue
                if a.asname:
                    mods.add(a.asname)
                elif len(parts) == 1:
                    mods.add(a.name)  # `import telemetry` itself
                else:
                    dotted.add(a.name)
        elif isinstance(node, ast.ImportFrom):
            mod_parts = (node.module or "").split(".")
            if _TELEMETRY_MODULE in mod_parts:
                # from ..telemetry import span / from ..telemetry.recorder
                # import Recorder — every bound name is a telemetry member
                for a in node.names:
                    members.add(a.asname or a.name)
            else:
                # from .. import telemetry [as tel]
                for a in node.names:
                    if a.name == _TELEMETRY_MODULE:
                        mods.add(a.asname or a.name)
    return mods, members, dotted


def _raw_dotted(node: ast.AST) -> Optional[str]:
    """The literal dotted text of a Name/Attribute chain (no alias
    expansion), or None for non-trivial roots (calls, subscripts)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


@rule("telemetry-emit-outside-traced", "ast",
      "telemetry Recorder calls are forbidden inside jit/shard_map-traced "
      "bodies",
      "a telemetry emit inside a traced body would execute ONCE at trace "
      "time (recording a single bogus event, never one per step) and — "
      "worse — any attempt to make it per-step would need a host callback "
      "or sync inside the compiled step, exactly the stall class the "
      "no-host-sync-in-step rule exists to kill. Instrumentation is "
      "host-side by contract: spans wrap the dispatched step, they never "
      "live inside it (PARITY.md pins telemetry-on/off HLO identity).")
def check_telemetry_in_traced(ctx: FileContext) -> List[Finding]:
    mods, members, dotted = _telemetry_bindings(ctx)
    if not mods and not members and not dotted:
        return []
    name = "telemetry-emit-outside-traced"
    out: List[Finding] = []
    seen: Set[int] = set()
    for fndef in _traced_defs(ctx):
        for node in ast.walk(fndef):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            func = node.func
            # telemetry.span(...) / tel.recorder.emit(...): any attribute
            # chain rooted at a telemetry module alias
            head = func
            while isinstance(head, ast.Attribute):
                head = head.value
            hit = (isinstance(head, ast.Name) and head.id in mods
                   and isinstance(func, ast.Attribute))
            # span(...) imported from the telemetry package directly
            hit = hit or (isinstance(func, ast.Name) and func.id in members)
            # pkg.telemetry.emit(...) under an unaliased dotted import:
            # matched against the dotted prefix, so pkg.parallel.psum(...)
            # rooted at the same package name never false-positives
            if not hit and dotted:
                raw = _raw_dotted(func)
                hit = bool(raw) and any(raw.startswith(d + ".")
                                        for d in dotted)
            if hit:
                out.append(Finding(
                    name,
                    f"telemetry call inside traced function "
                    f"`{fndef.name}` — emission is host-side only "
                    "(executes once at trace time here; wrap the "
                    "dispatched step instead)", ctx.loc(node)))
    return out


# ---------------------------------------------------------------------------
# Rule 8: every emitted span name is registered
# ---------------------------------------------------------------------------

# The emission helpers whose first argument is a span NAME (module-level
# `telemetry.span(...)` / `telemetry.span_event(...)` and their member
# imports — the only in-repo emission idioms; `Recorder.emit("span", ...)`
# stays internal to the telemetry package).
_SPAN_EMITTERS = frozenset({"span", "span_event"})


def _registered_span_names() -> frozenset:
    # telemetry/recorder.py is jax-free by contract (the engine's no-
    # backend rule holds), so unlike AXIS_NAMES the registry is imported,
    # not mirrored — one definition, nothing to drift.
    from ..telemetry.recorder import REGISTERED_SPAN_NAMES

    return frozenset(REGISTERED_SPAN_NAMES)


@rule("span-names-registered", "ast",
      "every telemetry span name emitted in-repo appears in the "
      "recorder's span-name registry",
      "`telemetry summary` buckets spans by NAME against the canonical "
      "registry (SPAN_NAMES / SERVING_SPAN_NAMES / ELASTIC_SPAN_NAMES / "
      "AUX_SPAN_NAMES in telemetry/recorder.py) and silently files "
      "anything else under 'unaccounted' — a typo'd or unregistered span "
      "name vanishes from the step-time split instead of failing loudly, "
      "and the fleet aggregator's phase attribution never sees it. New "
      "span names are one registry line away; dynamic (non-literal) "
      "names are flagged too, because a name the linter cannot read is a "
      "name the registry cannot vouch for.")
def check_span_names_registered(ctx: FileContext) -> List[Finding]:
    mods, members, dotted = _telemetry_bindings(ctx)
    if not mods and not members and not dotted:
        return []
    # local names bound to the emitters via member imports, ALIASES
    # included: `from ..telemetry import span_event as se` binds `se` to
    # span_event — _telemetry_bindings keeps only the bound name, so the
    # original-name mapping is re-derived here (the pallas rule's
    # alias-aware convention)
    member_emitters: Dict[str, str] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) \
                and _TELEMETRY_MODULE in (node.module or "").split("."):
            for a in node.names:
                if a.name in _SPAN_EMITTERS:
                    member_emitters[a.asname or a.name] = a.name
    registry = _registered_span_names()
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        emitter = None
        if isinstance(func, ast.Attribute) and func.attr in _SPAN_EMITTERS:
            head = func
            while isinstance(head, ast.Attribute):
                head = head.value
            if isinstance(head, ast.Name) and head.id in mods:
                emitter = func.attr
            elif dotted:
                raw = _raw_dotted(func)
                if raw and any(raw.startswith(d + ".") for d in dotted):
                    emitter = func.attr
        elif isinstance(func, ast.Name) and func.id in member_emitters:
            emitter = member_emitters[func.id]
        if emitter is None or not node.args:
            continue
        name_arg = node.args[0]
        if isinstance(name_arg, ast.Constant) \
                and isinstance(name_arg.value, str):
            if name_arg.value not in registry:
                out.append(Finding(
                    "span-names-registered",
                    f"span name {name_arg.value!r} in {emitter}(...) is "
                    "not in the telemetry span-name registry — "
                    "`telemetry summary` would bucket it into "
                    "'unaccounted'; add it to the right *_SPAN_NAMES "
                    "tuple in telemetry/recorder.py", ctx.loc(name_arg)))
        else:
            out.append(Finding(
                "span-names-registered",
                f"dynamic span name in {emitter}(...) — the registry "
                "cannot vouch for a name the linter cannot read; emit a "
                "registered literal (or suppress on this line if the "
                "dynamism is deliberate)", ctx.loc(name_arg)))
    return out


# ---------------------------------------------------------------------------
# Rule 9: control decisions reach the re-plan surface only via apply.py
# ---------------------------------------------------------------------------

# The one sanctioned home of re-plan calls from the control package:
# control/apply.py (apply_decision — the contract-gated commit point).
# Matched on exact trailing path components like OS_EXIT_HOME.
CONTROL_APPLY_HOME = ("control", "apply.py")

# The re-plan surface: the Supervisor's boundary commit points, the
# elastic re-plan primitives they ride, and the armed callbacks. A
# reference to ANY of these from a control/ module other than apply.py
# is a policy resharding the fleet directly.
_REPLAN_SURFACE = frozenset({
    "boundary_shrink", "boundary_retune", "reshard_train_state",
    "plan_elastic_world", "replan_cb", "retune_cb", "_replan",
    "_maybe_grow",
})


@rule("control-decisions-gated", "ast",
      "control/ modules reach the re-plan surface (boundary_shrink / "
      "boundary_retune / reshard_train_state / plan_elastic_world / the "
      "replan callbacks) only through control/apply.py",
      "control/ is split by contract: policies (straggler.py, tuner.py, "
      "autopilot.py) measure and PROPOSE; only apply.py COMMITS, because "
      "apply_decision is where the contract gate and the decision log "
      "live. A policy calling boundary_shrink or reshard_train_state "
      "directly reshapes the fleet with no gate run and no ControlDecision "
      "emitted — the exact ungoverned mutation the control plane exists "
      "to prevent. Flagged on the reference (Name or Attribute), not just "
      "calls: `commit = sup.boundary_shrink` then `commit(...)` is the "
      "same bypass with one extra hop.")
def check_control_decisions_gated(ctx: FileContext) -> List[Finding]:
    parts = tuple(ctx.relpath.replace("\\", "/").split("/"))
    if len(parts) < 2 or parts[-2] != "control":
        return []
    if parts[-2:] == CONTROL_APPLY_HOME:
        return []
    name = "control-decisions-gated"
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        hit: Optional[str] = None
        if isinstance(node, ast.Attribute) and node.attr in _REPLAN_SURFACE:
            hit = node.attr
        elif isinstance(node, ast.Name) and node.id in _REPLAN_SURFACE:
            hit = node.id
        if hit is not None:
            out.append(Finding(
                name,
                f"`{hit}` referenced from a control/ policy module — the "
                "re-plan surface is reachable from control/ only through "
                "apply.py's apply_decision (the contract gate + decision "
                "log); emit a ControlDecision and let the Supervisor's "
                "boundary hook commit it", ctx.loc(node)))
    return out


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


def iter_source_files(repo: Path = REPO_ROOT) -> List[Path]:
    """The linted set: the package plus repo-top-level scripts — the same
    scope the old regex lint covered. Tests are exempt (they hold the
    synthetic violations the mutation tests feed the rules)."""
    pkg = repo / "distributed_pytorch_training_tpu"
    return sorted(pkg.rglob("*.py")) + sorted(repo.glob("*.py"))


def run_ast_rules(files: Optional[Iterable[Path]] = None,
                  rules: Optional[List[str]] = None,
                  repo: Path = REPO_ROOT) -> List[Finding]:
    """Run every (selected) AST rule over `files` (default: the repo set).
    Files that fail to parse produce a finding instead of crashing the
    run — a syntax error is a finding, not an analyzer failure.

    Kind "ast" rules see one FileContext at a time; kind "ast-global"
    rules (the lock-order graph) run ONCE over the whole parsed set —
    their findings anchor to a file:line, so per-line suppression still
    applies through that file's context."""
    from .contracts import iter_rules

    selected = [r for r in iter_rules(names=rules)
                if r.kind in ("ast", "ast-global")]
    per_file = [r for r in selected if r.kind == "ast"]
    global_rules = [r for r in selected if r.kind == "ast-global"]
    findings: List[Finding] = []
    contexts: Dict[str, FileContext] = {}
    for path in (files if files is not None else iter_source_files(repo)):
        path = Path(path)
        try:
            ctx = FileContext.parse(path, repo=repo)
        except (SyntaxError, ValueError) as e:
            findings.append(Finding(
                "parse-error", f"could not parse: {e}",
                str(path)))
            continue
        contexts[ctx.relpath] = ctx
        for r in per_file:
            for f in r.check(ctx):
                if not ctx.suppressed(f):
                    findings.append(f)
    for r in global_rules:
        for f in r.check(list(contexts.values())):
            ctx = contexts.get(f.location.rsplit(":", 1)[0])
            if ctx is None or not ctx.suppressed(f):
                findings.append(f)
    return findings
