"""HLO contract checker: census parsers + declarative rules over compiled
train steps.

The parsers (`hlo_result_elements`, `collective_census`,
`weight_update_census`, `grad_sync_census`) moved here from
`experiments/trace_analysis.py` (which keeps re-export shims — the trace
half of that module is runtime analysis; this is the compile-time half,
now a checked contract instead of scattered helpers).

Rules consume a `StepArtifacts` snapshot of one lowered config — the
optimized HLO text, the pre-optimization text (the wire-dtype read on CPU,
whose float-normalization pass promotes bf16 collectives to f32 in the
optimized text), the config knobs, and the sharding facts the evaluator
read off the live state. Each rule returns `Finding`s instead of raising,
so one run reports every violation; the `verify_*` wrappers below keep the
historical raise-on-violation API for acceptance-gate callers.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

from .contracts import (
    Contract, Finding, WIRE_HLO_DTYPE, WIRE_MODES, collectives_per_bucket,
    rule,
)

# ---------------------------------------------------------------------------
# HLO text parsers (the census)
# ---------------------------------------------------------------------------

# HLO text: `%name = shape op-name(...)`. On TPU the latency-hiding scheduler
# splits collectives into async `-start`/`-done` pairs; count the `-start`
# half (and bare sync forms), never `-done`, so each collective counts once.
# `ragged-all-to-all` (MoE dispatch at uneven expert loads) precedes
# `all-to-all` in the alternation so the longer name wins.
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|ragged-all-to-all|all-to-all)"
    r"(-start|-done)?[.\w]*\(")

# The collective's device grouping, printed on the same HLO line: the
# explicit form `replica_groups={{0,1},{2,3}}` or the iota form
# `replica_groups=[G,S]<=[dims...]` with an optional transpose suffix
# `T(perm)` (XLA's strided-group print form — the data-axis groups of a
# (data, model) mesh). The capture must accept every shape
# `parse_replica_groups` can decode, or classifiable groups silently
# arrive as "" and the TP rules misfire.
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups="
    r"(\{\{[\d,{} ]*\}\}|\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)")

# One array shape inside an HLO result: "f32[1000,512]{1,0}" (possibly inside
# a tuple). Captures the bracketed dims; "f32[]" is a scalar.
_HLO_SHAPE_RE = re.compile(r"\w+\[([\d,]*)\]")

# Same shape token with the DTYPE captured instead ("f32", "bf16", "s8") —
# the wire-dtype read of `grad_sync_census`. Context/token dtypes (u32 ids
# in async tuples) ride along; the census reports all of them.
_HLO_TYPED_SHAPE_RE = re.compile(r"(\w+)\[[\d,]*\]")


def hlo_result_elements(shape_str: str) -> int:
    """Total elements across every array in an HLO result shape string
    (async collectives return tuples; sum the parts so `-start` forms
    compare like their sync equivalents)."""
    total = 0
    for m in _HLO_SHAPE_RE.finditer(shape_str):
        dims = m.group(1)
        if not dims:
            total += 1  # scalar
            continue
        n = 1
        for d in dims.split(","):
            n *= int(d)
        total += n
    return total


def collective_census(compiled_text: str) -> List[dict]:
    """Census of collective ops in optimized HLO text: op kind + result
    shape + the replica grouping (which mesh axis the collective rides —
    the 2-D TP x FSDP rules classify it via `replica_group_axis`).

    The static half of the grad-sync analysis: what the compiler actually
    scheduled (names/shapes straight from the executable), standing in for
    the reference's promised profiler-timeline read-off (README.md:35)."""
    rows = {}
    for line in compiled_text.splitlines():
        m = _HLO_COLLECTIVE_RE.search(line)
        if not m:
            continue
        shape, kind, suffix = m.group(1), m.group(2), m.group(3)
        if suffix == "-done":
            continue  # the paired completion of an async -start
        g = _REPLICA_GROUPS_RE.search(line)
        groups = g.group(1) if g else ""
        key = (kind, shape, groups)
        if key not in rows:
            rows[key] = {"op": kind, "result_shape": shape,
                         "replica_groups": groups, "count": 0}
        rows[key]["count"] += 1
    return sorted(rows.values(),
                  key=lambda r: (r["op"], r["result_shape"],
                                 r["replica_groups"]))


def parse_replica_groups(groups: str):
    """Explicit `{{0,1},{2,3}}` or iota `[G,S]<=[dims...]` replica groups
    (with an optional transpose suffix `T(perm)` — XLA's strided-group
    print form, e.g. the data-axis groups of a (data, model) mesh) as a
    tuple of tuples; None when absent/unparseable."""
    if not groups:
        return None
    if groups.startswith("{{"):
        try:
            return tuple(
                tuple(int(x) for x in part.split(",") if x.strip())
                for part in groups.strip("{}").split("},{"))
        except ValueError:
            return None
    m = re.fullmatch(r"\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?",
                     groups)
    if m:
        import numpy as _np

        n_groups, size = int(m.group(1)), int(m.group(2))
        dims = tuple(int(d) for d in m.group(3).split(","))
        total = int(_np.prod(dims))
        if n_groups * size != total:
            return None
        devices = _np.arange(total).reshape(dims)
        if m.group(4) is not None:
            perm = tuple(int(p) for p in m.group(4).split(","))
            if sorted(perm) != list(range(len(dims))):
                return None
            devices = devices.transpose(perm)
        flat = devices.reshape(-1)
        return tuple(tuple(int(x) for x in flat[g * size:(g + 1) * size])
                     for g in range(n_groups))
    return None


def replica_group_axis(groups: str, n_batch: int, n_model: int) -> str:
    """Which logical axis a collective's replica groups ride, on a 2-D
    (batch-shards x model) device layout with the model axis MINOR
    (parallel/mesh.AXIS_ORDER puts `model` last): "model" (consecutive-id
    groups of size M), "data" (stride-M groups of size N), "all" (one
    group spanning every device), or "other"/"unknown". The TP x FSDP
    rules use this to tell megatron activation psums from gradient
    traffic; artifacts without a model axis never consult it."""
    parsed = parse_replica_groups(groups)
    if parsed is None:
        return "unknown"
    got = {frozenset(g) for g in parsed}
    total = n_batch * n_model
    if got == {frozenset(range(b * n_model, (b + 1) * n_model))
               for b in range(n_batch)}:
        return "model"
    if got == {frozenset(range(m, total, n_model)) for m in range(n_model)}:
        return "data"
    if got == {frozenset(range(total))}:
        return "all"
    return "other"


def replica_group_tier(groups: str, n_slices: int, n_inner: int) -> str:
    """Which TIER a collective's replica groups ride on the two-tier
    (slice x intra-slice) layout: "ici" (groups stay inside one slice —
    the fast interconnect), "dcn" (groups cross slices — the slow
    inter-slice links), "all" (one group spanning the mesh), or
    "other"/"unknown". The slice axis is OUTERMOST in AXIS_ORDER
    (parallel/mesh.py), so device ids are slice-major: intra-slice groups
    are consecutive-id runs of size n_inner and cross-slice groups are
    stride-n_inner combs — exactly the geometry `replica_group_axis`
    already classifies with (n_batch, n_model) = (n_slices, n_inner);
    this wrapper renames its verdicts into tier vocabulary. With
    n_inner=1 (no intra-slice width) every hier collective spans all
    slices and classifies "dcn" — there is no fast tier to ride."""
    axis = replica_group_axis(groups, max(n_slices, 1), max(n_inner, 1))
    return {"model": "ici", "data": "dcn"}.get(axis, axis)


def weight_update_census(compiled_text: str, min_elements: int = 8192) -> dict:
    """The gradient-sync subset of the census: collectives whose result
    carries at least `min_elements` elements — gradient- and parameter-sized
    transfers. Scalar psums (metric fan-in, global-norm clipping, BatchNorm
    channel stats) fall under the floor, so the returned counts isolate the
    ops that move the model: the DDP-style grad all-reduce on the replicated
    path, reduce-scatter + all-gather on the zero1 path.

    Returns {"all-reduce": n, "reduce-scatter": n, "all-gather": n,
    "rows": [...]} (other collective kinds appear only if present)."""
    counts: Dict[str, int] = {"all-reduce": 0, "reduce-scatter": 0,
                              "all-gather": 0}
    rows = []
    for c in collective_census(compiled_text):
        if hlo_result_elements(c["result_shape"]) < min_elements:
            continue
        counts[c["op"]] = counts.get(c["op"], 0) + c["count"]
        rows.append(c)
    counts["rows"] = rows
    return counts


def grad_sync_census(hlo_text: str, min_elements: int = 8192) -> dict:
    """Census of the gradient-sync stage in HLO text: how many gradient-
    sized collectives the step carries, and what dtype rides the wire.

    The instrument for the bucketed reducer (parallel/grad_sync.py): with
    ``bucket_cap_mb`` set, the compiled step must show
    ``ceil(total_grad_bytes / cap)`` large collectives (one per bucket)
    instead of one per leaf, and with a compressed ``wire_dtype`` their
    operands must be bf16/s8, not f32. Accepts optimized HLO
    (``compiled.as_text()``) or pre-optimization HLO (`preopt_hlo_text`):
    CPU's float-normalization pass promotes bf16 collectives to f32 in the
    OPTIMIZED text, so wire-dtype checks on the test backend read the
    pre-optimization module (TPU keeps bf16 end-to-end).

    Returns {"n_collectives", "by_op": {op: n}, "wire_dtypes": {dtype: n},
    "rows": [...]} counting only collectives whose result carries at least
    `min_elements` elements (scalar metric psums and int8 scale gathers
    fall under the floor).
    """
    by_op: Dict[str, int] = {}
    wire: Dict[str, int] = {}
    rows = []
    total = 0
    for c in collective_census(hlo_text):
        if hlo_result_elements(c["result_shape"]) < min_elements:
            continue
        total += c["count"]
        by_op[c["op"]] = by_op.get(c["op"], 0) + c["count"]
        dtypes = sorted(set(
            m.group(1)
            for m in _HLO_TYPED_SHAPE_RE.finditer(c["result_shape"])))
        for d in dtypes:
            wire[d] = wire.get(d, 0) + c["count"]
        rows.append({**c, "dtypes": dtypes})
    return {"n_collectives": total, "by_op": by_op, "wire_dtypes": wire,
            "rows": rows}


def preopt_hlo_text(lowered) -> str:
    """Pre-optimization HLO text of a ``jax.jit(...).lower(...)`` result —
    the wire-dtype read for `grad_sync_census` (see its docstring: the CPU
    backend's float-normalization rewrites bf16 collectives to f32 before
    the optimized text is printed)."""
    return lowered.compiler_ir(dialect="hlo").as_hlo_text()


def expected_buckets(total_grad_bytes: int, bucket_cap_mb: float) -> int:
    """ceil(bytes/cap) with build_bucket_plan's EXACT floor-to-elements
    arithmetic — re-deriving it as ceil(bytes/cap_bytes) would under-count
    buckets whenever the cap is not element-aligned and flag a correctly
    engaged reducer."""
    total_elems = int(total_grad_bytes) // 4
    cap_elems = int(bucket_cap_mb * (1024 ** 2) // 4)
    if bucket_cap_mb <= 0 or cap_elems >= total_elems:
        return 1  # no/huge cap = one fused bucket
    return -(-total_elems // max(cap_elems, 1))


# ---------------------------------------------------------------------------
# Step artifacts: everything the rules need, snapshotted once per config
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class StepArtifacts:
    """One lowered/compiled train-step config, as the rules see it.

    Built by `evaluate_contract` (the matrix) and
    `experiments.harness.measure_config` (per bench arm); tests build them
    directly to feed rules synthetic violations (the mutation tests).
    """

    name: str
    optimized_text: str
    preopt_text: Optional[str] = None
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    n_shards: int = 1
    total_grad_bytes: int = 0
    min_elements: int = 8192
    # (path, n_elements) of optimizer-state leaves >= min_elements whose
    # sharding the evaluator found fully replicated (zero1 promises none).
    replicated_state_buffers: Tuple[Tuple[str, int], ...] = ()
    # Same read over the PARAMETER leaves (explicit FSDP promises none:
    # params live flat-sharded 1/N at rest — a replicated param buffer
    # means the mode is paying replicated memory while claiming the
    # division). Filled only for fsdp configs.
    replicated_param_buffers: Tuple[Tuple[str, int], ...] = ()
    # Per-group full padded element counts (n_shards x row_size, one per
    # LayerGroup of the trainer's grad_sync.build_layer_plan) — the
    # fsdp-layer-gather-bound / scatter-signature budget. The SIZES ride
    # along (not just the count) because the census floor hides sub-floor
    # groups (a tiny final layernorm's gather is metric noise by design):
    # the rules compute floor-aware expected counts from these. Empty when
    # the config is not explicit-FSDP.
    layer_group_padded_sizes: Tuple[int, ...] = ()
    # the backend the config was lowered FOR ("tpu"/"cpu"/...): rules whose
    # promise only exists in one backend's lowering (fused-quantize-kernel-
    # present: Pallas emits a custom-call on TPU but inlines as plain HLO
    # in CPU interpreter mode) abstain rather than guess when it is "".
    backend: str = ""
    # Explicit TP x FSDP (ISSUE 13): the mesh's model-axis size (1 = no
    # TP — every pre-existing artifact), and the trainer-derived model-axis
    # collective budget: `tp_expected_psums` counts the megatron psums of
    # one fwd+bwd step (one per residual join forward + its backward
    # mirror at each parallel-region input: 4/block, +2 with the
    # vocab-parallel embedding), `tp_expected_model_gathers` the
    # vocab-parallel logits gathers (1 when engaged). Snapshotted from the
    # trainer (Trainer.tp_expected_model_collectives), never hard-coded in
    # a rule.
    model_shards: int = 1
    tp_expected_psums: int = 0
    tp_expected_model_gathers: int = 0
    # Per-shard element count of EACH of the parallel-vocab CE's two
    # model-axis stat collectives (both (rows, seq-1, 2)-shaped by
    # construction — collectives.tp_parallel_cross_entropy). Batch-shaped,
    # so unlike the hidden-sized structural psums their census visibility
    # depends on batch x floor: `tp-psum-signature` adds 2 to the psum
    # budget iff this clears min_elements. Snapshotted from
    # Trainer.tp_expected_ce_stat_elements; 0 when the vocab-parallel
    # head is not engaged.
    tp_ce_stat_elements: int = 0
    # Two-tier hierarchical sync (int8_hier): the mesh's slice-axis size
    # (1 = single-slice — every pre-existing artifact). Snapshotted from
    # the trainer's resolved HierSpec, never re-derived in a rule: the
    # tier classification of every hier census row keys on it.
    slice_shards: int = 1

    @property
    def wire_mode(self) -> str:
        return self.config.get("wire_dtype", "fp32")

    @property
    def tp_engaged(self) -> bool:
        """Mirrors Trainer's engagement condition for explicit TP x FSDP."""
        return bool(self.config.get("fsdp_explicit")) and self.model_shards > 1

    def collective_axis(self, row: dict) -> str:
        """`replica_group_axis` of one census row under this artifact's
        (batch, model) shard counts."""
        return replica_group_axis(row.get("replica_groups", ""),
                                  max(self.n_shards, 1),
                                  max(self.model_shards, 1))

    @property
    def hier_engaged(self) -> bool:
        """Mirrors Trainer's engagement condition for the two-tier wire:
        int8_hier on a mesh with a real slice axis (on slices=1 the
        trainer resolves to the flat fp32 path BEFORE tracing, so no hier
        collective exists to classify)."""
        return (self.wire_mode == "int8_hier" and self.slice_shards > 1
                and self.n_shards > 1)

    def collective_tier(self, row: dict) -> str:
        """`replica_group_tier` of one census row under this artifact's
        (slice, intra-slice) factorization: n_inner is the intra-slice
        batch-shard count n_shards / slice_shards."""
        n_slices = max(self.slice_shards, 1)
        return replica_group_tier(row.get("replica_groups", ""), n_slices,
                                  max(self.n_shards // n_slices, 1))

    @property
    def zero1_engaged(self) -> bool:
        return bool(self.config.get("zero1")) and self.n_shards > 1

    @property
    def fsdp_engaged(self) -> bool:
        """Mirrors Trainer's engagement condition for explicit FSDP."""
        return bool(self.config.get("fsdp_explicit")) and self.n_shards > 1

    @property
    def grad_sync_engaged(self) -> bool:
        """Mirrors Trainer's engagement condition for the explicit reducer
        (fsdp_explicit owns its own wire layout — the per-layer cut — so a
        compressed wire under fsdp is NOT the bucketed reducer)."""
        return (not self.config.get("zero1")
                and not self.config.get("fsdp_explicit")
                and self.n_shards > 1
                and (float(self.config.get("bucket_cap_mb", 0.0)) > 0
                     or self.wire_mode != "fp32"))

    @property
    def wire_text(self) -> str:
        """The text wire-dtype reads use: pre-optimization when available
        (bf16 survives only there on CPU), optimized otherwise."""
        return self.preopt_text or self.optimized_text


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

# Collective kinds that REDUCE gradients (may legally compress). all-gather
# is excluded: the zero1 parameter gather is exact by design — fp32 there is
# the contract, not a violation. (The int8 code gather rides s8 anyway.)
_REDUCTION_KINDS = ("all-reduce", "reduce-scatter", "all-to-all",
                    "ragged-all-to-all")


def _multihop_hop_problems(census: dict) -> List[str]:
    """Problems with a census that CLAIMS the multi-hop int8 wire.

    The 2/bucket budget is an upper bound, so a single-collective-per-
    bucket impostor (e.g. the gather-form codec mislabeled as multihop)
    sails under it — the hop SIGNATURE is what catches it: hop 1 must
    appear as a scatter-kind collective (all-to-all or reduce-scatter) and
    hop 2 as an all-gather, both gradient-sized.
    """
    by_op = census["by_op"]
    problems = []
    if not (by_op.get("all-to-all", 0) + by_op.get("reduce-scatter", 0)):
        problems.append(
            "multihop wire shows no gradient-sized all-to-all/reduce-"
            "scatter — hop 1 (the s8 reduce-scatter) is missing")
    if not by_op.get("all-gather", 0):
        problems.append(
            "multihop wire shows no gradient-sized all-gather — hop 2 "
            "(the requantized s8 gather) is missing")
    return problems


@rule("grad-sync-bucket-bound", "hlo",
      "bucketed reducer emits <= buckets x per-bucket-cost + slack "
      "gradient-sized collectives",
      "O(buckets) large transfers instead of O(leaves) small ones is the "
      "reducer's whole win; an unbounded census means bucketing silently "
      "disengaged (parallel/grad_sync.py).")
def check_bucket_bound(a: StepArtifacts, slack: int = 2) -> List[Finding]:
    if not a.grad_sync_engaged:
        return []
    census = grad_sync_census(a.optimized_text, a.min_elements)
    n_buckets = expected_buckets(a.total_grad_bytes,
                                 float(a.config.get("bucket_cap_mb", 0.0)))
    bound = n_buckets * collectives_per_bucket(a.wire_mode) + slack
    out = []
    if census["n_collectives"] > bound:
        out.append(Finding(
            "grad-sync-bucket-bound",
            f"step carries {census['n_collectives']} gradient-sized "
            f"collectives, more than {n_buckets} bucket(s) x "
            f"{collectives_per_bucket(a.wire_mode)} ({a.wire_mode}) + "
            f"{slack} = {bound}: {census['by_op']}", a.name))
    if census["n_collectives"] == 0:
        out.append(Finding(
            "grad-sync-bucket-bound",
            f"no gradient-sized collectives found — the census floor "
            f"(min_elements={a.min_elements}) is above the model's gradient "
            "transfers, or the reducer never ran", a.name))
    elif a.wire_mode == "int8_multihop":
        out.extend(Finding("grad-sync-bucket-bound", p, a.name)
                   for p in _multihop_hop_problems(census))
    return out


@rule("compressed-wire", "hlo",
      "a compressed wire_dtype really puts bf16/s8 on the wire",
      "a silent fallback to fp32 operands erases the wire-byte win while "
      "the flag still claims it (the ISSUE-2 acceptance check).")
def check_compressed_wire(a: StepArtifacts) -> List[Finding]:
    if a.wire_mode == "fp32" or not (a.grad_sync_engaged or a.zero1_engaged
                                     or a.fsdp_engaged):
        return []
    if a.wire_mode == "int8_hier" and not a.hier_engaged:
        # slices=1 passthrough: the trainer resolved int8_hier to the flat
        # fp32 path before tracing — there is no s8 wire to demand
        return []
    if a.preopt_text is None:
        # No reliable wire read: CPU's float-normalization promotes bf16
        # collectives to f32 in the OPTIMIZED text, so checking it would
        # turn a pre-opt extraction failure into a false violation. The
        # wire rules abstain rather than guess (the evaluator and
        # measure_config always attempt the pre-opt read).
        return []
    expect = WIRE_HLO_DTYPE[a.wire_mode]
    wire = grad_sync_census(a.wire_text, a.min_elements)["wire_dtypes"]
    if not wire.get(expect):
        return [Finding(
            "compressed-wire",
            f"wire_dtype={a.wire_mode!r} promises {expect} collective "
            f"operands on the wire, but the HLO shows {wire}", a.name)]
    return []


@rule("no-fp32-wire", "hlo",
      "no fp32 bytes ride a compressed wire's gradient reductions",
      "compressed-wire proves bf16/s8 is present; this proves fp32 is "
      "ABSENT from the reducing collectives — both can hold at once only "
      "if every gradient byte is compressed. The zero1 parameter "
      "all-gather is exempt: it is exact by design.")
def check_no_fp32_wire(a: StepArtifacts) -> List[Finding]:
    if a.wire_mode == "fp32" or not (a.grad_sync_engaged or a.zero1_engaged
                                     or a.fsdp_engaged):
        return []
    if a.wire_mode == "int8_hier" and not a.hier_engaged:
        return []  # slices=1 passthrough — see check_compressed_wire
    if a.preopt_text is None:
        return []  # no reliable wire read — see check_compressed_wire
    census = grad_sync_census(a.wire_text, a.min_elements)
    # Explicit TP: megatron activation psums ride the MODEL axis in exact
    # fp32 BY DESIGN (they are forward/backward activations, not gradient
    # sync — the zero1 param-gather exemption's argument); only collectives
    # off the model axis must keep the compressed-wire promise.
    rows = census["rows"]
    if a.tp_engaged:
        rows = [r for r in rows if a.collective_axis(r) != "model"]
    if a.hier_engaged:
        # Two-tier wire: the INTRA-slice stage reduces in exact fp32 BY
        # DESIGN (that tier rides the fast interconnect; s8 is the
        # SLOW-tier promise — contracts.WIRE_HLO_DTYPE). Only the ici
        # tier is exempt: cross-slice rows (and anything the classifier
        # can't place) must still keep every gradient byte compressed.
        rows = [r for r in rows if a.collective_tier(r) != "ici"]
    bad = [r for r in rows
           if r["op"] in _REDUCTION_KINDS and "f32" in r["dtypes"]]
    if bad:
        return [Finding(
            "no-fp32-wire",
            f"wire_dtype={a.wire_mode!r} but {len(bad)} gradient-sized "
            f"reducing collective(s) carry f32 operands: "
            f"{[(r['op'], r['result_shape']) for r in bad]}", a.name)]
    return []


@rule("hier-tier-signature", "hlo",
      "the two-tier wire rides each tier with the right signature: exact "
      "reduce-scatter/all-gather INSIDE a slice, an s8 scatter+gather "
      "hop pair ACROSS slices, nothing spanning both",
      "the 4/bucket budget alone is a ceiling a flat codec sails under — "
      "the TIER-classified signature is what pins the hierarchy: a flat "
      "multihop mislabeled int8_hier shows no cross-slice-only hop (its "
      "groups span the whole mesh), a hierarchy that lost its fast stage "
      "shows no intra-slice reduce-scatter, and an fp32 byte on a "
      "cross-slice collective is paying exact-width traffic on the slow "
      "links the mode exists to compress (parallel/grad_sync.py "
      "_int8_hier_sum; slice-major device ids make the tiers readable "
      "straight off replica_groups — parallel/mesh.py AXIS_ORDER).")
def check_hier_tier_signature(a: StepArtifacts) -> List[Finding]:
    if not a.hier_engaged or not (a.grad_sync_engaged or a.zero1_engaged
                                  or a.fsdp_engaged):
        return []
    n_slices = max(a.slice_shards, 1)
    n_inner = max(a.n_shards // n_slices, 1)
    census = grad_sync_census(a.optimized_text, a.min_elements)
    by_tier_op: Dict[Tuple[str, str], int] = {}
    for r in census["rows"]:
        key = (a.collective_tier(r), r["op"])
        by_tier_op[key] = by_tier_op.get(key, 0) + r["count"]

    def n(tier: str, *ops: str) -> int:
        return sum(by_tier_op.get((tier, op), 0) for op in ops)

    out = []
    spanning = [(t, op, c) for (t, op), c in sorted(by_tier_op.items())
                if t not in ("ici", "dcn")]
    if spanning:
        out.append(Finding(
            "hier-tier-signature",
            f"{sum(c for _, _, c in spanning)} gradient-sized "
            f"collective(s) ride groups that are neither intra-slice nor "
            f"cross-slice: {spanning[:5]} — a hier collective grouped "
            "over the whole mesh (or off-pattern) is flat traffic wearing "
            "the two-tier flag", a.name))
    dcn_scatter = n("dcn", "all-to-all", "reduce-scatter")
    dcn_gather = n("dcn", "all-gather")
    if not dcn_scatter:
        out.append(Finding(
            "hier-tier-signature",
            "no gradient-sized CROSS-SLICE all-to-all/reduce-scatter — "
            "hop 1 of the slow-tier s8 exchange is missing", a.name))
    if not dcn_gather:
        out.append(Finding(
            "hier-tier-signature",
            "no gradient-sized CROSS-SLICE all-gather — hop 2 (the "
            "requantized s8 gather) is missing", a.name))
    if n_inner > 1:
        if not n("ici", "reduce-scatter"):
            out.append(Finding(
                "hier-tier-signature",
                "no gradient-sized INTRA-SLICE reduce-scatter — the "
                "exact fast-tier reduce is missing (every byte is riding "
                "the slow links)", a.name))
        if not n("ici", "all-gather"):
            out.append(Finding(
                "hier-tier-signature",
                "no gradient-sized INTRA-SLICE all-gather — the reduced "
                "buckets are never rebuilt across the slice", a.name))
    if a.grad_sync_engaged and a.total_grad_bytes:
        # The bucketed-reducer arm pins EXACT per-bucket counts per tier
        # (zero1/fsdp cut per shard-group/layer instead — presence-only
        # above). Every hop's census result clears the floor whenever the
        # smallest (the 1/n_inner slow-tier part) does, so one floor
        # check guards the whole expectation from tiny-bucket noise.
        n_buckets = expected_buckets(
            a.total_grad_bytes, float(a.config.get("bucket_cap_mb", 0.0)))
        part = (a.total_grad_bytes // 4) // max(n_buckets, 1) // n_inner
        if part >= a.min_elements:
            expect = [(dcn_scatter, "cross-slice scatter (hop 1)"),
                      (dcn_gather, "cross-slice all-gather (hop 2)")]
            if n_inner > 1:
                expect += [(n("ici", "reduce-scatter"),
                            "intra-slice reduce-scatter"),
                           (n("ici", "all-gather"), "intra-slice all-gather")]
            for got, label in expect:
                if got != n_buckets:
                    out.append(Finding(
                        "hier-tier-signature",
                        f"step carries {got} {label} collective(s), "
                        f"expected exactly {n_buckets} (one per bucket; "
                        f"census by (tier, op): "
                        f"{dict(sorted(by_tier_op.items()))})", a.name))
    if a.preopt_text is not None:
        # the dtype read (pre-opt text — see check_compressed_wire): no
        # fp32 byte may CROSS slices, on any collective kind. Stricter
        # than no-fp32-wire, which exempts gathers mode-wide: the hier
        # slow-tier gather is s8 by construction, so fp32 there is a
        # decompressed hop-2 paying 4x on the slow links.
        wrows = grad_sync_census(a.wire_text, a.min_elements)["rows"]
        bad = [(r["op"], r["result_shape"]) for r in wrows
               if a.collective_tier(r) == "dcn" and "f32" in r["dtypes"]]
        if bad:
            out.append(Finding(
                "hier-tier-signature",
                f"{len(bad)} CROSS-SLICE collective(s) carry f32 "
                f"operands: {bad[:5]} — the slow tier must ride s8 codes "
                "(+ sub-floor scale rows) only", a.name))
    return out


@rule("zero1-collectives", "hlo",
      "zero1 replaces gradient all-reduces with reduce-scatter + all-gather",
      "the collective signature of cross-replica weight-update sharding "
      "(Xu et al., arXiv:2004.13336): a surviving gradient-sized "
      "all-reduce means the sharded update silently fell back to the "
      "replicated one.")
def check_zero1_collectives(a: StepArtifacts) -> List[Finding]:
    if not a.zero1_engaged:
        return []
    census = weight_update_census(a.optimized_text, a.min_elements)
    out = []
    if census["all-reduce"]:
        out.append(Finding(
            "zero1-collectives",
            f"zero1 step still contains {census['all-reduce']} gradient-"
            f"sized all-reduce(s): "
            f"{[r for r in census['rows'] if r['op'] == 'all-reduce']}",
            a.name))
    # the int8 scatter rides an s8 all-to-all instead of reduce-scatter
    scatter_ops = census["reduce-scatter"] + census.get("all-to-all", 0)
    if not scatter_ops:
        out.append(Finding("zero1-collectives",
                           "zero1 step contains no reduce-scatter (or s8 "
                           "all-to-all) — gradients are not being scattered",
                           a.name))
    if not census["all-gather"]:
        out.append(Finding("zero1-collectives",
                           "zero1 step contains no all-gather — updated "
                           "parameter shards are never rebuilt", a.name))
    return out


@rule("zero1-sharded-state", "hlo",
      "no gradient-sized optimizer-state buffer stays replicated under zero1",
      "dividing moment memory by the DP degree IS the zero1 win; a "
      "replicated moment buffer means the sharded update is paying "
      "replicated memory (the arXiv:2004.13336 contract).")
def check_zero1_sharded_state(a: StepArtifacts) -> List[Finding]:
    if not a.zero1_engaged:
        return []
    if a.replicated_state_buffers:
        rows = ", ".join(f"{p} ({n} elements)"
                         for p, n in a.replicated_state_buffers[:5])
        more = len(a.replicated_state_buffers) - 5
        return [Finding(
            "zero1-sharded-state",
            f"{len(a.replicated_state_buffers)} optimizer-state buffer(s) "
            f">= {a.min_elements} elements are fully replicated under "
            f"zero1: {rows}" + (f" (+{more} more)" if more > 0 else ""),
            a.name)]
    return []


@rule("fsdp-layer-gather-bound", "hlo",
      "explicit FSDP gathers params exactly once per layer group",
      "the just-in-time per-layer gather IS the mode (SimpleFSDP, "
      "PAPERS.md): fewer gathers than layer groups means some layer reads "
      "stale or GSPMD-materialized full params; more means the per-layer "
      "plan degenerated into per-leaf traffic (the O(leaves) failure the "
      "LayerPlan exists to prevent). The budget comes from the trainer's "
      "build_layer_plan, never hard-coded.")
def check_fsdp_gather_bound(a: StepArtifacts) -> List[Finding]:
    if not a.fsdp_engaged:
        return []
    sizes = a.layer_group_padded_sizes
    if not sizes:
        return [Finding(
            "fsdp-layer-gather-bound",
            "fsdp config evaluated without a layer-plan budget "
            "(layer_group_padded_sizes empty) — the evaluator must "
            "snapshot the trainer's LayerPlan group sizes", a.name)]
    # A group's gather result carries its FULL padded size (fp32 f32 or
    # multihop s8 codes — same element count); groups under the census
    # floor are invisible by design, so the expectation is floor-aware.
    expected = sum(1 for s in sizes if s >= a.min_elements)
    census = grad_sync_census(a.optimized_text, a.min_elements)
    if a.tp_engaged:
        # 2-D mesh: count only the DATA-axis gathers — the vocab-parallel
        # logits gather rides the model axis and is tp-psum-signature's
        # budget, not a param gather
        gathers = sum(r["count"] for r in census["rows"]
                      if r["op"] == "all-gather"
                      and a.collective_axis(r) == "data")
    else:
        gathers = census["by_op"].get("all-gather", 0)
    if gathers != expected:
        return [Finding(
            "fsdp-layer-gather-bound",
            f"fsdp step carries {gathers} gradient/param-sized "
            + ("data-axis " if a.tp_engaged else "")
            + f"all-gather(s), expected exactly {expected} (one per layer "
            f"group over the census floor; {len(sizes)} group(s), "
            f"{len(sizes) - expected} under min_elements="
            f"{a.min_elements}): {census['by_op']}", a.name)]
    return []


@rule("fsdp-scatter-into-shard", "hlo",
      "explicit FSDP reduce-scatters each layer's gradient into the shard "
      "layout, with no gradient-sized all-reduce",
      "the scatter-into-shard signature: gradients must land as 1/N "
      "chunks (reduce-scatter, or the s8 all-to-all under the int8 "
      "codec), one per layer group. A surviving gradient-sized all-reduce "
      "means the step synced replicated gradients and the at-rest "
      "sharding is cosmetic.")
def check_fsdp_scatter_signature(a: StepArtifacts) -> List[Finding]:
    if not a.fsdp_engaged:
        return []
    census = grad_sync_census(a.optimized_text, a.min_elements)
    by_op = census["by_op"]
    out = []
    if a.tp_engaged:
        # 2-D mesh: the scatter census counts data-axis collectives; the
        # model-axis megatron psums are all-reduces by op kind and are
        # budgeted by tp-psum-signature instead — a gradient-sized
        # all-reduce on the DATA axes is still the violation here.
        rows = census["rows"]
        scatters = sum(r["count"] for r in rows
                       if r["op"] in ("reduce-scatter", "all-to-all")
                       and a.collective_axis(r) == "data")
        data_all_reduce = sum(r["count"] for r in rows
                              if r["op"] == "all-reduce"
                              and a.collective_axis(r) != "model")
    else:
        scatters = by_op.get("reduce-scatter", 0) + by_op.get("all-to-all", 0)
        data_all_reduce = by_op.get("all-reduce", 0)
    sizes = a.layer_group_padded_sizes
    if sizes:
        # Floor-aware expectation, per wire: the s8 codec's all-to-all
        # result carries the group's FULL padded size, a plain
        # reduce-scatter's result is the 1/N destination chunk — the same
        # group can be census-visible under one wire and not the other.
        if a.wire_mode in ("int8", "int8_multihop"):
            expected = sum(1 for s in sizes if s >= a.min_elements)
        else:
            expected = sum(1 for s in sizes
                           if s // max(a.n_shards, 1) >= a.min_elements)
        if scatters != expected:
            out.append(Finding(
                "fsdp-scatter-into-shard",
                f"fsdp step carries {scatters} gradient-sized "
                f"reduce-scatter/all-to-all(s), expected exactly "
                f"{expected} (one per layer group whose scatter result "
                f"clears the census floor; {len(sizes)} group(s), "
                f"min_elements={a.min_elements}, wire={a.wire_mode}): "
                f"{by_op}", a.name))
    if data_all_reduce:
        out.append(Finding(
            "fsdp-scatter-into-shard",
            f"fsdp step still contains {data_all_reduce} gradient-"
            "sized all-reduce(s)"
            + (" off the model axis" if a.tp_engaged else "")
            + " — gradients are being synced replicated "
            "instead of scattered into the shard layout", a.name))
    return out


@rule("tp-psum-signature", "hlo",
      "explicit TP carries exactly the megatron model-axis collective "
      "budget: one psum per residual join (+ backward mirror), the "
      "parallel-vocab CE's two stat collectives, and ZERO model-axis "
      "gathers",
      "the model-axis psums ARE the TP wire: fewer than the budget means "
      "a parallel region lost its f/g operator (silently wrong gradients "
      "or a dead region); more means extra model-axis traffic smuggled "
      "into every step — and ANY model-axis all-gather means the "
      "vocab-scale logits gather the parallel-vocab cross-entropy "
      "removed crept back. The budget comes from the trainer's TP model "
      "(4/block + 2 with the vocab-parallel embedding; the batch-shaped "
      "CE stats counted iff they clear the census floor), never "
      "hard-coded (parallel/collectives.py copy_to_tp / reduce_from_tp / "
      "tp_parallel_cross_entropy; ISSUEs 13 + 16).")
def check_tp_psum_signature(a: StepArtifacts) -> List[Finding]:
    if not a.tp_engaged:
        return []
    if not a.tp_expected_psums:
        return [Finding(
            "tp-psum-signature",
            "explicit-TP config evaluated without a model-axis collective "
            "budget (tp_expected_psums=0) — the evaluator must snapshot "
            "Trainer.tp_expected_model_collectives", a.name)]
    census = grad_sync_census(a.optimized_text, a.min_elements)
    psums = sum(r["count"] for r in census["rows"]
                if r["op"] == "all-reduce"
                and a.collective_axis(r) == "model")
    gathers = sum(r["count"] for r in census["rows"]
                  if r["op"] == "all-gather"
                  and a.collective_axis(r) == "model")
    # the CE stats (pmax + stacked psum, one shared size class) are
    # visible only when their batch-shaped operands clear the floor
    ce_visible = 2 if a.tp_ce_stat_elements >= a.min_elements else 0
    expected_psums = a.tp_expected_psums + ce_visible
    out = []
    if psums != expected_psums:
        out.append(Finding(
            "tp-psum-signature",
            f"step carries {psums} model-axis all-reduce(s), expected "
            f"exactly {expected_psums} ({a.tp_expected_psums} structural: "
            "one per residual join forward + its backward mirror per "
            "parallel region, +2 for the vocab-parallel embedding when "
            f"engaged; +{ce_visible} parallel-vocab CE stats at "
            f"{a.tp_ce_stat_elements} elements vs floor "
            f"{a.min_elements})", a.name))
    if gathers != a.tp_expected_model_gathers:
        out.append(Finding(
            "tp-psum-signature",
            f"step carries {gathers} model-axis all-gather(s), expected "
            f"exactly {a.tp_expected_model_gathers} — the parallel-vocab "
            "cross-entropy computes the loss from local logit columns; "
            "a vocab-scale model-axis gather is the regression it "
            "replaced", a.name))
    return out


@rule("fsdp-gather-rides-data-only", "hlo",
      "under TP x FSDP every param gather/scatter rides the data axes "
      "only — nothing spans the model axis or the whole mesh",
      "the 1/M wire reduction IS the composition's win: each model shard "
      "gathers/scatters only its local parameter slice over its data "
      "replicas. A collective grouped over (data x model) — or an extra "
      "model-axis gather beyond the logits budget — means the layout "
      "regressed to full-parameter traffic while the flag claims the "
      "division (training/loop.py _fsdp_step; ISSUE 13).")
def check_fsdp_gather_rides_data_only(a: StepArtifacts) -> List[Finding]:
    if not a.tp_engaged:
        return []
    census = grad_sync_census(a.optimized_text, a.min_elements)
    out = []
    spanning = [(r["op"], r["result_shape"]) for r in census["rows"]
                if r["op"] in ("all-gather", "reduce-scatter", "all-to-all")
                and a.collective_axis(r) in ("all", "other", "unknown")]
    if spanning:
        out.append(Finding(
            "fsdp-gather-rides-data-only",
            f"{len(spanning)} gradient/param-sized collective(s) ride "
            f"groups spanning beyond one axis: {spanning[:5]} — the FSDP "
            "wire must stay on the data axes (model-axis traffic is the "
            "TP psum/logits budget only)", a.name))
    model_movers = [(r["op"], r["result_shape"]) for r in census["rows"]
                    if r["op"] in ("reduce-scatter", "all-to-all")
                    and a.collective_axis(r) == "model"]
    if model_movers:
        out.append(Finding(
            "fsdp-gather-rides-data-only",
            f"{len(model_movers)} gradient-sized reduce-scatter/"
            f"all-to-all(s) ride the MODEL axis: {model_movers[:5]} — "
            "param/grad movement belongs on the data axes", a.name))
    return out


# Entry parameters the compiled module keeps fully replicated:
# `%param = f32[...] parameter(k), sharding={replicated}`. Index the shape
# from the same line so the check needs no cross-line state.
_REPLICATED_ENTRY_PARAM_RE = re.compile(
    r"=\s*(\S+\[[\d,]*\][^ ]*)\s+parameter\(\d+\)[^\n]*"
    r"sharding=\{replicated\}")


@rule("fsdp-no-full-param-residency", "hlo",
      "no parameter/moment-sized buffer is replicated at rest under "
      "explicit FSDP",
      "dividing at-rest parameter+moment memory by the DP degree is the "
      "mode's whole point; a replicated param input in the lowered module "
      "(or a replicated live buffer on the state) means the step is "
      "paying full residency while the flag claims the division — the "
      "zero1-sharded-state argument extended to the parameters "
      "themselves.")
def check_fsdp_no_full_param_residency(a: StepArtifacts) -> List[Finding]:
    if not a.fsdp_engaged:
        return []
    out = []
    for label, buffers in (("parameter", a.replicated_param_buffers),
                           ("optimizer-state", a.replicated_state_buffers)):
        if buffers:
            rows = ", ".join(f"{p} ({n} elements)" for p, n in buffers[:5])
            more = len(buffers) - 5
            out.append(Finding(
                "fsdp-no-full-param-residency",
                f"{len(buffers)} {label} buffer(s) >= {a.min_elements} "
                f"elements are fully replicated under fsdp_explicit: "
                f"{rows}" + (f" (+{more} more)" if more > 0 else ""),
                a.name))
    # the lowered-module read: entry parameters the compiled step takes as
    # REPLICATED operands at gradient/param scale (the live-state read
    # above can miss a layout the compiler re-materializes)
    big = [m.group(1) for m in
           _REPLICATED_ENTRY_PARAM_RE.finditer(a.optimized_text)
           if hlo_result_elements(m.group(1)) >= a.min_elements]
    if big:
        out.append(Finding(
            "fsdp-no-full-param-residency",
            f"compiled fsdp step takes {len(big)} replicated entry "
            f"parameter(s) at gradient/param scale: {big[:5]}", a.name))
    return out


@rule("donated-buffers-elided", "hlo",
      "donate_state really aliases input and output buffers",
      "a step that copies the full parameters instead of updating them "
      "in place doubles peak HBM; donation must survive to the optimized "
      "module's input_output_alias table, not just the jit argnums.")
def check_donation(a: StepArtifacts) -> List[Finding]:
    if not a.config.get("donate_state", True):
        return []
    # An engaged alias table prints entries like
    # `input_output_alias={ {0}: (0, {1}, may-alias), ... }`; a module that
    # kept no donation prints no table at all (an empty `{ }` never has the
    # inner `{index}` tuple key).
    if not re.search(r"input_output_alias=\{\s*\{", a.optimized_text):
        return [Finding(
            "donated-buffers-elided",
            "donate_state=True but the optimized module carries no "
            "input_output_alias entries — the update copies the full "
            "parameter buffers instead of reusing them", a.name)]
    return []


# The Pallas/Mosaic lowering marker on TPU: pallas_call compiles to a
# custom-call whose target names the Mosaic kernel. CPU interpreter mode
# inlines the kernel as ordinary HLO — no custom-call exists there, so the
# rule below only binds on TPU artifacts.
_PALLAS_CUSTOM_CALL_RE = re.compile(
    r'custom_call_target="(?:tpu_custom_call|[Mm]osaic[^"]*)"')

# The codec kernels' pallas_call names (ops/quantize.py) — they flow into
# the custom-call's op_name metadata / Mosaic module name, which is how a
# quantize custom-call is told apart from any OTHER Pallas kernel in the
# same step (flash/ring attention lowers to the same tpu_custom_call
# target; its presence must not vouch for the codec's).
_QUANTIZE_KERNEL_NAMES = ("fused_quantize_int8_rows",
                          "fused_dequant_sum_rows")


@rule("fused-quantize-kernel-present", "hlo",
      "a fused_quantize int8 config really lowers Pallas custom-calls",
      "the fused codec's win is ONE VMEM pass per quantize/dequant stage; "
      "if the Pallas kernels silently fail to lower (a gate regression, an "
      "import fallback) the step quietly runs the XLA-composed chain while "
      "the config claims the kernel path — the same silent-fallback class "
      "compressed-wire guards for the wire dtype (ops/quantize.py).")
def check_fused_quantize_kernel(a: StepArtifacts) -> List[Finding]:
    if a.wire_mode not in ("int8", "int8_multihop", "int8_hier"):
        return []  # no int8 codec in the step — nothing to fuse
    if not (a.grad_sync_engaged or a.zero1_engaged or a.fsdp_engaged):
        return []  # passthrough config: the codec never runs
    if a.wire_mode == "int8_hier" and not a.hier_engaged:
        return []  # slices=1 passthrough — see check_compressed_wire
    fused = a.config.get("fused_quantize")
    if fused is None and a.backend == "tpu":
        # auto (the production default): resolve the tri-state exactly the
        # way the codec does at trace time — on TPU auto selects the
        # kernels unless the env override pins them off. Abstaining on
        # auto would leave the DEFAULT configuration unguarded, the one
        # place the silent-fallback class this rule exists for ships from.
        try:
            from ..ops.quantize import resolve_fused
            fused = resolve_fused(None)
        except Exception:  # pragma: no cover - pallas import unavailable
            fused = False
    if not fused:
        return []
    if a.backend != "tpu":
        # interpreter mode inlines the kernels as plain HLO ops — there is
        # no custom-call to assert; the numerics are pinned by the parity
        # tests instead (tests/test_quantize.py)
        return []
    calls = [ln for ln in a.optimized_text.splitlines()
             if _PALLAS_CUSTOM_CALL_RE.search(ln)]
    if not calls:
        return [Finding(
            "fused-quantize-kernel-present",
            "fused_quantize=True on an int8 wire, but the optimized HLO "
            "contains no Pallas/Mosaic custom-call (tpu_custom_call) — "
            "the fused codec kernels did not lower; the step is running "
            "the XLA-composed chain while claiming the kernel path",
            a.name)]
    if any(name in ln for ln in calls for name in _QUANTIZE_KERNEL_NAMES):
        return []
    # Custom-calls exist but none is named as a codec kernel. Only treat
    # that as a violation when this HLO render demonstrably carries kernel
    # identity (op_name metadata) on those lines — a metadata-stripped
    # dump can't distinguish kernels, so presence has to suffice there.
    if any('op_name="' in ln for ln in calls):
        return [Finding(
            "fused-quantize-kernel-present",
            "fused_quantize=True on an int8 wire: the optimized HLO has "
            "Pallas/Mosaic custom-calls, but none is a quantize codec "
            "kernel (fused_quantize_int8_rows / fused_dequant_sum_rows) — "
            "another Pallas kernel (e.g. flash attention) is masking a "
            "silent fallback of the codec to the XLA-composed chain",
            a.name)]
    return []


# Host-transfer markers in optimized HLO: async transfers flagged
# is_host_transfer, infeed/outfeed ops, and python-callback custom calls
# (jax.debug.print / pure_callback / io_callback lower to these).
_HOST_TRANSFER_RE = re.compile(
    r"is_host_transfer=true"
    r"|\b(?:infeed|outfeed)(?:-start|-done)?[.\w]*\("
    r"|custom_call_target=\"[^\"]*(?:callback|host_|HostCallback)[^\"]*\"")


# one alias-table entry looks like `{3}: (31, {}, may-alias)`; counting the
# `{out}: (param` heads counts aliased buffers
_ALIAS_ENTRY_RE = re.compile(r"\{\d+\}:\s*\(\d+")


@rule("decode-cache-donated", "hlo",
      "the serving decode step aliases EVERY KV-cache buffer in place",
      "the decode hot loop donates its cache (serving/engine.py); if any "
      "per-block k/v buffer falls out of the alias table, every generated "
      "token copies that full (rows, bucket+max_new, heads, head_dim) "
      "buffer — a per-token memory+bandwidth tax the presence-only "
      "donation rule cannot see (one surviving alias entry satisfies it).")
def check_decode_cache_donated(a: StepArtifacts) -> List[Finding]:
    if not a.config.get("serving_decode"):
        return []
    expect = int(a.config.get("decode_cache_leaves", 0))
    # the table nests braces (`{0}: (28, {}, may-alias), ...`), so the
    # region ends at the first `)` directly followed by the closing `}`
    m = re.search(r"input_output_alias=\{(.*?\))\s*\}", a.optimized_text,
                  re.DOTALL)
    entries = len(_ALIAS_ENTRY_RE.findall(m.group(1))) if m else 0
    if entries < expect:
        return [Finding(
            "decode-cache-donated",
            f"decode step aliases {entries} of the {expect} KV-cache "
            "buffers — the un-aliased ones are copied on every generated "
            "token", a.name)]
    return []


@rule("paged-pool-donated", "hlo",
      "the paged decode step aliases EVERY page-pool buffer in place",
      "the slot engine's shared decode step donates the whole paged KV "
      "pool (serving/continuous.py lower_paged_decode): 2 layer-stacked "
      "buffers fp32 (k/v pages), 4 int8 (codes + scales). Any "
      "pool leaf out of the alias table is copied on EVERY generated "
      "token for EVERY slot — and the copy is pool-sized, not slot-sized, "
      "so the tax scales with the whole fleet's cache, exactly what "
      "paging exists to avoid. The presence-only donation rule cannot "
      "see one dropped leaf; this rule counts the table against the "
      "pool's leaf census (``paged_cache_leaves``).")
def check_paged_pool_donated(a: StepArtifacts) -> List[Finding]:
    if not a.config.get("serving_paged"):
        return []
    expect = int(a.config.get("paged_cache_leaves", 0))
    m = re.search(r"input_output_alias=\{(.*?\))\s*\}", a.optimized_text,
                  re.DOTALL)
    entries = len(_ALIAS_ENTRY_RE.findall(m.group(1))) if m else 0
    if entries < expect:
        return [Finding(
            "paged-pool-donated",
            f"paged decode step aliases {entries} of the >= {expect} "
            "pool buffers (k/v pages + int8 scales + slot control) — the "
            "un-aliased ones are copied pool-wide on every generated "
            "token", a.name)]
    return []


@rule("spec-verify-donated", "hlo",
      "the speculative verify step aliases the page pool AND every slot "
      "control buffer in place",
      "the K+1-window verify step replaces the plain decode step in every "
      "speculative round (serving/speculative.py lower_spec_verify) and "
      "donates pool + control exactly like it — but it also RETURNS an "
      "extra per-slot n_emit output, and an output-order slip there would "
      "silently knock donated buffers out of the alias table: every round "
      "would then copy the pool (pool-sized, fleet-wide — the tax paging "
      "exists to avoid) while the presence-only donation rule stays "
      "green. This rule counts the alias table against the FULL donated "
      "census (``spec_cache_leaves`` = pool leaves + control leaves), so "
      "the n_emit side output must cost zero entries.")
def check_spec_verify_donated(a: StepArtifacts) -> List[Finding]:
    if not a.config.get("serving_spec"):
        return []
    expect = int(a.config.get("spec_cache_leaves", 0))
    m = re.search(r"input_output_alias=\{(.*?\))\s*\}", a.optimized_text,
                  re.DOTALL)
    entries = len(_ALIAS_ENTRY_RE.findall(m.group(1))) if m else 0
    if entries < expect:
        return [Finding(
            "spec-verify-donated",
            f"speculative verify step aliases {entries} of the "
            f">= {expect} donated buffers (k/v pool + slot control) — "
            "the un-aliased ones are copied on every verify round",
            a.name)]
    return []


@rule("elastic-reshard-census", "hlo",
      "a resharded N->M state's train step carries exactly the clean-at-M "
      "collective census",
      "the elastic reshard promises a pure re-slice: same avals, same "
      "shardings, same compiled step. A leaf landed replicated (or in any "
      "off-canonical layout) makes XLA insert extra data movement into "
      "EVERY post-resize step while the resize claims zero overhead — "
      "this pins the resharded lowering to the clean-at-M census, op by "
      "op and shape by shape (resilience/elastic.py; ISSUE 11).")
def check_elastic_reshard_census(a: StepArtifacts) -> List[Finding]:
    if not a.config.get("elastic_reshard"):
        return []
    return _elastic_census_findings(a, "elastic-reshard-census",
                                    "clean-at-M")


def _elastic_census_findings(a: StepArtifacts, rule_name: str,
                             clean_noun: str) -> List[Finding]:
    """The shared census pin of both elastic directions: the resharded
    state's lowered step must carry EXACTLY the clean-world census
    (``elastic_expected_census``, embedded by the evaluator)."""
    expected = a.config.get("elastic_expected_census")
    if expected is None:
        return [Finding(
            rule_name,
            f"elastic config evaluated without a {clean_noun} expected "
            "census — the evaluator must lower the clean state and "
            "snapshot its collective_census", a.name)]
    got = collective_census(a.optimized_text)

    def keyed(rows):
        return {(r["op"], r["result_shape"], r.get("replica_groups", "")):
                r["count"] for r in rows}

    got_k, want_k = keyed(got), keyed(expected)
    if got_k != want_k:
        extra = {k: v for k, v in got_k.items()
                 if v != want_k.get(k, 0)}
        missing = {k: v for k, v in want_k.items()
                   if v != got_k.get(k, 0)}
        return [Finding(
            rule_name,
            "resharded step's collective census differs from the "
            f"{clean_noun} census — resharded-only/changed: {extra}; "
            f"clean-only/changed: {missing}. The reshard smuggled data "
            "movement into (or dropped it from) the step", a.name)]
    return []


@rule("elastic-grow-census", "hlo",
      "a grown M->N state's train step carries exactly the clean-at-N "
      "collective census",
      "the GROW leg of the elastic contract (ISSUE 12): a state resharded "
      "UP when preempted capacity returns (zero-extended flat shards, "
      "zero-extended EF rows) must lower to EXACTLY the census a "
      "clean-at-N state lowers to — a grow that lands a leaf replicated "
      "or off-layout would smuggle data movement into every post-grow "
      "step while the resize claims a pure re-slice "
      "(resilience/capacity.py + supervisor._maybe_grow).")
def check_elastic_grow_census(a: StepArtifacts) -> List[Finding]:
    if not a.config.get("elastic_grow"):
        return []
    return _elastic_census_findings(a, "elastic-grow-census",
                                    "clean-at-N")


@rule("no-host-transfer", "hlo",
      "no host transfers inside the compiled step",
      "a host callback or infeed/outfeed in the step serializes the device "
      "on the host every iteration — the .item()-per-step bottleneck the "
      "loop design removed (training/loop.py), reintroduced invisibly.")
def check_no_host_transfer(a: StepArtifacts) -> List[Finding]:
    hits = sorted({m.group(0).strip() for m in
                   _HOST_TRANSFER_RE.finditer(a.optimized_text)})
    if hits:
        return [Finding(
            "no-host-transfer",
            f"compiled step contains host transfers: {hits}", a.name)]
    return []


@rule("dp-sync-present", "hlo",
      "the plain data-parallel step really carries gradient-sized sync",
      "every other census bound is vacuous if the floor is above the "
      "model's gradient traffic — the dp arm proves the instrument sees "
      "the all-reduce DDP's reducer would issue.")
def check_dp_sync_present(a: StepArtifacts) -> List[Finding]:
    if (a.zero1_engaged or a.grad_sync_engaged or a.fsdp_engaged
            or a.n_shards <= 1
            or int(a.config.get("grad_accum", 1)) > 1
            # serving steps carry no gradients at all — this rule's floor
            # guard is about the TRAIN step's reducer, not a scoping knob
            # to relax: an inference forward with an all-reduce would be
            # the bug, not the absence of one
            or a.config.get("serving_decode")
            or a.config.get("serving_paged")
            or a.config.get("serving_spec")):
        # grad-accum keeps sync inside a scan; count it only on the plain arm
        return []
    census = weight_update_census(a.optimized_text, a.min_elements)
    if census["all-reduce"] == 0:
        return [Finding(
            "dp-sync-present",
            f"data-parallel step shows no gradient-sized all-reduce — the "
            f"census floor (min_elements={a.min_elements}) is above the "
            "model's gradient transfers, or gradient sync vanished",
            a.name)]
    return []


def check_artifacts(a: StepArtifacts,
                    rules: Optional[List[str]] = None) -> List[Finding]:
    """Run every (selected) HLO rule over one config's artifacts."""
    from .contracts import iter_rules

    findings: List[Finding] = []
    for r in iter_rules(kind="hlo", names=rules):
        findings.extend(r.check(a))
    return findings


# ---------------------------------------------------------------------------
# Contract evaluation (lower the canonical matrix on the local mesh)
# ---------------------------------------------------------------------------


def _tiny_lm_setup(mesh, config: Dict[str, Any]):
    """(trainer, state, batch) for the tiny contract model — small enough
    that the full matrix lowers on the CPU test mesh in well under a
    minute, big enough that every leaf clears the census floor."""
    import jax
    import numpy as np

    from ..models.gpt2 import GPT2LMHead
    from ..parallel import shard_batch
    from ..training import TrainConfig, Trainer
    from ..training.optim import sgd
    from ..training.tasks import LanguageModelingTask

    seq, vocab = 16, 64
    trainer = Trainer(LanguageModelingTask(), mesh,
                      TrainConfig(seed=0, **config))
    state = trainer.init_state(
        GPT2LMHead(vocab_size=vocab, hidden_dim=32, depth=2, num_heads=2,
                   max_position=seq),
        np.zeros((1, seq), np.int32), sgd(0.1), jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    n = 2 * mesh.size
    batch = shard_batch(
        {"input_ids": rng.randint(0, vocab, (n, seq)).astype(np.int32),
         "weight": np.ones(n, np.float32)}, mesh)
    return trainer, state, batch


def replicated_large_buffers(tree: Any, min_elements: int
                             ) -> Tuple[Tuple[str, int], ...]:
    """(path, size) of committed array leaves >= min_elements whose sharding
    is fully replicated — the zero1-sharded-state rule's input."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        sharding = getattr(leaf, "sharding", None)
        size = getattr(leaf, "size", 0)
        if sharding is None or size < min_elements:
            continue
        if sharding.is_fully_replicated:
            out.append((jax.tree_util.keystr(path), int(size)))
    return tuple(out)


def serving_artifacts(engine, bucket: int,
                      name: str = "serving_decode") -> StepArtifacts:
    """StepArtifacts of one serving engine's compiled KV-cache decode step
    — the serving sibling of the train-step snapshot. ``decode_cache_leaves``
    carries the cache's leaf count (2 per block: k and v) so
    `decode-cache-donated` can demand the WHOLE cache aliased, not just
    some buffer."""
    import jax

    from ..parallel.mesh import batch_shard_count

    lowered = engine.lower_decode(bucket)
    optimized = lowered.compile().as_text()
    try:
        preopt = preopt_hlo_text(lowered)
    except Exception:  # pragma: no cover - backend without HLO dialect
        preopt = None
    return StepArtifacts(
        name=name,
        optimized_text=optimized,
        preopt_text=preopt,
        config={"serving_decode": True, "donate_state": True,
                "decode_cache_leaves": 2 * engine.model.depth},
        n_shards=batch_shard_count(engine.mesh),
        backend=jax.default_backend(),
    )


def paged_serving_artifacts(engine, name: str = "serving_paged"
                            ) -> StepArtifacts:
    """StepArtifacts of a SlotEngine's shared paged decode step — the
    continuous-batching sibling of `serving_artifacts`. ``paged_cache_leaves``
    is the page pool's donated-leaf census — the pool is stacked across
    layers (models/layers.py PagedKV), so it is 2 buffers fp32 (k/v
    pages), 4 int8 (k/v codes + k/v scales), regardless of depth — and
    `paged-pool-donated` demands the WHOLE pool aliased, scales included:
    a dropped scale buffer silently doubles int8 pool traffic."""
    import jax

    from ..parallel.mesh import batch_shard_count

    lowered = engine.lower_paged_decode()
    optimized = lowered.compile().as_text()
    try:
        preopt = preopt_hlo_text(lowered)
    except Exception:  # pragma: no cover - backend without HLO dialect
        preopt = None
    pool_leaves = 4 if engine.config.kv_dtype == "int8" else 2
    return StepArtifacts(
        name=name,
        optimized_text=optimized,
        preopt_text=preopt,
        config={"serving_paged": True, "donate_state": True,
                "paged_cache_leaves": pool_leaves},
        n_shards=batch_shard_count(engine.mesh),
        backend=jax.default_backend(),
    )


def spec_serving_artifacts(engine, name: str = "serving_spec"
                           ) -> StepArtifacts:
    """StepArtifacts of a SpeculativeEngine's K+1-window verify step —
    the speculative sibling of `paged_serving_artifacts`.
    ``spec_cache_leaves`` is the FULL donated census: the fp32 pool's 2
    layer-stacked buffers plus every slot-control leaf — the verify step
    returns an extra (rows,) n_emit output, and `spec-verify-donated`
    demands that side output cost the alias table nothing."""
    import jax

    from ..parallel.mesh import batch_shard_count

    lowered = engine.lower_spec_verify()
    optimized = lowered.compile().as_text()
    try:
        preopt = preopt_hlo_text(lowered)
    except Exception:  # pragma: no cover - backend without HLO dialect
        preopt = None
    leaves = 2 + len(engine._control)
    return StepArtifacts(
        name=name,
        optimized_text=optimized,
        preopt_text=preopt,
        config={"serving_spec": True, "donate_state": True,
                "spec_cache_leaves": leaves},
        n_shards=batch_shard_count(engine.mesh),
        backend=jax.default_backend(),
    )


def evaluate_serving_contract(contract: Contract,
                              mesh=None) -> StepArtifacts:
    """Lower the tiny serving engine's decode step and snapshot artifacts —
    the ``kind="serving"`` arm of `evaluate_contract`. The tiny engine is
    the contract model's shape class (2-block GPT-2) behind the REAL
    engine code path (serving/engine.py lower_decode), so what the matrix
    checks is what serving ships."""
    import jax
    import numpy as np

    from ..models.gpt2 import GPT2LMHead
    from ..parallel.mesh import MeshSpec, batch_shard_count, build_mesh
    from ..serving.engine import InferenceEngine, ServeConfig

    if mesh is None:
        mesh = build_mesh(MeshSpec(), devices=jax.devices())
    n_shards = batch_shard_count(mesh)
    if n_shards < contract.min_shards:
        raise ValueError(
            f"contract {contract.name!r} needs >= {contract.min_shards} "
            f"batch shards (got {n_shards})")
    model = GPT2LMHead(vocab_size=64, hidden_dim=32, depth=2, num_heads=2,
                       max_position=32)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32),
                        train=False)["params"]
    engine = InferenceEngine(
        model, mesh, ServeConfig(buckets=(8,), rows=max(n_shards, 2),
                                 max_new_tokens=4), params)
    artifacts = serving_artifacts(engine, bucket=8, name=contract.name)
    return dataclasses.replace(
        artifacts, config={**artifacts.config, **contract.config,
                           "decode_cache_leaves":
                           artifacts.config["decode_cache_leaves"]},
        min_elements=contract.min_elements)


def evaluate_paged_serving_contract(contract: Contract,
                                    mesh=None) -> StepArtifacts:
    """The ``kind="serving_paged"`` evaluator: build the tiny contract
    model behind the REAL continuous-batching path (serving/continuous.py
    SlotEngine), lower the shared paged decode step, and snapshot its
    artifacts. The matrix entry pins the int8 arm
    (``paged_kv_dtype="int8"``) because that is the path with the most
    leaves to drop from the alias table — codes AND scales per block —
    and the fp32 arm's table is a strict subset of it."""
    import jax
    import numpy as np

    from ..models.gpt2 import GPT2LMHead
    from ..parallel.mesh import MeshSpec, batch_shard_count, build_mesh
    from ..serving.continuous import SlotEngine
    from ..serving.paged import PagedServeConfig

    if mesh is None:
        mesh = build_mesh(MeshSpec(), devices=jax.devices())
    n_shards = batch_shard_count(mesh)
    if n_shards < contract.min_shards:
        raise ValueError(
            f"contract {contract.name!r} needs >= {contract.min_shards} "
            f"batch shards (got {n_shards})")
    model = GPT2LMHead(vocab_size=64, hidden_dim=32, depth=2, num_heads=2,
                       max_position=32)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32),
                        train=False)["params"]
    cfg = PagedServeConfig(
        buckets=(8,), rows=4, max_new_tokens=4, page_size=4,
        kv_dtype=contract.config.get("paged_kv_dtype", "fp32"))
    engine = SlotEngine(model, mesh, cfg, params)
    artifacts = paged_serving_artifacts(engine, name=contract.name)
    return dataclasses.replace(
        artifacts, config={**artifacts.config, **contract.config,
                           "paged_cache_leaves":
                           artifacts.config["paged_cache_leaves"]},
        min_elements=contract.min_elements)


def evaluate_spec_serving_contract(contract: Contract,
                                   mesh=None) -> StepArtifacts:
    """The ``kind="serving_spec"`` evaluator: tiny target + even tinier
    draft behind the REAL speculative path (serving/speculative.py
    SpeculativeEngine), lower the K+1-window verify step, snapshot its
    artifacts. fp32 pool by construction — the engine refuses int8 (the
    exactness gate), so unlike `serving_paged` there is no int8 arm to
    pin; the census here is pool + full control."""
    import jax
    import numpy as np

    from ..models.gpt2 import GPT2LMHead
    from ..parallel.mesh import MeshSpec, batch_shard_count, build_mesh
    from ..serving.paged import PagedServeConfig
    from ..serving.speculative import SpeculativeEngine

    if mesh is None:
        mesh = build_mesh(MeshSpec(), devices=jax.devices())
    n_shards = batch_shard_count(mesh)
    if n_shards < contract.min_shards:
        raise ValueError(
            f"contract {contract.name!r} needs >= {contract.min_shards} "
            f"batch shards (got {n_shards})")
    # smallest config that still exercises the full alias table: the
    # donated census (pool + control leaves) is independent of depth /
    # width / rows / K, and the verify-window compile is the eval's
    # wall cost — this runs on every full-matrix pass in tier-1
    model = GPT2LMHead(vocab_size=64, hidden_dim=16, depth=1, num_heads=2,
                       max_position=32)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32),
                        train=False)["params"]
    draft = GPT2LMHead(vocab_size=64, hidden_dim=16, depth=1, num_heads=2,
                       max_position=32)
    draft_params = draft.init(jax.random.PRNGKey(1),
                              np.zeros((1, 8), np.int32),
                              train=False)["params"]
    cfg = PagedServeConfig(buckets=(8,), rows=2, max_new_tokens=2,
                           page_size=4)
    engine = SpeculativeEngine(model, mesh, cfg, params, draft,
                               draft_params, spec_k=1)
    artifacts = spec_serving_artifacts(engine, name=contract.name)
    return dataclasses.replace(
        artifacts, config={**artifacts.config, **contract.config,
                           "spec_cache_leaves":
                           artifacts.config["spec_cache_leaves"]},
        min_elements=contract.min_elements)


def evaluate_elastic_contract(contract: Contract,
                              mesh=None) -> StepArtifacts:
    """The ``kind="elastic"`` evaluator (ISSUEs 11 + 12), both
    directions. SHRINK (``elastic_reshard``): build the tiny contract
    state at the FULL world N, reshard it down to M = N/2 through the
    real elastic path (resilience.elastic.reshard_train_state — the same
    code a Supervisor resize runs), lower the M-world trainer's step on
    the resharded state, and snapshot its artifacts with the clean-at-M
    census embedded as the expectation (``elastic_expected_census``).
    GROW (``elastic_grow``): the mirror — build at M = N/2, reshard UP to
    N (zero-extended shards/EF rows, the capacity-return resize), lower
    the N-world trainer's step, expect the clean-at-N census. jit
    lowering keys on avals + shardings only, so census equality holds iff
    the reshard landed every leaf in the canonical target-world layout."""
    import jax

    from ..parallel.mesh import MeshSpec, batch_shard_count, build_mesh
    from ..resilience.elastic import reshard_train_state

    if mesh is None:
        mesh = build_mesh(MeshSpec(), devices=jax.devices())
    n = batch_shard_count(mesh)
    if n < contract.min_shards:
        raise ValueError(
            f"contract {contract.name!r} needs >= {contract.min_shards} "
            f"batch shards (got {n}) — the halved world must still "
            "engage the sharded update")
    m = n // 2
    sub_mesh = build_mesh(MeshSpec(),
                          devices=list(mesh.devices.flat)[:m])
    train_cfg = {k: v for k, v in contract.config.items()
                 if k not in ("elastic_reshard", "elastic_grow")}
    grow = bool(contract.config.get("elastic_grow"))
    trainer_n, state_n, batch_n = _tiny_lm_setup(mesh, train_cfg)
    trainer_m, state_m, batch_m = _tiny_lm_setup(sub_mesh, train_cfg)
    if grow:
        resharded = reshard_train_state(state_m, m, n, trainer_n, state_n)
        clean_trainer, clean_state, batch = trainer_n, state_n, batch_n
        out_shards = n
    else:
        resharded = reshard_train_state(state_n, n, m, trainer_m, state_m)
        clean_trainer, clean_state, batch = trainer_m, state_m, batch_m
        out_shards = m
    key = jax.random.PRNGKey(1)
    clean_text = clean_trainer._train_step.lower(
        clean_state, batch, key).compile().as_text()
    lowered = clean_trainer._train_step.lower(resharded, batch, key)
    optimized = lowered.compile().as_text()
    try:
        preopt = preopt_hlo_text(lowered)
    except Exception:  # pragma: no cover - backend without HLO dialect
        preopt = None
    return StepArtifacts(
        name=contract.name,
        optimized_text=optimized,
        preopt_text=preopt,
        config={**contract.config,
                "elastic_expected_census": collective_census(clean_text)},
        n_shards=out_shards,
        min_elements=contract.min_elements,
        backend=jax.default_backend(),
    )


def evaluate_contract(contract: Contract, mesh=None) -> StepArtifacts:
    """Lower + compile one contract's config on `mesh` (default: a pure-DP
    mesh over all local devices) and snapshot the artifacts the rules read.

    Raises ValueError when the mesh has fewer batch shards than the
    contract needs (zero1/grad_sync are identity passthroughs there —
    evaluating the contract would vacuously pass; the caller decides
    whether that is a skip or an error). ``kind="serving"`` contracts
    route to `evaluate_serving_contract` (the inference engine's decode
    step instead of a Trainer step); ``kind="serving_paged"`` to
    `evaluate_paged_serving_contract` (the SlotEngine's shared paged
    decode step); ``kind="serving_spec"`` to
    `evaluate_spec_serving_contract` (the speculative K+1-window verify
    step); ``kind="elastic"`` to `evaluate_elastic_contract`
    (the resharded-vs-clean census pin).
    """
    import jax

    from ..parallel.grad_sync import build_bucket_plan
    from ..parallel.mesh import MeshSpec, batch_shard_count, build_mesh

    if contract.kind == "serving":
        return evaluate_serving_contract(contract, mesh=mesh)
    if contract.kind == "serving_paged":
        return evaluate_paged_serving_contract(contract, mesh=mesh)
    if contract.kind == "serving_spec":
        return evaluate_spec_serving_contract(contract, mesh=mesh)
    if contract.kind == "elastic":
        return evaluate_elastic_contract(contract, mesh=mesh)
    if mesh is None:
        spec = (MeshSpec.parse(contract.mesh_spec) if contract.mesh_spec
                else MeshSpec())
        mesh = build_mesh(spec, devices=jax.devices())
    n_shards = batch_shard_count(mesh)
    if n_shards < contract.min_shards:
        raise ValueError(
            f"contract {contract.name!r} needs >= {contract.min_shards} "
            f"batch shards (got {n_shards}) — on fewer, the mode is an "
            "identity passthrough and the contract is vacuous")
    trainer, state, batch = _tiny_lm_setup(mesh, contract.config)
    lowered = trainer._train_step.lower(state, batch, jax.random.PRNGKey(1))
    optimized = lowered.compile().as_text()
    try:
        preopt = preopt_hlo_text(lowered)
    except Exception:  # pragma: no cover - backend without HLO dialect
        preopt = None
    plan = build_bucket_plan(state.params,
                             float(contract.config.get("bucket_cap_mb", 0.0)))
    is_fsdp = bool(contract.config.get("fsdp_explicit"))
    replicated = (replicated_large_buffers(state.opt_state,
                                           contract.min_elements)
                  if (contract.config.get("zero1") or is_fsdp) else ())
    replicated_params = (replicated_large_buffers(state.params,
                                                  contract.min_elements)
                        if is_fsdp else ())
    group_sizes = (trainer._fsdp_plan.padded_group_sizes
                   if is_fsdp and trainer._fsdp_plan is not None else ())
    tp_psums, tp_gathers = trainer.tp_expected_model_collectives()
    return StepArtifacts(
        name=contract.name,
        optimized_text=optimized,
        preopt_text=preopt,
        config=dict(contract.config),
        n_shards=n_shards,
        total_grad_bytes=plan.total_bytes,
        min_elements=contract.min_elements,
        replicated_state_buffers=replicated,
        replicated_param_buffers=replicated_params,
        layer_group_padded_sizes=group_sizes,
        backend=jax.default_backend(),
        model_shards=trainer._tp_n,
        tp_expected_psums=tp_psums,
        tp_expected_model_gathers=tp_gathers,
        # _tiny_lm_setup batches 2 rows per device over n_shards shards,
        # seq 16 — the same shapes the lowering above traced
        tp_ce_stat_elements=trainer.tp_expected_ce_stat_elements(
            2 * mesh.size // max(n_shards, 1), 16),
        slice_shards=(trainer._hier.n_slices if trainer._hier is not None
                      else 1),
    )


def run_contract_matrix(contracts=None, mesh=None, rules=None):
    """Evaluate the canonical matrix; returns (findings, statuses) where
    statuses maps contract name -> "pass" | "fail" | "skipped (...)".
    Skips (not enough shards for a mode to engage) are reported, never
    silently dropped — a matrix that quietly checked nothing would be the
    checker's own contract violation."""
    from .contracts import CONTRACT_MATRIX

    findings: List[Finding] = []
    statuses: Dict[str, str] = {}
    for contract in (contracts if contracts is not None else CONTRACT_MATRIX):
        try:
            artifacts = evaluate_contract(contract, mesh=mesh)
        except ValueError as e:
            statuses[contract.name] = f"skipped ({e})"
            continue
        found = check_artifacts(artifacts, rules=rules)
        findings.extend(found)
        statuses[contract.name] = "fail" if found else "pass"
    return findings, statuses


# ---------------------------------------------------------------------------
# Raise-on-violation wrappers (the historical acceptance-gate API;
# experiments/trace_analysis.py re-exports these for existing callers)
# ---------------------------------------------------------------------------


def verify_zero1_collectives(replicated_text: str, zero1_text: str,
                             min_elements: int = 8192) -> dict:
    """The acceptance check for the zero1 mode (ISSUE 1): in the compiled
    zero1 step, gradient-sized all-reduces are REPLACED by reduce-scatter +
    all-gather. Returns the two weight-update censuses plus a verdict dict;
    raises AssertionError naming the offending ops when the replacement did
    not happen (a silent fallback to all-reduce would erase the win while
    the flag still claims it)."""
    rep = weight_update_census(replicated_text, min_elements)
    z1 = weight_update_census(zero1_text, min_elements)
    if rep["all-reduce"] == 0:
        raise AssertionError(
            "replicated step shows no gradient-sized all-reduce — the "
            f"census floor ({min_elements} elements) is above the model's "
            "gradient transfers; lower min_elements")
    problems = []
    if z1["all-reduce"]:
        problems.append(
            f"zero1 step still contains {z1['all-reduce']} gradient-sized "
            f"all-reduce(s): {[r for r in z1['rows'] if r['op'] == 'all-reduce']}")
    if not z1["reduce-scatter"]:
        problems.append("zero1 step contains no reduce-scatter")
    if not z1["all-gather"]:
        problems.append("zero1 step contains no all-gather")
    if problems:
        raise AssertionError("; ".join(problems))
    return {"replicated": rep, "zero1": z1}


def verify_grad_sync_collectives(
    optimized_text: str,
    *,
    total_grad_bytes: int,
    bucket_cap_mb: float,
    wire_dtype: str = "fp32",
    wire_text: Optional[str] = None,
    min_elements: int = 8192,
    slack: int = 2,
) -> dict:
    """The ISSUE-2 acceptance check for the bucketed reducer: the compiled
    step performs at most ``ceil(total_grad_bytes / bucket_cap) x
    collectives_per_bucket(wire_dtype) + slack`` gradient-sized collectives,
    and compressed modes put bf16/int8 on the wire. The per-bucket factor is
    1 for the single-hop wires and 2 for the DynamiQ-style multi-hop int8
    mode (``wire_dtype="int8_multihop"``: s8 reduce-scatter + requantized s8
    gather legitimately spend two collectives per bucket) — the bound is
    parameterized by wire mode, not hard-coded, so implementing the
    multi-hop form never requires relaxing the checker. ``wire_text``
    defaults to ``optimized_text``; pass the pre-optimization HLO on
    backends that promote small floats (CPU). Raises AssertionError naming
    the violation; returns the censuses.
    """
    if wire_dtype not in WIRE_MODES:
        raise ValueError(f"unknown wire mode {wire_dtype!r} "
                         f"(choose from {WIRE_MODES})")
    census = grad_sync_census(optimized_text, min_elements)
    n_buckets = expected_buckets(total_grad_bytes, bucket_cap_mb)
    per_bucket = collectives_per_bucket(wire_dtype)
    bound = n_buckets * per_bucket + slack
    if census["n_collectives"] > bound:
        raise AssertionError(
            f"bucketed step carries {census['n_collectives']} gradient-"
            f"sized collectives, more than ceil({total_grad_bytes}B / "
            f"{bucket_cap_mb}MB) x {per_bucket} ({wire_dtype}) + {slack} = "
            f"{bound}: {census['by_op']} — bucketing is not engaged (or "
            f"the census floor min_elements={min_elements} is below scalar "
            "traffic)")
    if census["n_collectives"] == 0:
        raise AssertionError(
            "no gradient-sized collectives found — the census floor "
            f"(min_elements={min_elements}) is above the model's gradient "
            "transfers; lower it")
    if wire_dtype == "int8_multihop":
        problems = _multihop_hop_problems(census)
        if problems:
            raise AssertionError(
                "; ".join(problems) + f" — census: {census['by_op']} (a "
                "single-hop codec mislabeled as multihop sails under the "
                "2/bucket budget; the hop signature is the check)")
    wire_census = (grad_sync_census(wire_text, min_elements)
                   if wire_text is not None else census)
    expect = WIRE_HLO_DTYPE[wire_dtype]
    if not wire_census["wire_dtypes"].get(expect):
        raise AssertionError(
            f"wire_dtype={wire_dtype!r} promises {expect} collective "
            f"operands on the wire, but the HLO shows "
            f"{wire_census['wire_dtypes']}")
    return {"census": census, "wire": wire_census["wire_dtypes"],
            "bound": bound}
