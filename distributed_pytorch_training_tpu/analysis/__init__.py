"""Static analysis for the parallelism contracts this repo promises.

The reference DDP script gets its correctness guarantees implicitly from
torch's reducer; the TPU port makes every parallelism decision explicit
(zero1, bucketed grad-sync, wire compression) — so the guarantees must be
*checked* explicitly too. Two engines, one CLI:

* **HLO contract checker** (`hlo_rules`, `contracts`): declarative
  `Contract` objects lowered on the canonical config matrix (dp, zero1,
  grad_sync x {fp32, bf16, int8, int8_multihop}, grad-accum on/off) and
  evaluated by
  rules over the optimized / pre-optimization HLO text — collective
  counts, wire dtypes, donation aliasing, host transfers, sharded
  optimizer state.
* **AST lint engine** (`ast_rules`): an `ast`-visitor framework for the
  source-level contracts — shard_map only via the compat shim, no impure
  host calls inside traced bodies, no device syncs in step paths, axis
  names only from the `parallel/mesh.py` registry.

Run both: ``python -m distributed_pytorch_training_tpu.analysis check``
(or the ``analysis`` console script). Every rule ships with a mutation
test (a synthetic violation it must flag) so the analyzer itself is
verified, not just green — see tests/test_analysis_*.py.
"""

from .contracts import (  # noqa: F401
    CONTRACT_MATRIX, Contract, Finding, Rule, WIRE_MODES,
    collectives_per_bucket, iter_rules, rule,
)
