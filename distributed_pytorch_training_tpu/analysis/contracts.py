"""Framework for the parallelism contract checker: findings, the rule
registry, and the declarative config matrix the HLO engine evaluates.

A `Rule` is one named, documented check; `Finding` is one violation it
reports. Rules never raise on violations — they return findings, so one
`analysis check` run reports everything at once (the verify_* wrappers in
`hlo_rules` keep the old raise-on-violation behavior for callers that want
an acceptance gate, e.g. experiments/scaling.py).

A `Contract` is one canonical training config (TrainConfig kwargs plus the
floor below which collectives are metric noise). The matrix below is the
set of configs whose compiled HLO must keep its promises on every PR:
the plain data-parallel step, the zero1 sharded update, the explicit
bucketed reducer at each wire dtype (with and without grad accumulation),
and explicit full-parameter FSDP (fp32 and the fully compressed
int8_multihop wire).
`hlo_rules.evaluate_contract` lowers each on the CPU test mesh and runs
every HLO rule over the result.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# Wire modes the contracts understand — all five are implemented
# (parallel/grad_sync.py WIRE_DTYPES). "int8_multihop" is the DynamiQ-style
# s8 reduce-scatter + requantize + s8 all-gather form: it legitimately
# spends TWO collectives per bucket, so the census bound is parameterized
# by mode instead of hard-coding 1 — the mode landed with no checker
# relaxation, exactly as this comment promised when it was a ROADMAP item.
# "int8_hier" is the two-tier topology-aware form (ISSUE 16): exact fp32
# reduce-scatter + all-gather inside the slice, the s8 multihop pair across
# slices — 4 gradient-sized collectives per bucket, classified per tier by
# the hier-tier-signature rule.
WIRE_MODES = ("fp32", "bf16", "int8", "int8_multihop", "int8_hier")

# HLO dtype each wire mode promises on gradient-sized collective operands.
# For "int8_hier" this is the SLOW-TIER promise: cross-slice gradient
# collectives ride s8; the intra-slice pair is exempt (exact fp32 by
# design — no-fp32-wire filters by tier).
WIRE_HLO_DTYPE = {"fp32": "f32", "bf16": "bf16", "int8": "s8",
                  "int8_multihop": "s8", "int8_hier": "s8"}


def collectives_per_bucket(wire_mode: str) -> int:
    """Gradient collectives one bucket legitimately costs under `wire_mode`.

    Single-hop modes sync a bucket with ONE collective (psum, or the s8
    gather). The multi-hop int8 form reduces in two hops (s8 all-to-all
    reduce-scatter, requantized s8 all-gather), so its census bound is 2
    per bucket — the contract knows the mode, the bound is never hand-
    relaxed. The hierarchical form spends 4: the exact intra-slice
    reduce-scatter and all-gather bracket the cross-slice s8 pair
    (grad_sync._int8_hier_sum).
    """
    if wire_mode not in WIRE_MODES:
        raise ValueError(f"unknown wire mode {wire_mode!r} "
                         f"(choose from {WIRE_MODES})")
    return {"int8_multihop": 2, "int8_hier": 4}.get(wire_mode, 1)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation: where, what, and which rule said so."""

    rule: str
    message: str
    location: str = ""  # "path:line" (AST) or a contract/config name (HLO)

    def __str__(self) -> str:
        loc = f"{self.location}: " if self.location else ""
        return f"{loc}[{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "location": self.location,
                "message": self.message}


@dataclasses.dataclass(frozen=True)
class Rule:
    """One named check. `kind` is "hlo" (runs on StepArtifacts) or "ast"
    (runs on parsed source). `rationale` is the why — it renders in
    ``analysis check --list`` and the README catalog stays honest by
    quoting it."""

    name: str
    kind: str
    description: str
    rationale: str
    check: Callable[..., List[Finding]]


_REGISTRY: Dict[str, Rule] = {}


def rule(name: str, kind: str, description: str, rationale: str):
    """Decorator registering a check function as a named Rule."""

    def deco(fn: Callable[..., List[Finding]]):
        if name in _REGISTRY:
            raise ValueError(f"duplicate rule name {name!r}")
        _REGISTRY[name] = Rule(name=name, kind=kind, description=description,
                               rationale=rationale, check=fn)
        return fn

    return deco


def iter_rules(kind: Optional[str] = None,
               names: Optional[Iterable[str]] = None) -> List[Rule]:
    """Registered rules, optionally filtered by kind and/or names.

    Unknown names raise — a typo'd ``--rules`` selection silently checking
    nothing would be the checker failing its own contract. Importing the
    engines here (not at module import) keeps this module dependency-free
    for the AST-only path.
    """
    from . import ast_rules, concurrency_rules, hlo_rules  # noqa: F401  (registration side effect)

    if names is not None:
        wanted = list(names)
        unknown = [n for n in wanted if n not in _REGISTRY]
        if unknown:
            raise KeyError(
                f"unknown rule(s) {unknown}; known: {sorted(_REGISTRY)}")
        rules = [_REGISTRY[n] for n in wanted]
    else:
        rules = [_REGISTRY[n] for n in sorted(_REGISTRY)]
    if kind is not None:
        rules = [r for r in rules if r.kind == kind]
    return rules


@dataclasses.dataclass(frozen=True)
class Contract:
    """One canonical config whose lowered HLO must keep its promises.

    ``config`` holds TrainConfig kwargs (zero1 / bucket_cap_mb / wire_dtype
    / grad_accum / donate_state / overlap_grad_sync). ``min_elements`` is
    the census floor separating gradient-sized collectives from scalar
    metric traffic — sized to the tiny contract model, NOT the 8192 default
    of production censuses. ``min_shards`` gates configs that only engage
    on a multi-shard mesh (zero1 / grad_sync passthrough convention).
    ``kind`` selects the evaluator: "train" lowers a Trainer step
    (`hlo_rules._tiny_lm_setup`); "serving" lowers the inference engine's
    KV-cache decode step (`hlo_rules.evaluate_serving_contract`) — the
    decode-step contract of serving/ (ISSUE 10), run by the same tier-1
    ``analysis check`` gate; "serving_paged" lowers the SlotEngine's
    shared paged decode step (`hlo_rules.evaluate_paged_serving_contract`,
    ISSUE 17) — the continuous-batching page-pool-donation contract;
    "elastic" lowers the SAME train step twice at
    the target world — once from a clean state, once from a state
    resharded by resilience.elastic (down N->M for ``elastic_reshard``,
    UP M->N for ``elastic_grow``) — and pins the censuses equal
    (`hlo_rules.evaluate_elastic_contract`, ISSUEs 11 + 12).
    """

    name: str
    description: str
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    min_elements: int = 128
    min_shards: int = 1
    kind: str = "train"
    # Mesh the contract lowers on: "" = the default pure-DP mesh over all
    # local devices; the explicit TP x FSDP contracts (ISSUE 13) name a
    # 2-D spec ("data=4,model=2") parsed by parallel.mesh.MeshSpec.
    mesh_spec: str = ""


# The canonical matrix (ISSUE 3): dp, zero1, grad_sync x wire dtypes,
# grad-accum on/off. The bucket cap is tiny (in MB) so the tiny contract
# model still splits into >1 bucket and the ceil bound actually binds.
_CAP = 0.02  # ~5.2k fp32 elements per bucket

CONTRACT_MATRIX: Tuple[Contract, ...] = (
    Contract("dp", "implicit data-parallel step (XLA-inserted grad sync)"),
    Contract("dp_accum", "implicit path under gradient accumulation",
             config=dict(grad_accum=2)),
    Contract("zero1", "ZeRO-1 sharded weight update (scatter/update/gather)",
             config=dict(zero1=True), min_shards=2),
    Contract("zero1_bf16", "zero1 with the reduce-scatter half at bf16",
             config=dict(zero1=True, wire_dtype="bf16"), min_shards=2),
    Contract("zero1_int8_mh",
             "zero1 fully compressed: s8 all-to-all scatter (error "
             "feedback) + s8 delta-quantized param all-gather "
             "(quantized_delta_all_gather) — both halves off fp32",
             config=dict(zero1=True, wire_dtype="int8_multihop"),
             min_shards=2),
    Contract("gsync_fp32", "bucketed reducer, exact fp32 wire",
             config=dict(bucket_cap_mb=_CAP), min_shards=2),
    Contract("gsync_bf16", "bucketed reducer, bf16 wire",
             config=dict(bucket_cap_mb=_CAP, wire_dtype="bf16"),
             min_shards=2),
    Contract("gsync_int8", "bucketed reducer, int8 wire + error feedback",
             config=dict(bucket_cap_mb=_CAP, wire_dtype="int8"),
             min_shards=2),
    Contract("gsync_bf16_accum",
             "bucketed bf16 reducer with in-scan overlapped accumulation",
             config=dict(bucket_cap_mb=_CAP, wire_dtype="bf16",
                         grad_accum=2), min_shards=2),
    Contract("gsync_int8_mh",
             "bucketed reducer, DynamiQ multi-hop int8 wire (s8 "
             "reduce-scatter + requantized s8 all-gather, 2/bucket)",
             config=dict(bucket_cap_mb=_CAP, wire_dtype="int8_multihop"),
             min_shards=2),
    Contract("gsync_int8_mh_accum",
             "multi-hop int8 reducer with in-scan overlapped accumulation",
             config=dict(bucket_cap_mb=_CAP, wire_dtype="int8_multihop",
                         grad_accum=2), min_shards=2),
    # Two-tier topology-aware wire (ISSUE 16) on the (slice=2, data=4)
    # factored CPU mesh: per bucket, an exact fp32 intra-slice
    # reduce-scatter, the s8 multihop pair across slices (the ONLY
    # compressed tier — EF lives there), and an exact fp32 intra-slice
    # all-gather. The hier-tier-signature rule classifies every gradient
    # collective's replica groups by tier (the PR-12 axis classifier,
    # generalized) and pins the per-tier signature; no-fp32-wire exempts
    # only the intra-slice (ici) tier.
    Contract("gsync_int8_hier",
             "bucketed reducer, two-tier hier wire: exact fp32 ICI "
             "reduce-scatter/all-gather inside the slice, s8 multihop "
             "pair across slices (4/bucket, per-tier classified)",
             config=dict(bucket_cap_mb=_CAP, wire_dtype="int8_hier"),
             min_shards=2, mesh_spec="slice=2,data=4"),
    Contract("gsync_int8_hier_accum",
             "two-tier hier reducer with in-scan overlapped accumulation",
             config=dict(bucket_cap_mb=_CAP, wire_dtype="int8_hier",
                         grad_accum=2), min_shards=2,
             mesh_spec="slice=2,data=4"),
    Contract("zero1_int8_hier",
             "zero1 with the two-tier wire: hier scatter (exact fast "
             "reduce-scatter + s8 cross-slice exchange w/ EF) and the s8 "
             "cross-slice + exact intra-slice param delta gather",
             config=dict(zero1=True, wire_dtype="int8_hier"),
             min_shards=2, mesh_spec="slice=2,data=4"),
    Contract("gsync_int8_mh_fused",
             "multi-hop int8 wire with the fused Pallas codec kernels "
             "(ops/quantize.py; interpreter mode on the CPU matrix — the "
             "kernel path must keep every census/wire/donation promise "
             "the XLA-composed path keeps, with no relaxation; on TPU "
             "fused-quantize-kernel-present additionally asserts the "
             "Mosaic custom-calls really lowered)",
             config=dict(bucket_cap_mb=_CAP, wire_dtype="int8_multihop",
                         fused_quantize=True), min_shards=2),
    # Explicit full-parameter FSDP (ISSUE 7): params + moments flat-sharded
    # 1/N at rest, one just-in-time param all-gather per layer group, one
    # gradient reduce-scatter per layer group back into the shard layout.
    # The fsdp-* rules bind here: gather count == layer groups, scatter
    # signature present, no full-param/moment residency at rest.
    Contract("fsdp", "explicit FSDP, exact fp32 gathers + fp32 scatter",
             config=dict(fsdp_explicit=True), min_shards=2),
    Contract("fsdp_accum",
             "explicit FSDP under gradient accumulation (per-layer "
             "scatters inside the microbatch scan; gathers stay one per "
             "layer group in the step prologue)",
             config=dict(fsdp_explicit=True, grad_accum=2), min_shards=2),
    Contract("fsdp_int8_mh",
             "explicit FSDP fully compressed: s8 per-layer gradient "
             "scatter (error feedback) + s8 param gathers "
             "(quantized_shard_all_gather) — both wire directions off "
             "fp32, per-layer census unchanged",
             config=dict(fsdp_explicit=True, wire_dtype="int8_multihop"),
             min_shards=2),
    # Explicit TP x FSDP on the 2-D ("data","model") mesh (ISSUE 13): the
    # tp-psum-signature budget binds (one megatron psum per residual join
    # + backward mirrors + the vocab-parallel embedding pair + the
    # parallel-vocab CE's two stat psums, ZERO model-axis gathers —
    # ISSUE 16 replaced the vocab-scale logits gather), every param
    # gather/scatter rides the data axes only
    # (fsdp-gather-rides-data-only), the per-layer gather/scatter census
    # holds over the TP-LOCAL layer plan, and no gradient-sized all-reduce
    # survives off the model axis. No existing rule is relaxed: 1-D
    # artifacts never consult the axis classifier. min_elements=64 (not
    # the default 128): the CE stats are (rows, seq-1, 2)-shaped — 120
    # elements at the tiny contract batch — and the gather-regression pin
    # is only as strong as the floor that lets the census SEE the head's
    # collectives.
    Contract("fsdp_tp",
             "explicit megatron TP x FSDP on data=4,model=2: model-axis "
             "psum budget + data-axis-only param wire, exact fp32",
             config=dict(fsdp_explicit=True), min_shards=2,
             min_elements=64, mesh_spec="data=4,model=2"),
    Contract("fsdp_tp_int8_mh",
             "explicit TP x FSDP fully compressed: s8 data-axis gradient "
             "scatter (EF per model shard) + s8 data-axis param gathers; "
             "model-axis activation psums stay exact fp32 by design",
             config=dict(fsdp_explicit=True, wire_dtype="int8_multihop"),
             min_shards=2, min_elements=64, mesh_spec="data=4,model=2"),
    # The serving decode-step contract (ISSUE 10): the inference engine's
    # one-token KV-cache step must carry NO host transfers (a callback in
    # the decode loop stalls every generated token) and must DONATE the
    # cache (without the alias table every step copies the full
    # (rows, bucket + max_new, heads, head_dim) k/v — a per-token memory
    # tax that compounds with batch). The zero-recompile half of the
    # decode contract is runtime behavior, pinned by the compile-count
    # census in tests/test_serving.py and asserted by `serving bench`.
    Contract("serving_decode",
             "serving KV-cache decode: no host transfers, cache donated "
             "in place (serving/engine.py lower_decode)",
             config=dict(serving_decode=True, donate_state=True),
             kind="serving"),
    # The paged continuous-batching contract (ISSUE 17): the SlotEngine's
    # SHARED decode step — one program serving every slot at once — must
    # carry no host transfers and must alias the ENTIRE page pool in
    # place: paged-pool-donated counts the alias table against the pool's
    # leaf census (paged_cache_leaves). Pinned on the int8 arm because it
    # has the most leaves to drop (k/v codes + k/v scales per block); a
    # missing scale buffer is invisible to the presence-only donation
    # rule but doubles int8 pool traffic on every generated token. The
    # zero-recompile-across-joins/leaves half is runtime behavior, pinned
    # by tests/test_continuous.py and `serving bench --continuous`.
    Contract("serving_paged",
             "paged int8 continuous-batching decode: no host transfers, "
             "full page pool (codes + scales) donated in place "
             "(serving/continuous.py lower_paged_decode)",
             config=dict(serving_paged=True, donate_state=True,
                         paged_kv_dtype="int8"),
             kind="serving_paged"),
    # The speculative-verify contract (ISSUE 19): the target's K+1-window
    # verify step — the program that replaces the plain decode step in
    # every speculative round — must carry no host transfers and must
    # donate pool + control EXACTLY like the plain step: a verify path
    # that copies the pool pays the per-token memory tax the paged
    # contract exists to prevent, multiplied by every round, and the
    # extra n_emit output must NOT cost the alias table an entry
    # (spec-verify-donated counts entries against the fp32 pool + control
    # leaf census). The bitwise stream-parity half is runtime behavior,
    # pinned by tests/test_speculative.py.
    Contract("serving_spec",
             "speculative K+1-window verify: no host transfers, pool + "
             "control donated in place with the n_emit side output "
             "costing no alias entry (serving/speculative.py "
             "lower_spec_verify)",
             config=dict(serving_spec=True, donate_state=True),
             kind="serving_spec"),
    # The control re-plan base contract (ISSUE 20): the config the online
    # perf tuner's candidates are evaluated AGAINST. control/apply.py
    # contract_gate overlays a candidate's overrides (wire_dtype /
    # bucket_cap_mb / overlap_grad_sync / grad_accum — tuner.TUNABLE_KEYS)
    # on this base and runs the FULL HLO rule set over the lowered
    # result; any finding (or a config that cannot even lower) refuses
    # the candidate and the run keeps its old config. The base uses the
    # explicit bucketed reducer so a candidate's bucket-cap/wire choice
    # actually changes the lowered collectives the rules see.
    Contract("control_replan",
             "base config the online tuner's candidates overlay: "
             "bucketed fp32 reducer whose every candidate override must "
             "re-pass the full rule set before apply_decision commits it",
             config=dict(bucket_cap_mb=_CAP), min_shards=2),
    # The elastic-reshard contract (ISSUE 11): a state resharded N -> M by
    # resilience.elastic must lower to EXACTLY the HLO census a clean-at-M
    # state lowers to — a reshard that lands a leaf replicated (or in any
    # off-canonical layout) would smuggle extra collectives into every
    # post-resize step while the run claims a pure re-slice. Evaluated on
    # the zero1 layout (flat-padded moments — the shapes that actually
    # change across worlds); min_shards=4 so the halved world still
    # engages the sharded update.
    Contract("elastic_reshard",
             "a reshardedN->M train step's collective census matches the "
             "clean-at-M census (no reshard-smuggled collectives)",
             config=dict(elastic_reshard=True, zero1=True),
             min_shards=4, kind="elastic"),
    # The GROW leg (ISSUE 12): the same pin in the capacity-return
    # direction — a state grown M -> N (zero-extended flat shards +
    # zero-extended EF rows, the supervisor's boundary grow) must lower
    # to EXACTLY the clean-at-N census.
    Contract("elastic_grow",
             "a grown M->N train step's collective census matches the "
             "clean-at-N census (no grow-smuggled collectives)",
             config=dict(elastic_grow=True, zero1=True),
             min_shards=4, kind="elastic"),
)


def get_contract(name: str) -> Contract:
    for c in CONTRACT_MATRIX:
        if c.name == name:
            return c
    raise KeyError(f"unknown contract {name!r}; "
                   f"known: {[c.name for c in CONTRACT_MATRIX]}")
