"""Concurrency-discipline rules: the host-side control plane, linted.

The serving/resilience/telemetry layers are thread-heavy by design (worker
replicas, heartbeat relays, metrics servers), and the last two PRs each
shipped a hand-found race fix. These rules turn the locking discipline
into checked annotations instead of review folklore:

* **guarded-by** — declare the lock that protects a shared attribute at
  its assignment site::

      self._free: List[int] = []   # guarded-by: _lock

  Every read/write of ``self._free`` in the owning class outside a
  ``with self._lock:`` body is then a finding. Methods whose CALLERS hold
  the lock are marked on the ``def`` line::

      def _take_page(self):   # lock-held: _lock

  Several alternatives may be listed (``# guarded-by: _lock, _cv``) —
  holding any one satisfies the rule. Class-level state uses the same
  convention (``_seeds = iter(...)  # guarded-by: _seeds_lock``) and is
  matched through both ``self.X`` and ``ClassName.X`` access spellings.

* **lock-order-acyclic** — the one global rule (kind ``ast-global``):
  collect every lexically nested acquisition (``with A: ... with B:``)
  across all files into one graph of per-class lock identities
  (``PagePool._lock``, ``RequestQueue._cv``, module locks as
  ``profiling._SESSION_LOCK``) and flag cycles — two threads walking a
  cycle from different ends deadlock. Lexical nesting only: an
  acquisition reached through a method call in another class is invisible
  here; the runtime half (``utils/locktrace.py``, ``DPT_LOCKCHECK=1``)
  records those orders at test time and
  :func:`check_runtime_consistency` merges them back into this graph.

* **no-blocking-under-lock** — socket / urlopen / subprocess /
  ``time.sleep`` / ``.join()`` / ``.result()`` / ``.wait()`` /
  queue-``.get()`` calls lexically inside a held-lock body (the exact
  Router health-probe bug class PR 17 fixed by hand: an HTTP round trip
  under the router lock serializes every dispatch on every thread).
  Calling ``.wait()`` on the held lock itself is exempt — a Condition
  wait RELEASES its lock.

* **thread-lifecycle** — every ``threading.Thread`` must be
  ``daemon=True`` or joined somewhere in its file: a non-daemon,
  never-joined thread outlives shutdown and hangs interpreter exit.

All findings honor the per-line ``# analysis: disable=<rule>`` suppression
(visible in review, reason stated on the line). Like the rest of the AST
engine this module is dependency-free — linting must never require a
backend, so it must NOT import utils.locktrace (whose parent package pulls
jax); locktrace imports *this* module lazily for its cross-check.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .ast_rules import REPO_ROOT, FileContext, iter_source_files
from .contracts import Finding, rule

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([\w,\s]+)")
_LOCK_HELD_RE = re.compile(r"#\s*lock-held:\s*([\w,\s]+)")

# Constructor tails that produce a lock-ish object. named_lock /
# named_condition are utils.locktrace's instrumented constructors — from
# the rules' point of view they ARE the lock.
_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition",
                             "named_lock", "named_condition"})

# with-target attribute names that read as locks even when the constructor
# is out of view (helper-built locks, locks declared in another file).
_LOCKISH_NAME = re.compile(r"lock|mutex|cond(ition)?$|(^|_)cv$|(^|_)mu$",
                           re.IGNORECASE)

_BLOCKING_CALLS = frozenset({
    "time.sleep", "urllib.request.urlopen", "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
})

# receivers whose .get() blocks (queue.Queue and kin); dict.get never
# takes a timeout, so a timeout kwarg marks a blocking get regardless.
_QUEUEISH = re.compile(r"(^|_)q(ueue)?s?\d*$|queue", re.IGNORECASE)


def _raw(node: ast.AST) -> Optional[str]:
    """Literal dotted text of a Name/Attribute chain (no alias expansion):
    the identity locks are matched by (``self._lock``, ``t.daemon``)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _owned_attr(expr: ast.AST, cls_name: str) -> Optional[str]:
    """Attribute name X when `expr` is ``self.X`` or ``<ClassName>.X``."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id in ("self", cls_name):
        return expr.attr
    return None


def _comment_names(lines: List[str], lo: int, hi: int,
                   rx: re.Pattern) -> Tuple[str, ...]:
    """First `rx` annotation in source lines [lo, hi] (1-based, inclusive)
    — an assignment or def signature may span several physical lines."""
    for i in range(lo, min(hi, len(lines)) + 1):
        m = rx.search(lines[i - 1])
        if m:
            return tuple(n.strip() for n in m.group(1).split(",")
                         if n.strip())
    return ()


@dataclasses.dataclass
class ClassLockModel:
    """One class's declared locking discipline: which attributes are
    locks, which are guarded (and by what), which methods assume a lock
    is already held at entry."""

    name: str
    lock_attrs: Set[str]
    # attr -> (allowed lock names, declaration lineno)
    guards: Dict[str, Tuple[Tuple[str, ...], int]]
    # method name -> locks held by contract at entry
    lock_held: Dict[str, Tuple[str, ...]]

    @property
    def lock_universe(self) -> Set[str]:
        """Every name this class treats as a lock — constructed locks
        plus anything a guarded-by / lock-held annotation names (the
        declaration is authoritative even when the constructor is built
        by a helper the model cannot see)."""
        u = set(self.lock_attrs)
        for locks, _ in self.guards.values():
            u.update(locks)
        for locks in self.lock_held.values():
            u.update(locks)
        return u


def class_lock_model(ctx: FileContext, cls: ast.ClassDef) -> ClassLockModel:
    """Collect the lock/guard declarations of one class: class-level
    assignments plus ``self.X = ...`` sites anywhere in ``__init__``."""
    lock_attrs: Set[str] = set()
    guards: Dict[str, Tuple[Tuple[str, ...], int]] = {}
    lock_held: Dict[str, Tuple[str, ...]] = {}

    def scan_assign(stmt: ast.stmt, attr: str) -> None:
        hi = getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
        names = _comment_names(ctx.lines, stmt.lineno, hi, _GUARDED_RE)
        if names:
            guards[attr] = (names, stmt.lineno)
        value = getattr(stmt, "value", None)
        if isinstance(value, ast.Call):
            resolved = ctx.resolve(value.func) or ""
            if resolved.split(".")[-1] in _LOCK_FACTORIES:
                lock_attrs.add(attr)

    for node in cls.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    scan_assign(node, t.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sig_end = max(node.lineno, node.body[0].lineno - 1)
            held = _comment_names(ctx.lines, node.lineno, sig_end,
                                  _LOCK_HELD_RE)
            if held:
                lock_held[node.name] = held
            if node.name == "__init__":
                for stmt in ast.walk(node):
                    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                        tgts = stmt.targets if isinstance(stmt, ast.Assign) \
                            else [stmt.target]
                        for t in tgts:
                            attr = _owned_attr(t, cls.name)
                            if attr is not None:
                                scan_assign(stmt, attr)
    return ClassLockModel(name=cls.name, lock_attrs=lock_attrs,
                          guards=guards, lock_held=lock_held)


# ---------------------------------------------------------------------------
# Rule: guarded-by
# ---------------------------------------------------------------------------


def _guard_walk(ctx: FileContext, cls_name: str, model: ClassLockModel,
                universe: Set[str], node: ast.AST, held: Set[str],
                where: str, out: List[Finding]) -> None:
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # nested def: a closure may run on another thread after the lock
        # is gone — its body starts from its own lock-held contract only
        inner = set(model.lock_held.get(node.name, ()))
        for child in node.body:
            _guard_walk(ctx, cls_name, model, universe, child, inner,
                        node.name, out)
        return
    if isinstance(node, ast.Lambda):
        _guard_walk(ctx, cls_name, model, universe, node.body, set(),
                    where, out)
        return
    if isinstance(node, (ast.With, ast.AsyncWith)):
        acquired: Set[str] = set()
        for item in node.items:
            attr = _owned_attr(item.context_expr, cls_name)
            if attr is not None and attr in universe:
                acquired.add(attr)
            _guard_walk(ctx, cls_name, model, universe, item, held,
                        where, out)
        for child in node.body:
            _guard_walk(ctx, cls_name, model, universe, child,
                        held | acquired, where, out)
        return
    if isinstance(node, ast.Attribute):
        attr = _owned_attr(node, cls_name)
        if attr is not None and attr in model.guards:
            locks, decl = model.guards[attr]
            if not (set(locks) & held):
                want = " or ".join(f"`with self.{l}:`" for l in locks)
                out.append(Finding(
                    "guarded-by",
                    f"`{cls_name}.{where}` touches `self.{attr}` outside "
                    f"{want} — declared `# guarded-by: "
                    f"{', '.join(locks)}` at {ctx.relpath}:{decl}; hold "
                    "the lock, mark the method `# lock-held:`, or "
                    "suppress with the reason on this line",
                    ctx.loc(node)))
    for child in ast.iter_child_nodes(node):
        _guard_walk(ctx, cls_name, model, universe, child, held, where,
                    out)


@rule(
    "guarded-by", "ast",
    "a `# guarded-by:`-annotated attribute touched outside its lock",
    "declaring the protecting lock at the attribute's assignment site "
    "makes the locking discipline machine-checkable: every unlocked "
    "read/write in the owning class is a race the next refactor ships")
def check_guarded_by(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in (n for n in ast.walk(ctx.tree)
                if isinstance(n, ast.ClassDef)):
        model = class_lock_model(ctx, cls)
        if not model.guards:
            continue
        universe = model.lock_universe
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue    # construction precedes sharing
            held = set(model.lock_held.get(fn.name, ()))
            for stmt in fn.body:
                _guard_walk(ctx, cls.name, model, universe, stmt, held,
                            fn.name, out)
    return out


# ---------------------------------------------------------------------------
# Rule: no-blocking-under-lock
# ---------------------------------------------------------------------------


def _module_level_locks(ctx: FileContext) -> Set[str]:
    out: Set[str] = set()
    for node in ctx.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = getattr(node, "value", None)
            if isinstance(value, ast.Call):
                resolved = ctx.resolve(value.func) or ""
                if resolved.split(".")[-1] in _LOCK_FACTORIES:
                    out.update(t.id for t in targets
                               if isinstance(t, ast.Name))
    return out


def _blocking_reason(ctx: FileContext, call: ast.Call,
                     held: Sequence[str]) -> Optional[str]:
    resolved = ctx.resolve(call.func)
    if resolved in _BLOCKING_CALLS:
        return f"`{resolved}(...)`"
    if not isinstance(call.func, ast.Attribute):
        return None
    meth = call.func.attr
    recv = _raw(call.func.value)
    if recv is not None and recv in held:
        return None     # waiting on the held lock itself releases it
    kwnames = {k.arg for k in call.keywords}
    npos = len(call.args)
    show = recv or "<expr>"
    if meth == "join":
        numeric = npos == 1 and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, (int, float)) \
            and not isinstance(call.args[0].value, bool)
        if npos == 0 or "timeout" in kwnames or numeric:
            return f"`{show}.join(...)`"
    elif meth == "result" and npos <= 1:
        return f"`{show}.result(...)`"
    elif meth in ("wait", "wait_for"):
        return f"`{show}.{meth}(...)`"
    elif meth == "get":
        last = (recv or "").split(".")[-1]
        if "timeout" in kwnames or _QUEUEISH.search(last):
            return f"`{show}.get(...)`"
    return None


@rule(
    "no-blocking-under-lock", "ast",
    "a blocking call (socket/urlopen/subprocess/sleep/join/result/wait/"
    "queue-get) lexically inside a held-lock body",
    "a blocking call under a lock serializes every thread that needs the "
    "lock on the slowest caller — the Router health-probe bug class: one "
    "unreachable replica's 2s HTTP timeout stalled every dispatch")
def check_no_blocking_under_lock(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    module_locks = _module_level_locks(ctx)
    models: Dict[str, ClassLockModel] = {}

    def model_of(cls: ast.ClassDef) -> ClassLockModel:
        if cls.name not in models:
            models[cls.name] = class_lock_model(ctx, cls)
        return models[cls.name]

    def lockish(expr: ast.AST, cls: Optional[ast.ClassDef]) -> Optional[str]:
        raw = _raw(expr)
        if raw is None:
            return None
        parts = raw.split(".")
        if len(parts) == 2 and cls is not None \
                and parts[0] in ("self", cls.name):
            if parts[1] in model_of(cls).lock_universe \
                    or _LOCKISH_NAME.search(parts[1]):
                return raw
            return None
        if len(parts) == 1 and (parts[0] in module_locks
                                or _LOCKISH_NAME.search(parts[0])):
            return raw
        if len(parts) == 2 and _LOCKISH_NAME.search(parts[1]):
            return raw  # OtherClass._lock spelled cross-class
        return None

    def walk(node: ast.AST, held: List[str],
             cls: Optional[ast.ClassDef]) -> None:
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                walk(child, [], node)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            start: List[str] = []
            if cls is not None:
                start = [f"self.{l}" for l in
                         model_of(cls).lock_held.get(node.name, ())]
            for child in node.body:
                walk(child, start, cls)
            return
        if isinstance(node, ast.Lambda):
            walk(node.body, [], cls)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                raw = lockish(item.context_expr, cls)
                if raw is not None:
                    acquired.append(raw)
                walk(item, held, cls)
            for child in node.body:
                walk(child, held + acquired, cls)
            return
        if isinstance(node, ast.Call) and held:
            reason = _blocking_reason(ctx, node, held)
            if reason is not None:
                locks = ", ".join(f"`{h}`" for h in held)
                out.append(Finding(
                    "no-blocking-under-lock",
                    f"{reason} while holding {locks} — every thread that "
                    "needs the lock now waits on this call too; move it "
                    "outside the critical section (snapshot under the "
                    "lock, act outside it) or suppress with the reason "
                    "on this line",
                    ctx.loc(node)))
        for child in ast.iter_child_nodes(node):
            walk(child, held, cls)

    for stmt in ctx.tree.body:
        walk(stmt, [], None)
    return out


# ---------------------------------------------------------------------------
# Rule: lock-order-acyclic (global) + the exported graph
# ---------------------------------------------------------------------------


def _collect_lock_edges(
        ctxs: Sequence[FileContext]) -> Dict[Tuple[str, str], str]:
    """The global nested-acquisition graph: (outer, inner) -> first
    location where `inner` was taken while `outer` was held. Identities
    are class-qualified (``PagePool._lock``) so the same discipline reads
    identically from every file — and matches the names the runtime
    tracer records (utils/locktrace.py)."""
    edges: Dict[Tuple[str, str], str] = {}
    for ctx in ctxs:
        module_locks = _module_level_locks(ctx)
        stem = ctx.relpath.rsplit("/", 1)[-1].removesuffix(".py")
        models: Dict[str, ClassLockModel] = {}

        def model_of(cls: ast.ClassDef) -> ClassLockModel:
            if cls.name not in models:
                models[cls.name] = class_lock_model(ctx, cls)
            return models[cls.name]

        def lock_id(expr: ast.AST,
                    cls: Optional[ast.ClassDef]) -> Optional[str]:
            raw = _raw(expr)
            if raw is None:
                return None
            parts = raw.split(".")
            if len(parts) == 2 and cls is not None \
                    and parts[0] in ("self", cls.name):
                if parts[1] in model_of(cls).lock_universe \
                        or _LOCKISH_NAME.search(parts[1]):
                    return f"{cls.name}.{parts[1]}"
                return None
            if len(parts) == 2 and parts[0][:1].isupper() \
                    and _LOCKISH_NAME.search(parts[1]):
                return f"{parts[0]}.{parts[1]}"  # OtherClass._lock
            if len(parts) == 1 and parts[0] in module_locks:
                return f"{stem}.{parts[0]}"
            return None  # local/aliased locks carry no stable identity

        def walk(node: ast.AST, held: List[str],
                 cls: Optional[ast.ClassDef]) -> None:
            if isinstance(node, ast.ClassDef):
                for child in node.body:
                    walk(child, [], node)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                start: List[str] = []
                if cls is not None:
                    start = [f"{cls.name}.{l}" for l in
                             model_of(cls).lock_held.get(node.name, ())]
                for child in node.body:
                    walk(child, start, cls)
                return
            if isinstance(node, ast.Lambda):
                walk(node.body, [], cls)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new: List[str] = []
                for item in node.items:
                    lid = lock_id(item.context_expr, cls)
                    if lid is not None:
                        new.append(lid)
                    walk(item, held, cls)
                for outer in held:
                    for inner in new:
                        if outer != inner:
                            edges.setdefault((outer, inner),
                                             ctx.loc(node))
                for child in node.body:
                    walk(child, held + new, cls)
                return
            for child in ast.iter_child_nodes(node):
                walk(child, held, cls)

        for stmt in ctx.tree.body:
            walk(stmt, [], None)
    return edges


def _find_cycles(edge_keys: Iterable[Tuple[str, str]]) -> List[List[str]]:
    """Strongly-connected components of size > 1 (plus self-loops) —
    each is a set of locks acquirable in a cyclic order."""
    adj: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    self_loops: List[str] = []
    for a, b in edge_keys:
        nodes.update((a, b))
        if a == b:
            self_loops.append(a)
            continue
        adj.setdefault(a, []).append(b)
    # iterative Tarjan
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]
    for root in sorted(nodes):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            v, i = work.pop()
            if i == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            recursed = False
            children = adj.get(v, [])
            while i < len(children):
                w = children[i]
                i += 1
                if w not in index:
                    work.append((v, i))
                    work.append((w, 0))
                    recursed = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if recursed:
                continue
            if low[v] == index[v]:
                scc: List[str] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return sccs + [[n] for n in sorted(set(self_loops))]


@rule(
    "lock-order-acyclic", "ast-global",
    "a cycle in the global nested-lock-acquisition graph",
    "two threads that take a lock cycle from different ends deadlock; "
    "one global acquisition order (checked here, observed at runtime by "
    "utils/locktrace.py) makes that impossible by construction")
def check_lock_order_acyclic(
        ctxs: Sequence[FileContext]) -> List[Finding]:
    edges = _collect_lock_edges(list(ctxs))
    out: List[Finding] = []
    for cycle in _find_cycles(edges.keys()):
        members = set(cycle)
        def _line_order(loc: str) -> Tuple[str, int]:
            path, _, line = loc.rpartition(":")
            return (path, int(line) if line.isdigit() else 0)

        locs = sorted({loc for (a, b), loc in edges.items()
                       if a in members and b in members},
                      key=_line_order)
        out.append(Finding(
            "lock-order-acyclic",
            f"locks {' -> '.join(cycle + [cycle[0]])} are acquired in a "
            f"cycle (nested `with` sites: {', '.join(locs[:4])}) — "
            "impose one global acquisition order, or suppress on the "
            "first site with the reason the orders can never meet",
            locs[0] if locs else "<unknown>:0"))
    return out


def lock_order_graph(files: Optional[Iterable[Path]] = None,
                     repo: Path = REPO_ROOT) -> Dict[Tuple[str, str], str]:
    """The static acquisition graph over `files` (default: the linted
    set) — the reference utils/locktrace.py cross-checks runtime orders
    against. Unparseable files are skipped (run_ast_rules reports them)."""
    ctxs: List[FileContext] = []
    for p in (files if files is not None else iter_source_files(repo)):
        try:
            ctxs.append(FileContext.parse(Path(p), repo=repo))
        except (SyntaxError, ValueError):
            continue
    return _collect_lock_edges(ctxs)


def check_runtime_consistency(
        runtime_edges: Iterable[Tuple[str, str]],
        static_edges: Optional[Dict[Tuple[str, str], str]] = None,
) -> List[str]:
    """Merge runtime-observed acquisition orders into the static graph
    and report inconsistencies: a runtime edge that reverses a static
    one, or any cycle in the merged graph. Empty list = consistent."""
    static = dict(static_edges) if static_edges is not None \
        else lock_order_graph()
    problems: List[str] = []
    runtime = list(runtime_edges)
    for a, b in runtime:
        if (b, a) in static:
            problems.append(
                f"runtime order {a} -> {b} reverses the static "
                f"acquisition at {static[(b, a)]}")
    merged = dict(static)
    for a, b in runtime:
        merged.setdefault((a, b), "<runtime>")
    for cycle in _find_cycles(merged.keys()):
        problems.append(
            "merged static+runtime lock graph has a cycle: "
            + " -> ".join(cycle + [cycle[0]]))
    return problems


# ---------------------------------------------------------------------------
# Rule: thread-lifecycle
# ---------------------------------------------------------------------------


@rule(
    "thread-lifecycle", "ast",
    "a threading.Thread neither daemonized nor joined in its file",
    "a non-daemon thread nobody joins outlives every shutdown path: the "
    "interpreter hangs at exit waiting for it, and SIGTERM drains stall")
def check_thread_lifecycle(ctx: FileContext) -> List[Finding]:
    daemon_set: Set[str] = set()
    joined: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                raw = _raw(t)
                if raw and raw.endswith(".daemon") \
                        and isinstance(node.value, ast.Constant) \
                        and node.value.value is True:
                    daemon_set.add(raw[: -len(".daemon")])
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            recv = _raw(node.func.value)
            if recv:
                joined.add(recv)
    parents = {child: p for p in ast.walk(ctx.tree)
               for child in ast.iter_child_nodes(p)}
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and ctx.resolve(node.func) == "threading.Thread"):
            continue
        kw = {k.arg: k.value for k in node.keywords}
        d = kw.get("daemon")
        if isinstance(d, ast.Constant) and d.value is True:
            continue
        p = parents.get(node)
        targets: List[str] = []
        if isinstance(p, ast.Assign):
            targets = [r for t in p.targets if (r := _raw(t))]
        elif isinstance(p, ast.AnnAssign):
            r = _raw(p.target)
            targets = [r] if r else []
        if any(t in daemon_set or t in joined for t in targets):
            continue
        out.append(Finding(
            "thread-lifecycle",
            "threading.Thread created neither `daemon=True` nor joined "
            "anywhere in this file — it outlives shutdown and hangs "
            "interpreter exit; daemonize it, join it on the stop path, "
            "or suppress with the reason it is collected elsewhere",
            ctx.loc(node)))
    return out
