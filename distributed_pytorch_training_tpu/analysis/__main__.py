"""``python -m distributed_pytorch_training_tpu.analysis check`` — run the
parallelism contract checker (HLO engine over the canonical config matrix +
AST lint engine over the repo source) and exit nonzero on any finding.

Also installed as the ``analysis`` console script (pyproject.toml).

Flags:
  --json             machine-readable report on stdout
  --rules a,b        run only the named rules (see --list)
  --ast-only         skip the HLO matrix (no jax / device init — fast lint)
  --contracts a,b    evaluate only the named contracts from the matrix
  --list             print the rule catalog (name, kind, rationale) and exit

Exit codes: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _ensure_test_mesh() -> None:
    """Standalone CLI runs need a multi-device mesh for the zero1/grad_sync
    contracts to engage. On CPU (or unset platform) request the 8-device
    virtual mesh — the tests/conftest.py recipe. The image's sitecustomize
    imports jax at interpreter startup, but XLA backend init is LAZY, so
    the env mutations still take effect as long as no jax.devices() call
    has happened yet; callers that already initialized a backend (the
    tier-1 in-process test, a real TPU run) keep their devices."""
    platform = os.environ.get("JAX_PLATFORMS", "")
    if platform not in ("", "cpu"):
        return  # real accelerator run: keep its devices
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        import jax

        from ..runtime import honor_platform_env

        honor_platform_env()  # re-assert cpu via the config API
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # older jax: the XLA_FLAGS fallback above provides the devices
    except Exception:  # noqa: BLE001 - backend already up: nothing to do
        pass


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("command", choices=["check"],
                   help="'check' runs both engines")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule names to run (default: all)")
    p.add_argument("--contracts", default=None,
                   help="comma-separated contract names from the matrix "
                        "(default: all)")
    p.add_argument("--ast-only", action="store_true",
                   help="skip the HLO config matrix (no jax init)")
    p.add_argument("--list", action="store_true", dest="list_rules",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    from .contracts import CONTRACT_MATRIX, get_contract, iter_rules

    try:
        rule_names = ([r.strip() for r in args.rules.split(",") if r.strip()]
                      if args.rules else None)
        rules = iter_rules(names=rule_names)
    except KeyError as e:
        print(f"analysis: {e.args[0]}", file=sys.stderr)
        return 2

    if args.list_rules:
        for r in rules:
            print(f"{r.name} [{r.kind}]\n  {r.description}\n  why: "
                  f"{r.rationale}\n")
        return 0

    ast_rule_names = [r.name for r in rules if r.kind == "ast"]
    hlo_rule_names = [r.name for r in rules if r.kind == "hlo"]

    findings = []
    contract_status = {}

    if ast_rule_names:
        from .ast_rules import run_ast_rules

        findings += run_ast_rules(rules=ast_rule_names)

    if hlo_rule_names and not args.ast_only:
        try:
            contracts = ([get_contract(c.strip())
                          for c in args.contracts.split(",") if c.strip()]
                         if args.contracts else CONTRACT_MATRIX)
        except KeyError as e:
            print(f"analysis: {e.args[0]}", file=sys.stderr)
            return 2
        _ensure_test_mesh()
        from .hlo_rules import run_contract_matrix

        hlo_findings, contract_status = run_contract_matrix(
            contracts=contracts, rules=hlo_rule_names)
        findings += hlo_findings

    if args.as_json:
        print(json.dumps({
            "ok": not findings,
            "n_findings": len(findings),
            "findings": [f.as_dict() for f in findings],
            "contracts": contract_status,
            "rules_run": [r.name for r in rules],
        }, indent=2, sort_keys=True))
    else:
        for name, status in sorted(contract_status.items()):
            print(f"contract {name}: {status}")
        for f in findings:
            print(str(f))
        print(f"analysis check: {len(findings)} finding(s) from "
              f"{len(rules)} rule(s)"
              + (f", {len(contract_status)} contract(s)"
                 if contract_status else ""))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
