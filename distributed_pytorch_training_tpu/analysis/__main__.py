"""``python -m distributed_pytorch_training_tpu.analysis check`` — run the
parallelism contract checker (HLO engine over the canonical config matrix +
AST lint engine over the repo source) and exit nonzero on any finding.

Also installed as the ``analysis`` console script (pyproject.toml).

Flags:
  --json             machine-readable report on stdout
  --rules a,b        run only the named rules (see --list)
  --ast-only         skip the HLO matrix (no jax / device init — fast lint)
  --contracts a,b    evaluate only the named contracts from the matrix
  --changed          AST rules on git-changed files only (fast local loop);
                     whole-repo rules (the lock-order graph) and the HLO
                     matrix are unaffected — they are global by nature
  --list             print the rule catalog (name, kind, rationale) and exit

Exit codes: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import List, Optional

# --json report layout version. 1 was the implicit, unversioned layout;
# 2 added this field (consumers should treat a missing field as 1).
REPORT_SCHEMA_VERSION = 2


def _changed_source_files() -> Optional[List[Path]]:
    """Git-changed .py files (vs HEAD, plus untracked), intersected with
    the linted set. None when git is unavailable — the caller falls back
    to the full set: an incremental mode must never lint LESS than a
    broken git invocation would excuse."""
    import subprocess

    from .ast_rules import REPO_ROOT, iter_source_files

    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=REPO_ROOT, capture_output=True, text=True,
            check=True, timeout=30).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=REPO_ROOT, capture_output=True, text=True,
            check=True, timeout=30).stdout
    except Exception:  # noqa: BLE001 - not a repo / no git binary
        return None
    names = {ln.strip() for ln in (diff + "\n" + untracked).splitlines()
             if ln.strip().endswith(".py")}
    linted = {p.resolve() for p in iter_source_files()}
    out = []
    for n in sorted(names):
        p = (REPO_ROOT / n).resolve()
        if p in linted and p.exists():
            out.append(p)
    return out


def _ensure_test_mesh() -> None:
    """Standalone CLI runs need a multi-device mesh for the zero1/grad_sync
    contracts to engage. On CPU (or unset platform) request the 8-device
    virtual mesh — the tests/conftest.py recipe. The image's sitecustomize
    imports jax at interpreter startup, but XLA backend init is LAZY, so
    the env mutations still take effect as long as no jax.devices() call
    has happened yet; callers that already initialized a backend (the
    tier-1 in-process test, a real TPU run) keep their devices."""
    platform = os.environ.get("JAX_PLATFORMS", "")
    if platform not in ("", "cpu"):
        return  # real accelerator run: keep its devices
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        import jax

        from ..runtime import honor_platform_env

        honor_platform_env()  # re-assert cpu via the config API
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # older jax: the XLA_FLAGS fallback above provides the devices
    except Exception:  # noqa: BLE001 - backend already up: nothing to do
        pass


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("command", choices=["check"],
                   help="'check' runs both engines")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule names to run (default: all)")
    p.add_argument("--contracts", default=None,
                   help="comma-separated contract names from the matrix "
                        "(default: all)")
    p.add_argument("--ast-only", action="store_true",
                   help="skip the HLO config matrix (no jax init)")
    p.add_argument("--changed", action="store_true",
                   help="per-file AST rules on git-changed files only; "
                        "global rules and the HLO matrix still run whole")
    p.add_argument("--list", action="store_true", dest="list_rules",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    from .contracts import CONTRACT_MATRIX, get_contract, iter_rules

    try:
        rule_names = ([r.strip() for r in args.rules.split(",") if r.strip()]
                      if args.rules else None)
        rules = iter_rules(names=rule_names)
    except KeyError as e:
        print(f"analysis: {e.args[0]}", file=sys.stderr)
        return 2

    if args.list_rules:
        for r in rules:
            print(f"{r.name} [{r.kind}]\n  {r.description}\n  why: "
                  f"{r.rationale}\n")
        return 0

    ast_rule_names = [r.name for r in rules if r.kind == "ast"]
    global_rule_names = [r.name for r in rules if r.kind == "ast-global"]
    hlo_rule_names = [r.name for r in rules if r.kind == "hlo"]

    findings = []
    contract_status = {}

    if ast_rule_names or global_rule_names:
        from .ast_rules import run_ast_rules

        changed = _changed_source_files() if args.changed else None
        if args.changed and changed is not None:
            # incremental: per-file rules on the changed set only; the
            # whole-repo rules (lock-order graph) still see every file —
            # a cycle is a property of the union, not of one diff
            if ast_rule_names:
                findings += run_ast_rules(files=changed,
                                          rules=ast_rule_names)
            if global_rule_names:
                findings += run_ast_rules(rules=global_rule_names)
        else:
            findings += run_ast_rules(
                rules=ast_rule_names + global_rule_names)

    if hlo_rule_names and not args.ast_only:
        try:
            contracts = ([get_contract(c.strip())
                          for c in args.contracts.split(",") if c.strip()]
                         if args.contracts else CONTRACT_MATRIX)
        except KeyError as e:
            print(f"analysis: {e.args[0]}", file=sys.stderr)
            return 2
        _ensure_test_mesh()
        from .hlo_rules import run_contract_matrix

        hlo_findings, contract_status = run_contract_matrix(
            contracts=contracts, rules=hlo_rule_names)
        findings += hlo_findings

    if args.as_json:
        print(json.dumps({
            "schema_version": REPORT_SCHEMA_VERSION,
            "ok": not findings,
            "n_findings": len(findings),
            "findings": [f.as_dict() for f in findings],
            "contracts": contract_status,
            "rules_run": [r.name for r in rules],
        }, indent=2, sort_keys=True))
    else:
        for name, status in sorted(contract_status.items()):
            print(f"contract {name}: {status}")
        for f in findings:
            print(str(f))
        print(f"analysis check: {len(findings)} finding(s) from "
              f"{len(rules)} rule(s)"
              + (f", {len(contract_status)} contract(s)"
                 if contract_status else ""))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
