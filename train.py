"""TPU-native distributed training entry point.

The orchestration layer — maps `main()` of the reference
(/root/reference/train_ddp.py:314-390) onto the TPU-native stack:

    reference                          here
    ---------                          ----
    parse_args (:315)                  utils.config.parse_args (same flags)
    setup_distributed NCCL (:318)      runtime.setup_distributed + build_mesh
    set_seed(seed+rank) (:319)         runtime.set_seed (same seed+rank rule for
                                       host RNG); device randomness from one
                                       shared PRNGKey(seed) on the global batch
    get_dataloaders (:332)             data.ShardedLoader (pad+mask, prefetch)
    build_model + DDP wrap (:335-336)  models.get_model + shard_pytree
    criterion/optimizer/scaler (:338)  training.make_optimizer (no scaler: bf16)
    epoch loop + CSV (:356-384)        identical stdout/CSV contract
    cleanup (:386)                     runtime.cleanup_distributed

Run: python train.py --epochs 2 --synthetic        (single chip or CPU)
"""

from __future__ import annotations

import sys
from pathlib import Path

# Allow `python train.py` from anywhere.
sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_training_tpu.data import (
    CIFAR10_MEAN, CIFAR10_STD, IMAGENET_MEAN, IMAGENET_STD,
    ShardedLoader, get_dataset,
)
from distributed_pytorch_training_tpu.models import get_model
from distributed_pytorch_training_tpu.parallel import MeshSpec, barrier, build_mesh
from distributed_pytorch_training_tpu.parallel.mesh import (
    batch_shard_count, validate_mesh_usage,
)
from distributed_pytorch_training_tpu.runtime import (
    cleanup_distributed, enable_persistent_compile_cache, honor_platform_env,
    set_seed, setup_distributed,
)

honor_platform_env()  # JAX_PLATFORMS=cpu virtual-mesh runs work as expected
from distributed_pytorch_training_tpu.training import (
    TrainConfig, Trainer, make_optimizer, make_schedule,
)
from distributed_pytorch_training_tpu.training.tasks import ImageClassificationTask
from distributed_pytorch_training_tpu.utils import MetricsCSV, log_main, parse_args
from distributed_pytorch_training_tpu.utils.config import parse_model_overrides

IMAGE_STATS = {
    "cifar10": (CIFAR10_MEAN, CIFAR10_STD),
    "imagenet": (IMAGENET_MEAN, IMAGENET_STD),
}


def samples_per_step_list(n: int, global_batch: int, steps: int, drop_last: bool):
    """Host-known global sample count per step (for the throughput meter,
    ref :226 counts `batch_size * world_size` per step)."""
    counts = [global_batch] * steps
    if not drop_last and steps and n % global_batch:
        counts[-1] = n % global_batch
    return counts


def resolve_attention(requested: str, is_lm: bool, backend: str,
                      n_pipe: int, seq_len: int = 512) -> str:
    """Resolve ``--attention auto`` to the benched fast path: Pallas flash
    kernels on TPU (42% over the einsum for GPT-2 @ S=1024 on v5e); the XLA
    einsum elsewhere (CPU would run pallas in interpreter mode), inside
    pipeline stages (attention is a per-stage concern), for image models
    (no attention), and for sequence lengths the kernel has no usable block
    for — auto must never turn a previously-working default run into an
    error (an *explicit* --attention flash still fails loudly there)."""
    if requested != "auto":
        return requested
    from distributed_pytorch_training_tpu.ops.flash_attention import (
        flash_backend_supported, flash_supports_length,
    )

    return ("flash" if is_lm and flash_backend_supported(backend)
            and n_pipe == 1 and flash_supports_length(seq_len) else "xla")


def main(argv=None):
    args = parse_args(argv)
    if args.resume and not args.checkpoint_dir:
        raise ValueError("--resume requires --checkpoint-dir")
    if args.max_restarts > 0 and not args.checkpoint_dir:
        raise ValueError("--max-restarts requires --checkpoint-dir (the "
                         "supervisor restarts FROM checkpoints)")
    if args.max_restarts < 0:
        raise ValueError(f"--max-restarts must be >= 0, got "
                         f"{args.max_restarts}")
    if args.autopilot and args.max_restarts <= 0:
        raise ValueError("--autopilot requires --max-restarts (the "
                         "Supervisor owns the segment boundaries every "
                         "control decision is anchored at)")
    if args.autopilot and args.no_telemetry:
        raise ValueError("--autopilot requires telemetry: the control "
                         "plane's inputs AND its decision log are both "
                         "the stream (drop --no-telemetry)")
    if args.autopilot_tune and not args.autopilot:
        raise ValueError("--autopilot-tune requires --autopilot")

    # Preemption guard first: a SIGTERM during data load / compile must also
    # lead to a graceful stop, not a mid-init kill (preemption.py docstring).
    from distributed_pytorch_training_tpu.training.preemption import (
        PreemptionGuard,
    )
    from distributed_pytorch_training_tpu import telemetry

    guard = PreemptionGuard.install()
    try:
        _run(args, guard)
    except BaseException as e:
        # The flight recorder's train.py exit path: ANY abnormal exit
        # (unhandled exception, deathwatch sys.exit) leaves a postmortem
        # flight_<ts>.json with the last events + cause. Done here rather
        # than via sys.excepthook so it runs BEFORE the finally below can
        # tear telemetry down. Clean SystemExit(0) is not abnormal.
        if not (isinstance(e, SystemExit) and e.code in (0, None)):
            telemetry.flush_flight(
                cause=f"{type(e).__name__}: {e}",
                detail="train.py abnormal exit",
                rc=e.code if isinstance(e, SystemExit) else 1)
        raise
    finally:
        # The hard-exit deadline must not outlive this invocation: an
        # embedder (sweep / notebook) that catches a failure mid-preemption
        # would otherwise be os._exit(143)-killed up to `grace` seconds
        # later with no warning. Normal completion disarms after cleanup
        # inside _run; this is the exception path.
        guard.disarm()
        # endpoint down before the stream closes — guarded on the module
        # actually having loaded, so the metrics-off path never imports
        # metrics_http at all (its zero-cost-when-off contract)
        if "distributed_pytorch_training_tpu.telemetry.metrics_http" \
                in sys.modules:
            telemetry.stop_metrics_server()
        telemetry.reset()  # close the JSONL (fsync) and drop the global


def _log_save_blocked(ckpt) -> None:
    """The save_blocked_ms instrument (training/checkpoint.py): how long
    the train loop actually stalled on checkpointing — under async saves
    this collapses to ~the device→host snapshot cost."""
    if ckpt is None or not ckpt.saves_started:
        return
    log_main(f"Checkpointing: blocked {ckpt.save_blocked_ms:.0f}ms total "
             f"(snapshot {ckpt.snapshot_ms:.0f}ms) across "
             f"{ckpt.saves_started} save(s)")


def _run(args, guard):
    Path(args.output_dir).mkdir(parents=True, exist_ok=True)  # ref :316

    # Deterministic fault injection (resilience/faults.py): armed ONLY when
    # --chaos is given — every injection hook below is None otherwise, so
    # the un-instrumented hot path is untouched.
    chaos = None
    if args.chaos:
        from distributed_pytorch_training_tpu.resilience.faults import (
            FaultInjector, FaultPlan,
        )
        chaos = FaultInjector(FaultPlan.parse(args.chaos), log=log_main)
        log_main(f"CHAOS: fault plan armed: {args.chaos}")

    ctx = setup_distributed()  # ref :318
    # Structured run telemetry (telemetry/): per-rank JSONL stream in the
    # output dir + the in-memory ring the flight recorder flushes on
    # abnormal exits. Rank 0 always streams (the historical
    # telemetry_rank0.jsonl, unchanged disk cost); other ranks stream
    # only under --telemetry-all-ranks / DPT_TELEMETRY_ALL_RANKS — the
    # per-rank inputs `telemetry aggregate` merges. Host-side only —
    # PARITY.md pins that the lowered HLO is identical with telemetry on
    # or off, live /metrics surface included.
    from distributed_pytorch_training_tpu import telemetry
    tele_rank = telemetry.rank_identity(ctx.process_index)
    if not args.no_telemetry and telemetry.should_stream(
            tele_rank, args.telemetry_all_ranks):
        telemetry.configure(
            str(Path(args.output_dir)
                / telemetry.stream_filename(tele_rank)),
            rank=tele_rank, gen=telemetry.generation_identity(),
            meta={"entry": "train.py", "model": args.model,
                  "mesh": args.mesh, "chaos": args.chaos or ""})
    # Live metrics endpoint (telemetry/metrics_http.py): a stdlib-only
    # background HTTP thread serving Prometheus /metrics + step-fence
    # /healthz, fed by an observer on the recorder. Off (the default)
    # resolves port 0 and starts ZERO threads.
    metrics_port = telemetry.resolve_metrics_port(args.metrics_port,
                                                  tele_rank)
    if metrics_port and telemetry.is_configured():
        # a bind failure returns None (stderr-noted) instead of raising:
        # the live surface must never take the training run down
        if telemetry.start_metrics_server(
                metrics_port, telemetry.get(),
                backend=jax.default_backend()) is not None:
            log_main(f"Telemetry: serving /metrics + /healthz on "
                     f":{metrics_port}")
    # Relay-tunnel deathwatch (resilience/heartbeat.py, the layer bench.py
    # seeded): opt-in via DPT_RELAY_PORTS — on the tunneled single-chip
    # environment a dead relay turns every RPC into an unbounded
    # UNAVAILABLE retry loop with no client-side remedy, so a training run
    # there should abort promptly (rc=70) instead of burning its
    # preemption grace wedged. No-op everywhere else. Under the restart
    # supervisor the watch is ADVISORY (lethal=False): the Supervisor
    # drains the segment, flushes the pending async save, CHECKPOINTS,
    # and only then this process exits rc=70 — checkpoint-then-abort
    # instead of a bare kill, so the relaunch resumes this exact step.
    from distributed_pytorch_training_tpu.resilience.heartbeat import (
        DEATHWATCH_EXIT_CODE, Deathwatch, default_policy,
    )
    relay_watch = None
    if args.max_restarts > 0:
        relay_watch = Deathwatch.arm(
            # The abort path needs the in-flight step to RETURN, which a
            # dead relay can prevent (unbounded UNAVAILABLE retries) —
            # escalate to the lethal hard exit if the drain hasn't
            # finished by then, same bound as preemption's hard exit.
            policy=default_policy(lethal=False, escalate_after_s=600.0),
            log=log_main)
    else:
        Deathwatch.arm(log=log_main)
    set_seed(args.seed, ctx.process_index)  # seed+rank rule, ref :76-78/:319
    mesh_spec = MeshSpec.parse(args.mesh)
    if args.slices > 1:
        # --slices folds the slow-tier/outer axis into the mesh spec; an
        # explicit slice=... in --mesh must agree (two sources of truth
        # silently disagreeing is how wrong topologies ship)
        import dataclasses as _dc
        if mesh_spec.slice not in (1, args.slices):
            raise ValueError(
                f"--slices {args.slices} conflicts with --mesh "
                f"{args.mesh!r} (slice={mesh_spec.slice}); set the slice "
                "factor in one place")
        mesh_spec = _dc.replace(mesh_spec, slice=args.slices)
    mesh = build_mesh(mesh_spec)
    n_batch_shards = batch_shard_count(mesh)
    global_batch = args.batch_size * n_batch_shards
    # the /metrics world-size gauge (elastic relaunches land at different
    # worlds — the scrape shows which one this process actually got)
    telemetry.gauge("world_size", mesh.size)
    # Warm-restart compilation cache: reuse compiles across CLI invocations
    # AND across supervisor/elastic restarts (the TPU analogue of the
    # reference's cudnn.benchmark=True autotune persistence, ref :329).
    # Repo-local like bench.py/__graft_entry__.py — a per-output-dir cache
    # would start empty for every fresh experiment dir — and keyed by
    # (topology, config) so one mesh shape's entries never shadow
    # another's (the elastic-fleet story: each surviving world keeps its
    # own warm entries). DPT_COMPILE_CACHE ∈ {auto,on,off}; "auto"
    # refuses XLA:CPU, whose cache reloads are unsafe here. The verdict is
    # a `compile_cache_enabled` telemetry counter.
    from distributed_pytorch_training_tpu.runtime import compile_cache_dir
    enable_persistent_compile_cache(compile_cache_dir(
        Path(__file__).resolve().parent / ".jax_cache",
        topology=f"{jax.default_backend()}-"
                 + "-".join(f"{a}{s}" for a, s in sorted(mesh.shape.items())
                            if s > 1 or a == "data"),
        config_tag=f"{args.model}"
                   + ("-zero1" if args.zero1 else "")
                   + ("-fsdp" if args.fsdp_explicit else "")
                   + (f"-{args.wire_dtype}" if args.wire_dtype != "fp32"
                      else "")
                   + ("-amp" if args.amp else "")))

    # Banner ≙ ref :326-327 ("Using device: ..., world_size=..., amp=...").
    dev0 = mesh.devices.flat[0]
    log_main(
        f"Using device: {dev0.platform}:{dev0.id} "
        f"(mesh {dict(mesh.shape)}), world_size={mesh.size}, amp={args.amp}"
    )

    compute_dtype = jnp.bfloat16 if args.amp else jnp.float32
    overrides = parse_model_overrides(args.model_overrides)
    is_lm = args.model.startswith(("gpt2", "bert"))
    family = "bert" if args.model.startswith("bert") else "gpt2"
    resolved_seq = args.seq_len or (512 if family == "bert" else 1024)
    attention = resolve_attention(args.attention, is_lm,
                                  jax.default_backend(), mesh.shape["pipe"],
                                  resolved_seq)
    if args.download and (is_lm or args.dataset.lower() != "cifar10"):
        # never let a user believe they trained on fetched data when the
        # flag was silently inapplicable
        raise ValueError(
            "--download supports --dataset cifar10 (the reference's "
            "workload); LM/imagenet configs read preprocessed data from "
            "--data-dir or use --synthetic")

    # Data (ref :332). Process 0 prepares first (it may extract an archive on
    # a shared filesystem); others wait at the barrier, then read — the exact
    # rank-0-download + barrier gating of the reference (ref :103-112).
    if is_lm:
        from distributed_pytorch_training_tpu.data.text import (
            TokenLoader, get_token_dataset,
        )

        seq_len = resolved_seq

        def _load_datasets():
            train_ds = get_token_dataset(family, seq_len, args.data_dir,
                                         train=True,
                                         synthetic_size=args.synthetic_size,
                                         seed=args.seed)
            val_ds = get_token_dataset(family, seq_len, args.data_dir,
                                       train=False,
                                       synthetic_size=(args.synthetic_size or 0) // 5 or None,
                                       seed=args.seed)
            return train_ds, val_ds
    else:
        def _load_datasets():
            # download only on process 0 (ref `download=(rank==0)`, :106);
            # non-main processes reach here after the barrier, files on disk
            train_ds = get_dataset(args.dataset, args.data_dir, train=True,
                                   synthetic=args.synthetic,
                                   synthetic_size=args.synthetic_size, seed=args.seed,
                                   download=args.download and ctx.is_main)
            val_ds = get_dataset(args.dataset, args.data_dir, train=False,
                                 synthetic=args.synthetic or train_ds.synthetic,
                                 synthetic_size=(args.synthetic_size or 0) // 5 or None,
                                 seed=args.seed)
            return train_ds, val_ds

    if ctx.is_main:
        train_ds, val_ds = _load_datasets()
        barrier("data_ready")
    else:
        barrier("data_ready")
        train_ds, val_ds = _load_datasets()
    if train_ds.synthetic:
        log_main(f"NOTE: using synthetic data ({train_ds.name}, n={len(train_ds)})")

    # Loaders + model + task (ref :131-148, :335-338).
    pipelined = False
    if is_lm:
        from distributed_pytorch_training_tpu.training.tasks import (
            LanguageModelingTask, MaskedLMTask, MoeLanguageModelingTask,
        )

        train_loader = TokenLoader(train_ds, mesh, args.batch_size, shuffle=True,
                                   seed=args.seed, drop_last=args.drop_last,
                                   fault_hook=(chaos.on_loader_batch
                                               if chaos else None))
        val_loader = TokenLoader(val_ds, mesh, args.batch_size, shuffle=False,
                                 seed=args.seed)
        lm_kwargs = dict(dtype=compute_dtype, remat=args.remat)
        if mesh.shape["model"] > 1:
            # Megatron-style vocab padding: GPT-2's 50257 (and BERT's 30522
            # beyond model=2) is indivisible by TP degrees, so without this
            # the (vocab, d) embedding — the largest param — would silently
            # replicate over `model` (VERDICT r4 weak #4). lcm(128, tp) keeps
            # the padded vocab lane-aligned AND divisible by the TP degree.
            import math

            lm_kwargs["pad_vocab_to_multiple_of"] = math.lcm(
                128, mesh.shape["model"])
        lm_kwargs.update(overrides)
        if attention != "xla":
            if family == "bert" and attention in ("ring", "ulysses"):
                raise ValueError("--attention ring/ulysses is causal-only; "
                                 "bert_base uses the XLA or flash path")
            if attention == "flash":
                from distributed_pytorch_training_tpu.ops import (
                    make_flash_attention_fn,
                )
                # BERT is bidirectional: flash with causal=False. Legal
                # because MaskedLMTask feeds no padding mask (the kernel
                # path owns the attention structure).
                lm_kwargs["attention_fn"] = make_flash_attention_fn(
                    causal=family != "bert")
            elif attention == "ulysses":
                from distributed_pytorch_training_tpu.ops import (
                    make_ulysses_attention_fn,
                )
                lm_kwargs["attention_fn"] = make_ulysses_attention_fn(
                    mesh, causal=True)
            else:  # ring
                from distributed_pytorch_training_tpu.ops import (
                    make_ring_attention_fn,
                )
                lm_kwargs["attention_fn"] = make_ring_attention_fn(
                    mesh, causal=True)
        n_pipe = mesh.shape["pipe"]
        if n_pipe > 1 and family == "gpt2" and "moe" not in args.model:
            # GPipe path: blocks stage-stacked over the `pipe` axis
            # (models/gpt2_pipe.py). Attention runs inside the stages via
            # the XLA path; kernel attention is a per-stage concern.
            if attention != "xla":
                raise ValueError("--mesh pipe>1 uses the XLA attention path "
                                 "inside pipeline stages; drop --attention")
            from distributed_pytorch_training_tpu.models.gpt2_pipe import (
                GPT2PipeLMHead,
            )

            pipelined = True
            # config holder for the named size (+ any CLI shrink overrides)
            cfg = get_model(args.model, **overrides)
            pipe_kwargs = dict(
                mesh=mesh, num_microbatches=args.microbatches,
                vocab_size=cfg.vocab_size, hidden_dim=cfg.hidden_dim,
                depth=cfg.depth, num_heads=cfg.num_heads,
                max_position=max(cfg.max_position, seq_len),
                dtype=compute_dtype, remat=args.remat)
            # overrides of pipe-model fields beyond the explicit list above
            # (e.g. layernorm_epsilon) must not be silently dropped
            import dataclasses as _dc

            pipe_fields = {f.name for f in _dc.fields(GPT2PipeLMHead)}
            pipe_kwargs.update({k: v for k, v in overrides.items()
                                if k in pipe_fields and k not in pipe_kwargs})
            model = GPT2PipeLMHead(**pipe_kwargs)
        else:
            model = get_model(args.model, **lm_kwargs)
        model_vocab = getattr(model, "vocab_size", None)
        if model_vocab and model_vocab < train_ds.vocab_size:
            # A model vocab shrunk below the dataset's stamped vocab can
            # index past the embedding, and out-of-range jnp gathers fill
            # with NaN instead of raising — a run that trains straight to
            # NaN loss with no hint. Scan the ids actually present (only in
            # this override case — the scan is the price of the shrink, not
            # of every startup): a byte-tokenized corpus loads under the
            # gpt2 stamp (50257) yet only uses ids < 256, which is fine.
            for split_ds, split in ((train_ds, "train"), (val_ds, "val")):
                max_id = int(split_ds.tokens.max()) if len(split_ds) else -1
                if max_id >= model_vocab:
                    raise ValueError(
                        f"{split} dataset {split_ds.name} contains token id "
                        f"{max_id}, which exceeds the model's vocab_size "
                        f"({model_vocab}): such ids index past the "
                        "embedding, which JAX fills with NaN. Align "
                        "--model-overrides vocab_size with the data (byte "
                        f"corpora: 256; full {family} tokens: "
                        f"{train_ds.vocab_size}).")
        if family == "bert":
            # The masking recipe samples replacement ids and inserts [MASK]:
            # both must stay inside the (possibly shrunk) embedding, or the
            # task itself manufactures the out-of-range ids the guard above
            # just excluded from the data.
            bert_vocab = min(model_vocab or train_ds.vocab_size,
                             train_ds.vocab_size)
            task = MaskedLMTask(vocab_size=bert_vocab,
                                compute_dtype=compute_dtype)
            if task.mask_token_id >= bert_vocab:
                raise ValueError(
                    f"vocab_size {bert_vocab} does not contain the [MASK] "
                    f"token id {task.mask_token_id}; use a vocab of at "
                    f"least {task.mask_token_id + 1}")
        elif "moe" in args.model:
            # MoE models add the Switch router load-balancing loss
            task = MoeLanguageModelingTask(compute_dtype=compute_dtype)
        else:
            task = LanguageModelingTask(compute_dtype=compute_dtype)
        sample_input = np.zeros((1, seq_len), np.int32)
    else:
        train_loader = ShardedLoader(train_ds, mesh, args.batch_size, shuffle=True,
                                     seed=args.seed, drop_last=args.drop_last,
                                     prefetch=max(2, args.workers // 2),
                                     fault_hook=(chaos.on_loader_batch
                                                 if chaos else None))
        val_loader = ShardedLoader(val_ds, mesh, args.batch_size, shuffle=False,
                                   seed=args.seed, prefetch=2)
        mean, std = IMAGE_STATS[args.dataset.lower()]
        model_kwargs = dict(num_classes=train_ds.num_classes, dtype=compute_dtype)
        model_kwargs.update(overrides)
        if args.model.startswith("resnet"):
            # explicit --model-overrides wins over the dedicated flag
            model_kwargs.setdefault("cifar_stem", args.cifar_stem)
            if args.remat:
                raise ValueError("--remat applies to transformer models "
                                 "(vit/bert/gpt2); ResNets are activation-light")
        elif args.remat:
            model_kwargs["remat"] = True
        model = get_model(args.model, **model_kwargs)
        task = ImageClassificationTask(mean=mean, std=std,
                                       augment=not args.no_augment,
                                       compute_dtype=compute_dtype)
        h, w = train_ds.images.shape[1:3]
        sample_input = np.zeros((1, h, w, 3), np.float32)

    # Optimizer (ref :339-344; schedule is an extension, ref is constant-LR).
    steps_per_epoch = len(train_loader)
    schedule = make_schedule(args.schedule, args.lr,
                             total_steps=steps_per_epoch * args.epochs,
                             warmup_steps=args.warmup_steps)
    from distributed_pytorch_training_tpu.parallel.mesh import BATCH_AXES

    # zero1/fsdp on a single batch shard run the replicated (non-shard_map)
    # update, where a shard-axes psum would hit unbound axis names — the
    # clip's shard awareness must follow the same passthrough condition.
    # The zero1 x model-axis composition runs the GSPMD update on GLOBAL
    # flat arrays (training/loop.py), so its clip stays stock too.
    model_axis = mesh.shape.get("model", 1) > 1
    # Explicit TP x FSDP (ISSUE 13): the update shards over
    # (model,) + batch axes — the clip's norm psum must ride all three,
    # with model-replicated leaves down-weighted 1/M (they are stored once
    # per model shard; parallel/sharding.tp_clip_weights).
    explicit_tp = args.fsdp_explicit and model_axis
    sharded_update = ((args.zero1 and not model_axis) or args.fsdp_explicit) \
        and (n_batch_shards > 1 or explicit_tp)
    shard_axes = None
    clip_weights = None
    rules = (type(model).partition_rules()
             if hasattr(type(model), "partition_rules") else None)
    if sharded_update:
        from distributed_pytorch_training_tpu.parallel.mesh import MODEL
        shard_axes = ((MODEL,) + BATCH_AXES) if explicit_tp else BATCH_AXES
    if explicit_tp and rules is not None:
        from distributed_pytorch_training_tpu.parallel.sharding import (
            tp_clip_weights_for_model,
        )
        clip_weights = tp_clip_weights_for_model(
            model, rules, mesh.shape["model"],
            np.zeros((mesh.shape["model"],) + tuple(sample_input.shape[1:]),
                     np.asarray(sample_input).dtype))
    tx = make_optimizer(args.optimizer, schedule, momentum=args.momentum,
                        weight_decay=args.weight_decay,
                        shard_axes=shard_axes,
                        clip_leaf_weights=clip_weights)
    # Refuse silently-wasted devices: every mesh axis > 1 must be one the
    # selected model/attention combination can actually use.
    validate_mesh_usage(mesh, rules=rules,
                        attention=attention if is_lm else "xla",
                        is_moe="moe" in args.model, pipelined=pipelined)

    trainer = Trainer(task, mesh,
                      TrainConfig(per_device_batch=args.batch_size,
                                  print_freq=args.print_freq, seed=args.seed,
                                  bf16=args.amp, grad_accum=args.grad_accum,
                                  zero1=args.zero1,
                                  fsdp_explicit=args.fsdp_explicit,
                                  bucket_cap_mb=args.bucket_cap_mb,
                                  wire_dtype=args.wire_dtype,
                                  slice_axis=args.slice_axis,
                                  overlap_grad_sync=not
                                  args.no_overlap_grad_sync,
                                  fused_quantize={"auto": None, "on": True,
                                                  "off": False}[
                                                      args.fused_quantize]),
                      rules=rules)
    if explicit_tp:
        log_main(f"TP x FSDP (explicit): megatron tensor parallelism over "
                 f"model={mesh.shape['model']} inside the FSDP shard_map "
                 f"(one psum per residual join); params + moments "
                 f"flat-sharded 1/{n_batch_shards * mesh.shape['model']} "
                 "at rest for TP-split tensors; per-layer gathers/scatters "
                 "ride the data axes over each shard's 1/"
                 f"{mesh.shape['model']} slice"
                 + (f"; {args.wire_dtype} wire" if args.wire_dtype != "fp32"
                    else ""))
    elif args.fsdp_explicit and n_batch_shards > 1:
        log_main(f"FSDP (explicit): params + moments flat-sharded "
                 f"{n_batch_shards}-way at rest; per-layer just-in-time "
                 "param gathers, gradients reduce-scattered into the shard "
                 "layout"
                 + (f"; {args.wire_dtype} wire" if args.wire_dtype != "fp32"
                    else ""))
    elif args.zero1 and n_batch_shards > 1:
        log_main(f"ZeRO-1: weight update sharded {n_batch_shards}-way over "
                 "the batch axes ("
                 + ("per-leaf GSPMD update — model-axis mesh"
                    if trainer._zero1_gspmd else
                    "reduce-scatter grads -> 1/N optimizer update -> "
                    "all-gather params")
                 + (f"; {args.wire_dtype} gradient wire"
                    if args.wire_dtype != "fp32" else "") + ")")
    elif trainer._grad_sync:
        log_main(f"Gradient sync: explicit bucketed reducer over "
                 f"{n_batch_shards} shards — bucket_cap_mb="
                 f"{args.bucket_cap_mb or 'inf (one bucket)'}, "
                 f"wire={args.wire_dtype}, overlap="
                 f"{'off' if args.no_overlap_grad_sync else 'on'}")
    if trainer._hier is not None:
        h = trainer._hier
        log_main(f"Two-tier wire (int8_hier): {h.n_slices} slices x "
                 f"{h.n_inner} replicas/slice — exact fp32 reduce-scatter "
                 f"inside the slice, s8+EF exchange across "
                 f"{h.slice_axis!r} (~2 B/element per slice on the slow "
                 "tier, slice-count independent)")

    if not args.no_telemetry:
        # anomaly watchdog fed by train_epoch's host-side timings + the
        # print-boundary losses; abort hook off unless asked (with
        # --max-restarts an abort is a restartable failure: restore+replay).
        # Detector knobs honor DPT_WATCHDOG_* env overrides — how an
        # orchestrator tunes warm-up/floors on children it cannot pass
        # flags to (the fleet's anomaly-capture story on short runs).
        from distributed_pytorch_training_tpu.telemetry.watchdog import (
            kwargs_from_env,
        )
        trainer.watchdog = telemetry.AnomalyWatchdog(
            abort=args.telemetry_abort, **kwargs_from_env())

    state = trainer.init_state(model, sample_input, tx,
                               jax.random.PRNGKey(args.seed))
    n_params = state.param_count()
    if trainer._fsdp and trainer._fsdp_template is not None:
        # report the model-shaped count, not the flat-padded at-rest sizes
        n_params = sum(
            int(np.prod(t.shape) or 1) for t in
            jax.tree_util.tree_leaves(trainer._fsdp_template))
    pad_extra = getattr(model, "vocab_pad_params", 0)
    if pad_extra:
        # Report the HF-exact count; padding rows are a TP layout artifact.
        log_main(f"Model {args.model}: {n_params - pad_extra:,} params "
                 f"(+{pad_extra:,} vocab-pad rows for TP)")
    else:
        log_main(f"Model {args.model}: {n_params:,} params")
    if trainer._grad_sync:
        from distributed_pytorch_training_tpu.parallel.grad_sync import (
            build_bucket_plan,
        )
        plan = build_bucket_plan(state.params, args.bucket_cap_mb)
        log_main(f"Gradient sync: {plan.n_buckets} bucket(s) over "
                 f"{plan.total_bytes / 2 ** 20:.1f} MB of fp32 gradient")
    if trainer._fsdp and trainer._fsdp_plan is not None:
        lp = trainer._fsdp_plan
        mb = lp.total_padded * 4 / 2 ** 20
        log_main(f"FSDP plan: {len(lp.groups)} layer gather group(s), "
                 f"{mb:.1f} MB padded fp32 params "
                 f"({mb / n_batch_shards:.1f} MB/replica at rest)")
    if telemetry.is_configured() and n_batch_shards > 1 and not args.zero1:
        # setup-time wire accounting counters (grad_sync/FSDP plans) —
        # the per-tier byte substrate `telemetry summary` reports.
        # zero1's split wire (compressed scatter + exact param gather) is
        # outside wire_bytes_for_config's conventions — omitted, exactly
        # as the bench harness omits it
        from distributed_pytorch_training_tpu.parallel.grad_sync import (
            emit_wire_accounting,
        )
        # fsdp states hold flat-sharded leaves; their padded totals match
        # the model-shaped ones (the harness records them the same way).
        # Explicit TP: the data-axis terms come from the TP-LOCAL template
        # (each model shard gathers/scatters its slice only — the 1/M
        # reduction), and the model-axis psum bytes land in their own
        # tier row (axis="model") so `telemetry summary` splits them.
        acct_params, acct_cfg = trainer.wire_accounting_inputs(
            state, dict(wire_dtype=args.wire_dtype,
                        bucket_cap_mb=args.bucket_cap_mb,
                        fsdp_explicit=args.fsdp_explicit,
                        slices=(trainer._hier.n_slices
                                if trainer._hier is not None else 1)),
            global_batch, seq_len if is_lm else 0)
        emit_wire_accounting(acct_params, acct_cfg, n_batch_shards)

    # MFU in the step log (TPU only — needs a known chip peak): analytic
    # matmul/conv FLOPs of one train step, traced once on a peeked batch.
    from distributed_pytorch_training_tpu.experiments import flops as flops_mod

    peak = flops_mod.chip_peak_tflops(dev0)
    if peak:
        try:
            peek = next(iter(train_loader.epoch(0)))
            fwd = flops_mod.jaxpr_matmul_flops(
                lambda s, b: task.loss_and_metrics(
                    s, trainer._fsdp_unflatten(s.params) if trainer._fsdp
                    else s.params, b, jax.random.PRNGKey(0), train=True)[0],
                state, peek)
            trainer.set_mfu_reference(3.0 * fwd / global_batch,
                                      peak * 1e12 * mesh.size)
        except Exception as e:  # MFU is a log nicety, never a crash
            log_main(f"NOTE: MFU logging disabled ({e})")

    # Checkpointing (extension; the reference has none — SURVEY.md §5).
    # Step-granular: labels are epoch * steps_per_epoch + step, so a
    # mid-epoch preemption save sorts between the epoch boundaries and
    # resume continues at that exact step (deterministic sampler).
    ckpt = None
    start_epoch = start_step = 0
    if args.checkpoint_dir:
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )
        ckpt = CheckpointManager(
            args.checkpoint_dir,
            post_save_hook=chaos.on_save if chaos else None,
            pre_finalize_hook=chaos.on_save_finalize if chaos else None)
        if args.resume:
            from distributed_pytorch_training_tpu.training.checkpoint import (
                CheckpointWorldSizeMismatch,
            )
            try:
                restored = ckpt.restore_latest(
                    state, template_world_size=n_batch_shards)
            except CheckpointWorldSizeMismatch as mismatch:
                # Cross-PROCESS elastic resume (ISSUE 12): a fleet
                # relaunch at a different world size lands here — the
                # flat-padded layouts (zero1 moments, fsdp params, EF
                # residuals) changed shape with the DP degree. Restore
                # the newest valid checkpoint RAW (its own saved shapes
                # are the old-world template; this process cannot build
                # device templates for a mesh it doesn't have) and
                # reshard the host arrays into this run's layout. The
                # named error escapes only when there is genuinely
                # nothing reshardable (no valid checkpoint / no recorded
                # world — a foreign directory, not an elastic relaunch).
                known = getattr(mismatch, "label", None)
                raw = ckpt.restore_latest_raw(
                    among=None if known is None else {known})
                if raw is None or raw[2] is None:
                    raise
                from distributed_pytorch_training_tpu.resilience.elastic \
                    import reshard_raw_state
                arrays, label, saved_world, r_epoch, r_step = raw
                with telemetry.span("elastic_reshard",
                                    from_world=saved_world,
                                    to_world=n_batch_shards, label=label,
                                    cross_process=True):
                    state = reshard_raw_state(arrays, saved_world,
                                              n_batch_shards, trainer,
                                              state)
                restored = (state, r_epoch, r_step)
                log_main(f"ELASTIC RESUME: checkpoint {label} was laid "
                         f"out for world size {saved_world}; resharded "
                         f"to {n_batch_shards} (flat-padded re-slice + "
                         "EF row fold — sampler/step-fence/RNG schedule "
                         "unchanged)")
            except Exception as e:
                # Param SHAPES depend on the TP layout (vocab padding is
                # lcm(128, model-axis)): resuming under a different --mesh
                # builds a mismatched template and orbax fails opaquely.
                # Diagnose precisely from the saved shape metadata.
                hint = ("resume with the SAME --mesh, --zero1 and "
                        "--fsdp-explicit settings (vocab padding for TP "
                        "follows the model axis; zero1 stores optimizer "
                        "state flat-sharded, fsdp-explicit stores params "
                        "flat-sharded too, the replicated path stores "
                        "both param-shaped)")
                try:
                    meta = ckpt.latest_metadata()
                    saved_params = meta["params"] if meta else {}
                    for emb_name in ("wte", "token_embedding"):
                        if emb_name in saved_params:
                            saved_rows = saved_params[emb_name][
                                "embedding"].shape[0]
                            have = getattr(model, "padded_vocab",
                                           getattr(model, "vocab_size", "?"))
                            if saved_rows != have:
                                hint = (
                                    f"the checkpoint's {emb_name} has "
                                    f"{saved_rows} vocab rows but this run "
                                    f"built {have} — pass --model-overrides "
                                    f"pad_vocab_to_multiple_of=<m> (or the "
                                    f"original --mesh) so the padded vocab "
                                    f"matches {saved_rows}")
                except Exception:
                    pass  # metadata diagnosis is best-effort only
                raise RuntimeError(
                    f"checkpoint restore failed — {hint}: {e}") from e
            if restored is not None:
                state, start_epoch, start_step = restored
                if start_step >= steps_per_epoch:  # stale steps_per_epoch
                    start_epoch, start_step = start_epoch + 1, 0
                log_main(f"Resumed from epoch {start_epoch}"
                         + (f" step {start_step}" if start_step else ""))

    csv = MetricsCSV(args.output_dir)  # ref :349-354

    if args.max_restarts > 0:
        # Restart supervisor (resilience/supervisor.py): segments the epoch
        # loop, checkpoints every epoch, and on a step/save failure restores
        # the latest VALID checkpoint and replays behind the step fence.
        # Validation + the CSV row run per completed epoch via the callback
        # (identical stdout/CSV contract). --profile-dir and
        # --checkpoint-every are not threaded through the supervised loop
        # (it owns the save cadence); preemption drains exactly like the
        # plain loop: checkpoint + stop, relaunch resumes with --resume.
        if args.profile_dir:
            log_main("NOTE: --profile-dir is ignored under --max-restarts")
        from distributed_pytorch_training_tpu.resilience.supervisor import (
            RetryPolicy, Supervisor,
        )

        def state_factory():
            return trainer.init_state(model, sample_input, tx,
                                      jax.random.PRNGKey(args.seed))

        def epoch_end(epoch, st, train_loss, train_acc, epoch_time):
            val_loss, val_acc = trainer.evaluate(st, val_loader.epoch(0))
            log_main(
                f"[Epoch {epoch + 1}/{args.epochs}] "
                f"Train: loss={train_loss:.4f}, acc={train_acc:.2f}% | "
                f"Val: loss={val_loss:.4f}, acc={val_acc:.2f}% | "
                f"Epoch time: {epoch_time:.2f}s"
            )
            csv.append(epoch, train_loss, train_acc, val_loss, val_acc,
                       epoch_time)

        # Control-plane autopilot (ISSUE 20): constructed ONLY under
        # --autopilot — off means no object, no observer, no threads, and
        # a recorder stream/HLO byte-identical to a build without the
        # control package. Eviction decisions on this fixed-world
        # supervisor are refused by the re-plan surface (no replan_cb)
        # and logged as `refuse` records — the audit trail still shows
        # what the policy wanted; the chaos harness proves the applied
        # path on its elastic rig.
        autopilot = None
        retune_cb = None
        if args.autopilot:
            from distributed_pytorch_training_tpu.control import (
                Autopilot, PerfTuner,
            )
            if args.autopilot_tune:
                import dataclasses as _dc

                from distributed_pytorch_training_tpu.resilience.elastic \
                    import ElasticPlan

                def retune_cb(overrides):
                    # same world, same loader, same optimizer — only the
                    # TrainConfig re-plans; boundary_retune carries every
                    # state leaf the new config keeps the layout of
                    new_trainer = Trainer(
                        task, mesh,
                        _dc.replace(trainer.config, **overrides),
                        rules=rules)
                    return ElasticPlan(
                        trainer=new_trainer, loader=train_loader,
                        state_factory=lambda: new_trainer.init_state(
                            model, sample_input, tx,
                            jax.random.PRNGKey(args.seed)),
                        world=new_trainer.batch_shards)
            autopilot = Autopilot(
                tuner=PerfTuner() if args.autopilot_tune else None
            ).attach()

        # trust_existing=args.resume: a fresh run pointed at a directory
        # holding a previous run's checkpoints must never restore one
        # mid-recovery (only --resume opts into the directory's history)
        sup = Supervisor(trainer, ckpt, state_factory, train_loader,
                         retry=RetryPolicy(max_restarts=args.max_restarts),
                         guard=guard, injector=chaos,
                         trust_existing=args.resume,
                         epoch_end_cb=epoch_end, deathwatch=relay_watch,
                         control=autopilot, retune_cb=retune_cb)
        try:
            state, report = sup.run(args.epochs,
                                    initial=(state, start_epoch,
                                             start_step))
        finally:
            if autopilot is not None:
                autopilot.detach()
        if autopilot is not None and autopilot.decisions:
            acts = ", ".join(f"{d.action}"
                             + ("[applied]" if d.applied else "")
                             for d in autopilot.decisions)
            log_main(f"Autopilot: {len(autopilot.decisions)} control "
                     f"decision(s): {acts}")
        log_main(f"Supervisor: completed={report.completed} "
                 f"restarts={report.restarts} "
                 f"steps_replayed={report.steps_replayed} "
                 f"torn_checkpoints_skipped={report.checkpoints_skipped}"
                 + (f" faults_fired={report.faults_fired}"
                    if report.faults_fired else ""))
        ckpt.wait()
        _log_save_blocked(ckpt)
        ckpt.close()
        cleanup_distributed()  # ref :386
        guard.disarm()
        if report.relay_death:
            # the Supervisor already checkpointed-and-flushed; exit with
            # the deathwatch's contract code so outer watchdogs key their
            # crash-salvage branch exactly as for the lethal watch
            sys.exit(DEATHWATCH_EXIT_CODE)
        return

    # The device-time attribution plane (ISSUE 15): a re-armable
    # StepProfiler exists whenever --profile-dir names a static window OR
    # the live /metrics surface is up (captures then land under
    # <output-dir>/profiles). Armed three ways: the static
    # --profile-steps window, POST /profile?steps=K on the metrics port,
    # and the watchdog's anomaly capture hook (a step-time spike /
    # loader stall records its own trace while it happens). Every closed
    # window is ingested by telemetry/device.py into a typed
    # device_profile event — per-phase device ms, per-collective rollup,
    # exposed-comm ratio, measured MFU. With both surfaces off, no
    # profiler object exists and the loop's step_hook stays None — the
    # zero-per-step-cost contract (pinned by test) is structural.
    profiler = None
    profile_base = args.profile_dir
    if profile_base is None and metrics_port and telemetry.is_configured():
        profile_base = str(Path(args.output_dir) / "profiles")
    if profile_base is not None:
        from distributed_pytorch_training_tpu.telemetry import (
            device as tele_device,
        )
        from distributed_pytorch_training_tpu.utils.profiling import (
            StepProfiler,
        )

        start = stop = None
        if args.profile_dir:
            start, stop = (int(x) for x in args.profile_steps.split(","))

        def _mfu_ref():
            # lazily read: set_mfu_reference runs after this closure is
            # built, and only on backends with a known chip peak
            if trainer._flops_per_sample and trainer._peak_flops_total:
                return (trainer._flops_per_sample * global_batch,
                        trainer._peak_flops_total)
            return None

        profiler = StepProfiler(
            profile_base, start, stop,
            on_capture=tele_device.make_ingestor(mfu_ref=_mfu_ref))
        server = (telemetry.get_metrics_server()
                  if metrics_port and telemetry.is_configured() else None)
        if server is not None:
            server.profile_handler = profiler.request_capture
        if trainer.watchdog is not None:
            trainer.watchdog.capture_hook = (
                lambda name, step: profiler.request_capture(
                    2, reason=f"anomaly:{name}", trigger_step=step))
        log_main(f"Profiler: on-demand capture armed (traces under "
                 f"{profile_base}"
                 + (f"; static window steps {start}-{stop}"
                    if start is not None else "") + ")")

    # Context-managed: an exception (or preemption-path raise) mid-epoch
    # must still stop an open jax.profiler session — a leaked session
    # fails every later start_trace in the process and loses the trace.
    import contextlib

    with profiler if profiler is not None else contextlib.nullcontext():
        for epoch in range(start_epoch, args.epochs):  # ref :356
            counts = samples_per_step_list(len(train_ds), global_batch,
                                           steps_per_epoch, args.drop_last)
            fault_hook = None
            if chaos is not None:
                # absolute global-step fence for crash/sigterm injections
                base = epoch * steps_per_epoch + start_step
                fault_hook = (lambda i, _base=base: chaos.on_step(_base + i))
            state, train_loss, train_acc, epoch_time, steps_done = \
                trainer.train_epoch(
                    state, train_loader.epoch(epoch, start_step=start_step),
                    epoch, steps_per_epoch,
                    samples_per_step=counts[start_step:], step_hook=profiler,
                    start_step=start_step,
                    stop_fn=lambda: guard.should_stop,
                    fault_hook=fault_hook)
            abs_step = start_step + steps_done
            start_step = 0

            if guard.should_stop and abs_step < steps_per_epoch:
                # Preempted MID-epoch: persist (epoch, step) immediately — a
                # resume replays nothing (the r3 story lost up to an epoch,
                # VERDICT r3 #5). No CSV row: the epoch is incomplete.
                telemetry.flush_flight(
                    cause=f"preemption (sigterm) drained at epoch {epoch} "
                          f"step {abs_step}", rc=0)
                if ckpt:
                    ckpt.save(epoch * steps_per_epoch + abs_step, state,
                              wait=True, epoch=epoch, step_in_epoch=abs_step,
                              world_size=n_batch_shards)
                    log_main(f"Preempted: checkpointed epoch {epoch} step "
                             f"{abs_step}/{steps_per_epoch}; relaunch with "
                             "--resume to continue mid-epoch")
                else:
                    log_main("Preempted: stopping (no --checkpoint-dir, "
                             "nothing persisted beyond the metrics CSV)")
                break

            val_loss, val_acc = trainer.evaluate(state, val_loader.epoch(0))

            # Epoch summary + CSV row (ref :373-384, formats identical).
            log_main(
                f"[Epoch {epoch + 1}/{args.epochs}] "
                f"Train: loss={train_loss:.4f}, acc={train_acc:.2f}% | "
                f"Val: loss={val_loss:.4f}, acc={val_acc:.2f}% | "
                f"Epoch time: {epoch_time:.2f}s"
            )
            csv.append(epoch, train_loss, train_acc, val_loss, val_acc, epoch_time)
            if telemetry.is_configured() and \
                    jax.tree_util.tree_leaves(state.grad_sync):
                # int8-wire error-feedback health: the carried residual's
                # global norm (epoch boundary — a host fetch happens here
                # anyway). A norm that grows without bound means the
                # telescoping sum stopped telescoping.
                sq = sum(float(jnp.vdot(r.astype(jnp.float32),
                                        r.astype(jnp.float32)))
                         for r in jax.tree_util.tree_leaves(state.grad_sync))
                telemetry.gauge("ef_residual_norm", float(np.sqrt(sq)),
                                epoch=epoch)

            if ckpt and (epoch + 1) % args.checkpoint_every == 0:
                ckpt.save((epoch + 1) * steps_per_epoch, state, epoch=epoch + 1,
                          world_size=n_batch_shards)

            if guard.should_stop:
                telemetry.flush_flight(
                    cause=f"preemption (sigterm) drained at epoch boundary "
                          f"{epoch + 1}", rc=0)
                if ckpt:
                    if (epoch + 1) % args.checkpoint_every != 0:  # not saved above
                        ckpt.save((epoch + 1) * steps_per_epoch, state,
                                  epoch=epoch + 1,
                                  world_size=n_batch_shards)
                    ckpt.wait()
                    log_main(f"Preempted: checkpointed epoch {epoch + 1}; "
                             "relaunch with --resume to continue")
                else:
                    log_main("Preempted: stopping (no --checkpoint-dir, "
                             "nothing persisted beyond the metrics CSV)")
                break

    if ckpt:
        ckpt.wait()  # finalize async writes before exit
        _log_save_blocked(ckpt)
        ckpt.close()
    cleanup_distributed()  # ref :386
    # Only now is it safe to cancel the hard-exit deadline: a preempted
    # multi-host cleanup can itself wedge on a dead peer, and a lingering
    # process would hold its device claim — the scenario the deadline exists
    # to prevent.
    guard.disarm()


if __name__ == "__main__":
    main()
