"""telemetry/ (ISSUE 8): the recorder's JSONL+ring contract, the flight
recorder's crash artifacts, the anomaly watchdog's detections (and its
chaos-tested abort hook under the restart Supervisor), the CLI summary's
self-consistency on a real 20-step CPU-mesh run, and the PARITY guarantee
that telemetry-on vs telemetry-off lowers to IDENTICAL HLO.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import numpy as np
import pytest

from distributed_pytorch_training_tpu import telemetry
from distributed_pytorch_training_tpu.telemetry.__main__ import (
    main as telemetry_main, read_stream, summarize, to_perfetto,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_global_recorder():
    """No test leaks a configured recorder into the next (the global is
    process-wide by design)."""
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# Recorder
# ---------------------------------------------------------------------------


class TestRecorder:
    def test_jsonl_schema_and_ring(self, tmp_path):
        p = tmp_path / "t.jsonl"
        rec = telemetry.configure(str(p), ring_size=4)
        rec.counter("c", 1.5, tag="x")
        rec.gauge("g", 7)
        with rec.span("data_wait", step=0):
            pass
        rec.close()
        events, bad = read_stream(str(p))
        assert bad == 0
        # first line is the meta header with the schema version
        assert events[0]["kind"] == "meta"
        assert events[0]["schema"] == telemetry.SCHEMA_VERSION
        kinds = [e["kind"] for e in events]
        assert kinds == ["meta", "counter", "gauge", "span"]
        span = events[-1]
        assert span["name"] == "data_wait" and "dur_ms" in span \
            and "t0" in span and span["step"] == 0
        # every event carries the version stamp + a wall timestamp
        assert all(e["v"] == telemetry.SCHEMA_VERSION and "ts" in e
                   for e in events)

    def test_ring_is_bounded(self):
        rec = telemetry.Recorder(None, ring_size=8)
        for i in range(100):
            rec.counter("n", i)
        assert len(rec.ring) == 8
        assert rec.ring[-1]["value"] == 99  # newest survives

    def test_helpers_noop_when_unconfigured(self):
        assert telemetry.get() is None
        telemetry.counter("x", 1)  # must not raise
        telemetry.gauge("x", 1)
        telemetry.span_event("x", 0.1)
        with telemetry.span("x"):
            pass
        assert telemetry.get() is None

    def test_emit_survives_closed_handle(self, tmp_path):
        """A dying disk/handle must never take the training run down."""
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        rec._fh.close()  # simulate the handle dying under us
        rec.counter("after", 1)  # must not raise
        assert rec.ring[-1]["name"] == "after"  # ring still records


class TestRankIdentity:
    """ISSUE 14: per-rank streams. The fleet env stamps win, the caller's
    process index is the fallback, every event carries gen/rank, and the
    DEFAULT gating still writes only telemetry_rank0.jsonl."""

    def test_env_stamps_win_and_events_carry_them(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv(telemetry.FLEET_GENERATION_ENV, "2")
        monkeypatch.setenv(telemetry.FLEET_RANK_ENV, "3")
        assert telemetry.rank_identity(process_index=7) == 3  # env wins
        assert telemetry.generation_identity() == 2
        rec = telemetry.configure(
            str(tmp_path / telemetry.stream_filename(3)))
        rec.counter("c", 1)
        telemetry.reset()
        events, _ = read_stream(
            str(tmp_path / "telemetry_rank3.jsonl"))
        assert all(e["gen"] == 2 and e["rank"] == 3 for e in events)
        assert events[0]["schema"] == telemetry.SCHEMA_VERSION == 2

    def test_process_index_fallback_outside_a_fleet(self, monkeypatch):
        monkeypatch.delenv(telemetry.FLEET_RANK_ENV, raising=False)
        monkeypatch.delenv(telemetry.FLEET_GENERATION_ENV, raising=False)
        assert telemetry.rank_identity(process_index=5) == 5
        assert telemetry.rank_identity() == 0
        assert telemetry.generation_identity() == 0

    def test_default_gating_is_rank0_only(self, monkeypatch):
        """The disk-cost contract: without the opt-in, only rank 0
        streams — a default run still writes ONE telemetry_rank0.jsonl."""
        monkeypatch.delenv(telemetry.ALL_RANKS_ENV, raising=False)
        assert telemetry.should_stream(0)
        assert not telemetry.should_stream(1)
        assert not telemetry.should_stream(7)
        # the flag OR the env arms every rank
        assert telemetry.should_stream(1, all_ranks=True)
        monkeypatch.setenv(telemetry.ALL_RANKS_ENV, "1")
        assert telemetry.should_stream(7)
        monkeypatch.setenv(telemetry.ALL_RANKS_ENV, "0")
        assert not telemetry.should_stream(7)

    def test_stream_filename_keeps_rank0_name(self):
        assert telemetry.stream_filename(0) == "telemetry_rank0.jsonl"
        assert telemetry.stream_filename(4) == "telemetry_rank4.jsonl"

    def test_v1_stream_still_reads(self, tmp_path):
        """The schema bump's reader contract: a v1 stream (no gen/rank
        stamps) still summarizes, and the aggregator normalizes it to
        gen 0 / rank 0."""
        from distributed_pytorch_training_tpu.telemetry.aggregate import (
            split_streams,
        )

        p = tmp_path / "v1.jsonl"
        with open(p, "w") as f:
            f.write(json.dumps({"v": 1, "ts": 1.0, "kind": "meta",
                                "name": "stream", "schema": 1,
                                "run_id": "old"}) + "\n")
            f.write(json.dumps({"v": 1, "ts": 1.1, "kind": "span",
                                "name": "step_dispatch", "t0": 1.0,
                                "dur_ms": 5.0, "step": 0}) + "\n")
            f.write(json.dumps({"v": 1, "ts": 1.2, "kind": "counter",
                                "name": "epoch_time_s",
                                "value": 0.01}) + "\n")
        events, bad = read_stream(str(p))
        assert bad == 0
        assert summarize(events)["spans"]["step_dispatch"]["count"] == 1
        (seg,) = split_streams([p])
        assert seg.key == (0, 0) and seg.run_id == "old"


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_flight_carries_ring_and_cause(self, tmp_path):
        telemetry.configure(str(tmp_path / "t.jsonl"), ring_size=16)
        for i in range(20):
            telemetry.counter("step", i)
        p = telemetry.flush_flight("FaultError: injected crash@step=3",
                                   detail="unit", rc=70)
        body = json.loads(Path(p).read_text())
        assert body["cause"] == "FaultError: injected crash@step=3"
        assert body["rc"] == 70
        # the ring's bound applies: last 16 of the 21 events (meta + 20)
        assert body["n_events"] == 16
        assert body["events"][-1]["value"] == 19
        # the exit record also landed in the stream itself
        events, _ = read_stream(str(tmp_path / "t.jsonl"))
        assert events[-1]["kind"] == "exit" \
            and events[-1]["flight_path"] == str(p)

    def test_flight_carries_fleet_generation_and_rank(self, tmp_path,
                                                      monkeypatch):
        """ISSUE-12 satellite: a fleet-orchestrated child's flights carry
        the launch generation + rank (from the env resilience/fleet.py
        stamps) both in the CAUSE — '[fleet gen=2 rank=0]', the first
        thing a reader sees — and as structured fields the fleet's flight
        accounting keys on."""
        from distributed_pytorch_training_tpu.telemetry.flight import (
            FLEET_GENERATION_ENV, FLEET_RANK_ENV,
        )

        monkeypatch.setenv(FLEET_GENERATION_ENV, "2")
        monkeypatch.setenv(FLEET_RANK_ENV, "0")
        p = telemetry.flush_flight("FaultError: injected crash@step=6",
                                   directory=str(tmp_path), rc=1)
        body = json.loads(Path(p).read_text())
        assert body["cause"] == ("FaultError: injected crash@step=6 "
                                 "[fleet gen=2 rank=0]")
        assert body["fleet_generation"] == "2"
        assert body["fleet_rank"] == "0"

    def test_flight_without_fleet_env_is_unstamped(self, tmp_path,
                                                   monkeypatch):
        from distributed_pytorch_training_tpu.telemetry.flight import (
            FLEET_GENERATION_ENV, FLEET_RANK_ENV,
        )

        monkeypatch.delenv(FLEET_GENERATION_ENV, raising=False)
        monkeypatch.delenv(FLEET_RANK_ENV, raising=False)
        p = telemetry.flush_flight("plain", directory=str(tmp_path))
        body = json.loads(Path(p).read_text())
        assert body["cause"] == "plain"
        assert "fleet_generation" not in body

    def test_two_flights_never_collide(self, tmp_path):
        telemetry.configure(str(tmp_path / "t.jsonl"))
        a = telemetry.flush_flight("one")
        b = telemetry.flush_flight("two")
        assert a != b and a.exists() and b.exists()

    def test_unconfigured_flight_is_none_unless_directory_given(
            self, tmp_path):
        assert telemetry.flush_flight("x") is None
        p = telemetry.flush_flight("x", directory=str(tmp_path))
        assert p is not None and json.loads(p.read_text())["cause"] == "x"

    def test_excepthook_flushes_before_traceback(self, tmp_path):
        """An unhandled exception leaves a postmortem (subprocess: the
        hook only fires on interpreter-level crashes)."""
        src = textwrap.dedent(f"""
            import sys; sys.path.insert(0, {str(REPO)!r})
            from distributed_pytorch_training_tpu import telemetry
            telemetry.configure({str(tmp_path / 't.jsonl')!r})
            telemetry.install_excepthook()
            telemetry.counter("ok", 1)
            raise RuntimeError("mid-run boom")
        """)
        r = subprocess.run([sys.executable, "-c", src],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 1
        flights = list(tmp_path.glob("flight_*.json"))
        assert len(flights) == 1
        body = json.loads(flights[0].read_text())
        assert "RuntimeError: mid-run boom" in body["cause"]
        assert any(e.get("name") == "ok" for e in body["events"])


# ---------------------------------------------------------------------------
# Anomaly watchdog
# ---------------------------------------------------------------------------


class TestAnomalyWatchdog:
    def test_spike_needs_warmup_then_fires(self):
        telemetry.configure(None)  # ring-only: anomalies land somewhere
        w = telemetry.AnomalyWatchdog(min_samples=5, spike_factor=5.0)
        for i in range(5):
            w.observe_step(i, 0.010, data_wait_s=0.001)
        w.observe_step(5, 0.012)  # 1.2x median: normal
        assert not w.anomalies
        w.observe_step(6, 0.100)  # 10x median: spike
        assert [a[0] for a in w.anomalies] == ["step_time_spike"]
        assert telemetry.get().ring[-1]["kind"] == "anomaly"

    def test_first_steps_never_judged(self):
        """Compile-dominated first steps must not self-report as spikes."""
        w = telemetry.AnomalyWatchdog(min_samples=10)
        w.observe_step(0, 60.0)   # the compile step
        w.observe_step(1, 0.01)
        assert not w.anomalies

    def test_loader_stall_needs_absolute_and_relative_bar(self):
        w = telemetry.AnomalyWatchdog(min_samples=3, stall_factor=10.0,
                                      stall_min_s=0.5)
        for i in range(4):
            w.observe_step(i, 0.01, data_wait_s=0.001)
        w.observe_step(4, 0.01, data_wait_s=0.3)   # 300x median but < 0.5s
        assert not w.anomalies
        w.observe_step(5, 0.01, data_wait_s=2.0)   # over both bars
        assert [a[0] for a in w.anomalies] == ["loader_stall"]

    def test_non_finite_loss(self):
        w = telemetry.AnomalyWatchdog()
        w.observe_loss(10, 2.5)
        assert not w.anomalies
        w.observe_loss(20, float("nan"))
        w.observe_loss(30, float("inf"))
        assert [a[0] for a in w.anomalies] == ["non_finite_loss"] * 2

    def test_abort_hook_raises(self):
        w = telemetry.AnomalyWatchdog(abort=True)
        with pytest.raises(telemetry.AnomalyAbort, match="non_finite_loss"):
            w.observe_loss(0, float("nan"))


# ---------------------------------------------------------------------------
# CLI: summary / tail / export
# ---------------------------------------------------------------------------


class TestCli:
    def _stream(self, tmp_path):
        p = tmp_path / "t.jsonl"
        rec = telemetry.configure(str(p))
        rec.span_event("data_wait", 0.010, step=0)
        rec.span_event("step_dispatch", 0.030, step=0)
        rec.span_event("save_blocked", 0.005, label=1)
        rec.counter("epoch_time_s", 0.050)
        rec.counter("samples", 256)
        rec.counter("wire_bytes_per_replica", 1024, tier="ici")
        rec.anomaly("loader_stall", step=3)
        telemetry.reset()
        return p

    def test_summary_split_is_self_consistent(self, tmp_path):
        events, _ = read_stream(str(self._stream(tmp_path)))
        s = summarize(events)
        # the split is computed against the stream's OWN recorded wall
        # total, and the phases sum (with the unaccounted remainder) to it
        assert s["totals"]["recorded_wall_ms"] == pytest.approx(50.0)
        acc = sum(v["total_ms"] for v in s["spans"].values())
        assert s["totals"]["accounted_span_ms"] == pytest.approx(acc)
        assert sum(s["step_split_pct"].values()) == pytest.approx(100.0,
                                                                  abs=0.1)
        assert s["throughput"]["samples_per_sec"] == pytest.approx(
            256 / 0.050, rel=1e-3)
        assert s["wire"]["wire_bytes_per_replica"] == 1024
        assert s["anomalies"][0]["name"] == "loader_stall"

    def test_cli_commands_run(self, tmp_path, capsys):
        p = self._stream(tmp_path)
        assert telemetry_main(["summary", str(p), "--json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["n_events"] > 0
        assert telemetry_main(["tail", str(p), "-n", "3"]) == 0
        assert len(capsys.readouterr().out.strip().splitlines()) == 3
        assert telemetry_main(["summary", str(tmp_path / "missing")]) == 1

    def test_perfetto_export_loads_spans(self, tmp_path):
        p = self._stream(tmp_path)
        out = tmp_path / "trace.json"
        assert telemetry_main(["export", str(p), "--perfetto",
                               "-o", str(out)]) == 0
        trace = json.loads(out.read_text())
        spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in spans} == {"data_wait", "step_dispatch",
                                              "save_blocked"}
        dw = next(e for e in spans if e["name"] == "data_wait")
        # chrome trace-event contract: microsecond ts + dur
        assert dw["dur"] == pytest.approx(10_000, rel=1e-3)
        assert dw["ts"] > 1e15  # wall-clock us (aligns with an XLA trace)

    def test_elastic_spans_are_bucketed_compile_is_not_double_counted(
            self):
        """ISSUE-11 satellite: the resize/reshard span names are canonical
        phases — `telemetry summary` buckets them into the step-time split
        instead of lumping them into unaccounted. The `compile` span is
        deliberately EXCLUDED from the accounted sum (a lazy compile runs
        inside the prefill/decode/step_dispatch span that triggered it —
        summing it as its own phase would double-count the wall) but stays
        visible in the spans table."""
        # real emission order: spans first, the enclosing epoch total
        # last (a counter BEFORE its spans would read the tail as a
        # crash-truncated partial epoch — the ISSUE 14 satellite)
        events = [
            {"kind": "span", "name": "elastic_replan", "dur_ms": 100.0},
            {"kind": "span", "name": "elastic_reshard", "dur_ms": 200.0},
            # 700ms dispatch that INCLUDES a 300ms nested compile
            {"kind": "span", "name": "step_dispatch", "dur_ms": 700.0},
            {"kind": "span", "name": "compile", "dur_ms": 300.0},
            {"kind": "counter", "name": "epoch_time_s", "value": 1.0},
        ]
        s = summarize(events)
        split = s["step_split_pct"]
        assert split["elastic_replan"] == 10.0
        assert split["elastic_reshard"] == 20.0
        assert split["step_dispatch"] == 70.0
        assert "compile" not in split          # no double-count
        assert "unaccounted" not in split      # phases close to 100 exactly
        assert s["spans"]["compile"]["total_ms"] == 300.0  # still visible

    def test_grow_and_capacity_spans_are_bucketed(self):
        """ISSUE-12 satellite: the grow-side phases — `elastic_grow` (the
        live M->N reshard) and `capacity_watch` (the Supervisor's
        boundary polls) — are canonical phases in the named split, not
        'unaccounted'."""
        events = [
            {"kind": "span", "name": "elastic_grow", "dur_ms": 400.0},
            {"kind": "span", "name": "capacity_watch", "dur_ms": 50.0},
            {"kind": "span", "name": "capacity_watch", "dur_ms": 50.0},
            {"kind": "span", "name": "step_dispatch", "dur_ms": 500.0},
            {"kind": "counter", "name": "epoch_time_s", "value": 1.0},
        ]
        split = summarize(events)["step_split_pct"]
        assert split["elastic_grow"] == 40.0
        assert split["capacity_watch"] == 10.0  # both polls summed
        assert "unaccounted" not in split

    def test_torn_stream_still_summarizes(self, tmp_path):
        p = self._stream(tmp_path)
        with open(p, "a") as f:
            f.write('{"v": 1, "ts": 1, "kind": "cou')  # crash mid-line
        events, bad = read_stream(str(p))
        assert bad == 1 and events
        assert summarize(events)["n_events"] == len(events)


# ---------------------------------------------------------------------------
# the instrumented train loop: a real 20-step CPU-mesh run
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_rig(mesh8):
    """The chaos CLI's tiny-ResNet workload: 20 steps/epoch at
    per_device_batch=2 over the 8-device mesh (dataset 320 / global 16)."""
    from distributed_pytorch_training_tpu.resilience.__main__ import (
        _build_rig,
    )

    return _build_rig(mesh8, seed=0, dataset_size=320, per_device_batch=2)


class TestInstrumentedLoop:
    def test_mock_step_loop_emits_the_contract(self, tmp_path, mesh8):
        """Tier-1 shape of the acceptance test (the real 20-step compiled
        run is the slow-marked test below — the suite sits within ~40s of
        its 870s budget, and this pins the SAME instrumentation contract
        for ~0.5s): train_epoch over a mocked step emits one data_wait +
        one step_dispatch span per step, one device_sync, and epoch
        counters whose totals the summary split closes against."""
        import jax.numpy as jnp

        from distributed_pytorch_training_tpu.resilience.__main__ import (
            _build_rig,
        )

        trainer, state_factory, loader = _build_rig(
            mesh8, seed=0, dataset_size=320, per_device_batch=2)
        metrics = {"loss_sum": jnp.float32(1.0),
                   "correct": jnp.float32(1.0),
                   "weight": jnp.float32(16.0)}
        trainer._train_step = lambda state, batch, key: (state, metrics)
        p = tmp_path / "telemetry_rank0.jsonl"
        telemetry.configure(str(p))
        spe = len(loader)
        assert spe == 20
        _, _, _, epoch_time, done = trainer.train_epoch(
            None, loader.epoch(0), 0, spe, samples_per_step=[16] * spe)
        telemetry.reset()
        assert done == 20

        events, bad = read_stream(str(p))
        assert bad == 0
        s = summarize(events)
        assert s["spans"]["data_wait"]["count"] == 20
        assert s["spans"]["step_dispatch"]["count"] == 20
        assert s["spans"]["device_sync"]["count"] == 1
        assert s["totals"]["recorded_wall_ms"] == pytest.approx(
            epoch_time * 1e3, abs=1e-3)  # summary rounds ms to 3 decimals
        in_epoch = sum(s["spans"][n]["total_ms"]
                       for n in ("data_wait", "step_dispatch",
                                 "device_sync"))
        assert in_epoch <= s["totals"]["recorded_wall_ms"] * 1.001 + 1e-3
        assert sum(s["step_split_pct"].values()) == pytest.approx(
            100.0, abs=0.5)
        assert s["throughput"]["samples"] == 320

    @pytest.mark.slow
    def test_20_step_run_summary_reproduces_split(self, tmp_path, tiny_rig):
        """The ISSUE 8 acceptance bar: `telemetry summary` reproduces the
        step-time split for a 20-step CPU-mesh run WITHIN the JSONL's own
        recorded totals — per-step data_wait + step_dispatch spans, the
        epoch's device_sync, and phase totals that never exceed the
        recorded epoch wall."""
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        trainer, state_factory, loader = tiny_rig
        p = tmp_path / "telemetry_rank0.jsonl"
        telemetry.configure(str(p))
        state = state_factory()
        spe = len(loader)
        assert spe == 20
        state, _, _, epoch_time, done = trainer.train_epoch(
            state, loader.epoch(0), 0, spe,
            samples_per_step=[16] * spe)
        assert done == 20
        # an epoch-boundary save so the save_blocked phase is in the split
        ckpt = CheckpointManager(str(tmp_path / "ckpt"))
        ckpt.save(spe, state, epoch=1)
        ckpt.wait()
        ckpt.close()
        telemetry.reset()

        events, bad = read_stream(str(p))
        assert bad == 0
        s = summarize(events)
        # one data_wait + one step_dispatch span per executed step, one
        # device_sync for the epoch's single host fetch, and the save's
        # blocked-time spans (save + wait barrier)
        assert s["spans"]["data_wait"]["count"] == 20
        assert s["spans"]["step_dispatch"]["count"] == 20
        assert s["spans"]["device_sync"]["count"] == 1
        assert s["spans"]["save_blocked"]["count"] == 2
        assert "save_blocked" in s["step_split_pct"]
        # the split's denominator is the stream's own epoch_time_s counter
        # and it matches what train_epoch returned
        assert s["totals"]["recorded_wall_ms"] == pytest.approx(
            epoch_time * 1e3, rel=1e-6)
        # phases are measured independently of the total, so consistency
        # is earned, not definitional: the IN-epoch phases can never
        # exceed the recorded epoch wall (save_blocked sits outside it —
        # the summary's adaptive denominator covers that), and the split
        # closes to 100%
        in_epoch = sum(s["spans"][n]["total_ms"]
                       for n in ("data_wait", "step_dispatch",
                                 "device_sync"))
        assert in_epoch <= s["totals"]["recorded_wall_ms"] * 1.001
        assert sum(s["step_split_pct"].values()) == pytest.approx(
            100.0, abs=0.5)
        assert s["throughput"]["samples"] == 320
        assert s["throughput"]["samples_per_sec"] == pytest.approx(
            320 / epoch_time, rel=1e-3)

    def test_hlo_identical_with_telemetry_on_and_off(self, tmp_path,
                                                     tiny_rig,
                                                     monkeypatch):
        """PARITY.md's guarantee, pinned: telemetry adds surfaces and never
        changes training numerics — the lowered step of the SAME config is
        textually identical whether a recorder + watchdog are installed or
        not (instrumentation is host-side only; the AST rules keep emits
        out of traced bodies). Extended for ISSUE 14: the ON side now
        carries the FULL new surface — a fleet-stamped per-rank recorder
        (gen/rank on every event), the all-ranks opt-in armed, AND a live
        /metrics server observing the stream — and the HLO still cannot
        tell. Extended for ISSUE 15: the ON side additionally lowers
        with the whole profiling surface armed — a re-armable
        StepProfiler wired as the server's POST /profile handler, the
        watchdog's anomaly capture hook installed, and an on-demand
        jax.profiler capture session OPEN while lowering runs."""
        trainer, state_factory, loader = tiny_rig
        state = state_factory()
        batch = next(iter(loader.epoch(0)))
        key = jax.random.PRNGKey(0)
        assert telemetry.get() is None
        off = trainer._train_step.lower(state, batch, key).as_text()
        monkeypatch.setenv(telemetry.ALL_RANKS_ENV, "1")
        monkeypatch.setenv(telemetry.FLEET_GENERATION_ENV, "3")
        monkeypatch.setenv(telemetry.FLEET_RANK_ENV, "1")
        rec = telemetry.configure(
            str(tmp_path / telemetry.stream_filename(1)))
        assert (rec.gen, rec.rank) == (3, 1)
        server = telemetry.MetricsServer(0, recorder=rec)  # ephemeral
        server.start()
        from distributed_pytorch_training_tpu.telemetry import (
            device as tele_device,
        )
        from distributed_pytorch_training_tpu.utils.profiling import (
            StepProfiler,
        )
        profiler = StepProfiler(str(tmp_path / "prof"),
                                on_capture=tele_device.make_ingestor())
        server.profile_handler = profiler.request_capture
        trainer.watchdog = telemetry.AnomalyWatchdog(
            capture_hook=lambda name, step: profiler.request_capture(
                2, reason=f"anomaly:{name}", trigger_step=step))
        try:
            with profiler.capture(reason="hlo-pin") as trace_dir:
                assert trace_dir is not None
                on = trainer._train_step.lower(state, batch,
                                               key).as_text()
        finally:
            trainer.watchdog = None
            server.stop()
            telemetry.reset()
        assert server.port is None  # stopped: the thread is gone
        assert on == off

    @pytest.mark.slow
    def test_watchdog_abort_is_chaos_recoverable(self, tmp_path, tiny_rig):
        """The abort hook, chaos-tested ON: an injected loader_stall trips
        the watchdog's loader_stall detector, AnomalyAbort raises at the
        step boundary, and the restart Supervisor treats it as any other
        restartable failure — restore, replay, complete — leaving an
        AnomalyAbort flight artifact."""
        from distributed_pytorch_training_tpu.data.loader import (
            ShardedLoader,
        )
        from distributed_pytorch_training_tpu.resilience.faults import (
            FaultInjector, FaultPlan,
        )
        from distributed_pytorch_training_tpu.resilience.supervisor import (
            RetryPolicy, Supervisor,
        )
        from distributed_pytorch_training_tpu.training.checkpoint import (
            CheckpointManager,
        )

        trainer, state_factory, loader = tiny_rig
        telemetry.configure(str(tmp_path / "telemetry_rank0.jsonl"))
        injector = FaultInjector(FaultPlan.parse("loader_stall@step=8:1.5s"))
        stalled = ShardedLoader(loader.dataset, trainer.mesh, 2,
                                shuffle=True, seed=0,
                                fault_hook=injector.on_loader_batch)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"))
        trainer.watchdog = telemetry.AnomalyWatchdog(
            min_samples=2, stall_factor=3.0, stall_min_s=0.5, abort=True)
        try:
            sup = Supervisor(
                trainer, ckpt, state_factory, stalled,
                retry=RetryPolicy(max_restarts=3, backoff_base_s=0.01,
                                  backoff_max_s=0.02),
                injector=injector, checkpoint_every_steps=4)
            state, report = sup.run(1)
        finally:
            trainer.watchdog = None
            ckpt.close()
            telemetry.reset()
        assert report.completed
        assert report.restarts >= 1
        assert any("AnomalyAbort" in f for f in report.failures)
        assert injector.fired == ["loader_stall@step=8:1.5s"]
        # detection emitted the structured anomaly AND the flight artifact
        events, _ = read_stream(str(tmp_path / "telemetry_rank0.jsonl"))
        stalls = [e for e in events if e["kind"] == "anomaly"
                  and e["name"] == "loader_stall"]
        assert stalls and stalls[0]["data_wait_s"] >= 1.0
        flights = [json.loads(f.read_text())
                   for f in tmp_path.glob("flight_*.json")]
        assert any("AnomalyAbort" in (b["cause"] or "") for b in flights)


def test_telemetry_console_script_declared():
    """pyproject registers the `telemetry` entry point next to `analysis`
    and `resilience`, and it resolves to the CLI main."""
    pyproject = (REPO / "pyproject.toml").read_text()
    assert ('telemetry = "distributed_pytorch_training_tpu.telemetry.'
            '__main__:main"') in pyproject
    assert callable(telemetry_main)


# ---------------------------------------------------------------------------
# crash-truncated streams (ISSUE 14 satellite): the partial epoch is
# reported explicitly, never folded into a misleading split
# ---------------------------------------------------------------------------


class TestPartialEpoch:
    def test_sigkilled_run_reports_partial_epoch(self, tmp_path):
        """Regression: a SIGKILL mid-epoch-2 leaves per-step spans with no
        enclosing epoch_time_s. The summary used to fold them into the
        accounted split (the adaptive denominator then claimed a
        self-consistent 100% over an epoch that never finished); it must
        now report them as an explicit PARTIAL block, excluded from the
        completed epoch's percentages. The child SIGKILLs itself — no
        atexit, no flush-at-exit — so this also pins the recorder's
        per-line flush durability."""
        src = textwrap.dedent(f"""
            import os, signal, sys
            sys.path.insert(0, {str(REPO)!r})
            from distributed_pytorch_training_tpu import telemetry
            rec = telemetry.configure(
                {str(tmp_path / 'telemetry_rank0.jsonl')!r})
            for s in range(20):           # epoch 0 completes
                rec.span_event("data_wait", 0.001, step=s, epoch=0)
                rec.span_event("step_dispatch", 0.002, step=s, epoch=0)
            rec.counter("epoch_time_s", 0.08, epoch=0)
            rec.counter("steps", 20, epoch=0)
            for s in range(20, 27):       # epoch 1 truncated at step 7
                rec.span_event("data_wait", 0.001, step=s, epoch=1)
                rec.span_event("step_dispatch", 0.002, step=s, epoch=1)
            os.kill(os.getpid(), signal.SIGKILL)
        """)
        r = subprocess.run([sys.executable, "-c", src],
                           capture_output=True, timeout=120)
        assert r.returncode == -signal.SIGKILL
        events, bad = read_stream(str(tmp_path / "telemetry_rank0.jsonl"))
        assert bad == 0
        s = summarize(events)
        # the partial epoch is named: 7 steps, both phases, with their ms
        assert s["partial_epoch"] is not None
        assert s["partial_epoch"]["steps"] == 7
        assert set(s["partial_epoch"]["span_ms"]) == {"data_wait",
                                                      "step_dispatch"}
        assert s["partial_epoch"]["total_ms"] == pytest.approx(
            7 * 3.0, rel=0.01)
        # the split covers ONLY the completed epoch and still closes
        assert s["totals"]["recorded_wall_ms"] == pytest.approx(80.0)
        assert s["totals"]["accounted_span_ms"] == pytest.approx(
            20 * 3.0, rel=0.01)
        assert sum(s["step_split_pct"].values()) == pytest.approx(
            100.0, abs=0.1)
        # the text report names it too
        assert summarize(events)  # (idempotent)
        assert telemetry_main(
            ["summary", str(tmp_path / "telemetry_rank0.jsonl")]) == 0

    def test_appended_relaunch_truncates_previous_segment(self):
        """A relaunch APPENDS to the shared stream: the crashed previous
        segment's orphan spans fold into the partial block at the meta
        boundary instead of polluting the new segment's split."""
        events = [
            {"kind": "meta", "name": "stream", "schema": 2},
            {"kind": "span", "name": "step_dispatch", "dur_ms": 5.0,
             "step": 0},
            # crash here — relaunch appends a fresh header
            {"kind": "meta", "name": "stream", "schema": 2},
            {"kind": "span", "name": "step_dispatch", "dur_ms": 7.0,
             "step": 0},
            {"kind": "counter", "name": "epoch_time_s", "value": 0.007},
        ]
        s = summarize(events)
        assert s["partial_epoch"]["steps"] == 1
        assert s["partial_epoch"]["total_ms"] == pytest.approx(5.0)
        assert s["totals"]["accounted_span_ms"] == pytest.approx(7.0)

    def test_complete_run_has_no_partial_block(self, tmp_path):
        rec = telemetry.configure(str(tmp_path / "t.jsonl"))
        rec.span_event("step_dispatch", 0.002, step=0)
        rec.counter("epoch_time_s", 0.002)
        telemetry.reset()
        events, _ = read_stream(str(tmp_path / "t.jsonl"))
        assert summarize(events)["partial_epoch"] is None


# ---------------------------------------------------------------------------
# MetricsCSV durability (satellite): the row survives a SIGKILL
# ---------------------------------------------------------------------------


def test_metrics_csv_row_survives_crash_after_append(tmp_path):
    """MetricsCSV.append fsyncs per row, so a crash/SIGKILL immediately
    after an epoch completes (the chaos crash faults' timing) cannot drop
    the just-written row. The child appends one row and SIGKILLs itself —
    no atexit, no interpreter shutdown flush — and the row must already be
    on disk."""
    src = textwrap.dedent(f"""
        import os, signal, sys
        sys.path.insert(0, {str(REPO)!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        from distributed_pytorch_training_tpu.utils.metrics import MetricsCSV
        csv = MetricsCSV({str(tmp_path)!r})
        csv.append(0, 1.2345, 50.0, 2.3456, 40.0, 12.5)
        os.kill(os.getpid(), signal.SIGKILL)  # dies before any flush-at-exit
    """)
    r = subprocess.run([sys.executable, "-c", src], capture_output=True,
                       timeout=120)
    assert r.returncode == -signal.SIGKILL
    rows = (tmp_path / "metrics_rank0.csv").read_text().splitlines()
    assert rows[0] == ("epoch,train_loss,train_acc,val_loss,val_acc,"
                      "epoch_time_seconds")
    assert rows[1] == "1,1.2345,50.00,2.3456,40.00,12.5000"
