"""Compat lint (ROADMAP "jax version skew"): every shard_map in the repo
must go through the one version-compat shim, `parallel/collectives.py
shard_map` — the entry point moved (jax.experimental.shard_map ->
jax.shard_map) and the replication-check flag was renamed (check_rep ->
check_vma) across the jax versions this code runs under. A direct import
anywhere else works on ONE jax version and breaks on the next; this tier-1
test fails the moment a new violation lands.

MIGRATED onto the AST engine (analysis/ast_rules.py `shard-map-shim-only`,
ISSUE 3): the old regex fired on entry-point MENTIONS inside docstrings and
string literals — prose about the rule tripped the rule. The AST rule only
sees real imports, attribute accesses, and call kwargs, so that false-
positive class is gone structurally (pinned below).
"""

from pathlib import Path

from distributed_pytorch_training_tpu.analysis.ast_rules import (
    SHARD_MAP_SHIM, run_ast_rules,
)

REPO = Path(__file__).resolve().parent.parent
SHIM = REPO / "distributed_pytorch_training_tpu" / "parallel" / "collectives.py"


def test_no_direct_shard_map_outside_collectives_shim():
    offenders = run_ast_rules(rules=["shard-map-shim-only"])
    assert not offenders, (
        "direct jax shard_map entry-point use outside the "
        "parallel/collectives.py shim (import `shard_map` from "
        "distributed_pytorch_training_tpu.parallel instead):\n  "
        + "\n  ".join(str(f) for f in offenders))


def test_docstring_mentions_no_longer_false_positive(tmp_path):
    """The known false-positive class of the regex lint (ISSUE 3
    satellite): a file whose docstrings/strings MENTION the raw entry
    points — exactly what the shim and this test's own docstring do —
    must pass; a real import in the same file must still flag."""
    prose = tmp_path / "prose.py"
    prose.write_text(
        '"""Use jax.shard_map via the shim; never\n'
        'from jax.experimental import shard_map directly."""\n'
        'HINT = "jax.experimental.shard_map.shard_map moved"\n')
    assert run_ast_rules(files=[prose],
                         rules=["shard-map-shim-only"]) == []

    real = tmp_path / "real.py"
    real.write_text('"""Innocent docstring."""\n'
                    "from jax.experimental import shard_map\n")
    found = run_ast_rules(files=[real], rules=["shard-map-shim-only"])
    assert len(found) == 1 and found[0].location.endswith(":2")


def test_this_repo_prose_would_have_tripped_the_old_regex():
    """Regression direction-proof: the repo really contains entry-point
    mentions in prose (the shim's own docstring at minimum), so the AST
    migration is load-bearing, not a rename."""
    assert "jax.experimental.shard_map" in SHIM.read_text()


def test_shim_itself_still_wraps_the_raw_entry_points():
    """The lint is only meaningful while the shim really is the compat
    layer: it must reference both historical entry points, and the rule
    must keep pointing at this file."""
    src = SHIM.read_text()
    assert "jax.shard_map" in src
    assert "jax.experimental.shard_map" in src
    assert SHIM.as_posix().endswith(SHARD_MAP_SHIM)
