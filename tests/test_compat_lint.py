"""Compat lint (ROADMAP "jax version skew"): every shard_map in the repo
must go through the one version-compat shim, `parallel/collectives.py
shard_map` — the entry point moved (jax.experimental.shard_map ->
jax.shard_map) and the replication-check flag was renamed (check_rep ->
check_vma) across the jax versions this code runs under. A direct import
anywhere else works on ONE jax version and breaks on the next; this tier-1
test fails the moment a new violation lands.
"""

import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "distributed_pytorch_training_tpu"

# The one allowed home of the raw entry point.
SHIM = PKG / "parallel" / "collectives.py"

# Direct uses of the raw entry points, in any of the forms jax has offered:
#   jax.shard_map(...), jax.experimental.shard_map.shard_map(...),
#   from jax.experimental.shard_map import shard_map,
#   from jax.experimental import shard_map
_DIRECT_RE = re.compile(
    r"jax\.shard_map"
    r"|jax\.experimental\.shard_map"
    r"|from\s+jax\.experimental\s+import\s+([\w\s,]*\b)?shard_map")


def _strip_comments(src: str) -> str:
    """Drop #-comments so prose mentioning the entry points doesn't trip
    the lint (docstrings still count: code examples there would be copied)."""
    return "\n".join(line.split("#", 1)[0] for line in src.splitlines())


def test_no_direct_shard_map_outside_collectives_shim():
    offenders = []
    files = sorted(PKG.rglob("*.py")) + sorted(REPO.glob("*.py"))
    for path in files:
        if path.resolve() == SHIM.resolve():
            continue
        for i, line in enumerate(
                _strip_comments(path.read_text()).splitlines(), 1):
            if _DIRECT_RE.search(line):
                offenders.append(f"{path.relative_to(REPO)}:{i}: "
                                 f"{line.strip()}")
    assert not offenders, (
        "direct jax shard_map entry-point use outside the "
        "parallel/collectives.py shim (import `shard_map` from "
        "distributed_pytorch_training_tpu.parallel instead):\n  "
        + "\n  ".join(offenders))


def test_shim_itself_still_wraps_the_raw_entry_points():
    """The lint is only meaningful while the shim really is the compat
    layer: it must reference both historical entry points."""
    src = SHIM.read_text()
    assert "jax.shard_map" in src
    assert "jax.experimental.shard_map" in src
