"""Experiment tooling (experiments/scaling.py): the HLO collective census
must find the all-reduce XLA inserts for a cross-device reduction."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_training_tpu.experiments.scaling import (
    collective_census,
)


def test_census_finds_allreduce_in_sharded_reduction(mesh8):
    sharding = NamedSharding(mesh8, P("data"))
    x = jax.device_put(np.arange(32, dtype=np.float32), sharding)

    f = jax.jit(lambda v: v.sum(), in_shardings=sharding,
                out_shardings=NamedSharding(mesh8, P()))
    text = f.lower(x).compile().as_text()
    census = collective_census(text)
    assert any(c["op"] == "all-reduce" for c in census), census


def test_census_empty_on_local_computation():
    f = jax.jit(lambda v: v * 2)
    text = f.lower(jnp.ones(4)).compile().as_text()
    assert collective_census(text) == []
