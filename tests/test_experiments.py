"""Experiment tooling (experiments/scaling.py): the HLO collective census
must find the all-reduce XLA inserts for a cross-device reduction."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_training_tpu.experiments.scaling import (
    collective_census,
)


def test_census_finds_allreduce_in_sharded_reduction(mesh8):
    sharding = NamedSharding(mesh8, P("data"))
    x = jax.device_put(np.arange(32, dtype=np.float32), sharding)

    f = jax.jit(lambda v: v.sum(), in_shardings=sharding,
                out_shardings=NamedSharding(mesh8, P()))
    text = f.lower(x).compile().as_text()
    census = collective_census(text)
    assert any(c["op"] == "all-reduce" for c in census), census


def test_census_empty_on_local_computation():
    f = jax.jit(lambda v: v * 2)
    text = f.lower(jnp.ones(4)).compile().as_text()
    assert collective_census(text) == []


@pytest.mark.slow
def test_trace_derived_collective_share(mesh8, tmp_path):
    """The jax.profiler trace parser must find the data-parallel all-reduce
    and report a share in (0, 100] — the README's '~X%' number, measured
    (VERDICT r2 #8: nothing parsed a captured trace)."""
    from distributed_pytorch_training_tpu.experiments.harness import (
        build_image_trainer, synth_image_batch,
    )
    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        capture_step_trace, collective_share,
    )

    trainer, state, mesh = build_image_trainer(jax.devices(), False)
    batch, _ = synth_image_batch(mesh, 8)
    key = jax.random.PRNGKey(0)
    state, _ = trainer._train_step(state, batch, key)  # warmup/compile
    td = str(tmp_path / "trace")
    capture_step_trace(trainer._train_step, state, batch, key, td, steps=3)

    share = collective_share(td)
    assert "all-reduce" in share["by_op"], share
    assert 0.0 < share["share_pct"] <= 100.0, share
    assert share["op_us"] > share["collective_us"] > 0.0


def test_trace_parser_raises_without_trace(tmp_path):
    import pytest

    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        collective_share,
    )
    with pytest.raises(FileNotFoundError):
        collective_share(str(tmp_path))


# ---- smoke-run every experiment driver (VERDICT r2 #9) -------------------

def _run_experiment(argv):
    from distributed_pytorch_training_tpu.experiments import scaling
    scaling.main(argv)


_SMOKE = ["--batch-size", "8", "--steps", "1", "--repeats", "1",
          "--min-window-s", "0.01"]


@pytest.mark.slow
def test_experiment_scaling_smoke(capsys):
    _run_experiment(["scaling"] + _SMOKE)
    out = capsys.readouterr().out
    assert "scaling_efficiency_pct" in out


@pytest.mark.slow
def test_experiment_batch_smoke(capsys):
    _run_experiment(["batch"] + _SMOKE + ["--batch-list", "8,16"])
    out = capsys.readouterr().out
    assert "per_device_batch" in out


@pytest.mark.slow
def test_experiment_amp_smoke(capsys):
    _run_experiment(["amp"] + _SMOKE)
    out = capsys.readouterr().out
    assert "bf16_speedup" in out


@pytest.mark.slow
def test_experiment_gradsync_smoke(capsys, tmp_path):
    _run_experiment(["gradsync"] + _SMOKE
                    + ["--csv", str(tmp_path / "gs.csv")])
    out = capsys.readouterr().out
    assert "grad_sync_share_1vsN_pct" in out
    assert "grad_sync_share_trace_pct" in out
    assert "all-reduce" in out  # census + trace breakdown both present
    assert (tmp_path / "gs.csv").exists()


@pytest.mark.slow
def test_experiment_grad_sync_smoke(capsys):
    """The explicit-reducer arm: every mode row carries the census columns
    (engagement proof) and the bucketed rows show the compressed wire."""
    _run_experiment(["grad_sync", "--model", "gpt2_124m", "--lm-tiny",
                     "--seq-len", "32", "--bucket-cap-mb", "25"] + _SMOKE)
    out = capsys.readouterr().out
    assert "grad_collectives" in out
    assert "bucketed_bf16" in out and "bucketed_int8" in out
    assert "bucketed_int8_multihop" in out
    assert "wire_bytes_per_replica" in out
    assert "exposed_comm_pct" in out


@pytest.mark.slow
def test_experiment_fsdp_smoke(capsys):
    """The explicit-FSDP arm (ISSUE 7): replicated-vs-fsdp rows with the
    per-layer collective census, at-rest residency division and the
    fsdp_gather_bytes wire term."""
    _run_experiment(["fsdp", "--model", "gpt2_124m", "--lm-tiny",
                     "--seq-len", "32"] + _SMOKE)
    out = capsys.readouterr().out
    assert "fsdp_fp32" in out and "fsdp_int8_multihop" in out
    assert "param_bytes_at_rest_per_replica" in out
    assert "fsdp_gather_bytes" in out
    assert "all_gathers" in out


def test_comm_overlap_split_math(tmp_path):
    """Interval arithmetic of the exposed-vs-hidden split on a synthetic
    trace: one collective fully covered by compute, one half covered, one
    fully exposed."""
    import gzip
    import json

    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        comm_overlap_split,
    )

    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 2,
         "args": {"name": "XLA Ops"}},
        # compute lane: [0, 100) and [200, 250)
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1", "ts": 0,
         "dur": 100},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.2", "ts": 200,
         "dur": 50},
        # hidden: all-reduce [10, 60) inside compute
        {"ph": "X", "pid": 1, "tid": 2, "name": "all-reduce.1", "ts": 10,
         "dur": 50},
        # half hidden: [80, 120) overlaps compute only until 100
        {"ph": "X", "pid": 1, "tid": 2, "name": "all-gather.1", "ts": 80,
         "dur": 40},
        # fully exposed: [130, 160)
        {"ph": "X", "pid": 1, "tid": 2, "name": "reduce-scatter.1",
         "ts": 130, "dur": 30},
        # completion markers must not count
        {"ph": "X", "pid": 1, "tid": 2, "name": "all-reduce-done.1",
         "ts": 160, "dur": 500},
    ]
    d = tmp_path / "plugins"
    d.mkdir()
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    split = comm_overlap_split(str(tmp_path))
    assert split["collective_us"] == 120.0
    assert split["hidden_us"] == 70.0   # 50 + 20
    assert split["exposed_us"] == 50.0  # 20 + 30
    assert split["exposed_frac_pct"] == round(100.0 * 50 / 120, 2)


def test_comm_overlap_split_cross_pid_and_async_start(tmp_path):
    """ISSUE-6 satellite: the two split properties only exercised
    implicitly before. (a) Per-pid isolation — compute on ANOTHER device
    never hides a collective (overlap is same-device concurrency, not
    wall-clock coincidence). (b) Async ``-start`` events span the transfer
    and are the measured interval; their ``-done`` completion markers (a
    wait, not work) must add nothing."""
    import gzip
    import json

    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        comm_overlap_split,
    )

    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/device:TPU:1"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 2,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "thread_name", "pid": 2, "tid": 1,
         "args": {"name": "XLA Ops"}},
        # device 0 compute: [0, 100)
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1", "ts": 0,
         "dur": 100},
        # (a) device 1 collective [10, 60) — device 0's compute must NOT
        # hide it: device 1 runs nothing else, so it is fully exposed
        {"ph": "X", "pid": 2, "tid": 1, "name": "all-gather.7", "ts": 10,
         "dur": 50},
        # (b) async start on device 0: [20, 70) spans the transfer, fully
        # inside device 0's compute -> fully hidden
        {"ph": "X", "pid": 1, "tid": 2, "name": "all-reduce-start.3",
         "ts": 20, "dur": 50},
        # its completion marker: wait-not-work, counts nothing
        {"ph": "X", "pid": 1, "tid": 2, "name": "all-reduce-done.3",
         "ts": 70, "dur": 400},
    ]
    d = tmp_path / "plugins"
    d.mkdir()
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    split = comm_overlap_split(str(tmp_path))
    assert split["collective_us"] == 100.0  # 50 (dev1) + 50 (async start)
    assert split["hidden_us"] == 50.0       # only the same-device overlap
    assert split["exposed_us"] == 50.0      # the cross-device one
    assert split["exposed_frac_pct"] == 50.0


def test_trace_census_ragged_all_to_all_and_async_pairing(tmp_path):
    """The widened trace regex (ISSUE 3 satellite): `ragged-all-to-all`
    (MoE dispatch) counts as communication, and an async `-start`/`-done`
    pair counts ONCE — the `-done` completion marker's duration is
    wait-not-work, so adding it would double the collective share."""
    import gzip
    import json

    from distributed_pytorch_training_tpu.experiments.trace_analysis import (
        collective_share,
    )

    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1", "ts": 0,
         "dur": 100},
        # async pair: the -start span covers the transfer (40us of work);
        # the -done marker is a 500us wait that must NOT count
        {"ph": "X", "pid": 1, "tid": 1, "name": "all-reduce-start.3",
         "ts": 100, "dur": 40},
        {"ph": "X", "pid": 1, "tid": 1, "name": "all-reduce-done.3",
         "ts": 140, "dur": 500},
        # MoE dispatch op the old alternation missed entirely
        {"ph": "X", "pid": 1, "tid": 1, "name": "ragged-all-to-all.7",
         "ts": 700, "dur": 25},
    ]
    d = tmp_path / "plugins"
    d.mkdir()
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    share = collective_share(str(tmp_path))
    assert share["by_op"] == {"all-reduce": 40.0, "ragged-all-to-all": 25.0}
    assert share["collective_us"] == 65.0  # -done's 500us excluded
    assert share["op_us"] == 665.0


@pytest.mark.slow
def test_experiment_pipeline_smoke(capsys):
    _run_experiment(["pipeline"] + _SMOKE)
    out = capsys.readouterr().out
    assert "bubble_predicted_pct" in out
    assert "dp=8 (baseline)" in out
    assert "pipe=2,data=4" in out


def test_plot_generation_all_kinds(tmp_path):
    """plots.py renders a PNG for every experiment CSV shape (the README's
    'Tables + plots' promise — plots regenerate from the CSVs)."""
    import csv as csv_mod

    from distributed_pytorch_training_tpu.experiments import plots

    fixtures = {
        "scaling": [
            {"chips": 1, "global_samples_per_s": 100.0,
             "per_chip_samples_per_s": 100.0, "scaling_efficiency_pct": 100.0},
            {"chips": 8, "global_samples_per_s": 730.0,
             "per_chip_samples_per_s": 91.2, "scaling_efficiency_pct": 91.2},
        ],
        "batch": [
            {"per_device_batch": 32, "global_samples_per_s": 50.0},
            {"per_device_batch": 256, "global_samples_per_s": 300.0},
        ],
        "amp": [
            {"precision": "fp32", "global_samples_per_s": 100.0},
            {"precision": "bf16", "global_samples_per_s": 420.0},
            {"precision": "bf16_speedup", "global_samples_per_s": 4.2},
        ],
        "gradsync": [
            {"measurement": "step_time_1chip_ms", "value": 10.0},
            {"measurement": "grad_sync_share_1vsN_pct", "value": 12.0},
            {"measurement": "grad_sync_share_trace_pct", "value": 10.5},
        ],
        "pipeline": [
            {"config": "dp=8 (baseline)", "microbatches": "-",
             "samples_per_s": 100.0, "bubble_predicted_pct": 0.0,
             "vs_dp_pct": 100.0},
            {"config": "pipe=2,data=4", "microbatches": 4,
             "samples_per_s": 80.0, "bubble_predicted_pct": 20.0,
             "vs_dp_pct": 80.0},
        ],
    }
    for kind, rows in fixtures.items():
        path = tmp_path / f"{kind}.csv"
        with open(path, "w", newline="") as f:
            w = csv_mod.DictWriter(f, fieldnames=list(rows[0]))
            w.writeheader()
            w.writerows(rows)
        out = tmp_path / f"{kind}.png"
        plots.main([str(path), "--out", str(out)])  # kind auto-detected
        assert out.exists() and out.stat().st_size > 5000, kind


def test_plot_appended_csv_uses_latest_run(tmp_path):
    """The documented flow APPENDS rows across runs; plots must render the
    latest sweep, not a zigzag across all of them."""
    import csv as csv_mod

    from distributed_pytorch_training_tpu.experiments import plots

    run1 = [{"per_device_batch": b, "global_samples_per_s": v}
            for b, v in ((32, 10.0), (64, 20.0))]
    run2 = [{"per_device_batch": b, "global_samples_per_s": v}
            for b, v in ((32, 11.0), (64, 22.0))]
    path = tmp_path / "batch.csv"
    with open(path, "w", newline="") as f:
        w = csv_mod.DictWriter(f, fieldnames=["per_device_batch",
                                              "global_samples_per_s"])
        w.writeheader()
        w.writerows(run1 + run2)

    rows = plots._latest(plots._read(str(path)), "batch")
    assert [r["global_samples_per_s"] for r in rows] == ["11.0", "22.0"]
    out = tmp_path / "b.png"
    plots.main([str(path), "--out", str(out)])
    assert out.exists() and out.stat().st_size > 5000


@pytest.mark.slow
def test_experiment_gradsync_bert_smoke(capsys):
    """The BASELINE matrix's config 4 is 'BERT-base MLM seq-len 512
    (grad-sync profiling run)' — the gradsync driver must serve LM models,
    not only the image configs (tiny shapes here; real seq on hardware)."""
    from distributed_pytorch_training_tpu.experiments import scaling
    scaling.main(["gradsync", "--model", "bert_base", "--seq-len", "64",
                  "--batch-size", "2", "--steps", "1", "--repeats", "1",
                  "--min-window-s", "0.01", "--lm-tiny"])
    out = capsys.readouterr().out
    assert "grad_sync_share_trace_pct" in out
    assert "all-reduce" in out


def test_flash_causal_flops_use_kernel_cost_estimate():
    """The analytic FLOPs instrument must use the kernel's own CostEstimate
    (causal-aware: only live diagonal blocks), not one tile x the full grid
    — the r3 advisor found causal attention MFU ~2x overcounted (ADVICE r3)."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_training_tpu.experiments.flops import (
        jaxpr_matmul_flops,
    )
    from distributed_pytorch_training_tpu.ops import flash_attention
    from distributed_pytorch_training_tpu.ops.flash_attention import _live_pairs

    b, s, h, d, blk = 1, 1024, 2, 64, 512
    q = jnp.zeros((b, s, h, d), jnp.float32)

    def fwd(q):
        return flash_attention(q, q, q, True, None, blk, blk)

    got = jaxpr_matmul_flops(fwd, q)
    live = _live_pairs(s // blk, s // blk, blk, blk, True)  # 3 of 4 blocks
    assert live == 3
    expect = b * h * live * 4 * blk * blk * d
    np.testing.assert_allclose(got, expect, rtol=1e-6)
    # and the non-causal kernel counts the full rectangle
    got_full = jaxpr_matmul_flops(
        lambda q: flash_attention(q, q, q, False, None, blk, blk), q)
    np.testing.assert_allclose(got_full, b * h * 4 * 4 * blk * blk * d,
                               rtol=1e-6)


class TestBenchReport:
    """report.py regenerates the README benchmark table from the committed
    bench_history.jsonl (VERDICT r4 missing #2: provenance for every row)."""

    ENTRY = {
        "metric": "resnet18_cifar10_train_throughput_bf16_b4096",
        "value": 1000.0, "n_chips": 1, "chip": "TPU v5 lite",
        "vs_baseline": 4.0, "timestamp": "2026-07-30T00:00:00Z",
        "configs": [
            {"model": "resnet18", "bf16": True, "per_device_batch": 4096,
             "samples_per_sec_chip": 1000.0, "mfu_pct": 50.0, "image_hw": 32},
            {"model": "resnet18", "bf16": False, "per_device_batch": 4096,
             "samples_per_sec_chip": 250.0, "mfu_pct": 12.0, "image_hw": 32},
            {"model": "gpt2_124m", "bf16": True, "per_device_batch": 8,
             "seq_len": 1024, "samples_per_sec_chip": 100.0,
             "tokens_per_sec": 102400.0, "mfu_pct": 45.0},
        ],
        "configs_skipped": ["bert_base"],
    }

    def test_renders_latest_entry_as_markdown(self, tmp_path, capsys):
        import json

        from distributed_pytorch_training_tpu.experiments.report import main

        hist = tmp_path / "bench_history.jsonl"
        older = dict(self.ENTRY, value=900.0, timestamp="2026-07-29T00:00:00Z")
        hist.write_text(json.dumps(older) + "\n" + json.dumps(self.ENTRY) + "\n")
        assert main(["--history", str(hist)]) == 0
        out = capsys.readouterr().out
        assert "| ResNet-18 / CIFAR-10 (headline) | 4096 | 1,000 | 50.0% |" in out
        assert "fp32 `HIGHEST` baseline" in out
        assert "GPT-2 124M @ S=1024 | 8 | 100 (102k tok/s) | 45.0% |" in out
        assert "2026-07-30" in out  # the LATEST entry won
        assert "bert_base" in out   # skipped configs stay visible

    def test_all_lists_every_run(self, tmp_path, capsys):
        import json

        from distributed_pytorch_training_tpu.experiments.report import main

        hist = tmp_path / "bench_history.jsonl"
        hist.write_text(json.dumps(self.ENTRY) + "\n")
        assert main(["--history", str(hist), "--all"]) == 0
        assert "resnet18_cifar10" in capsys.readouterr().out

    def test_missing_history_fails_loudly(self, tmp_path, capsys):
        from distributed_pytorch_training_tpu.experiments.report import main

        assert main(["--history", str(tmp_path / "nope.jsonl")]) == 1
        assert "no history" in capsys.readouterr().err

    def test_merged_view_joins_chunked_runs(self, tmp_path, capsys):
        """The full matrix accumulates through `bench.py --only` chunk runs;
        the default report view must join them — newest per config, CPU
        mechanism-validation rows excluded once a TPU row exists."""
        import json

        from distributed_pytorch_training_tpu.experiments.report import main

        cpu = {"metric": "m", "value": 1.0, "chip": "cpu",
               "timestamp": "2026-07-28T00:00:00Z",
               "configs": [{"model": "resnet18", "bf16": True,
                            "per_device_batch": 256,
                            "samples_per_sec_chip": 1.0, "mfu_pct": None}]}
        chunk = {"metric": "gpt2_124m_train_throughput_bf16", "value": 100.0,
                 "chip": "TPU v5 lite", "timestamp": "2026-07-31T02:00:00Z",
                 "only": ["gpt2_124m"],
                 "configs": [{"model": "gpt2_124m", "label": "gpt2_124m",
                              "bf16": True, "per_device_batch": 8,
                              "seq_len": 1024, "samples_per_sec_chip": 100.0,
                              "mfu_pct": 45.0}],
                 "configs_skipped": []}
        stale = dict(chunk, timestamp="2026-07-30T00:00:00Z")
        stale["configs"] = [dict(chunk["configs"][0],
                                 samples_per_sec_chip=90.0)]
        hist = tmp_path / "h.jsonl"
        hist.write_text("\n".join(json.dumps(e) for e in
                                  (cpu, stale, self.ENTRY, chunk)) + "\n")
        assert main(["--history", str(hist)]) == 0
        out = capsys.readouterr().out
        assert "ResNet-18 / CIFAR-10 (headline)" in out   # from ENTRY
        assert "| 100 " in out and "| 90 " not in out     # newest chunk won
        assert "2026-07-31T02:00:00Z" in out              # per-row source
        assert "| 256 " not in out                        # cpu entry excluded
        assert "bert_base" in out                         # still unmeasured

    def test_latest_flag_keeps_single_entry_view(self, tmp_path, capsys):
        import json

        from distributed_pytorch_training_tpu.experiments.report import main

        hist = tmp_path / "h.jsonl"
        hist.write_text(json.dumps(self.ENTRY) + "\n")
        assert main(["--history", str(hist), "--latest"]) == 0
        assert "Measured on 1x TPU v5 lite" in capsys.readouterr().out


class TestBenchHistoryHelpers:
    """The salvage path's provenance hygiene: marker resolution and
    teardown-hang dedupe (bench.py watchdog)."""

    def test_provisional_marker_resolves_to_unmeasured_labels(self):
        import bench

        d = {"configs": [{"model": "resnet18", "bf16": True},
                         {"model": "resnet18", "bf16": False}],
             "configs_skipped": ["<provisional>"]}
        bench._resolve_provisional_marker(d, None)
        assert "<provisional>" not in d["configs_skipped"]
        assert set(d["configs_skipped"]) == \
            {l for l, _, _, _ in bench.EXTRA_CONFIGS}

    def test_provisional_marker_respects_only_selection(self):
        import bench

        d = {"configs": [{"model": "resnet18", "bf16": True}],
             "configs_skipped": ["<provisional>"]}
        bench._resolve_provisional_marker(d, "headline,fp32,resnet50")
        # fp32 arm never ran (no bf16=False config) and resnet50 never ran
        assert set(d["configs_skipped"]) == {"fp32", "resnet50"}

    def test_marker_resolution_keeps_real_lists_untouched(self):
        import bench

        d = {"configs": [], "configs_skipped": ["resnet50"]}
        bench._resolve_provisional_marker(d, None)
        assert d["configs_skipped"] == ["resnet50"]

    def test_history_dedupe_ignores_bookkeeping_keys(self, tmp_path,
                                                     monkeypatch):
        import json

        import bench

        row = {"metric": "m", "value": 1.0, "configs": []}
        hist = tmp_path / "h.jsonl"
        hist.write_text(json.dumps(dict(row, timestamp="t1")) + "\n")
        monkeypatch.setattr(bench, "HISTORY_PATH", hist)
        assert bench._history_has(dict(row, salvaged_after_deadline=True))
        assert not bench._history_has(dict(row, value=2.0))


def test_report_write_updates_readme_between_markers(tmp_path):
    """--write keeps the README's committed-measurements table a pure
    projection of bench_history.jsonl (hand-edited numbers are what VERDICT
    r4 called 'indistinguishable from fiction'). Idempotent: a second write
    reports no change."""
    from distributed_pytorch_training_tpu.experiments import report

    readme = tmp_path / "README.md"
    readme.write_text(
        "intro\n\n<!-- bench-table:begin (regen hint) -->\nstale\n"
        "<!-- bench-table:end -->\n\nfooter\n")
    entries = [{"chip": "TPU v5 lite", "timestamp": "2026-07-31T01:05:56Z",
                "vs_baseline": 4.135,
                "configs": [{"model": "resnet18", "bf16": True,
                             "per_device_batch": 4096,
                             "samples_per_sec_chip": 459280.51,
                             "mfu_pct": 52.17}],
                "configs_skipped": ["gpt2_124m"]}]
    assert report.write_readme_table(entries, readme) is True
    text = readme.read_text()
    assert "stale" not in text
    assert "459,281" in text and "52.17%" in text
    assert "still unmeasured on this chip: gpt2_124m" in text
    assert text.startswith("intro\n\n<!-- bench-table:begin")
    assert text.rstrip().endswith("footer")
    # idempotent second write
    assert report.write_readme_table(entries, readme) is False

    # missing markers must fail loudly, not corrupt the file
    bare = tmp_path / "bare.md"
    bare.write_text("no markers here\n")
    try:
        report.write_readme_table(entries, bare)
    except SystemExit as e:
        assert "markers" in str(e)
    else:
        raise AssertionError("expected SystemExit on missing markers")
