"""Checksum-verified dataset fetching (data/download.py) — the torchvision
``CIFAR10(download=(rank==0))`` capability (/root/reference/train_ddp.py:106).

Zero-egress environment, so everything runs against a loopback HTTP server:
fetch, idempotence, atomicity, checksum rejection, retry-on-transient-error,
archive extraction, and the full ensure_cifar10 -> load_cifar10 pipeline on
a miniature but format-exact CIFAR-10 archive.
"""

import hashlib
import http.server
import io
import pickle
import tarfile
import threading

import numpy as np
import pytest

from distributed_pytorch_training_tpu.data.download import (
    ChecksumError, ensure_cifar10, fetch, fetch_and_extract, sha256_file,
)


class _Server:
    """Tiny loopback HTTP server serving an in-memory {path: bytes} dict."""

    def __init__(self, files, fail_first=0):
        self.files = dict(files)
        self.fail_remaining = fail_first
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if outer.fail_remaining > 0:
                    outer.fail_remaining -= 1
                    self.send_error(503, "transient")
                    return
                body = outer.files.get(self.path)
                if body is None:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self.httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()

    def url(self, path):
        host, port = self.httpd.server_address
        return f"http://{host}:{port}{path}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def payload():
    data = b"framework test payload " * 1000
    return data, hashlib.sha256(data).hexdigest()


def test_fetch_verifies_and_is_idempotent(tmp_path, payload):
    data, digest = payload
    srv = _Server({"/blob.bin": data})
    try:
        dest = tmp_path / "blob.bin"
        got = fetch(srv.url("/blob.bin"), str(dest), digest)
        assert got == dest and dest.read_bytes() == data
        assert not dest.with_suffix(".bin.part").exists()  # atomic rename

        # second call must not touch the network at all
        srv.files.clear()
        again = fetch(srv.url("/blob.bin"), str(dest), digest)
        assert again == dest and dest.read_bytes() == data
    finally:
        srv.close()


def test_fetch_rejects_bad_checksum(tmp_path, payload):
    data, _ = payload
    srv = _Server({"/blob.bin": data})
    try:
        with pytest.raises(ChecksumError, match="SHA-256 mismatch"):
            fetch(srv.url("/blob.bin"), str(tmp_path / "x"), "0" * 64,
                  backoff=0)
        # a rejected download leaves NOTHING behind a loader could read
        assert list(tmp_path.iterdir()) == []
    finally:
        srv.close()


def test_fetch_retries_transient_errors(tmp_path, payload):
    data, digest = payload
    srv = _Server({"/blob.bin": data}, fail_first=2)
    try:
        dest = fetch(srv.url("/blob.bin"), str(tmp_path / "b"), digest,
                     retries=3, backoff=0)
        assert sha256_file(dest) == digest
    finally:
        srv.close()


def test_fetch_refetches_corrupt_cache(tmp_path, payload):
    data, digest = payload
    srv = _Server({"/blob.bin": data})
    try:
        dest = tmp_path / "blob.bin"
        dest.write_bytes(b"corrupted cache")
        fetch(srv.url("/blob.bin"), str(dest), digest)
        assert dest.read_bytes() == data
    finally:
        srv.close()


def _mini_cifar_archive():
    """A format-exact (but 20-image) cifar-10-python.tar.gz."""
    rng = np.random.RandomState(0)

    def record(n):
        return {"data": rng.randint(0, 256, (n, 3072), dtype=np.uint8)
                          .astype(np.uint8),
                "labels": rng.randint(0, 10, n).tolist()}

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name in ([f"data_batch_{i}" for i in range(1, 6)]
                     + ["test_batch"]):
            blob = pickle.dumps(record(2))
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    raw = buf.getvalue()
    return raw, hashlib.sha256(raw).hexdigest()


def test_ensure_cifar10_downloads_extracts_and_loads(tmp_path):
    from distributed_pytorch_training_tpu.data.datasets import load_cifar10

    raw, digest = _mini_cifar_archive()
    srv = _Server({"/cifar-10-python.tar.gz": raw})
    try:
        data_dir = tmp_path / "data"
        # absent + download=False: reports False, touches nothing
        assert ensure_cifar10(str(data_dir)) is False

        url = srv.url("/cifar-10-python.tar.gz")
        assert ensure_cifar10(str(data_dir), download=True, url=url,
                              sha256=digest) is True
        ds = load_cifar10(str(data_dir), train=True)
        assert ds is not None and len(ds) == 10 and not ds.synthetic
        assert ds.images.shape == (10, 32, 32, 3)

        # second ensure: files exist, no network (server cleared)
        srv.files.clear()
        assert ensure_cifar10(str(data_dir), download=True, url=url,
                              sha256=digest) is True
    finally:
        srv.close()


def test_get_dataset_download_path(tmp_path):
    """get_dataset(download=True) produces REAL (non-synthetic) data via the
    fetch pipeline — the end-to-end torchvision-contract parity."""
    from distributed_pytorch_training_tpu.data import download as dl
    from distributed_pytorch_training_tpu.data.datasets import get_dataset

    raw, digest = _mini_cifar_archive()
    srv = _Server({"/cifar-10-python.tar.gz": raw})
    try:
        old_url, old_sha = dl.CIFAR10_URL, dl.CIFAR10_SHA256
        dl.CIFAR10_URL = srv.url("/cifar-10-python.tar.gz")
        dl.CIFAR10_SHA256 = digest
        try:
            ds = get_dataset("cifar10", str(tmp_path / "d"), train=True,
                             download=True)
        finally:
            dl.CIFAR10_URL, dl.CIFAR10_SHA256 = old_url, old_sha
        assert not ds.synthetic
        assert len(ds) == 10
    finally:
        srv.close()


def test_fetch_and_extract_rejects_bad_archive_checksum(tmp_path):
    raw, _ = _mini_cifar_archive()
    srv = _Server({"/a.tar.gz": raw})
    try:
        with pytest.raises(ChecksumError):
            fetch_and_extract(srv.url("/a.tar.gz"), str(tmp_path), "f" * 64,
                              backoff=0)
        assert not (tmp_path / "cifar-10-batches-py").exists()
    finally:
        srv.close()


def test_fetch_retries_truncated_body(tmp_path, payload):
    """A connection dropped mid-body raises http.client.IncompleteRead (an
    HTTPException, not an OSError) — it must be retried like any other
    transient network failure."""
    import http.server

    data, digest = payload
    state = {"truncate": 1}

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            if state["truncate"] > 0:
                state["truncate"] -= 1
                self.wfile.write(data[: len(data) // 2])  # truncated body
                self.wfile.flush()
                self.connection.close()
            else:
                self.wfile.write(data)

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/b"
        dest = fetch(url, str(tmp_path / "b"), digest, retries=3, backoff=0)
        assert sha256_file(dest) == digest
    finally:
        httpd.shutdown()
        httpd.server_close()
