"""End-to-end integration: the CLI contract (SURVEY.md §4: "ResNet-18/CIFAR-10
CPU-runnable end-to-end asserting the CSV contract (ref :352-354) and
decreasing loss")."""

import numpy as np
import pytest


@pytest.mark.slow
def test_train_cli_end_to_end(tmp_path, capsys):
    import train

    out = tmp_path / "exp"
    train.main([
        "--epochs", "2", "--synthetic", "--synthetic-size", "512",
        "--batch-size", "8", "--lr", "0.02", "--print-freq", "4", "--seed", "0",
        "--output-dir", str(out), "--cifar-stem",
    ])
    captured = capsys.readouterr().out

    # stdout contract (ref :326-327, :237-242, :374-379)
    assert "Using device:" in captured and "world_size=8" in captured
    assert "Throughput:" in captured and "samples/s (global)" in captured
    assert "[Epoch 2/2]" in captured

    # CSV contract (ref :349-354)
    csv_path = out / "metrics_rank0.csv"
    lines = csv_path.read_text().strip().splitlines()
    assert lines[0] == "epoch,train_loss,train_acc,val_loss,val_acc,epoch_time_seconds"
    assert len(lines) == 3
    rows = [line.split(",") for line in lines[1:]]
    assert [r[0] for r in rows] == ["1", "2"]
    # decreasing train loss across epochs
    assert float(rows[1][1]) < float(rows[0][1])

    # append-only across runs (ref :350): rerun 1 epoch, header not rewritten
    train.main([
        "--epochs", "1", "--synthetic", "--synthetic-size", "512",
        "--batch-size", "8", "--lr", "0.02", "--print-freq", "100", "--seed", "0",
        "--output-dir", str(out), "--cifar-stem",
    ])
    lines2 = csv_path.read_text().strip().splitlines()
    assert len(lines2) == 4 and lines2[0] == lines[0]


@pytest.mark.slow
def test_train_cli_bf16_and_checkpoint_resume(tmp_path):
    import train

    out = tmp_path / "exp_bf16"
    ck = tmp_path / "ckpt"
    common = [
        "--synthetic", "--synthetic-size", "128", "--batch-size", "4",
        "--print-freq", "100", "--seed", "0", "--amp", "--cifar-stem",
        "--output-dir", str(out), "--checkpoint-dir", str(ck),
    ]
    train.main(["--epochs", "1"] + common)
    # resume continues at epoch 2
    train.main(["--epochs", "2", "--resume"] + common)
    lines = (out / "metrics_rank0.csv").read_text().strip().splitlines()
    assert [line.split(",")[0] for line in lines[1:]] == ["1", "2"]


@pytest.mark.slow
def test_train_cli_fsdp_explicit_and_checkpoint_resume(tmp_path, capsys):
    """CLI-level explicit FSDP (ISSUE 7): --fsdp-explicit trains a
    BatchNorm model end to end (flat-sharded at rest, per-layer gathers),
    logs the layer plan, checkpoints the flat layout and resumes from it."""
    import train

    out = tmp_path / "exp_fsdp"
    ck = tmp_path / "ckpt_fsdp"
    common = [
        "--synthetic", "--synthetic-size", "128", "--batch-size", "4",
        "--lr", "0.02", "--print-freq", "100", "--seed", "0",
        "--cifar-stem", "--fsdp-explicit",
        "--output-dir", str(out), "--checkpoint-dir", str(ck),
    ]
    train.main(["--epochs", "1"] + common)
    captured = capsys.readouterr().out
    assert "FSDP (explicit): params + moments flat-sharded 8-way" in captured
    assert "FSDP plan:" in captured and "layer gather group(s)" in captured
    # the reported param count is the model's, not the padded flat total
    assert "11,173,962 params" in captured
    # resume restores the flat-sharded layout and continues at epoch 2
    train.main(["--epochs", "2", "--resume"] + common)
    lines = (out / "metrics_rank0.csv").read_text().strip().splitlines()
    assert [line.split(",")[0] for line in lines[1:]] == ["1", "2"]
    assert float(lines[2].split(",")[1]) < float(lines[1].split(",")[1])


def test_attention_auto_resolution():
    """--attention auto = flash exactly when (LM, TPU backend, no pipeline);
    explicit choices pass through untouched."""
    import train as train_mod

    r = train_mod.resolve_attention
    assert r("auto", True, "tpu", 1) == "flash"
    assert r("auto", True, "tpu", 2) == "xla"      # pipeline stages: einsum
    assert r("auto", True, "cpu", 1) == "xla"      # interpreter-mode pallas
    assert r("auto", True, "gpu", 1) == "xla"      # pltpu scratch won't lower
    assert r("auto", False, "tpu", 1) == "xla"     # image models
    # auto never errors where the old default worked: S=2056 has no usable
    # flash block (raise for explicit flash), so auto stays on xla
    assert r("auto", True, "tpu", 1, seq_len=2056) == "xla"
    assert r("auto", True, "tpu", 1, seq_len=4096) == "flash"
    for explicit in ("xla", "flash", "ring", "ulysses"):
        assert r(explicit, True, "cpu", 4) == explicit


@pytest.mark.slow
def test_train_cli_pipeline_parallel_end_to_end(tmp_path, capsys):
    """CLI-level GPipe run: --mesh pipe=2 + --microbatches drives the
    stage-stacked GPT-2 through train.py's full orchestration (stage
    placement, microbatch split, CSV/stdout contract) — the pipeline path
    previously pinned only at trainer level (tests/test_pipeline.py)."""
    import train

    out = tmp_path / "exp"
    train.main([
        "--model", "gpt2_124m",
        # depth must be divisible by pipe stages; widths shrunk for CPU
        # full 50257 vocab: the synthetic gpt2 tokens use it, and a
        # shrunk vocab now fails the startup vocab guard (by design)
        "--model-overrides",
        "depth=4,hidden_dim=32,num_heads=2,max_position=32",
        "--mesh", "pipe=2,data=4", "--microbatches", "2",
        "--synthetic", "--synthetic-size", "64",
        "--epochs", "1", "--batch-size", "2", "--seq-len", "32",
        "--optimizer", "adamw", "--lr", "0.001",
        "--print-freq", "2", "--seed", "0", "--output-dir", str(out),
    ])
    captured = capsys.readouterr().out
    assert "pipe': 2" in captured or "pipe=2" in captured.replace('"', "'")

    lines = (out / "metrics_rank0.csv").read_text().strip().splitlines()
    assert lines[0] == ("epoch,train_loss,train_acc,val_loss,val_acc,"
                        "epoch_time_seconds")
    row = lines[1].split(",")
    assert row[0] == "1"
    import math
    # finite and plausible for a near-uniform 50257-way next-token model
    assert 0 < float(row[1]) < 12.5 and math.isfinite(float(row[1]))


def test_train_cli_rejects_vocab_smaller_than_data(tmp_path):
    """A model vocab shrunk below the dataset's vocab must fail loudly at
    startup: out-of-range ids gather as NaN (observed: a pipeline CLI run
    trained straight to NaN loss with no diagnostic)."""
    import pytest as _pytest

    import train

    with _pytest.raises(ValueError, match="exceeds the model's vocab_size"):
        train.main([
            "--model", "gpt2_124m",
            "--model-overrides",
            "vocab_size=128,depth=2,hidden_dim=32,num_heads=2,max_position=32",
            "--synthetic", "--synthetic-size", "32",
            "--epochs", "1", "--batch-size", "2", "--seq-len", "32",
            "--optimizer", "adamw", "--seed", "0",
            "--output-dir", str(tmp_path / "exp"),
        ])
