"""Model zoo tests: shapes, param-count parity with torchvision, registry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models import get_model, list_models


def _param_count(params):
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


class TestResNet:
    def test_resnet18_param_count_matches_torchvision(self):
        """torchvision.models.resnet18(num_classes=10) (ref :154) has
        11,181,642 parameters — architecture parity check."""
        model = get_model("resnet18", num_classes=10)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32, 32, 3)), train=False)
        # count params + batch_stats the way torch's numel over parameters()
        # counts (torch excludes BN running stats from parameters())
        assert _param_count(variables["params"]) == 11_181_642

    def test_resnet50_param_count(self):
        """torchvision resnet50(num_classes=1000): 25,557,032 params."""
        model = get_model("resnet50", num_classes=1000)
        variables = jax.eval_shape(
            lambda: get_model("resnet50", num_classes=1000).init(
                jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)), train=False))
        total = sum(int(np.prod(x.shape))
                    for x in jax.tree_util.tree_leaves(variables["params"]))
        assert total == 25_557_032

    def test_forward_shapes(self):
        model = get_model("resnet18", num_classes=10)
        x = jnp.zeros((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        logits = model.apply(variables, x, train=False)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

    def test_bf16_compute_fp32_logits(self):
        model = get_model("resnet18", num_classes=10, dtype=jnp.bfloat16)
        x = jnp.zeros((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        for leaf in jax.tree_util.tree_leaves(variables["params"]):
            assert leaf.dtype == jnp.float32  # params stored fp32
        logits = model.apply(variables, x, train=False)
        assert logits.dtype == jnp.float32  # loss math in fp32

    def test_train_mode_updates_batch_stats(self):
        model = get_model("resnet18", num_classes=10, cifar_stem=True)
        x = jnp.ones((4, 16, 16, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        _, mutated = model.apply(variables, x, train=True,
                                 mutable=["batch_stats"])
        before = jax.tree_util.tree_leaves(variables["batch_stats"])
        after = jax.tree_util.tree_leaves(mutated["batch_stats"])
        assert any(not np.allclose(a, b) for a, b in zip(before, after))

    def test_cifar_stem_changes_spatial_handling(self):
        # ImageNet stem downsamples 32->8 before stages; cifar stem keeps 32.
        m_std = get_model("resnet18", num_classes=10)
        m_cif = get_model("resnet18", num_classes=10, cifar_stem=True)
        x = jnp.zeros((1, 32, 32, 3))
        v1 = m_std.init(jax.random.PRNGKey(0), x, train=False)
        v2 = m_cif.init(jax.random.PRNGKey(0), x, train=False)
        # both produce valid logits
        assert m_std.apply(v1, x, train=False).shape == (1, 10)
        assert m_cif.apply(v2, x, train=False).shape == (1, 10)


class TestRegistry:
    def test_list_and_errors(self):
        assert "resnet18" in list_models() and "resnet50" in list_models()
        with pytest.raises(ValueError, match="unknown model"):
            get_model("resnet99")


def test_remat_preserves_values_and_grads():
    """--remat (gradient checkpointing) must be a memory/compute trade with
    ZERO math change: identical logits, identical grads, identical param
    tree. The TPU HBM-for-FLOPs idiom (jax.checkpoint per block)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead

    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    kw = dict(vocab_size=64, hidden_dim=32, depth=2, num_heads=2,
              max_position=16)
    plain = GPT2LMHead(**kw)
    remat = GPT2LMHead(remat=True, **kw)

    variables = plain.init(jax.random.PRNGKey(0), ids, train=False)
    v2 = remat.init(jax.random.PRNGKey(0), ids, train=False)
    assert (jax.tree_util.tree_structure(variables)
            == jax.tree_util.tree_structure(v2))

    out_plain = plain.apply(variables, ids, train=False)
    out_remat = remat.apply(variables, ids, train=False)
    np.testing.assert_allclose(np.asarray(out_plain), np.asarray(out_remat),
                               rtol=1e-6, atol=1e-6)

    def loss(m, v):
        return (m.apply(v, ids, train=False) ** 2).mean()

    g1 = jax.grad(lambda v: loss(plain, v))(variables)
    g2 = jax.grad(lambda v: loss(remat, v))(variables)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), g1, g2)


@pytest.mark.slow  # ~7 s apply smoke; remat exactness stays fast via test_remat_preserves_values_and_grads, bert/vit forwards ride the LM-task suites
def test_remat_bert_and_vit_apply():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_pytorch_training_tpu.models import get_model

    ids = jnp.zeros((1, 16), jnp.int32)
    bert = get_model("bert_base", hidden_dim=32, depth=2, num_heads=2,
                     mlp_dim=64, max_position=16, remat=True)
    v = bert.init(jax.random.PRNGKey(0), ids, train=False)
    assert np.isfinite(np.asarray(bert.apply(v, ids, train=False))).all()

    imgs = jnp.zeros((1, 32, 32, 3), jnp.float32)
    vit = get_model("vit_b16", num_classes=10, hidden_dim=32, depth=2,
                    num_heads=2, mlp_dim=64, patch_size=16, remat=True)
    v = vit.init(jax.random.PRNGKey(0), imgs, train=False)
    assert np.isfinite(np.asarray(vit.apply(v, imgs, train=False))).all()


def test_lm_flagship_param_counts():
    """Pin the flagship LM architectures (BASELINE.json:11-12). GPT-2 sizes
    are exact matches for the HF reference checkpoints (gpt2: 124,439,808;
    gpt2-medium: 354,823,168 — tied wte/lm_head like HF); BERT-base pins our
    own MLM-head construction (~109.5M, within 0.03% of HF bert-base)."""
    expected = {
        "gpt2_124m": 124_439_808,
        "gpt2_355m": 354_823_168,
        "bert_base": 109_514_298,
    }
    for name, want in expected.items():
        variables = jax.eval_shape(
            lambda n=name: get_model(n).init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                train=False))
        got = sum(int(np.prod(x.shape))
                  for x in jax.tree_util.tree_leaves(variables["params"]))
        assert got == want, f"{name}: {got:,} != {want:,}"
