"""Preemption guard (training/preemption.py): SIGTERM requests a graceful
stop; train.py checkpoints — step-granular since r4 — and a relaunch
resumes the exact trajectory."""

import os
import signal
import threading

import jax
import pytest
import numpy as np

from distributed_pytorch_training_tpu.training.preemption import (
    PreemptionGuard,
)


def test_sigterm_sets_stop_flag():
    guard = PreemptionGuard.install()
    assert not guard.should_stop
    os.kill(os.getpid(), signal.SIGTERM)
    assert guard.should_stop
    guard.reset()


def test_install_is_idempotent_and_rearms():
    g1 = PreemptionGuard.install()
    g1.request_stop()
    g2 = PreemptionGuard.install()  # fresh run: stale flag cleared
    assert g1 is g2
    assert not g2.should_stop


def test_signal_arms_hard_deadline(monkeypatch):
    """A SIGTERM that lands while the process is stuck (mid-compile, wedged
    backend) must still kill it: the first signal arms a hard deadline that
    force-exits if the graceful path never completes. A swallowed SIGTERM
    zombie keeps its device claim and wedges the chip for every later job."""
    monkeypatch.setenv("DPT_PREEMPT_GRACE_SECONDS", "0.2")
    guard = PreemptionGuard.install()
    fired = threading.Event()
    guard._force_exit = fired.set  # observe instead of os._exit(143)
    os.kill(os.getpid(), signal.SIGTERM)
    assert guard.should_stop
    assert fired.wait(timeout=2.0), "hard-exit deadline never fired"
    guard.reset()


def test_disarm_cancels_hard_deadline(monkeypatch):
    monkeypatch.setenv("DPT_PREEMPT_GRACE_SECONDS", "0.3")
    guard = PreemptionGuard.install()
    fired = threading.Event()
    guard._force_exit = fired.set
    os.kill(os.getpid(), signal.SIGTERM)
    guard.disarm()  # graceful path completed promptly
    assert not fired.wait(timeout=0.8), "deadline fired after disarm"
    guard.reset()


@pytest.mark.slow
def test_midepoch_resume_matches_uninterrupted_trajectory(tmp_path, mesh8):
    """The r3 story lost up to an epoch on preemption (VERDICT r3 #5). Now:
    stop after k steps MID-epoch, checkpoint (epoch, step), restore into a
    fresh state, resume at start_step=k — the final params must be
    bit-identical to a never-interrupted run. Pins the whole chain:
    deterministic sampler offset + state.step-folded RNG + (epoch, step)
    metadata."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from test_training import _tiny_setup

    from distributed_pytorch_training_tpu.data.datasets import ArrayDataset
    from distributed_pytorch_training_tpu.data.loader import ShardedLoader
    from distributed_pytorch_training_tpu.training.checkpoint import (
        CheckpointManager,
    )

    trainer, state0, images, labels = _tiny_setup(mesh8, n=64)
    ds = ArrayDataset(images=images, labels=labels, num_classes=4,
                      name="tiny", synthetic=True)
    loader = ShardedLoader(ds, mesh8, per_device_batch=2, shuffle=True,
                           seed=0)  # 64 / (2*8) = 4 steps per epoch
    spe = len(loader)
    assert spe == 4

    # --- run A: uninterrupted, 2 epochs -----------------------------------
    state_a = state0
    for epoch in range(2):
        state_a, *_ = trainer.train_epoch(
            state_a, loader.epoch(epoch), epoch, spe)

    # --- run B: stop after 2 steps of epoch 0, checkpoint, resume ---------
    # fresh (bit-identical) initial state: run A's first step DONATED
    # state0's buffers (TrainConfig.donate_state), so reusing state0 here
    # would execute against deleted buffers
    _, state_b, _, _ = _tiny_setup(mesh8, n=64)
    executed = [0]

    def stop_after_two():
        executed[0] += 1
        return executed[0] >= 2

    state_b, _, _, _, steps_done = trainer.train_epoch(
        state_b, loader.epoch(0), 0, spe, stop_fn=stop_after_two)
    assert steps_done == 2  # genuinely mid-epoch

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(0 * spe + steps_done, state_b, wait=True, epoch=0,
             step_in_epoch=steps_done)

    # fresh process stand-in: new template state, restore coordinates
    _, template, _, _ = _tiny_setup(mesh8, n=64)
    restored, r_epoch, r_step = mgr.restore_latest(template)
    mgr.close()
    assert (r_epoch, r_step) == (0, 2)

    state_b = restored
    for epoch in range(r_epoch, 2):
        start = r_step if epoch == r_epoch else 0
        state_b, *_ = trainer.train_epoch(
            state_b, loader.epoch(epoch, start_step=start), epoch, spe,
            start_step=start)

    assert int(state_b.step) == int(state_a.step)
    for a, b in zip(jax.tree_util.tree_leaves(state_a.params),
                    jax.tree_util.tree_leaves(state_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_cli_checkpoints_on_preemption(tmp_path, mesh8):
    """Drive main() with SIGTERM arriving mid-run: it must stop early at an
    epoch boundary, write a checkpoint, and a --resume run continues."""
    import train as train_mod

    ckpt_dir = tmp_path / "ckpt"
    epochs = 50
    # deliver the real signal once training is underway
    timer = threading.Timer(
        3.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
    timer.start()
    try:
        train_mod.main([
            "--epochs", str(epochs), "--synthetic", "--synthetic-size", "64",
            "--batch-size", "8", "--model", "resnet18", "--cifar-stem",
            "--checkpoint-dir", str(ckpt_dir),
            "--output-dir", str(tmp_path / "out"),
        ])
    finally:
        timer.cancel()
        PreemptionGuard.install()  # disarm for other tests
    saved = sorted(int(p.name) for p in ckpt_dir.iterdir()
                   if p.name.isdigit())
    assert saved, "preempted run must leave a checkpoint"
    stopped_at = max(saved)
    assert stopped_at < epochs, "run must have stopped early"

    # resume continues from the checkpoint
    train_mod.main([
        "--epochs", str(stopped_at + 1), "--synthetic",
        "--synthetic-size", "64", "--batch-size", "8",
        "--model", "resnet18", "--cifar-stem",
        "--checkpoint-dir", str(ckpt_dir), "--resume",
        "--output-dir", str(tmp_path / "out2"),
    ])
    saved2 = sorted(int(p.name) for p in ckpt_dir.iterdir()
                    if p.name.isdigit())
    assert max(saved2) == stopped_at + 1
