"""Preemption guard (training/preemption.py): SIGTERM requests a graceful
stop; train.py checkpoints at the epoch boundary and a relaunch resumes."""

import os
import signal
import threading

from distributed_pytorch_training_tpu.training.preemption import (
    PreemptionGuard,
)


def test_sigterm_sets_stop_flag():
    guard = PreemptionGuard.install()
    assert not guard.should_stop
    os.kill(os.getpid(), signal.SIGTERM)
    assert guard.should_stop
    guard.reset()


def test_install_is_idempotent_and_rearms():
    g1 = PreemptionGuard.install()
    g1.request_stop()
    g2 = PreemptionGuard.install()  # fresh run: stale flag cleared
    assert g1 is g2
    assert not g2.should_stop


def test_signal_arms_hard_deadline(monkeypatch):
    """A SIGTERM that lands while the process is stuck (mid-compile, wedged
    backend) must still kill it: the first signal arms a hard deadline that
    force-exits if the graceful path never completes. A swallowed SIGTERM
    zombie keeps its device claim and wedges the chip for every later job."""
    monkeypatch.setenv("DPT_PREEMPT_GRACE_SECONDS", "0.2")
    guard = PreemptionGuard.install()
    fired = threading.Event()
    guard._force_exit = fired.set  # observe instead of os._exit(143)
    os.kill(os.getpid(), signal.SIGTERM)
    assert guard.should_stop
    assert fired.wait(timeout=2.0), "hard-exit deadline never fired"
    guard.reset()


def test_disarm_cancels_hard_deadline(monkeypatch):
    monkeypatch.setenv("DPT_PREEMPT_GRACE_SECONDS", "0.3")
    guard = PreemptionGuard.install()
    fired = threading.Event()
    guard._force_exit = fired.set
    os.kill(os.getpid(), signal.SIGTERM)
    guard.disarm()  # graceful path completed promptly
    assert not fired.wait(timeout=0.8), "deadline fired after disarm"
    guard.reset()


def test_cli_checkpoints_on_preemption(tmp_path, mesh8):
    """Drive main() with SIGTERM arriving mid-run: it must stop early at an
    epoch boundary, write a checkpoint, and a --resume run continues."""
    import train as train_mod

    ckpt_dir = tmp_path / "ckpt"
    epochs = 50
    # deliver the real signal once training is underway
    timer = threading.Timer(
        3.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
    timer.start()
    try:
        train_mod.main([
            "--epochs", str(epochs), "--synthetic", "--synthetic-size", "64",
            "--batch-size", "8", "--model", "resnet18", "--cifar-stem",
            "--checkpoint-dir", str(ckpt_dir),
            "--output-dir", str(tmp_path / "out"),
        ])
    finally:
        timer.cancel()
        PreemptionGuard.install()  # disarm for other tests
    saved = sorted(int(p.name) for p in ckpt_dir.iterdir()
                   if p.name.isdigit())
    assert saved, "preempted run must leave a checkpoint"
    stopped_at = max(saved)
    assert stopped_at < epochs, "run must have stopped early"

    # resume continues from the checkpoint
    train_mod.main([
        "--epochs", str(stopped_at + 1), "--synthetic",
        "--synthetic-size", "64", "--batch-size", "8",
        "--model", "resnet18", "--cifar-stem",
        "--checkpoint-dir", str(ckpt_dir), "--resume",
        "--output-dir", str(tmp_path / "out2"),
    ])
    saved2 = sorted(int(p.name) for p in ckpt_dir.iterdir()
                    if p.name.isdigit())
    assert max(saved2) == stopped_at + 1
