"""AST lint engine (analysis/ast_rules.py): every rule has a mutation test
(a synthetic violation it must flag) and a false-positive test (idiomatic
code it must NOT flag) — the analyzer is verified, not just green.
"""

import textwrap

import pytest

from distributed_pytorch_training_tpu.analysis.ast_rules import (
    AXIS_NAMES, FileContext, iter_source_files, run_ast_rules,
    traced_function_names,
)


def _lint(tmp_path, source, rules=None, name="mod.py"):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_ast_rules(files=[path], rules=rules)


def _rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# shard-map-shim-only
# ---------------------------------------------------------------------------


class TestShardMapShimOnly:
    def test_mutation_every_import_form_flags(self, tmp_path):
        for src in (
            "import jax.experimental.shard_map\n",
            "from jax.experimental.shard_map import shard_map\n",
            "from jax.experimental import shard_map\n",
            "from jax.experimental import mesh_utils, shard_map\n",
            "from jax import shard_map\n",
            "import jax\nf = jax.shard_map(lambda x: x)\n",
            "import jax\nf = jax.experimental.shard_map.shard_map\n",
        ):
            findings = _lint(tmp_path, src, rules=["shard-map-shim-only"])
            assert findings, f"did not flag: {src!r}"

    def test_chained_attribute_use_reports_once(self, tmp_path):
        """`jax.experimental.shard_map.shard_map` is ONE use, not two —
        the inner Attribute chain must not double the finding count."""
        src = "import jax\nf = jax.experimental.shard_map.shard_map\n"
        findings = _lint(tmp_path, src, rules=["shard-map-shim-only"])
        assert len(findings) == 1, findings

    def test_mutation_check_rep_kwarg_outside_shim_flags(self, tmp_path):
        src = """
            from distributed_pytorch_training_tpu.parallel import shard_map
            f = shard_map(lambda x: x, mesh=None, in_specs=None,
                          out_specs=None, check_rep=False)
        """
        findings = _lint(tmp_path, src, rules=["shard-map-shim-only"])
        assert _rules_of(findings) == {"shard-map-shim-only"}
        assert "check_rep" in findings[0].message
        # the renamed flag is the same violation
        src_vma = src.replace("check_rep", "check_vma")
        assert _lint(tmp_path, src_vma, rules=["shard-map-shim-only"])

    def test_docstring_and_string_mentions_do_not_flag(self, tmp_path):
        """THE false-positive class the regex lint had (ISSUE 3 satellite):
        prose about the entry points is not a use of them."""
        src = '''
            """Module docs: jax.experimental.shard_map moved to
            jax.shard_map; never `from jax.experimental import shard_map`.
            """
            MSG = "use jax.shard_map via the shim"

            def f():
                """Docs quoting jax.experimental.shard_map.shard_map(...)."""
                return MSG  # comment: jax.shard_map is the new entry point
        '''
        assert _lint(tmp_path, src, rules=["shard-map-shim-only"]) == []

    def test_shim_import_from_parallel_is_clean(self, tmp_path):
        src = """
            from distributed_pytorch_training_tpu.parallel import shard_map
            g = shard_map(lambda x: x, mesh=None, in_specs=None,
                          out_specs=None)
        """
        assert _lint(tmp_path, src, rules=["shard-map-shim-only"]) == []


# ---------------------------------------------------------------------------
# no-impure-calls-in-traced
# ---------------------------------------------------------------------------


class TestImpureCallsInTraced:
    def test_mutation_time_random_nprandom_flag(self, tmp_path):
        src = """
            import time, random
            import numpy as np
            import jax

            def step(x):
                t = time.perf_counter()
                r = random.random()
                z = np.random.rand(3)
                return x + t + r + z.sum()

            f = jax.jit(step)
        """
        findings = _lint(tmp_path, src,
                         rules=["no-impure-calls-in-traced"])
        msgs = "\n".join(f.message for f in findings)
        assert len(findings) == 3, msgs
        assert "time.perf_counter" in msgs
        assert "random.random" in msgs
        assert "numpy.random.rand" in msgs

    def test_mutation_nested_and_decorated_and_from_imports(self, tmp_path):
        src = """
            import jax
            from functools import partial
            from time import time as now

            @partial(jax.jit, donate_argnums=(0,))
            def step(x):
                def inner(y):
                    return y * now()
                return inner(x)
        """
        findings = _lint(tmp_path, src,
                         rules=["no-impure-calls-in-traced"])
        assert len(findings) == 1 and "time.time" in findings[0].message

    def test_shard_map_body_by_name_is_traced(self, tmp_path):
        src = """
            import numpy as np
            from distributed_pytorch_training_tpu.parallel import shard_map

            def body(x):
                return x * np.random.rand()

            f = shard_map(body, mesh=None, in_specs=None, out_specs=None)
        """
        findings = _lint(tmp_path, src,
                         rules=["no-impure-calls-in-traced"])
        assert len(findings) == 1

    def test_pure_numpy_shape_math_and_untraced_calls_clean(self, tmp_path):
        src = """
            import time
            import numpy as np
            import jax

            def step(x):
                n = np.prod(np.shape(x)) or 1   # trace-time shape math: OK
                k = jax.random.fold_in(jax.random.PRNGKey(0), 1)  # pure
                return x.reshape(n) + jax.random.normal(k, (n,))

            f = jax.jit(step)

            def host_loop():
                return time.time()  # not traced: OK
        """
        assert _lint(tmp_path, src,
                     rules=["no-impure-calls-in-traced"]) == []


# ---------------------------------------------------------------------------
# no-host-sync-in-step
# ---------------------------------------------------------------------------


class TestHostSyncInStep:
    def test_mutation_item_float_device_get_flag(self, tmp_path):
        src = """
            import jax

            class Trainer:
                def _train_step_impl(self, state, batch):
                    loss = compute(state, batch)
                    host = loss.item()
                    also = float(loss)
                    got = jax.device_get(loss)
                    return host + also + got
        """
        findings = _lint(tmp_path, src, rules=["no-host-sync-in-step"],
                         name="training/loop.py")
        assert len(findings) == 3
        msgs = "\n".join(f.message for f in findings)
        assert ".item()" in msgs and "float()" in msgs \
            and "jax.device_get" in msgs

    def test_scoped_to_loop_py_and_step_paths_only(self, tmp_path):
        src_other = """
            def _train_step_impl(self, state):
                return float(state)
        """
        # same violation in another file: out of scope
        assert _lint(tmp_path, src_other, rules=["no-host-sync-in-step"],
                     name="training/other.py") == []
        # loop.py, but a print-boundary fetch OUTSIDE the step path: allowed
        src_epoch = """
            def train_epoch(self, state, batches):
                for b in batches:
                    state, metrics = self._train_step(state, b)
                return float(metrics)
        """
        assert _lint(tmp_path, src_epoch, rules=["no-host-sync-in-step"],
                     name="training/loop.py") == []
        # float(literal) in a step path is not a device sync
        src_lit = """
            def _eval_step_impl(self, state):
                return state * float(2)
        """
        assert _lint(tmp_path, src_lit, rules=["no-host-sync-in-step"],
                     name="training/loop.py") == []


# ---------------------------------------------------------------------------
# axis-name-registry
# ---------------------------------------------------------------------------


class TestAxisNameRegistry:
    def test_registry_matches_mesh_module(self):
        """The lint registry is import-free by design; it must stay the
        mirror of the real one (parallel/mesh.py AXIS_NAMES)."""
        from distributed_pytorch_training_tpu.parallel import mesh

        assert AXIS_NAMES == mesh.AXIS_NAMES == frozenset(mesh.AXIS_ORDER)

    def test_mutation_literals_in_axis_positions_flag(self, tmp_path):
        src = """
            from jax import lax
            from jax.sharding import PartitionSpec as P
            from distributed_pytorch_training_tpu.parallel.collectives import (
                all_gather, psum,
            )

            def body(x):
                a = lax.psum(x, "data")
                b = psum(x, ("data", "fsdp"))
                c = all_gather(x, axis_name="model")
                return a + b + c

            SPEC = P("data", None)
        """
        findings = _lint(tmp_path, src, rules=["axis-name-registry"])
        flagged = sorted(f.message.split("'")[1] for f in findings)
        assert flagged == ["data", "data", "data", "fsdp", "model"], findings

    def test_non_axis_positions_do_not_flag(self, tmp_path):
        src = """
            cfg = {"model": "resnet18", "seq": 16}

            def report(cfg):
                return cfg.get("model"), cfg["seq"], "data"

            def loss(x):
                return x.sum("data")  # not a collective call
        """
        assert _lint(tmp_path, src, rules=["axis-name-registry"]) == []


# ---------------------------------------------------------------------------
# no-bare-os-exit
# ---------------------------------------------------------------------------


class TestNoBareOsExit:
    def test_mutation_every_call_form_flags(self, tmp_path):
        """A synthetic os._exit in any import form must be caught — abrupt
        claim-holder death wedges the server-side TPU grant (observed
        live), so the primitive lives ONLY behind heartbeat.hard_exit."""
        for src in (
            "import os\nos._exit(1)\n",
            "import os as operating\noperating._exit(2)\n",
            "from os import _exit\n_exit(3)\n",
            # aliasing is the same hazard with one extra hop
            "import os\nex = os._exit\n",
        ):
            findings = _lint(tmp_path, src, rules=["no-bare-os-exit"])
            assert _rules_of(findings) == {"no-bare-os-exit"}, src

    def test_heartbeat_home_is_exempt(self, tmp_path):
        src = "import os\n\ndef hard_exit(code):\n    os._exit(code)\n"
        findings = _lint(tmp_path, src, rules=["no-bare-os-exit"],
                         name="resilience/heartbeat.py")
        assert findings == []

    def test_per_line_suppression_honored(self, tmp_path):
        src = ("import os\n"
               "os._exit(70)  # analysis: disable=no-bare-os-exit\n")
        assert _lint(tmp_path, src, rules=["no-bare-os-exit"]) == []

    def test_docstring_mentions_and_sys_exit_clean(self, tmp_path):
        src = '''
            import sys

            def stop():
                """Docs may say os._exit without tripping the rule."""
                sys.exit(1)  # a normal exit is not an abrupt one

            comment = "os._exit(70) as a string is prose, not a call"
        '''
        assert _lint(tmp_path, src, rules=["no-bare-os-exit"]) == []


# ---------------------------------------------------------------------------
# pallas-call-in-ops-only
# ---------------------------------------------------------------------------


class TestPallasCallInOpsOnly:
    def test_mutation_every_import_form_flags(self, tmp_path):
        """A raw pl.pallas_call outside ops/ ships an ungated kernel (no
        backend gate, no interpreter fallback) — every import form must be
        caught (ISSUE 6 satellite)."""
        for src in (
            "from jax.experimental import pallas as pl\n"
            "k = pl.pallas_call(None, out_shape=None)\n",
            "from jax.experimental.pallas import pallas_call\n"
            "k = pallas_call(None, out_shape=None)\n",
            "import jax.experimental.pallas as pl\n"
            "k = pl.pallas_call\n",  # aliasing: same escape, one extra hop
        ):
            findings = _lint(tmp_path, src,
                             rules=["pallas-call-in-ops-only"])
            assert _rules_of(findings) == {"pallas-call-in-ops-only"}, src

    def test_ops_home_is_exempt(self, tmp_path):
        src = ("from jax.experimental import pallas as pl\n"
               "k = pl.pallas_call(None, out_shape=None)\n")
        findings = _lint(
            tmp_path, src, rules=["pallas-call-in-ops-only"],
            name="distributed_pytorch_training_tpu/ops/mykernel.py")
        assert findings == []

    def test_lookalike_ops_dir_not_exempt(self, tmp_path):
        """Exact trailing-component match (the OS_EXIT_HOME convention): a
        future `somewhere_else/ops/` must not inherit the exemption."""
        src = ("from jax.experimental import pallas as pl\n"
               "k = pl.pallas_call(None, out_shape=None)\n")
        findings = _lint(tmp_path, src, rules=["pallas-call-in-ops-only"],
                         name="serving/ops/rogue.py")
        assert _rules_of(findings) == {"pallas-call-in-ops-only"}

    def test_docstring_mentions_and_suppression_clean(self, tmp_path):
        src = '''
            """Prose about pl.pallas_call is not a kernel escape."""
            from jax.experimental import pallas as pl

            grid = pl.BlockSpec  # other pallas APIs are not the kernel
            MSG = "wrap pl.pallas_call in ops/ behind a gate"
        '''
        assert _lint(tmp_path, src,
                     rules=["pallas-call-in-ops-only"]) == []
        suppressed = (
            "from jax.experimental import pallas as pl\n"
            "k = pl.pallas_call  "
            "# analysis: disable=pallas-call-in-ops-only\n")
        assert _lint(tmp_path, suppressed,
                     rules=["pallas-call-in-ops-only"]) == []

    def test_repo_ops_kernels_are_the_only_users(self):
        """The rule binds on the real tree: every pallas_call in the repo
        lives under the package's ops/ (flash/ring/ulysses attention, the
        fused quantize codecs)."""
        assert run_ast_rules(rules=["pallas-call-in-ops-only"]) == []


# ---------------------------------------------------------------------------
# profiler-session-via-stepprofiler-only
# ---------------------------------------------------------------------------


class TestProfilerSessionHome:
    RULE = ["profiler-session-via-stepprofiler-only"]

    def test_mutation_every_use_form_flags(self, tmp_path):
        for src in (
            "import jax\njax.profiler.start_trace('/tmp/t')\n",
            "import jax\njax.profiler.stop_trace()\n",
            "import jax\nst = jax.profiler.start_trace\nst('/tmp/t')\n",
            "from jax.profiler import start_trace\nstart_trace('/tmp/t')\n",
            "from jax.profiler import stop_trace as halt\nhalt()\n",
        ):
            findings = _lint(tmp_path, src, rules=self.RULE)
            assert findings, f"did not flag: {src!r}"
            assert _rules_of(findings) == set(self.RULE)

    def test_profiling_home_is_exempt(self, tmp_path):
        src = ("import jax\n\ndef open_session(d):\n"
               "    jax.profiler.start_trace(d)\n")
        assert _lint(tmp_path, src, rules=self.RULE,
                     name="utils/profiling.py") == []
        # exact path-component match: lookalikes must not inherit it
        assert _lint(tmp_path, src, rules=self.RULE,
                     name="myutils/profiling.py") != []
        assert _lint(tmp_path, src, rules=self.RULE,
                     name="utils/my_profiling.py") != []

    def test_docstring_mentions_and_other_profiler_api_clean(self,
                                                             tmp_path):
        src = '''
            """Docs may say jax.profiler.start_trace freely."""
            import jax

            def annotate(name):
                # other jax.profiler API is not a session entry point
                return jax.profiler.TraceAnnotation(name)
        '''
        assert _lint(tmp_path, src, rules=self.RULE) == []
        suppressed = (
            "import jax\njax.profiler.start_trace('/t')  "
            "# analysis: disable=profiler-session-via-stepprofiler-only\n")
        assert _lint(tmp_path, suppressed, rules=self.RULE) == []

    def test_repo_profiling_is_the_only_user(self):
        """The rule binds on the real tree: every raw session entry in
        the repo lives in utils/profiling.py (trace_analysis's
        capture_step_trace migrated onto trace_session)."""
        assert run_ast_rules(rules=self.RULE) == []


# ---------------------------------------------------------------------------
# control-decisions-gated (ISSUE 20)
# ---------------------------------------------------------------------------


class TestControlDecisionsGated:
    RULE = ["control-decisions-gated"]

    def test_mutation_every_reference_form_flags(self, tmp_path):
        """A control/ policy module touching the re-plan surface is the
        gate bypass this rule exists for — attribute calls, bare names
        from imports, AND bound-method aliasing (the one-extra-hop
        bypass) must all flag."""
        for src in (
            "def decide(sup, report, state):\n"
            "    return sup.boundary_shrink(report, state, epoch=0,"
            " step=1)\n",
            "def decide(sup, report, state):\n"
            "    return sup.boundary_retune(report, state, epoch=0,"
            " step=1, overrides={})\n",
            "from ..resilience.elastic import reshard_train_state\n"
            "def decide(state):\n"
            "    return reshard_train_state(state, 8, 4, None, None)\n",
            "from ..resilience.elastic import plan_elastic_world\n"
            "W = plan_elastic_world(7, 16)\n",
            "def decide(sup):\n"
            "    commit = sup.boundary_shrink\n"   # aliasing is the same
            "    return commit\n",                  # bypass
            "def decide(sup, report, state, epoch, step):\n"
            "    return sup._maybe_grow(report, state, epoch, step)\n",
            "def decide(sup):\n"
            "    return sup.replan_cb(4)\n",
        ):
            findings = _lint(tmp_path, src, rules=self.RULE,
                             name="control/policy.py")
            assert findings, f"did not flag: {src!r}"
            assert _rules_of(findings) == set(self.RULE)

    def test_apply_home_is_exempt(self, tmp_path):
        """control/apply.py IS the one sanctioned entry — the same code
        there is clean; a lookalike directory must not inherit the
        exemption."""
        src = ("def _apply_evict(sup, report, state):\n"
               "    return sup.boundary_shrink(report, state, epoch=0,"
               " step=1)\n")
        assert _lint(tmp_path, src, rules=self.RULE,
                     name="control/apply.py") == []
        assert _lint(tmp_path, src, rules=self.RULE,
                     name="mycontrol/apply.py") == []   # not a control/ dir
        assert _lint(tmp_path, src, rules=self.RULE,
                     name="control/apply_helpers.py") != []

    def test_outside_control_is_out_of_scope(self, tmp_path):
        """The Supervisor and the elastic module CALL this surface —
        that is their job; the rule binds only inside control/."""
        src = ("def run(sup, report, state):\n"
               "    return sup.boundary_shrink(report, state, epoch=0,"
               " step=1)\n")
        assert _lint(tmp_path, src, rules=self.RULE,
                     name="resilience/supervisor_helper.py") == []

    def test_docstring_mentions_do_not_flag(self, tmp_path):
        src = '''
            """Policies PROPOSE; control/apply.py commits via
            Supervisor.boundary_shrink / boundary_retune after the
            contract gate (reshard_train_state, plan_elastic_world)."""
            NOTE = "see boundary_retune for the apply path"

            def propose():
                """Docs quoting replan_cb(survivors) are not a call."""
                return NOTE
        '''
        assert _lint(tmp_path, src, rules=self.RULE,
                     name="control/notes.py") == []

    def test_repo_control_package_is_clean(self):
        """The rule binds on the real tree: every re-plan reference in
        control/ lives in apply.py."""
        assert run_ast_rules(rules=self.RULE) == []


# ---------------------------------------------------------------------------
# engine mechanics
# ---------------------------------------------------------------------------


class TestTelemetryEmitOutsideTraced:
    RULE = ["telemetry-emit-outside-traced"]

    def test_mutation_every_import_form_flags(self, tmp_path):
        header = "import jax\n"
        footer = "jax.jit(step)\n"
        for body in (
            # absolute package import, attribute call
            "from distributed_pytorch_training_tpu import telemetry\n"
            "def step(x):\n    telemetry.counter('bad', 1)\n    return x\n",
            # relative module import (the repo's own idiom)
            "from .. import telemetry\n"
            "def step(x):\n    telemetry.span_event('bad', 0.1)\n"
            "    return x\n",
            # member from-import, relative
            "from ..telemetry import span_event\n"
            "def step(x):\n    span_event('bad', 0.1)\n    return x\n",
            # member from a submodule, absolute, aliased
            "from distributed_pytorch_training_tpu.telemetry.recorder "
            "import counter as c\n"
            "def step(x):\n    c('bad', 1)\n    return x\n",
            # plain-import alias
            "import distributed_pytorch_training_tpu.telemetry as tel\n"
            "def step(x):\n    tel.emit('event', 'bad')\n    return x\n",
            # unaliased dotted import, full-path call
            "import distributed_pytorch_training_tpu.telemetry\n"
            "def step(x):\n"
            "    distributed_pytorch_training_tpu.telemetry.emit('e', 'b')\n"
            "    return x\n",
        ):
            findings = _lint(tmp_path, header + body + footer,
                             rules=self.RULE)
            assert _rules_of(findings) == set(self.RULE), \
                f"did not flag: {body!r}"

    def test_shard_map_body_flags_too(self, tmp_path):
        src = """
            import jax
            from distributed_pytorch_training_tpu.parallel import shard_map
            from .. import telemetry
            def body(x):
                telemetry.gauge('depth', 1)
                return x
            f = shard_map(body, None, in_specs=(), out_specs=())
        """
        findings = _lint(tmp_path, src, rules=self.RULE)
        assert _rules_of(findings) == set(self.RULE)

    def test_host_side_emission_is_clean(self, tmp_path):
        """The instrumented loop's real shape: spans AROUND the dispatched
        step (train_epoch is not traced) never flag, nor do docstring
        mentions inside traced bodies."""
        src = '''
            import jax
            from .. import telemetry
            def _train_step_impl(state, batch):
                """telemetry.counter is forbidden here (a mention, not a
                call)."""
                return state
            step = jax.jit(_train_step_impl)
            def train_epoch(state, batches):
                for batch in batches:
                    with telemetry.span("step_dispatch"):
                        state = step(state, batch)
                telemetry.counter("steps", 1)
                return state
        '''
        assert _lint(tmp_path, src, rules=self.RULE) == []

    def test_unaliased_dotted_import_does_not_taint_package_root(
            self, tmp_path):
        """`import pkg.telemetry` binds only the root name `pkg` — a call
        to pkg.parallel.psum(...) inside a traced body is NOT a telemetry
        emit (the root-alias false positive the dotted-prefix matching
        exists to prevent)."""
        src = (
            "import jax\n"
            "import distributed_pytorch_training_tpu.telemetry\n"
            "def step(x):\n"
            "    return distributed_pytorch_training_tpu.parallel"
            ".collectives.psum(x, axis)\n"
            "jax.jit(step)\n")
        assert _lint(tmp_path, src, rules=self.RULE) == []

    def test_unrelated_telemetry_name_is_clean(self, tmp_path):
        """A user-defined object that happens to be NAMED telemetry (no
        import binding it to the package) is not the rule's business."""
        src = """
            import jax
            class Telemetry:
                def counter(self, *a): ...
            telemetry = Telemetry()
            def step(x):
                return x
            jax.jit(step)
            telemetry.counter('outside', 1)
        """
        assert _lint(tmp_path, src, rules=self.RULE) == []

    def test_per_line_suppression_honored(self, tmp_path):
        src = (
            "import jax\nfrom .. import telemetry\n"
            "def step(x):\n"
            "    telemetry.counter('x', 1)  "
            "# analysis: disable=telemetry-emit-outside-traced\n"
            "    return x\n"
            "jax.jit(step)\n")
        assert _lint(tmp_path, src, rules=self.RULE) == []


class TestEngine:
    def test_suppression_comment_skips_finding(self, tmp_path):
        src = """
            from jax import lax

            def body(x):
                a = lax.psum(x, "data")  # analysis: disable=axis-name-registry
                b = lax.pmean(x, "data")  # analysis: disable=all
                c = lax.pmax(x, "data")
                return a + b + c
        """
        findings = _lint(tmp_path, src, rules=["axis-name-registry"])
        assert len(findings) == 1
        assert findings[0].location.endswith(":7")

    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        findings = _lint(tmp_path, "def broken(:\n")
        assert _rules_of(findings) == {"parse-error"}

    def test_unknown_rule_name_raises(self, tmp_path):
        with pytest.raises(KeyError, match="no-such-rule"):
            _lint(tmp_path, "x = 1\n", rules=["no-such-rule"])

    def test_traced_name_discovery(self, tmp_path):
        path = tmp_path / "t.py"
        path.write_text(textwrap.dedent("""
            import jax
            from distributed_pytorch_training_tpu.parallel import shard_map

            class T:
                def __init__(self):
                    self._step = jax.jit(self._step_impl, donate_argnums=(0,))

                def _step_impl(self, s):
                    return s

            g = shard_map(lambda x: x, mesh=None, in_specs=None,
                          out_specs=None)

            @jax.jit
            def decorated(x):
                return x
        """))
        names = traced_function_names(FileContext.parse(path))
        assert {"_step_impl", "decorated"} <= names

    def test_source_file_set_covers_package_and_scripts_not_tests(self):
        files = {p.name for p in iter_source_files()}
        assert "loop.py" in files and "bench.py" in files \
            and "train.py" in files
        assert "test_analysis_ast.py" not in files


class TestSpanNamesRegistered:
    """ISSUE 14 satellite: every span name emitted in-repo must appear in
    the recorder's registry — `telemetry summary` silently buckets
    unknown names into 'unaccounted', so a typo'd span VANISHES from the
    split instead of failing loudly."""

    RULE = ["span-names-registered"]

    def test_mutation_unregistered_literal_flags(self, tmp_path):
        for src in (
            # module-attribute form, context manager
            "from .. import telemetry\n"
            "with telemetry.span('rogue_phase'):\n    pass\n",
            # span_event hot-loop form
            "from .. import telemetry\n"
            "telemetry.span_event('also_rogue', 0.1)\n",
            # member import
            "from ..telemetry import span_event\n"
            "span_event('rogue_member', 0.1, step=3)\n",
            # ALIASED member import (the pallas rule's alias-aware bar)
            "from ..telemetry import span_event as se\n"
            "se('aliased_rogue', 0.1)\n",
            "from distributed_pytorch_training_tpu.telemetry.recorder "
            "import span as s\n"
            "s('aliased_rogue_2')\n",
            # unaliased dotted import
            "import distributed_pytorch_training_tpu.telemetry\n"
            "distributed_pytorch_training_tpu.telemetry"
            ".span('dotted_rogue')\n",
        ):
            findings = _lint(tmp_path, src, rules=self.RULE)
            assert _rules_of(findings) == set(self.RULE), \
                f"did not flag: {src!r}"

    def test_mutation_dynamic_name_flags(self, tmp_path):
        src = ("from .. import telemetry\n"
               "nm = 'x'\n"
               "telemetry.span(nm)\n")
        findings = _lint(tmp_path, src, rules=self.RULE)
        assert _rules_of(findings) == set(self.RULE)
        assert "dynamic span name" in findings[0].message

    def test_registered_names_and_other_emits_are_clean(self, tmp_path):
        src = """
            from .. import telemetry
            with telemetry.span("step_dispatch", epoch=0):
                pass
            telemetry.span_event("data_wait", 0.1, step=0)
            telemetry.span_event("prefill", 0.1)
            with telemetry.span("elastic_grow"):
                pass
            with telemetry.span("compile", program="decode"):
                pass
            telemetry.counter("any_counter_name", 1)   # counters are free
            telemetry.gauge("any_gauge_name", 1)
            MSG = "telemetry.span('prose_mention') in a string is fine"
        """
        assert _lint(tmp_path, src, rules=self.RULE) == []

    def test_suppression_and_no_import_are_clean(self, tmp_path):
        suppressed = (
            "from .. import telemetry\n"
            "telemetry.span('rogue')  "
            "# analysis: disable=span-names-registered\n")
        assert _lint(tmp_path, suppressed, rules=self.RULE) == []
        # a local object named `span` with no telemetry import bound
        unbound = "def span(n):\n    return n\nspan('whatever')\n"
        assert _lint(tmp_path, unbound, rules=self.RULE) == []

    def test_registry_matches_the_recorder(self):
        """The rule reads the REAL registry (one definition): every
        canonical tuple is included."""
        from distributed_pytorch_training_tpu.analysis.ast_rules import (
            _registered_span_names,
        )
        from distributed_pytorch_training_tpu.telemetry.recorder import (
            AUX_SPAN_NAMES, ELASTIC_SPAN_NAMES, SERVING_SPAN_NAMES,
            SPAN_NAMES,
        )

        reg = _registered_span_names()
        assert set(SPAN_NAMES) <= reg
        assert set(SERVING_SPAN_NAMES) <= reg
        assert set(ELASTIC_SPAN_NAMES) <= reg
        assert set(AUX_SPAN_NAMES) <= reg

    def test_repo_emits_only_registered_names(self):
        """The rule binds on the real tree: every span emission in the
        package + scripts uses a registered name today."""
        assert run_ast_rules(rules=["span-names-registered"]) == []


@pytest.mark.slow  # ~6 s; strictly redundant with the check --json gate in test_analysis_cli, which runs every AST rule over the repo
def test_repo_is_clean_under_every_ast_rule():
    """The tier-1 gate for the source-level contracts: the package and the
    top-level scripts carry zero violations (suppressions included)."""
    findings = run_ast_rules()
    assert not findings, "\n".join(str(f) for f in findings)
