"""Mesh construction tests (SURVEY.md §7 step 1)."""

import jax
import numpy as np
import pytest

from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh
from distributed_pytorch_training_tpu.parallel.mesh import (
    DATA,
    MODEL,
    SEQ,
    batch_shard_count,
    local_batch_size,
)


def test_default_spec_is_pure_dp(devices):
    mesh = build_mesh(devices=devices)
    assert mesh.shape[DATA] == 8
    assert all(v == 1 for k, v in mesh.shape.items() if k != DATA)


def test_wildcard_fills_remaining(devices):
    mesh = build_mesh(MeshSpec(data=-1, model=2), devices=devices)
    assert mesh.shape[DATA] == 4
    assert mesh.shape[MODEL] == 2


def test_3d_mesh(devices):
    mesh = build_mesh(MeshSpec(data=2, model=2, seq=2), devices=devices)
    assert mesh.shape[DATA] == 2
    assert mesh.shape[MODEL] == 2
    assert mesh.shape[SEQ] == 2
    assert mesh.size == 8


def test_bad_shapes_raise(devices):
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(data=3), devices=devices)  # 3 does not divide 8
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=-1).resolved(8)  # two wildcards


def test_mesh_spec_parse():
    spec = MeshSpec.parse("data=4,model=2")
    assert spec.data == 4 and spec.model == 2 and spec.seq == 1


def test_batch_shard_count_and_local_batch(devices):
    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    assert batch_shard_count(mesh) == 4
    # per-device batch 16 (ref train_ddp.py:27 semantic), single host:
    # local batch == global batch == 16 * 4 data-shards.
    assert local_batch_size(16, mesh) == 64


def test_all_devices_used_once(devices):
    mesh = build_mesh(MeshSpec(data=2, seq=4), devices=devices)
    ids = sorted(d.id for d in np.asarray(mesh.devices).flat)
    assert ids == sorted(d.id for d in devices)


def test_mesh_spec_parse_errors():
    with pytest.raises(ValueError, match="unknown axis"):
        MeshSpec.parse("bogus=2")
    with pytest.raises(ValueError, match="expected"):
        MeshSpec.parse("data")
    with pytest.raises(ValueError, match="expected"):
        MeshSpec.parse("data=x")


def test_mesh_spec_rejects_zero_and_negative():
    with pytest.raises(ValueError, match="axis size"):
        MeshSpec.parse("data=0")
    with pytest.raises(ValueError, match="axis size"):
        MeshSpec.parse("data=-3")
    with pytest.raises(ValueError, match=">= 1"):
        MeshSpec(data=0).resolved(8)


class TestValidateMeshUsage:
    """--mesh axes the config cannot use must fail loudly, not waste devices
    (VERDICT r2 #6: `--mesh pipe=2` silently replicated all work)."""

    def _mesh(self, devices, **kw):
        from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh
        return build_mesh(MeshSpec(**kw), devices=devices)

    def test_pipe_without_pipeline_rejected(self, devices):
        import pytest
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, pipe=2, data=4)
        with pytest.raises(ValueError, match="pipe=2"):
            validate_mesh_usage(mesh, pipelined=False)
        validate_mesh_usage(mesh, pipelined=True)  # and the cure works

    def test_seq_without_seq_attention_rejected(self, devices):
        import pytest
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, seq=2, data=4)
        with pytest.raises(ValueError, match="seq=2"):
            validate_mesh_usage(mesh, attention="xla")
        validate_mesh_usage(mesh, attention="ring")
        validate_mesh_usage(mesh, attention="ulysses")

    def test_expert_without_moe_rejected(self, devices):
        import pytest
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, expert=2, data=4)
        with pytest.raises(ValueError, match="expert=2"):
            validate_mesh_usage(mesh, is_moe=False)
        validate_mesh_usage(mesh, is_moe=True)

    def test_model_axis_needs_tp_rules(self, devices):
        import pytest
        from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
        from distributed_pytorch_training_tpu.models.resnet import ResNet
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, model=2, data=4)
        with pytest.raises(ValueError, match="model=2"):
            validate_mesh_usage(mesh, rules=ResNet.partition_rules())
        validate_mesh_usage(mesh, rules=GPT2LMHead.partition_rules())

    def test_fsdp_without_fsdp_rules_warns_not_raises(self, devices, caplog):
        import logging
        from distributed_pytorch_training_tpu.models.resnet import ResNet
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, fsdp=2, data=4)
        with caplog.at_level(logging.WARNING):
            validate_mesh_usage(mesh, rules=ResNet.partition_rules())
        assert any("fsdp=2" in r.getMessage() for r in caplog.records)

    def test_pure_dp_mesh_always_valid(self, mesh8):
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        validate_mesh_usage(mesh8)


class TestHybridDcnMesh:
    """Multi-slice (DCN-joined) pods get a hybrid mesh: slice-spanning
    parallelism on the latency-tolerant axes only (VERDICT r3 #7)."""

    def test_dcn_factors_data_first(self):
        from distributed_pytorch_training_tpu.parallel.mesh import (
            AXIS_ORDER, dcn_factors,
        )

        sizes = dict(pipe=1, data=8, fsdp=1, expert=1, seq=1, model=4)
        per, dcn = dcn_factors(sizes, n_slices=4)
        assert dcn["data"] == 4 and per["data"] == 2
        assert per["model"] == 4 and dcn["model"] == 1  # TP stays on ICI
        import math
        assert math.prod(dcn[a] for a in AXIS_ORDER) == 4
        for a in AXIS_ORDER:
            # absent axes (the newer explicit `slice`) count as size 1
            assert per[a] * dcn[a] == sizes.get(a, 1)

    def test_dcn_factors_spills_to_pipe_and_fsdp(self):
        from distributed_pytorch_training_tpu.parallel.mesh import dcn_factors

        sizes = dict(pipe=2, data=2, fsdp=2, expert=1, seq=1, model=1)
        per, dcn = dcn_factors(sizes, n_slices=8)
        assert (dcn["data"], dcn["pipe"], dcn["fsdp"]) == (2, 2, 2)
        assert (per["data"], per["pipe"], per["fsdp"]) == (1, 1, 1)

    def test_dcn_factors_rejects_model_axis_spill(self):
        from distributed_pytorch_training_tpu.parallel.mesh import dcn_factors

        # only model-parallelism available to span slices -> must refuse
        sizes = dict(pipe=1, data=1, fsdp=1, expert=1, seq=1, model=8)
        with pytest.raises(ValueError, match="ICI"):
            dcn_factors(sizes, n_slices=2)

    def test_build_mesh_uses_hybrid_layout_on_multislice(self, devices,
                                                         monkeypatch):
        """Mocked 2-slice device set: build_mesh must call
        create_hybrid_device_mesh with the dcn split on the data axis."""
        from jax.experimental import mesh_utils

        from distributed_pytorch_training_tpu.parallel.mesh import (
            AXIS_ORDER, MeshSpec, build_mesh,
        )

        class FakeDev:
            def __init__(self, i, slice_index):
                self.id = i
                self.slice_index = slice_index

        fakes = [FakeDev(i, slice_index=i // 4) for i in range(8)]
        calls = {}

        def fake_hybrid(mesh_shape, dcn_mesh_shape, devices=None):
            calls["mesh_shape"] = tuple(mesh_shape)
            calls["dcn_mesh_shape"] = tuple(dcn_mesh_shape)
            import numpy as np
            return np.asarray(jax.devices()).reshape(
                tuple(m * d for m, d in zip(mesh_shape, dcn_mesh_shape)))

        monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh",
                            fake_hybrid)
        mesh = build_mesh(MeshSpec(data=4, model=2), devices=fakes)
        # per-slice: data=2, model=2; across DCN: data=2
        i_data = AXIS_ORDER.index("data")
        i_model = AXIS_ORDER.index("model")
        assert calls["dcn_mesh_shape"][i_data] == 2
        assert calls["mesh_shape"][i_data] == 2
        assert calls["dcn_mesh_shape"][i_model] == 1
        assert calls["mesh_shape"][i_model] == 2
        assert dict(mesh.shape)["data"] == 4 and dict(mesh.shape)["model"] == 2

    def test_single_slice_devices_skip_hybrid(self, devices):
        """CPU test devices carry no slice_index: the plain path runs."""
        from distributed_pytorch_training_tpu.parallel.mesh import (
            MeshSpec, build_mesh,
        )

        mesh = build_mesh(MeshSpec(data=8), devices=devices)
        assert dict(mesh.shape)["data"] == 8
