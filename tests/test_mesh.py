"""Mesh construction tests (SURVEY.md §7 step 1)."""

import numpy as np
import pytest

from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh
from distributed_pytorch_training_tpu.parallel.mesh import (
    DATA,
    MODEL,
    SEQ,
    batch_shard_count,
    local_batch_size,
)


def test_default_spec_is_pure_dp(devices):
    mesh = build_mesh(devices=devices)
    assert mesh.shape[DATA] == 8
    assert all(v == 1 for k, v in mesh.shape.items() if k != DATA)


def test_wildcard_fills_remaining(devices):
    mesh = build_mesh(MeshSpec(data=-1, model=2), devices=devices)
    assert mesh.shape[DATA] == 4
    assert mesh.shape[MODEL] == 2


def test_3d_mesh(devices):
    mesh = build_mesh(MeshSpec(data=2, model=2, seq=2), devices=devices)
    assert mesh.shape[DATA] == 2
    assert mesh.shape[MODEL] == 2
    assert mesh.shape[SEQ] == 2
    assert mesh.size == 8


def test_bad_shapes_raise(devices):
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(data=3), devices=devices)  # 3 does not divide 8
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=-1).resolved(8)  # two wildcards


def test_mesh_spec_parse():
    spec = MeshSpec.parse("data=4,model=2")
    assert spec.data == 4 and spec.model == 2 and spec.seq == 1


def test_batch_shard_count_and_local_batch(devices):
    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    assert batch_shard_count(mesh) == 4
    # per-device batch 16 (ref train_ddp.py:27 semantic), single host:
    # local batch == global batch == 16 * 4 data-shards.
    assert local_batch_size(16, mesh) == 64


def test_all_devices_used_once(devices):
    mesh = build_mesh(MeshSpec(data=2, seq=4), devices=devices)
    ids = sorted(d.id for d in np.asarray(mesh.devices).flat)
    assert ids == sorted(d.id for d in devices)


def test_mesh_spec_parse_errors():
    with pytest.raises(ValueError, match="unknown axis"):
        MeshSpec.parse("bogus=2")
    with pytest.raises(ValueError, match="expected"):
        MeshSpec.parse("data")
    with pytest.raises(ValueError, match="expected"):
        MeshSpec.parse("data=x")


def test_mesh_spec_rejects_zero_and_negative():
    with pytest.raises(ValueError, match="axis size"):
        MeshSpec.parse("data=0")
    with pytest.raises(ValueError, match="axis size"):
        MeshSpec.parse("data=-3")
    with pytest.raises(ValueError, match=">= 1"):
        MeshSpec(data=0).resolved(8)


class TestValidateMeshUsage:
    """--mesh axes the config cannot use must fail loudly, not waste devices
    (VERDICT r2 #6: `--mesh pipe=2` silently replicated all work)."""

    def _mesh(self, devices, **kw):
        from distributed_pytorch_training_tpu.parallel import MeshSpec, build_mesh
        return build_mesh(MeshSpec(**kw), devices=devices)

    def test_pipe_without_pipeline_rejected(self, devices):
        import pytest
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, pipe=2, data=4)
        with pytest.raises(ValueError, match="pipe=2"):
            validate_mesh_usage(mesh, pipelined=False)
        validate_mesh_usage(mesh, pipelined=True)  # and the cure works

    def test_seq_without_seq_attention_rejected(self, devices):
        import pytest
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, seq=2, data=4)
        with pytest.raises(ValueError, match="seq=2"):
            validate_mesh_usage(mesh, attention="xla")
        validate_mesh_usage(mesh, attention="ring")
        validate_mesh_usage(mesh, attention="ulysses")

    def test_expert_without_moe_rejected(self, devices):
        import pytest
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, expert=2, data=4)
        with pytest.raises(ValueError, match="expert=2"):
            validate_mesh_usage(mesh, is_moe=False)
        validate_mesh_usage(mesh, is_moe=True)

    def test_model_axis_needs_tp_rules(self, devices):
        import pytest
        from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
        from distributed_pytorch_training_tpu.models.resnet import ResNet
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, model=2, data=4)
        with pytest.raises(ValueError, match="model=2"):
            validate_mesh_usage(mesh, rules=ResNet.partition_rules())
        validate_mesh_usage(mesh, rules=GPT2LMHead.partition_rules())

    def test_fsdp_without_fsdp_rules_warns_not_raises(self, devices, caplog):
        import logging
        from distributed_pytorch_training_tpu.models.resnet import ResNet
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        mesh = self._mesh(devices, fsdp=2, data=4)
        with caplog.at_level(logging.WARNING):
            validate_mesh_usage(mesh, rules=ResNet.partition_rules())
        assert any("fsdp=2" in r.getMessage() for r in caplog.records)

    def test_pure_dp_mesh_always_valid(self, mesh8):
        from distributed_pytorch_training_tpu.parallel.mesh import validate_mesh_usage
        validate_mesh_usage(mesh8)
