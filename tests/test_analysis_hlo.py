"""HLO contract checker (analysis/hlo_rules.py): synthetic-HLO fixture
tests for the census parsers (no compilation needed — parser regressions
caught on hand-built text) and a mutation test per rule (a synthetic
violation each rule must flag).
"""

import numpy as np
import pytest

from distributed_pytorch_training_tpu.analysis.contracts import (
    WIRE_MODES, collectives_per_bucket,
)
from distributed_pytorch_training_tpu.analysis.hlo_rules import (
    StepArtifacts, check_artifacts, collective_census, expected_buckets,
    grad_sync_census, hlo_result_elements, verify_grad_sync_collectives,
    weight_update_census,
)

# --- hand-built HLO text fixtures ------------------------------------------

HEADER = ("HloModule jit_step, is_scheduled=true, "
          "input_output_alias={ {0}: (0, {}, may-alias) }, "
          "entry_computation_layout={(f32[64]{0})->f32[64]{0}}")
HEADER_NO_ALIAS = ("HloModule jit_step, is_scheduled=true, "
                   "entry_computation_layout={(f32[64]{0})->f32[64]{0}}")


def big_allreduce(i=0, n=16384, dt="f32"):
    return (f"  %all-reduce.{i} = {dt}[{n}]{{0}} "
            f"all-reduce({dt}[{n}]{{0}} %x.{i}), replica_groups={{}}")


def _module(body_lines, header=HEADER):
    return header + "\n\nENTRY %main {\n" + "\n".join(body_lines) + "\n}\n"


SYNTH = _module([
    "  %p = f32[64]{0} parameter(0)",
    big_allreduce(1),                              # 16384 elements, counted
    "  %ar2 = f32[10]{0} all-reduce(f32[10]{0} %p)",     # under the floor
    "  %rs = bf16[8192]{0} reduce-scatter(bf16[8192]{0} %p), dimensions={0}",
    "  %ag = f32[65536]{0} all-gather(f32[8192]{0} %p), dimensions={0}",
    # async pair: -start counts once, -done never
    "  %ars = (f32[16384]{0}, u32[]) all-reduce-start(f32[16384]{0} %p)",
    "  %ard = f32[16384]{0} all-reduce-done((f32[16384]{0}, u32[]) %ars)",
    # MoE dispatch op (the widened alternation)
    "  %ra = s8[32768]{0} ragged-all-to-all(s8[32768]{0} %p, s32[8]{0} %s)",
    "  %scal = f32[] all-reduce(f32[] %w)",              # scalar metric psum
])


class TestParsers:
    def test_hlo_result_elements(self):
        assert hlo_result_elements("f32[100,5]{1,0}") == 500
        assert hlo_result_elements("f32[]") == 1
        assert hlo_result_elements("(f32[8]{0}, u32[])") == 9
        assert hlo_result_elements("(bf16[4,4]{1,0}, f32[2]{0})") == 18

    def test_collective_census_counts_each_async_pair_once(self):
        census = {(c["op"], c["result_shape"]): c["count"]
                  for c in collective_census(SYNTH)}
        assert census[("all-reduce", "f32[16384]{0}")] == 1
        assert census[("all-reduce", "(f32[16384]{0}, u32[])")] == 1
        assert ("all-reduce", "f32[16384]{0}") in census  # -done skipped:
        assert sum(n for (op, _), n in census.items()
                   if op == "all-reduce") == 4  # 16384, 10, start-pair, scalar

    def test_collective_census_finds_ragged_all_to_all(self):
        ops = {c["op"] for c in collective_census(SYNTH)}
        assert "ragged-all-to-all" in ops
        assert "all-to-all" not in ops  # not double-keyed under the substring

    def test_weight_update_census_floor_and_counts(self):
        c = weight_update_census(SYNTH, min_elements=8192)
        assert c["all-reduce"] == 2       # big sync + async start, no scalar
        assert c["reduce-scatter"] == 1
        assert c["all-gather"] == 1
        assert c["ragged-all-to-all"] == 1
        assert all(hlo_result_elements(r["result_shape"]) >= 8192
                   for r in c["rows"])

    def test_grad_sync_census_wire_dtypes(self):
        c = grad_sync_census(SYNTH, min_elements=8192)
        assert c["n_collectives"] == 5
        assert c["wire_dtypes"]["bf16"] == 1
        assert c["wire_dtypes"]["s8"] == 1
        assert c["wire_dtypes"]["f32"] == 3
        assert c["by_op"]["all-reduce"] == 2

    def test_expected_buckets_matches_build_bucket_plan(self):
        """The checker's ceil bound must reproduce build_bucket_plan's
        floor-to-elements arithmetic exactly, odd caps included."""
        from distributed_pytorch_training_tpu.parallel.grad_sync import (
            build_bucket_plan,
        )

        params = {"a": np.zeros(5000), "b": np.zeros((300, 7))}
        for cap in (0.0, 0.0007, 0.0031, 0.01, 0.02, 100.0):
            plan = build_bucket_plan(params, cap)
            assert expected_buckets(plan.total_bytes, cap) == plan.n_buckets, cap


# --- per-rule mutation tests ------------------------------------------------


def _artifacts(body_lines, header=HEADER, preopt=None, **kw):
    kw.setdefault("n_shards", 8)
    kw.setdefault("min_elements", 8192)
    return StepArtifacts(name="synthetic",
                         optimized_text=_module(body_lines, header),
                         preopt_text=_module(preopt) if preopt else None,
                         **kw)


def _run(artifacts, rule):
    return check_artifacts(artifacts, rules=[rule])


class TestBucketBoundRule:
    CFG = dict(bucket_cap_mb=0.125)  # 32768 fp32 elements per bucket

    def test_mutation_unbucketed_step_flags(self):
        # 2 buckets promised, 10 collectives delivered
        a = _artifacts([big_allreduce(i) for i in range(10)],
                       config=self.CFG, total_grad_bytes=2 * 131072)
        assert _run(a, "grad-sync-bucket-bound")

    def test_mutation_empty_census_flags(self):
        a = _artifacts(["  %p = f32[64]{0} parameter(0)"],
                       config=self.CFG, total_grad_bytes=2 * 131072)
        assert _run(a, "grad-sync-bucket-bound")

    def test_engaged_step_within_bound_is_clean(self):
        a = _artifacts([big_allreduce(i) for i in range(2)],
                       config=self.CFG, total_grad_bytes=2 * 131072)
        assert _run(a, "grad-sync-bucket-bound") == []

    def test_not_engaged_skips(self):
        a = _artifacts([big_allreduce(i) for i in range(10)],
                       config={}, total_grad_bytes=2 * 131072)
        assert _run(a, "grad-sync-bucket-bound") == []


class TestWireRules:
    CFG = dict(bucket_cap_mb=1.0, wire_dtype="bf16")

    def test_mutation_fp32_only_wire_flags_compressed_wire(self):
        a = _artifacts([big_allreduce()], preopt=[big_allreduce()],
                       config=self.CFG, total_grad_bytes=65536)
        assert _run(a, "compressed-wire")

    def test_mutation_fp32_alongside_bf16_flags_no_fp32_wire(self):
        pre = [big_allreduce(1, dt="bf16"), big_allreduce(2, dt="f32")]
        a = _artifacts([big_allreduce()], preopt=pre,
                       config=self.CFG, total_grad_bytes=65536)
        assert _run(a, "compressed-wire") == []   # bf16 is present...
        assert _run(a, "no-fp32-wire")            # ...but f32 rides along

    def test_wire_rules_abstain_without_preopt_text(self):
        """No pre-opt text = no reliable wire read (CPU promotes bf16 to
        f32 in the optimized module): the wire rules must abstain, not
        convert an extraction failure into a false violation."""
        a = _artifacts([big_allreduce()], preopt=None,
                       config=self.CFG, total_grad_bytes=65536)
        assert _run(a, "compressed-wire") == []
        assert _run(a, "no-fp32-wire") == []

    def test_bf16_wire_is_clean_and_param_gather_exempt(self):
        pre = [big_allreduce(1, dt="bf16"),
               # the zero1 param all-gather stays exact by design
               "  %ag = f32[65536]{0} all-gather(f32[8192]{0} %p)"]
        a = _artifacts([big_allreduce()], preopt=pre,
                       config=dict(zero1=True, wire_dtype="bf16"),
                       total_grad_bytes=65536)
        assert _run(a, "no-fp32-wire") == []
        assert _run(a, "compressed-wire") == []


class TestZero1Rules:
    CFG = dict(zero1=True)
    RS = "  %rs = f32[8192]{0} reduce-scatter(f32[65536]{0} %g)"
    AG = "  %ag = f32[65536]{0} all-gather(f32[8192]{0} %p)"

    def test_mutation_surviving_all_reduce_flags(self):
        a = _artifacts([big_allreduce(), self.RS, self.AG], config=self.CFG)
        assert _run(a, "zero1-collectives")

    def test_mutation_missing_gather_or_scatter_flags(self):
        assert _run(_artifacts([self.RS], config=self.CFG),
                    "zero1-collectives")
        assert _run(_artifacts([self.AG], config=self.CFG),
                    "zero1-collectives")

    def test_scatter_gather_signature_is_clean_incl_int8_all_to_all(self):
        a = _artifacts([self.RS, self.AG], config=self.CFG)
        assert _run(a, "zero1-collectives") == []
        a2a = "  %c = s8[65536]{0} all-to-all(s8[65536]{0} %q)"
        a = _artifacts([a2a, self.AG],
                       config=dict(zero1=True, wire_dtype="int8"))
        assert _run(a, "zero1-collectives") == []

    def test_mutation_replicated_moment_buffer_flags(self):
        a = _artifacts([self.RS, self.AG], config=self.CFG,
                       replicated_state_buffers=(("['m'].mu", 65536),))
        found = _run(a, "zero1-sharded-state")
        assert found and "mu" in found[0].message
        assert _run(_artifacts([self.RS, self.AG], config=self.CFG),
                    "zero1-sharded-state") == []

    def test_zero1_evaluation_reads_real_shardings(self, mesh8):
        """Integration: the evaluator's sharding read on a real zero1 state
        finds nothing replicated, and on a replicated (dp) state it finds
        every moment buffer — the rule's input is live data, not a stub."""
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            replicated_large_buffers,
        )
        from distributed_pytorch_training_tpu.analysis.contracts import (
            get_contract,
        )
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            _tiny_lm_setup,
        )

        _, state_dp, _ = _tiny_lm_setup(mesh8, {})
        assert replicated_large_buffers(state_dp.opt_state, 128)
        _, state_z1, _ = _tiny_lm_setup(mesh8, get_contract("zero1").config)
        assert replicated_large_buffers(state_z1.opt_state, 128) == ()


class TestDonationRule:
    CFG = dict(donate_state=True)

    def test_mutation_missing_alias_table_flags(self):
        a = _artifacts([big_allreduce()], header=HEADER_NO_ALIAS,
                       config=self.CFG)
        assert _run(a, "donated-buffers-elided")

    def test_alias_table_is_clean_and_no_donate_skips(self):
        assert _run(_artifacts([big_allreduce()], config=self.CFG),
                    "donated-buffers-elided") == []
        a = _artifacts([big_allreduce()], header=HEADER_NO_ALIAS,
                       config=dict(donate_state=False))
        assert _run(a, "donated-buffers-elided") == []


class TestHostTransferRule:
    def test_mutation_each_marker_flags(self):
        markers = [
            "  %s = f32[8]{0} send(f32[8]{0} %p, token[] %t), "
            "is_host_transfer=true",
            "  %o = token[] outfeed(f32[8]{0} %p, token[] %t)",
            '  %cc = () custom-call(f32[] %m), '
            'custom_call_target="xla_python_cpu_callback"',
        ]
        for line in markers:
            a = _artifacts([line])
            assert _run(a, "no-host-transfer"), line
        assert _run(_artifacts([big_allreduce()]), "no-host-transfer") == []

    def test_fires_on_real_debug_print_hlo(self):
        """Mutation on REAL compiler output: a step with jax.debug.print
        carries a host callback the rule must see."""
        import jax
        import jax.numpy as jnp

        def leaky(x):
            jax.debug.print("loss={l}", l=x.sum())
            return x * 2

        text = jax.jit(leaky).lower(jnp.ones(16)).compile().as_text()
        a = StepArtifacts(name="leaky", optimized_text=text)
        assert _run(a, "no-host-transfer")

        clean = jax.jit(lambda x: x * 2).lower(jnp.ones(16)) \
            .compile().as_text()
        assert _run(StepArtifacts(name="ok", optimized_text=clean),
                    "no-host-transfer") == []


class TestFusedQuantizeKernelRule:
    """fused-quantize-kernel-present (ISSUE 6 satellite): a config claiming
    the Pallas codec kernels must really carry Mosaic custom-calls in its
    TPU lowering — a silent fallback to the XLA-composed chain is the same
    fraud class compressed-wire-present guards for the wire dtype."""

    CFG = dict(bucket_cap_mb=1.0, wire_dtype="int8_multihop",
               fused_quantize=True)
    MOSAIC = ('  %fq = (s8[8,16384]{1,0}, f32[8,1]{1,0}) '
              'custom-call(f32[8,16384]{1,0} %x), '
              'custom_call_target="tpu_custom_call", '
              'metadata={op_name="jit(step)/pallas_call'
              '[name=fused_quantize_int8_rows]"}')
    # a DIFFERENT Pallas kernel in the same step (flash attention lowers
    # to the same tpu_custom_call target) — its presence must not vouch
    # for the codec kernels
    MOSAIC_ATTN = ('  %fa = f32[8,128,64]{2,1,0} '
                   'custom-call(f32[8,128,64]{2,1,0} %q), '
                   'custom_call_target="tpu_custom_call", '
                   'metadata={op_name="jit(step)/pallas_call'
                   '[name=flash_attention_fwd]"}')
    # metadata-stripped render: kernel identity is unknowable, so bare
    # presence has to suffice
    MOSAIC_ANON = ('  %fq = (s8[8,16384]{1,0}, f32[8,1]{1,0}) '
                   'custom-call(f32[8,16384]{1,0} %x), '
                   'custom_call_target="tpu_custom_call"')

    def test_mutation_missing_custom_call_flags(self):
        a = _artifacts([big_allreduce()], config=self.CFG, backend="tpu")
        assert _run(a, "fused-quantize-kernel-present")

    def test_mutation_other_kernel_does_not_mask_fallback(self):
        """An attention Mosaic call with op_name metadata but NO codec
        kernel is the silent-fallback-masked-by-another-kernel case."""
        a = _artifacts([self.MOSAIC_ATTN, big_allreduce()],
                       config=self.CFG, backend="tpu")
        findings = _run(a, "fused-quantize-kernel-present")
        assert findings and "masking" in findings[0].message

    def test_tpu_lowering_with_mosaic_call_is_clean(self):
        a = _artifacts([self.MOSAIC, big_allreduce()], config=self.CFG,
                       backend="tpu")
        assert _run(a, "fused-quantize-kernel-present") == []
        # codec kernel present alongside another Pallas kernel: clean
        a = _artifacts([self.MOSAIC_ATTN, self.MOSAIC, big_allreduce()],
                       config=self.CFG, backend="tpu")
        assert _run(a, "fused-quantize-kernel-present") == []

    def test_metadata_stripped_render_accepts_presence(self):
        a = _artifacts([self.MOSAIC_ANON, big_allreduce()],
                       config=self.CFG, backend="tpu")
        assert _run(a, "fused-quantize-kernel-present") == []

    def test_auto_tristate_is_guarded_on_tpu(self, monkeypatch):
        """fused_quantize=None (auto, THE production default) must resolve
        exactly like the codec does — a TPU artifact whose auto resolves
        to the kernel path is checked, not abstained on; auto resolved
        off (env override) abstains."""
        cfg = dict(self.CFG)
        del cfg["fused_quantize"]  # auto
        monkeypatch.setenv("DPT_FUSED_QUANTIZE", "1")
        a = _artifacts([big_allreduce()], config=cfg, backend="tpu")
        assert _run(a, "fused-quantize-kernel-present")
        a = _artifacts([self.MOSAIC, big_allreduce()], config=cfg,
                       backend="tpu")
        assert _run(a, "fused-quantize-kernel-present") == []
        monkeypatch.setenv("DPT_FUSED_QUANTIZE", "0")
        a = _artifacts([big_allreduce()], config=cfg, backend="tpu")
        assert _run(a, "fused-quantize-kernel-present") == []

    def test_cpu_interpreter_mode_abstains(self):
        """Interpreter mode inlines the kernels as plain HLO — no
        custom-call exists to assert; parity tests pin the numerics
        (tests/test_quantize.py)."""
        a = _artifacts([big_allreduce()], config=self.CFG, backend="cpu")
        assert _run(a, "fused-quantize-kernel-present") == []
        # unknown backend (hand-built artifacts) must also abstain
        a = _artifacts([big_allreduce()], config=self.CFG)
        assert _run(a, "fused-quantize-kernel-present") == []

    def test_unfused_and_non_int8_configs_skip(self):
        for cfg in (
            dict(bucket_cap_mb=1.0, wire_dtype="int8_multihop"),  # no claim
            dict(bucket_cap_mb=1.0, wire_dtype="bf16",
                 fused_quantize=True),  # nothing to fuse on a bf16 wire
        ):
            a = _artifacts([big_allreduce()], config=cfg, backend="tpu")
            assert _run(a, "fused-quantize-kernel-present") == [], cfg

    def test_unengaged_codec_skips(self):
        """One shard: the reducer never engages, the codec never runs — a
        missing kernel is vacuous, not a violation."""
        a = _artifacts([big_allreduce()], config=self.CFG, backend="tpu",
                       n_shards=1)
        assert _run(a, "fused-quantize-kernel-present") == []


class TestDpSyncPresentRule:
    def test_mutation_vanished_grad_sync_flags(self):
        a = _artifacts(["  %p = f32[64]{0} parameter(0)"], config={})
        assert _run(a, "dp-sync-present")

    def test_plain_dp_with_all_reduce_is_clean_and_modes_skip(self):
        assert _run(_artifacts([big_allreduce()], config={}),
                    "dp-sync-present") == []
        # engaged modes and accum are exempt (their own rules apply)
        assert _run(_artifacts([], config=dict(zero1=True)),
                    "dp-sync-present") == []
        assert _run(_artifacts([], config=dict(grad_accum=2)),
                    "dp-sync-present") == []


# --- wire-mode parameterization (ISSUE 3 satellite: DynamiQ unblocked) -----


class TestMultihopBound:
    def test_collectives_per_bucket_by_mode(self):
        # fp32/bf16/int8 single-hop, int8_multihop 2 hops, int8_hier 2
        # exact ICI + 2 s8 DCN
        assert [collectives_per_bucket(m) for m in WIRE_MODES] == \
            [1, 1, 1, 2, 4]
        with pytest.raises(ValueError, match="unknown wire mode"):
            collectives_per_bucket("int4")

    def test_multihop_int8_gets_two_collectives_per_bucket(self):
        """A DynamiQ-style implementation (s8 reduce-scatter + requantized
        s8 gather = 2 collectives/bucket) must pass under its own mode and
        fail under the single-hop bound — the contract is parameterized by
        wire mode, not hand-relaxed."""
        n_buckets, cap = 4, 0.125  # 32768-element buckets
        total_bytes = n_buckets * 131072
        lines = []
        for i in range(n_buckets):
            lines.append(f"  %rs.{i} = s8[4096]{{0}} "
                         f"all-to-all(s8[32768]{{0}} %g.{i})")
            lines.append(f"  %ag.{i} = s8[32768]{{0}} "
                         f"all-gather(s8[4096]{{0}} %r.{i})")
        text = _module(lines)
        verdict = verify_grad_sync_collectives(
            text, total_grad_bytes=total_bytes, bucket_cap_mb=cap,
            wire_dtype="int8_multihop", min_elements=1024)
        assert verdict["bound"] == 2 * n_buckets + 2
        with pytest.raises(AssertionError, match="bucketing is not engaged"):
            verify_grad_sync_collectives(
                text, total_grad_bytes=total_bytes, bucket_cap_mb=cap,
                wire_dtype="int8", min_elements=1024)

    def test_mutation_single_collective_impostor_flags(self):
        """A single-hop codec MISLABELED as multihop (one gather-based
        collective per bucket — the ISSUE-4 impostor) sails under the
        2/bucket upper bound, so the hop SIGNATURE must catch it: no
        gradient-sized all-to-all/reduce-scatter means hop 1 is missing."""
        n_buckets, cap = 4, 0.125
        total_bytes = n_buckets * 131072
        lines = [f"  %ag.{i} = s8[262144]{{0}} "
                 f"all-gather(s8[32768]{{0}} %q.{i})"
                 for i in range(n_buckets)]
        text = _module(lines)
        with pytest.raises(AssertionError, match="hop 1 .* missing"):
            verify_grad_sync_collectives(
                text, total_grad_bytes=total_bytes, bucket_cap_mb=cap,
                wire_dtype="int8_multihop", min_elements=1024)
        # the same impostor through the rule engine (the matrix's view)
        a = StepArtifacts(
            name="impostor", optimized_text=text,
            config=dict(bucket_cap_mb=cap, wire_dtype="int8_multihop"),
            n_shards=8, total_grad_bytes=total_bytes, min_elements=1024)
        found = check_artifacts(a, rules=["grad-sync-bucket-bound"])
        assert found and "hop 1" in found[0].message
        # ...and a scatter-only impostor is caught as a missing hop 2
        lines = [f"  %rs.{i} = s8[4096]{{0}} "
                 f"all-to-all(s8[32768]{{0}} %g.{i})"
                 for i in range(n_buckets)]
        with pytest.raises(AssertionError, match="hop 2 .* missing"):
            verify_grad_sync_collectives(
                _module(lines), total_grad_bytes=total_bytes,
                bucket_cap_mb=cap, wire_dtype="int8_multihop",
                min_elements=1024)

    def test_multihop_contracts_in_matrix(self):
        """The canonical matrix carries the multihop configs (the checker
        gates the mode in tier-1, not just in this file's synthetics)."""
        from distributed_pytorch_training_tpu.analysis.contracts import (
            get_contract,
        )

        for name, accum in (("gsync_int8_mh", 1), ("gsync_int8_mh_accum", 2)):
            c = get_contract(name)
            assert c.config["wire_dtype"] == "int8_multihop"
            assert c.config.get("grad_accum", 1) == accum
            assert c.min_shards == 2
            assert c.config["bucket_cap_mb"] > 0


class TestFsdpRules:
    """Mutation tests for the explicit-FSDP rules (ISSUE 7): per-layer
    gather bound, scatter-into-shard signature, no full-param residency.
    Expectations are FLOOR-AWARE: the budget is the per-group padded sizes
    (layer_group_padded_sizes), and a group whose collective result falls
    under min_elements is invisible to the census by design — a gather
    result carries the full padded group, a plain reduce-scatter result
    only the 1/N destination chunk, the s8 all-to-all the full group."""

    CFG = dict(fsdp_explicit=True)
    SIZES = (65536, 65536)  # both >= the 8192 floor; rs result 8192 each
    AG = ["  %ag.{i} = f32[65536]{{0}} all-gather(f32[8192]{{0}} %p.{i})"
          .format(i=i) for i in range(2)]
    RS = ["  %rs.{i} = f32[8192]{{0}} reduce-scatter(f32[65536]{{0}} %g.{i})"
          .format(i=i) for i in range(2)]

    def test_mutation_missing_budget_flags(self):
        a = _artifacts(self.AG + self.RS, config=self.CFG)
        found = _run(a, "fsdp-layer-gather-bound")
        assert found and "budget" in found[0].message

    def test_mutation_missing_or_extra_gather_flags(self):
        a = _artifacts(self.AG[:1] + self.RS, config=self.CFG,
                       layer_group_padded_sizes=self.SIZES)
        assert _run(a, "fsdp-layer-gather-bound")
        a = _artifacts(self.AG + self.AG + self.RS, config=self.CFG,
                       layer_group_padded_sizes=self.SIZES)
        assert _run(a, "fsdp-layer-gather-bound")

    def test_gather_expectation_is_floor_aware(self):
        """A sub-floor group (the tiny final layernorm) must NOT be
        demanded from the census — 2 visible gathers against 3 groups of
        which one is under the floor is clean."""
        a = _artifacts(self.AG + self.RS, config=self.CFG,
                       layer_group_padded_sizes=self.SIZES + (4096,))
        assert _run(a, "fsdp-layer-gather-bound") == []
        assert _run(a, "fsdp-scatter-into-shard") == []

    def test_mutation_missing_scatter_flags(self):
        a = _artifacts(self.AG + self.RS[:1], config=self.CFG,
                       layer_group_padded_sizes=self.SIZES)
        assert _run(a, "fsdp-scatter-into-shard")

    def test_mutation_surviving_all_reduce_flags(self):
        """A gradient-sized all-reduce means replicated gradient sync —
        the at-rest sharding would be cosmetic."""
        a = _artifacts(self.AG + self.RS + [big_allreduce()],
                       config=self.CFG, layer_group_padded_sizes=self.SIZES)
        found = _run(a, "fsdp-scatter-into-shard")
        assert found and any("all-reduce" in f.message for f in found)

    def test_scatter_expectation_follows_wire(self):
        """fp32: a group is scatter-visible only if its 1/N chunk clears
        the floor (65536//8 = 8192 yes, 16384//8 = 2048 no). int8: the s8
        all-to-all carries the FULL group, so the gather-visibility rule
        applies to both directions."""
        a = _artifacts(self.AG + ["  %ag.2 = f32[16384]{0} all-gather("
                                  "f32[2048]{0} %p.2)"] + self.RS,
                       config=self.CFG,
                       layer_group_padded_sizes=self.SIZES + (16384,))
        assert _run(a, "fsdp-layer-gather-bound") == []
        assert _run(a, "fsdp-scatter-into-shard") == []
        a2a = ["  %c.{i} = s8[65536]{{0}} all-to-all(s8[65536]{{0}} %q.{i})"
               .format(i=i) for i in range(2)]
        ag8 = ["  %ag.{i} = s8[65536]{{0}} all-gather(s8[8192]{{0}} %p.{i})"
               .format(i=i) for i in range(2)]
        a = _artifacts(ag8 + a2a,
                       config=dict(fsdp_explicit=True,
                                   wire_dtype="int8_multihop"),
                       layer_group_padded_sizes=self.SIZES)
        assert _run(a, "fsdp-layer-gather-bound") == []
        assert _run(a, "fsdp-scatter-into-shard") == []

    def test_mutation_replicated_param_buffer_flags(self):
        a = _artifacts(self.AG + self.RS, config=self.CFG,
                       layer_group_padded_sizes=self.SIZES,
                       replicated_param_buffers=(
                           ("['wte']['embedding']", 65536),))
        found = _run(a, "fsdp-no-full-param-residency")
        assert found and "wte" in found[0].message

    def test_mutation_replicated_entry_param_flags(self):
        """The lowered-module read: a compiled step taking a param-sized
        REPLICATED entry operand pays full residency whatever the live
        state claims."""
        leak = ("  %arg0.1 = f32[65536]{0} parameter(0), "
                "sharding={replicated}")
        a = _artifacts(self.AG + self.RS + [leak], config=self.CFG,
                       layer_group_padded_sizes=self.SIZES)
        found = _run(a, "fsdp-no-full-param-residency")
        assert found and "entry" in found[0].message
        # sharded entry params and sub-floor replicated scalars are clean
        ok = ("  %arg0.1 = f32[8192]{0} parameter(0), "
              "sharding={devices=[8]<=[8]}")
        scal = "  %arg1.1 = f32[] parameter(1), sharding={replicated}"
        a = _artifacts(self.AG + self.RS + [ok, scal], config=self.CFG,
                       layer_group_padded_sizes=self.SIZES)
        assert _run(a, "fsdp-no-full-param-residency") == []

    def test_not_engaged_skips(self):
        a = _artifacts([], config=dict(fsdp_explicit=True), n_shards=1)
        for rule in ("fsdp-layer-gather-bound", "fsdp-scatter-into-shard",
                     "fsdp-no-full-param-residency"):
            assert _run(a, rule) == []

    def test_fsdp_evaluation_reads_real_shardings(self, mesh8):
        """Integration: on a real fsdp state the evaluator's sharding read
        finds NO replicated param buffer; on the replicated (dp) state it
        finds them all — the residency rule's input is live data."""
        from distributed_pytorch_training_tpu.analysis.contracts import (
            get_contract,
        )
        from distributed_pytorch_training_tpu.analysis.hlo_rules import (
            _tiny_lm_setup, replicated_large_buffers,
        )

        _, state_dp, _ = _tiny_lm_setup(mesh8, {})
        assert replicated_large_buffers(state_dp.params, 128)
        _, state_fs, _ = _tiny_lm_setup(mesh8, get_contract("fsdp").config)
        assert replicated_large_buffers(state_fs.params, 128) == ()

    def test_fsdp_contracts_in_matrix(self):
        """The canonical matrix carries the fsdp configs (tier-1 gates the
        mode end to end, not just this file's synthetics)."""
        from distributed_pytorch_training_tpu.analysis.contracts import (
            get_contract,
        )

        for name, wire, accum in (("fsdp", "fp32", 1),
                                  ("fsdp_accum", "fp32", 2),
                                  ("fsdp_int8_mh", "int8_multihop", 1)):
            c = get_contract(name)
            assert c.config["fsdp_explicit"] is True
            assert c.config.get("wire_dtype", "fp32") == wire
            assert c.config.get("grad_accum", 1) == accum
            assert c.min_shards == 2
            assert "bucket_cap_mb" not in c.config
