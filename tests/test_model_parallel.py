"""Model-level parallelism parity: the same GPT-2 weights must produce the
same logits whether params are replicated (DDP layout), tensor-parallel over
`model`, or running ring/ulysses attention over `seq` — XLA inserts different
collectives per layout, the math must not change."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.ops import (
    make_ring_attention_fn,
    make_ulysses_attention_fn,
)
from distributed_pytorch_training_tpu.parallel import (
    MeshSpec,
    build_mesh,
    shard_batch,
    shard_pytree,
)

TINY = dict(vocab_size=64, hidden_dim=16, depth=2, num_heads=4,
            max_position=16)  # 4 heads: divisible by model x seq axes below


@pytest.fixture(scope="module")
def tiny_gpt2():
    model = GPT2LMHead(**TINY)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (4, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    ref = model.apply({"params": params}, ids, train=False)
    return model, params, ids, np.asarray(ref)


def test_tensor_parallel_logits_match(devices, tiny_gpt2):
    model, params, ids, ref = tiny_gpt2
    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    sharded = shard_pytree(params, mesh, GPT2LMHead.partition_rules())
    batch = shard_batch({"ids": np.asarray(ids)}, mesh)

    out = jax.jit(
        lambda p, b: model.apply({"params": p}, b["ids"], train=False)
    )(sharded, batch)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


class TestVocabPaddingTP:
    """Megatron-style vocab padding (VERDICT r4 weak #4): at the real GPT-2
    vocab (50257, indivisible by any TP degree) the embedding must actually
    shard over `model` once padded, and the padded head must be loss-exact
    vs both the unpadded head and the replicated layout."""

    VOCAB = 50257
    TINY = dict(vocab_size=VOCAB, hidden_dim=16, depth=1, num_heads=2,
                max_position=16)

    def _loss(self, model, params, ids):
        import optax

        logits = model.apply({"params": params}, ids, train=False)
        return float(optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1].astype(jnp.float32), ids[:, 1:]).mean())

    @pytest.mark.slow
    def test_padded_embedding_shards_over_model_and_loss_matches(self, devices):
        import math

        pad_m = math.lcm(128, 2)
        model = GPT2LMHead(**self.TINY, pad_vocab_to_multiple_of=pad_m)
        assert model.padded_vocab == 50304  # 50257 -> next multiple of 128
        ids = jnp.asarray(
            np.random.RandomState(1).randint(0, self.VOCAB, (8, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
        assert params["wte"]["embedding"].shape == (50304, 16)

        # TP mesh: the padded vocab dim must REALLY shard over `model`
        # (pre-padding it degraded to replication, sharding.feasible_spec).
        mesh_tp = build_mesh(MeshSpec(data=4, model=2), devices=devices)
        sharded = shard_pytree(params, mesh_tp, GPT2LMHead.partition_rules())
        spec = sharded["wte"]["embedding"].sharding.spec
        assert spec[0] == "model", f"vocab dim not sharded: {spec}"

        # Loss under TP == loss replicated (same params, different layout).
        loss_tp = self._loss(model, sharded, shard_batch(
            {"ids": np.asarray(ids)}, mesh_tp)["ids"])
        mesh_dp = build_mesh(MeshSpec(data=8), devices=devices)
        replicated = shard_pytree(params, mesh_dp, None)
        loss_rep = self._loss(model, replicated, shard_batch(
            {"ids": np.asarray(ids)}, mesh_dp)["ids"])
        np.testing.assert_allclose(loss_tp, loss_rep, rtol=1e-6)

    def test_padded_head_matches_unpadded(self):
        """Zero-padding the embedding rows changes nothing: real-column
        logits identical, pad columns masked to the fp32 min, loss equal."""
        unpadded = GPT2LMHead(**self.TINY)
        padded = GPT2LMHead(**self.TINY, pad_vocab_to_multiple_of=128)
        ids = jnp.asarray(
            np.random.RandomState(2).randint(0, self.VOCAB, (2, 16)), jnp.int32)
        params = unpadded.init(jax.random.PRNGKey(0), ids, train=False)["params"]
        n_pad = padded.padded_vocab - self.VOCAB
        params_p = jax.tree_util.tree_map(lambda x: x, params)
        params_p["wte"] = {"embedding": jnp.pad(
            params["wte"]["embedding"], ((0, n_pad), (0, 0)))}

        out_u = unpadded.apply({"params": params}, ids, train=False)
        out_p = padded.apply({"params": params_p}, ids, train=False)
        assert out_p.shape[-1] == 50304
        np.testing.assert_array_equal(np.asarray(out_p[..., :self.VOCAB]),
                                      np.asarray(out_u))
        assert np.all(np.asarray(out_p[..., self.VOCAB:])
                      == np.finfo(np.float32).min)
        assert padded.vocab_pad_params == n_pad * 16
        np.testing.assert_allclose(self._loss(padded, params_p, ids),
                                   self._loss(unpadded, params, ids),
                                   rtol=1e-7)

    def test_bert_padded_head_matches_unpadded(self):
        """BERT's padding path has a bespoke branch (mlm_bias stays at the
        HF-exact (vocab,) shape and is zero-padded at apply time): real
        columns identical, pads masked."""
        from distributed_pytorch_training_tpu.models.bert import (
            BertForMaskedLM,
        )

        tiny = dict(vocab_size=30522, hidden_dim=16, depth=1, num_heads=2,
                    mlp_dim=32, max_position=16)
        unpadded = BertForMaskedLM(**tiny)
        padded = BertForMaskedLM(**tiny, pad_vocab_to_multiple_of=128)
        assert padded.padded_vocab == 30592
        ids = jnp.asarray(
            np.random.RandomState(3).randint(0, 30522, (2, 16)), jnp.int32)
        params = unpadded.init(jax.random.PRNGKey(0), ids, train=False)["params"]
        n_pad = 30592 - 30522
        params_p = dict(params)
        params_p["token_embedding"] = {"embedding": jnp.pad(
            params["token_embedding"]["embedding"], ((0, n_pad), (0, 0)))}
        assert params_p["mlm_bias"].shape == (30522,)  # bias stays HF-exact

        out_u = unpadded.apply({"params": params}, ids, train=False)
        out_p = padded.apply({"params": params_p}, ids, train=False)
        assert out_p.shape[-1] == 30592
        np.testing.assert_array_equal(np.asarray(out_p[..., :30522]),
                                      np.asarray(out_u))
        assert np.all(np.asarray(out_p[..., 30522:])
                      == np.finfo(np.float32).min)


@pytest.mark.parametrize("make_fn", [make_ring_attention_fn,
                                     make_ulysses_attention_fn])
def test_seq_parallel_attention_logits_match(devices, tiny_gpt2, make_fn):
    _, params, ids, ref = tiny_gpt2
    mesh = build_mesh(MeshSpec(data=2, seq=2, model=2), devices=devices)
    model_sp = GPT2LMHead(**TINY, attention_fn=make_fn(mesh, causal=True))
    sharded = shard_pytree(params, mesh, GPT2LMHead.partition_rules())
    batch = shard_batch({"ids": np.asarray(ids)}, mesh)

    out = jax.jit(
        lambda p, b: model_sp.apply({"params": p}, b["ids"], train=False)
    )(sharded, batch)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)
