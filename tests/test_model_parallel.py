"""Model-level parallelism parity: the same GPT-2 weights must produce the
same logits whether params are replicated (DDP layout), tensor-parallel over
`model`, or running ring/ulysses attention over `seq` — XLA inserts different
collectives per layout, the math must not change."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.ops import (
    make_ring_attention_fn,
    make_ulysses_attention_fn,
)
from distributed_pytorch_training_tpu.parallel import (
    MeshSpec,
    build_mesh,
    shard_batch,
    shard_pytree,
)

TINY = dict(vocab_size=64, hidden_dim=16, depth=2, num_heads=4,
            max_position=16)  # 4 heads: divisible by model x seq axes below


@pytest.fixture(scope="module")
def tiny_gpt2():
    model = GPT2LMHead(**TINY)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (4, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    ref = model.apply({"params": params}, ids, train=False)
    return model, params, ids, np.asarray(ref)


def test_tensor_parallel_logits_match(devices, tiny_gpt2):
    model, params, ids, ref = tiny_gpt2
    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    sharded = shard_pytree(params, mesh, GPT2LMHead.partition_rules())
    batch = shard_batch({"ids": np.asarray(ids)}, mesh)

    out = jax.jit(
        lambda p, b: model.apply({"params": p}, b["ids"], train=False)
    )(sharded, batch)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("make_fn", [make_ring_attention_fn,
                                     make_ulysses_attention_fn])
def test_seq_parallel_attention_logits_match(devices, tiny_gpt2, make_fn):
    _, params, ids, ref = tiny_gpt2
    mesh = build_mesh(MeshSpec(data=2, seq=2, model=2), devices=devices)
    model_sp = GPT2LMHead(**TINY, attention_fn=make_fn(mesh, causal=True))
    sharded = shard_pytree(params, mesh, GPT2LMHead.partition_rules())
    batch = shard_batch({"ids": np.asarray(ids)}, mesh)

    out = jax.jit(
        lambda p, b: model_sp.apply({"params": p}, b["ids"], train=False)
    )(sharded, batch)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)
