"""resilience/: fault-tolerant supervisor, heartbeat, fault injection,
checkpoint integrity (ISSUE 5 acceptance).

The binding contracts:
* chaos recovery parity — a run with ``crash@step=k`` under the supervisor
  resumes from checkpoint and reaches final params BITWISE equal to an
  uninterrupted same-seed run (fp32, CPU mesh);
* step fence — a fault between the optimizer update and the checkpoint
  save does not advance the step counter twice after restore;
* checkpoint integrity — a truncated checkpoint on disk is skipped with a
  loud log and the previous valid one restores; legacy (manifest-less)
  checkpoints still restore.
"""

import json
import os
import socket
import sys
import threading
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from distributed_pytorch_training_tpu.resilience.faults import (
    FaultError, FaultInjector, FaultPlan,
)
from distributed_pytorch_training_tpu.resilience.heartbeat import (
    Deathwatch, LivenessPolicy, port_listening, relay_ports,
)
from distributed_pytorch_training_tpu.resilience.supervisor import (
    RetryPolicy, Supervisor, SupervisorError,
)
from distributed_pytorch_training_tpu.training.checkpoint import (
    CheckpointManager,
)

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# shared rig: one compiled tiny-ResNet trainer for every supervisor test
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def rig(mesh8):
    """(trainer, state_factory, make_loader) — the chaos CLI's own tiny
    workload (resilience/__main__._build_rig), shared so the compile cost
    is paid once. `make_loader(fault_hook)` builds a fresh loader over the
    SAME dataset/seed (identical batch order) per test."""
    from distributed_pytorch_training_tpu.data.loader import ShardedLoader
    from distributed_pytorch_training_tpu.resilience.__main__ import (
        _build_rig,
    )

    trainer, state_factory, loader = _build_rig(
        mesh8, seed=0, dataset_size=64, per_device_batch=2)
    ds = loader.dataset

    def make_loader(fault_hook=None):
        return ShardedLoader(ds, mesh8, 2, shuffle=True, seed=0,
                             fault_hook=fault_hook)

    return trainer, state_factory, make_loader


def _control_params(trainer, state_factory, loader, epochs):
    """The uninterrupted same-seed trajectory (no supervisor, no faults)."""
    state = state_factory()
    spe = len(loader)
    for epoch in range(epochs):
        state, *_ = trainer.train_epoch(state, loader.epoch(epoch), epoch,
                                        spe)
    return state


def _assert_bitwise_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(x)),
                                      np.asarray(jax.device_get(y)))


_FAST_RETRY = RetryPolicy(max_restarts=4, backoff_base_s=0.01,
                          backoff_max_s=0.02, seed=0)


# ---------------------------------------------------------------------------
# FaultPlan / FaultInjector
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_parse_every_kind(self):
        plan = FaultPlan.parse("crash@step=7, sigterm@step=12,"
                               "torn_ckpt@save=2,loader_stall@step=5:2.5s")
        labels = [f.label() for f in plan.faults]
        assert labels == ["crash@step=7", "sigterm@step=12",
                          "torn_ckpt@save=2", "loader_stall@step=5:2.5s"]
        assert plan.faults[3].seconds == 2.5

    def test_empty_spec_is_empty_plan(self):
        assert not FaultPlan.parse(None)
        assert not FaultPlan.parse("")

    def test_parse_rejects_malformed(self):
        for bad, match in (
            ("explode@step=1", "unknown chaos fault kind"),
            ("crash@save=1", "triggers on"),
            ("torn_ckpt@step=1", "triggers on"),
            ("crash@step", "not kind@trigger"),
            ("loader_stall@step=5", "duration"),
            ("crash@step=5:2s", "no :SECs"),
        ):
            with pytest.raises(ValueError, match=match):
                FaultPlan.parse(bad)

    def test_injector_fires_once_and_reports(self):
        inj = FaultInjector(FaultPlan.parse("crash@step=3"),
                            log=lambda _m: None)
        inj.on_step(2)  # no match
        with pytest.raises(FaultError, match="crash@step=3"):
            inj.on_step(3)
        inj.on_step(3)  # the REPLAY of step 3 after restore must pass
        assert inj.fired == ["crash@step=3"]
        assert inj.unfired() == []

    def test_repeat_counts_parse_and_fire_per_occurrence(self):
        """ISSUE-11 satellite: `kind@trigger=N xK` fires K times, one per
        matching trigger occurrence (the elastic replay re-crosses the
        fence), then is spent; existing one-shot specs are unchanged."""
        plan = FaultPlan.parse("replica_death@step=3x2, crash@step=5")
        assert [f.count for f in plan.faults] == [2, 1]
        # the spec-form label reports the REMAINING repeats
        assert plan.faults[0].label(remaining=2) == "replica_death@step=3x2"
        inj = FaultInjector(plan, log=lambda _m: None)
        from distributed_pytorch_training_tpu.resilience.faults import (
            ReplicaDeathError,
        )

        for _ in range(2):
            with pytest.raises(ReplicaDeathError, match="replica_death"):
                inj.on_step(3)
        inj.on_step(3)  # spent: the third crossing passes
        assert inj.fired == ["replica_death@step=3"] * 2
        assert inj.unfired() == ["crash@step=5"]
        # space form parses too (the ISSUE's `kind@trigger=N xK` spelling)
        assert FaultPlan.parse("crash@step=3 x2").faults[0].count == 2

    def test_repeat_count_zero_is_loud(self):
        with pytest.raises(ValueError, match="repeat count"):
            FaultPlan.parse("crash@step=3x0")

    def test_capacity_return_parses_and_notifies_watch(self):
        """ISSUE-12: capacity_return@step=k is a non-raising fault — it
        credits the armed CapacityWatch back to the full registry at the
        step fence and records itself in `fired` like any other fault."""
        from distributed_pytorch_training_tpu.resilience.capacity import (
            CapacityWatch,
        )

        watch = CapacityWatch(total=8, available=5)
        inj = FaultInjector(FaultPlan.parse("capacity_return@step=2"),
                            log=lambda _m: None, capacity_watch=watch)
        inj.on_step(1)
        assert watch.available() == 5
        inj.on_step(2)  # no raise: capacity coming back is not a failure
        assert watch.available() == 8
        assert watch.returned.is_set()
        inj.on_step(2)  # spent
        assert inj.fired == ["capacity_return@step=2"]
        assert inj.unfired() == []

    def test_capacity_return_without_watch_is_harmless(self):
        logs = []
        inj = FaultInjector(FaultPlan.parse("capacity_return@step=0"),
                            log=logs.append)
        inj.on_step(0)
        assert inj.fired == ["capacity_return@step=0"]
        assert any("no CapacityWatch" in m for m in logs)

    def test_loader_stall_sleeps_once(self):
        inj = FaultInjector(FaultPlan.parse("loader_stall@step=1:0.15s"),
                            log=lambda _m: None)
        t0 = time.monotonic()
        inj.on_loader_batch(0)
        assert time.monotonic() - t0 < 0.1
        inj.on_loader_batch(1)
        assert time.monotonic() - t0 >= 0.15
        assert inj.fired == ["loader_stall@step=1:0.15s"]


# ---------------------------------------------------------------------------
# checkpoint integrity (manifest + verified restore)
# ---------------------------------------------------------------------------


def _truncate_largest(step_dir: Path) -> Path:
    files = sorted((p for p in step_dir.rglob("*") if p.is_file()),
                   key=lambda p: p.stat().st_size, reverse=True)
    with open(files[0], "r+b") as f:
        f.truncate(files[0].stat().st_size // 2)
    return files[0]


class TestCheckpointIntegrity:
    def test_truncated_checkpoint_skipped_loudly(self, rig, tmp_path,
                                                 capsys):
        """The acceptance case: tear the NEWEST checkpoint on disk —
        restore_latest must log loudly, skip it, and restore the previous
        valid one instead of crashing."""
        _trainer, state_factory, _ml = rig
        state = state_factory()
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(1, state, epoch=1)
        mgr.save(2, state, epoch=2)
        mgr.wait()  # tampering below simulates POST-finalize corruption
        _truncate_largest(tmp_path / "ckpt" / "2")

        restored = mgr.restore_latest(state_factory())
        mgr.close()
        assert restored is not None
        _state, epoch, step = restored
        assert (epoch, step) == (1, 0)  # the previous valid one
        assert mgr.last_skipped == [2]
        out = capsys.readouterr().out
        assert "CHECKPOINT INTEGRITY" in out and "truncated" in out

    def test_digest_corruption_detected(self, rig, tmp_path):
        """Same-size corruption (bit flips) must be caught by the sha256,
        not just the size check."""
        _trainer, state_factory, _ml = rig
        state = state_factory()
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(1, state, epoch=1)
        mgr.save(2, state, epoch=2)
        mgr.wait()  # corrupt the FINALIZED files, not an in-flight write
        files = sorted(((tmp_path / "ckpt" / "2").rglob("*")),
                       key=lambda p: p.stat().st_size if p.is_file() else 0,
                       reverse=True)
        blob = bytearray(files[0].read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        files[0].write_bytes(bytes(blob))
        assert "digest mismatch" in mgr.verify(2)
        restored = mgr.restore_latest(state_factory())
        mgr.close()
        assert restored is not None and restored[1] == 1

    def test_legacy_manifestless_checkpoint_restores(self, rig, tmp_path):
        """Checkpoints written before manifests existed have nothing to
        verify — they must restore exactly as before (no false skip)."""
        _trainer, state_factory, _ml = rig
        state = state_factory()
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(3, state, epoch=3)
        mgr.wait()
        manifest = tmp_path / "ckpt" / ".manifests" / "3.json"
        assert manifest.exists()
        manifest.unlink()
        assert mgr.verify(3) is None  # legacy: nothing to check
        restored = mgr.restore_latest(state_factory())
        mgr.close()
        assert restored is not None and restored[1] == 3
        assert mgr.last_skipped == []

    def test_all_checkpoints_torn_returns_none(self, rig, tmp_path, capsys):
        _trainer, state_factory, _ml = rig
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(1, state_factory(), epoch=1)
        mgr.wait()
        _truncate_largest(tmp_path / "ckpt" / "1")
        assert mgr.restore_latest(state_factory()) is None
        mgr.close()
        assert "failed verification" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# async (snapshot-then-write) checkpointing
# ---------------------------------------------------------------------------


class TestAsyncSave:
    """ISSUE 6 tentpole 1: ``save`` blocks only for the device→host
    snapshot; the orbax write + manifest run on a background writer. The
    async window must not widen the torn-checkpoint window silently, and a
    failed background write must surface at the next save/wait barrier."""

    def test_save_returns_before_write_finalizes(self, rig, tmp_path):
        """The overlap itself: save() returns while the writer still holds
        the un-finalized checkpoint (pending marker present, no manifest);
        wait() finalizes it and the manifest verifies clean."""
        _trainer, state_factory, _ml = rig
        gate, entered = threading.Event(), threading.Event()

        def hold(_label):
            entered.set()
            assert gate.wait(timeout=30.0)

        mgr = CheckpointManager(str(tmp_path / "ckpt"),
                                pre_finalize_hook=hold)
        mgr.save(1, state_factory(), epoch=1)
        # save() already returned; the writer is parked inside the hook
        # (after the orbax commit, before the manifest)
        assert entered.wait(timeout=30.0)
        manifests = tmp_path / "ckpt" / ".manifests"
        assert (manifests / "1.pending").exists()
        assert not (manifests / "1.json").exists()
        gate.set()
        mgr.wait()
        assert (manifests / "1.json").exists()
        assert not (manifests / "1.pending").exists()
        assert mgr.verify(1) is None
        mgr.close()

    def test_blocked_time_collapses_to_snapshot(self, rig, tmp_path):
        """The acceptance A/B (CPU mesh): with a 0.3s stall planted in the
        write path, the sync save blocks the caller >=300ms; the async save
        returns without paying it — blocked time ~= the snapshot cost."""
        _trainer, state_factory, _ml = rig
        state = state_factory()

        def stall(_label):
            time.sleep(0.3)

        sync = CheckpointManager(str(tmp_path / "sync"), async_save=False,
                                 pre_finalize_hook=stall)
        sync.save(1, state, epoch=1)
        sync_blocked = sync.save_blocked_ms
        sync.close()

        asyn = CheckpointManager(str(tmp_path / "async"),
                                 pre_finalize_hook=stall)
        asyn.save(1, state, epoch=1)
        async_blocked = asyn.save_blocked_ms  # before wait(): the loop's view
        asyn.wait()
        asyn.close()
        assert sync_blocked >= 300.0
        assert async_blocked <= sync_blocked - 250.0  # the stall moved off
        assert asyn.snapshot_ms <= async_blocked
        assert asyn.saves_started == sync.saves_started == 1

    def test_checkpoint_save_ab_instrument(self, rig, tmp_path):
        """The bench instrument (experiments/harness.py): one sync + one
        async throwaway save, blocked-ms per mode, nothing left on disk."""
        from distributed_pytorch_training_tpu.experiments.harness import (
            checkpoint_save_ab,
        )

        _trainer, state_factory, _ml = rig
        out = checkpoint_save_ab(state_factory(), base_dir=str(tmp_path))
        assert set(out) == {"sync_blocked_ms", "async_blocked_ms",
                            "snapshot_ms", "write_ms"}
        assert all(v >= 0.0 for v in out.values())
        assert out["snapshot_ms"] <= out["async_blocked_ms"]
        assert list(tmp_path.iterdir()) == []  # the A/B dir is gone

    def test_crash_between_commit_and_finalize_skipped_loudly(
            self, rig, tmp_path, capsys):
        """CI satellite: a crash injected between the orbax commit and the
        manifest finalize (the exact async window) leaves a checkpoint that
        restore_latest skips LOUDLY — never one that masquerades as a
        trusted legacy checkpoint — and a re-save over the torn label
        recovers it fully."""
        _trainer, state_factory, _ml = rig
        inj = FaultInjector(FaultPlan.parse("crash_during_save@save=1"),
                            log=lambda _m: None)
        mgr = CheckpointManager(str(tmp_path / "ckpt"),
                                pre_finalize_hook=inj.on_save_finalize)
        state = state_factory()
        mgr.save(1, state, epoch=1)
        with pytest.raises(FaultError, match="crash_during_save"):
            mgr.wait()  # the writer's death surfaces at the barrier
        manifests = tmp_path / "ckpt" / ".manifests"
        assert (manifests / "1.pending").exists()
        assert not (manifests / "1.json").exists()
        assert "never finalized" in mgr.verify(1)
        assert mgr.restore_latest(state_factory()) is None
        assert mgr.last_skipped == [1]
        assert "never finalized" in capsys.readouterr().out
        # the fault fired once: the replayed save must finalize normally
        mgr.save(1, state, epoch=1)
        mgr.wait()
        assert mgr.verify(1) is None
        restored = mgr.restore_latest(state_factory())
        mgr.close()
        assert restored is not None and restored[1] == 1

    def test_failed_async_write_surfaces_at_next_save(self, rig, tmp_path):
        """The other barrier: the NEXT save joins the failed write first
        and re-raises — a lost checkpoint is never silent, and the next
        attempt proceeds cleanly afterwards."""
        _trainer, state_factory, _ml = rig
        armed = {"on": True}

        def hook(_label):
            if armed["on"]:
                armed["on"] = False
                raise RuntimeError("disk gone")

        mgr = CheckpointManager(str(tmp_path / "ckpt"),
                                pre_finalize_hook=hook)
        state = state_factory()
        mgr.save(1, state, epoch=1)
        with pytest.raises(RuntimeError, match="disk gone"):
            mgr.save(2, state, epoch=2)
        mgr.save(2, state, epoch=2)  # the error was consumed at the barrier
        mgr.wait()
        assert mgr.verify(2) is None
        restored = mgr.restore_latest(state_factory())
        mgr.close()
        assert restored is not None and restored[1] == 2
        assert "never finalized" in mgr.verify(1)  # the lost save is torn


# ---------------------------------------------------------------------------
# CapacityWatch: the grow-side registry (ISSUE 12)
# ---------------------------------------------------------------------------


class TestCapacityWatch:
    def _watch(self, **kw):
        from distributed_pytorch_training_tpu.resilience.capacity import (
            CapacityWatch,
        )

        return CapacityWatch(**kw)

    def test_lose_restore_sync_bounds(self):
        w = self._watch(total=8)
        assert w.available() == 8
        assert w.lose(3) == 5
        assert w.lose(99) == 0     # floor at zero, never negative
        assert w.restore(2) == 2
        assert w.restore() == 8    # None = back to full
        assert w.restore(99) == 8  # ceiling at total
        assert w.sync(3) == 3      # absolute (the death-restart path)
        assert w.sync(99) == 8     # clamped both ways
        assert w.sync(-1) == 0

    def test_poll_grow_only_above_current_world(self):
        w = self._watch(total=8, available=4)
        assert w.poll_grow(4) is None      # nothing returned yet
        assert w.poll_grow(None) is None   # unknown world: never grow
        w.restore()
        assert w.poll_grow(4) == 8
        assert not w.returned.is_set()     # poll consumes the hint
        assert w.poll_grow(8) is None      # already at capacity

    def test_probe_feed_syncs_available(self):
        feed = {"n": 3}
        w = self._watch(total=8, probe=lambda: feed["n"])
        assert w.available() == 3
        feed["n"] = 12                     # clamped to the registry total
        assert w.available() == 8
        assert w.returned.is_set()

    def test_validation_is_loud(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match=">= 1 replica"):
            self._watch(total=0)
        with _pytest.raises(ValueError, match="must lie in"):
            self._watch(total=4, available=9)


# ---------------------------------------------------------------------------
# supervisor: crash recovery, step fence, torn-save recovery, preemption
# ---------------------------------------------------------------------------


class TestSupervisor:
    def test_crash_recovery_bitwise_parity(self, rig, tmp_path):
        """ISSUE-5 acceptance: crash@step=5 under the supervisor — the
        last checkpoint precedes the crash (step 4's update applied but
        unsaved: the fault sits BETWEEN optimizer update and save), so the
        supervisor must restore, replay exactly the lost step, and land
        bitwise where the uninterrupted run lands (fp32, CPU mesh). The
        final step counter equals the uninterrupted run's — no step
        double-applied, none skipped."""
        trainer, state_factory, make_loader = rig
        inj = FaultInjector(FaultPlan.parse("crash@step=5"),
                            log=lambda _m: None)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"),
                                 post_save_hook=inj.on_save)
        sup = Supervisor(trainer, ckpt, state_factory,
                         make_loader(inj.on_loader_batch),
                         retry=_FAST_RETRY, injector=inj,
                         checkpoint_every_steps=2)
        state, report = sup.run(epochs=2)
        ckpt.close()
        assert report.completed and report.restarts == 1
        assert report.fence_violations == 0
        assert report.steps_replayed == 1  # step 4 ran twice, nothing else
        assert report.faults_fired == ["crash@step=5"]
        assert int(state.step) == 8  # 2 epochs x 4 steps, no double-apply

        control = _control_params(trainer, state_factory, make_loader(), 2)
        assert int(control.step) == 8
        _assert_bitwise_equal(state.params, control.params)
        _assert_bitwise_equal(state.batch_stats, control.batch_stats)

    def test_torn_save_skipped_then_bitwise_parity(self, rig, tmp_path):
        """torn_ckpt@save=2 tears the epoch-0 checkpoint AFTER its manifest
        was written; the later crash must restore PAST it (integrity skip)
        to the older valid save, replay the longer gap, and still land
        bitwise-equal."""
        trainer, state_factory, make_loader = rig
        inj = FaultInjector(
            FaultPlan.parse("torn_ckpt@save=2,crash@step=5"),
            log=lambda _m: None)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"),
                                 post_save_hook=inj.on_save)
        sup = Supervisor(trainer, ckpt, state_factory,
                         make_loader(inj.on_loader_batch),
                         retry=_FAST_RETRY, injector=inj,
                         checkpoint_every_steps=2)
        state, report = sup.run(epochs=2)
        ckpt.close()
        assert report.completed and report.restarts == 1
        assert report.checkpoints_skipped == 1  # the torn save 2 (label 4)
        assert report.steps_replayed == 3       # restored at 2, crashed at 5
        assert int(state.step) == 8
        control = _control_params(trainer, state_factory, make_loader(), 2)
        _assert_bitwise_equal(state.params, control.params)

    def test_sigterm_drains_then_resumes_bitwise(self, rig, tmp_path):
        """sigterm@step=6 goes through the real PreemptionGuard: the
        segment stops at the next step boundary, checkpoints, and (chaos
        mode) the simulated relaunch resumes the exact trajectory."""
        from distributed_pytorch_training_tpu.training.preemption import (
            PreemptionGuard,
        )

        trainer, state_factory, make_loader = rig
        inj = FaultInjector(FaultPlan.parse("sigterm@step=6"),
                            log=lambda _m: None)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"),
                                 post_save_hook=inj.on_save)
        guard = PreemptionGuard.install()
        try:
            sup = Supervisor(trainer, ckpt, state_factory,
                             make_loader(inj.on_loader_batch),
                             retry=_FAST_RETRY, guard=guard, injector=inj,
                             checkpoint_every_steps=2,
                             resume_preempted=True)
            state, report = sup.run(epochs=2)
        finally:
            guard.reset()
            ckpt.close()
        assert report.completed
        assert report.preemptions_drained == 1
        assert report.restarts == 0  # a drain is not a failure
        assert int(state.step) == 8
        control = _control_params(trainer, state_factory, make_loader(), 2)
        _assert_bitwise_equal(state.params, control.params)

    def test_crash_during_save_recovered_bitwise(self, rig, tmp_path):
        """ISSUE-6 acceptance: crash_during_save@save=2 kills the async
        BACKGROUND writer between orbax commit and manifest. The failure
        surfaces at the next save barrier — inside the recovery scope — so
        the supervisor restores past the half-born checkpoint (integrity
        skip via the pending marker), replays, and lands bitwise-equal to
        the uninterrupted same-seed run with async saves enabled."""
        trainer, state_factory, make_loader = rig
        inj = FaultInjector(FaultPlan.parse("crash_during_save@save=2"),
                            log=lambda _m: None)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"),
                                 post_save_hook=inj.on_save,
                                 pre_finalize_hook=inj.on_save_finalize)
        sup = Supervisor(trainer, ckpt, state_factory,
                         make_loader(inj.on_loader_batch),
                         retry=_FAST_RETRY, injector=inj,
                         checkpoint_every_steps=2)
        state, report = sup.run(epochs=2)
        ckpt.close()
        assert report.completed and report.restarts == 1
        assert report.faults_fired == ["crash_during_save@save=2"]
        assert report.checkpoints_skipped == 1  # the half-born label 4
        assert report.fence_violations == 0
        assert int(state.step) == 8
        control = _control_params(trainer, state_factory, make_loader(), 2)
        _assert_bitwise_equal(state.params, control.params)
        _assert_bitwise_equal(state.batch_stats, control.batch_stats)

    def test_relay_death_checkpoints_then_aborts_then_resumes(
            self, rig, tmp_path, capsys):
        """ISSUE-6 satellite: an advisory deathwatch reporting the relay
        dead mid-epoch drains the segment at the next step boundary,
        writes AND FLUSHES the checkpoint, and aborts with
        report.relay_death — checkpoint-then-abort, not a bare rc=70. The
        simulated relaunch resumes that exact step and lands bitwise."""
        import types

        trainer, state_factory, make_loader = rig
        watch = types.SimpleNamespace(died=threading.Event(),
                                      dead_ports=[8082])
        watch.died.set()  # tunnel already dead at the first step boundary
        ckpt = CheckpointManager(str(tmp_path / "ckpt"))
        sup = Supervisor(trainer, ckpt, state_factory, make_loader(),
                         retry=_FAST_RETRY, checkpoint_every_steps=2,
                         deathwatch=watch)
        state, report = sup.run(epochs=2)
        ckpt.close()
        assert report.relay_death and not report.completed
        assert int(state.step) == 1  # drained after ONE step, mid-epoch
        assert ckpt.verify(1) is None  # the abort save is flushed + intact
        assert "relay tunnel died" in capsys.readouterr().out

        ckpt2 = CheckpointManager(str(tmp_path / "ckpt"))
        sup2 = Supervisor(trainer, ckpt2, state_factory, make_loader(),
                          retry=_FAST_RETRY, checkpoint_every_steps=2)
        state, report2 = sup2.run(epochs=2)
        ckpt2.close()
        assert report2.completed and not report2.relay_death
        assert int(state.step) == 8
        control = _control_params(trainer, state_factory, make_loader(), 2)
        _assert_bitwise_equal(state.params, control.params)

    def test_step_fence_detects_mismatched_coordinate(self, rig, tmp_path):
        """A checkpoint whose optimizer step disagrees with its (epoch,
        step) coordinate is the double-apply hazard: the supervisor must
        flag it and resume at the OPTIMIZER's position."""
        trainer, state_factory, make_loader = rig
        state = state_factory()  # step 0
        ckpt = CheckpointManager(str(tmp_path / "ckpt"))
        ckpt.save(3, state, epoch=0, step_in_epoch=3)  # lies: claims step 3
        sup = Supervisor(trainer, ckpt, state_factory, make_loader(),
                         retry=_FAST_RETRY)
        from distributed_pytorch_training_tpu.resilience.supervisor import (
            RunReport,
        )
        report = RunReport()
        _state, epoch, step = sup._restore_or_fresh(report, spe=4)
        ckpt.close()
        assert report.fence_violations == 1
        assert (epoch, step) == (0, 0)  # the optimizer's true position

    def test_gives_up_after_retry_budget(self, rig, tmp_path):
        trainer, state_factory, make_loader = rig
        inj = FaultInjector(FaultPlan.parse("crash@step=0,crash@step=1"),
                            log=lambda _m: None)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"))
        sup = Supervisor(trainer, ckpt, state_factory, make_loader(),
                         retry=RetryPolicy(max_restarts=1,
                                           backoff_base_s=0.01),
                         injector=inj, checkpoint_every_steps=2)
        with pytest.raises(SupervisorError, match="giving up"):
            sup.run(epochs=1)
        ckpt.close()

    def test_fresh_run_never_restores_stale_checkpoints(self, rig,
                                                        tmp_path):
        """trust_existing=False (train.py without --resume): a directory
        holding a PREVIOUS run's checkpoints must not leak into a fresh
        trajectory — a crash before the first in-run save restarts from
        scratch (the stale label, higher than anything this run wrote,
        would otherwise place the trajectory past `epochs` and the run
        would 'complete' on another run's params)."""
        trainer, state_factory, make_loader = rig
        stale = CheckpointManager(str(tmp_path / "ckpt"))
        stale.save(8, state_factory(), epoch=2)  # a finished 2-epoch run
        stale.close()

        inj = FaultInjector(FaultPlan.parse("crash@step=1"),
                            log=lambda _m: None)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"),
                                 post_save_hook=inj.on_save)
        sup = Supervisor(trainer, ckpt, state_factory,
                         make_loader(inj.on_loader_batch),
                         retry=_FAST_RETRY, injector=inj,
                         checkpoint_every_steps=2, trust_existing=False)
        state, report = sup.run(epochs=2,
                                initial=(state_factory(), 0, 0))
        ckpt.close()
        assert report.completed and report.restarts == 1
        assert int(state.step) == 8  # trained 2 real epochs, not stale
        control = _control_params(trainer, state_factory, make_loader(), 2)
        _assert_bitwise_equal(state.params, control.params)

    def test_loader_stall_is_survived(self, rig, tmp_path):
        trainer, state_factory, make_loader = rig
        inj = FaultInjector(FaultPlan.parse("loader_stall@step=1:0.2s"),
                            log=lambda _m: None)
        sup = Supervisor(trainer, None, state_factory,
                         make_loader(inj.on_loader_batch),
                         retry=_FAST_RETRY, injector=inj)
        state, report = sup.run(epochs=1)
        assert report.completed and report.restarts == 0
        assert report.faults_fired == ["loader_stall@step=1:0.2s"]
        assert int(state.step) == 4

    @pytest.mark.slow  # ~7 s; restart/resize/flight accounting stays fast via the chaos CLI bidirectional e2e, jitter via the RetryPolicy unit legs
    def test_elastic_resize_one_restart_one_flight_deterministic_jitter(
            self, rig, tmp_path):
        """ISSUE-11 satellite: a restart that RESIZES rides the normal
        retry path — exactly one restart counted, one flight flushed (its
        cause quotes the replica_death label), and the RetryPolicy's
        deterministic jitter is the one backoff slept. The resize record
        lands in report.resizes (label None: no checkpoint manager, the
        restart is from scratch at the new world)."""
        import random

        from distributed_pytorch_training_tpu import telemetry
        from distributed_pytorch_training_tpu.parallel import (
            MeshSpec, build_mesh,
        )
        from distributed_pytorch_training_tpu.resilience.__main__ import (
            _build_rig,
        )
        from distributed_pytorch_training_tpu.resilience.elastic import (
            ElasticPlan,
        )

        trainer, state_factory, make_loader = rig
        inj = FaultInjector(FaultPlan.parse("replica_death@step=1"),
                            log=lambda _m: None)
        mesh4 = build_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
        # same GLOBAL batch (16): per-device batch doubles at world 4
        t4, sf4, l4 = _build_rig(mesh4, seed=0, dataset_size=64,
                                 per_device_batch=4)

        def replan(survivors):
            assert survivors == 7  # world 8 minus the dead replica
            return ElasticPlan(trainer=t4, loader=l4, state_factory=sf4,
                               world=4)

        sleeps = []
        telemetry.configure(str(tmp_path / "telemetry.jsonl"))
        try:
            sup = Supervisor(trainer, None, state_factory,
                             make_loader(inj.on_loader_batch),
                             retry=_FAST_RETRY, injector=inj,
                             replan_cb=replan, sleep=sleeps.append)
            state, report = sup.run(epochs=1)
        finally:
            telemetry.reset()
        assert report.completed and report.restarts == 1
        assert report.resizes == [{"from_world": 8, "to_world": 4,
                                   "survivors": 7, "label": None,
                                   "epoch": 0, "step": 0,
                                   "direction": "shrink"}]
        assert int(state.step) == 4  # the full epoch ran at world 4
        flights = sorted(tmp_path.glob("flight_*.json"))
        assert len(flights) == 1
        assert "replica_death@step=1" in flights[0].read_text()
        expect = _FAST_RETRY.delay_s(1, random.Random(_FAST_RETRY.seed))
        assert sleeps == [expect]  # jitter stays deterministic

    def test_retry_budget_resets_after_clean_segment(self, rig, tmp_path):
        """ISSUE-12 satellite: two isolated faults separated by clean
        segments must BOTH restart at consecutive-attempt 1 — max_restarts
        bounds consecutive failures, not lifetime faults. max_restarts=1
        here: before the reset existed, the second fault pushed the
        lifetime counter to 2 > 1 and a perfectly recoverable run died."""
        import random

        trainer, state_factory, make_loader = rig
        inj = FaultInjector(FaultPlan.parse("crash@step=1,crash@step=5"),
                            log=lambda _m: None)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"),
                                 post_save_hook=inj.on_save)
        retry = RetryPolicy(max_restarts=1, backoff_base_s=0.01,
                            backoff_max_s=0.02, seed=0)
        sleeps = []
        sup = Supervisor(trainer, ckpt, state_factory,
                         make_loader(inj.on_loader_batch),
                         retry=retry, injector=inj,
                         checkpoint_every_steps=2, sleep=sleeps.append)
        state, report = sup.run(epochs=2)
        ckpt.close()
        assert report.completed and report.restarts == 2
        assert report.faults_fired == ["crash@step=1", "crash@step=5"]
        # both backoffs are ATTEMPT-1 delays (the exponent reset with the
        # budget); the jitter stream still advances deterministically
        rng = random.Random(retry.seed)
        assert sleeps == [retry.delay_s(1, rng), retry.delay_s(1, rng)]
        assert int(state.step) == 8
        control = _control_params(trainer, state_factory, make_loader(), 2)
        _assert_bitwise_equal(state.params, control.params)

    def test_supervisor_grows_at_segment_boundary(self, rig, tmp_path):
        """ISSUE-12 tentpole: capacity returning mid-segment grows the
        run at the NEXT segment boundary — no restart, no replay, no
        flight; the resize record anchors on the boundary checkpoint and
        the run finishes at the grown world."""
        from distributed_pytorch_training_tpu import telemetry
        from distributed_pytorch_training_tpu.parallel import (
            MeshSpec, build_mesh,
        )
        from distributed_pytorch_training_tpu.resilience.__main__ import (
            _build_rig,
        )
        from distributed_pytorch_training_tpu.resilience.capacity import (
            CapacityWatch,
        )
        from distributed_pytorch_training_tpu.resilience.elastic import (
            ElasticPlan, plan_elastic_world,
        )

        mesh4 = build_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
        # the run STARTS shrunken (world 4, per-device batch 4) — the
        # fleet lost half its replicas before this process launched
        t4, sf4, l4 = _build_rig(mesh4, seed=0, dataset_size=64,
                                 per_device_batch=4)
        trainer8, state_factory8, make_loader = rig
        watch = CapacityWatch(total=8, available=4)
        inj = FaultInjector(FaultPlan.parse("capacity_return@step=1"),
                            log=lambda _m: None, capacity_watch=watch)
        worlds_asked = []

        def replan(available):
            worlds_asked.append(available)
            world = plan_elastic_world(available, 16)
            assert world == 8
            return ElasticPlan(trainer=trainer8,
                               loader=make_loader(inj.on_loader_batch),
                               state_factory=state_factory8, world=8)

        ckpt = CheckpointManager(str(tmp_path / "ckpt"))
        telemetry.configure(str(tmp_path / "telemetry.jsonl"))
        try:
            sup = Supervisor(t4, ckpt, sf4, l4, retry=_FAST_RETRY,
                             injector=inj, checkpoint_every_steps=2,
                             replan_cb=replan, capacity_watch=watch)
            state, report = sup.run(epochs=1)
            events = telemetry.get().tail(512)
        finally:
            telemetry.reset()
            ckpt.close()
        assert report.completed and report.restarts == 0
        assert report.resizes == [{"from_world": 4, "to_world": 8,
                                   "survivors": 8, "label": 2,
                                   "epoch": 0, "step": 2,
                                   "direction": "grow"}]
        assert worlds_asked == [8]
        assert int(state.step) == 4
        assert not list(tmp_path.glob("flight_*.json"))  # a grow is not
        # an abnormal exit
        names = [e["name"] for e in events if e["kind"] == "span"]
        assert "elastic_grow" in names and "capacity_watch" in names

    def test_grow_skipped_when_no_larger_world_is_feasible(self, rig,
                                                           tmp_path):
        """Capacity returning in a quantity no feasible world can use
        (6 available, global batch 16 -> largest divisor is still 4)
        must keep the run at its current world, resize-free."""
        from distributed_pytorch_training_tpu.parallel import (
            MeshSpec, build_mesh,
        )
        from distributed_pytorch_training_tpu.resilience.__main__ import (
            _build_rig,
        )
        from distributed_pytorch_training_tpu.resilience.capacity import (
            CapacityWatch,
        )
        from distributed_pytorch_training_tpu.resilience.elastic import (
            ElasticPlan, plan_elastic_world,
        )

        mesh4 = build_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
        t4, sf4, l4 = _build_rig(mesh4, seed=0, dataset_size=64,
                                 per_device_batch=4)
        # only 6 replicas ever exist: restore() tops out at 6, whose
        # largest batch-dividing world is still 4
        watch = CapacityWatch(total=6, available=4)
        inj = FaultInjector(FaultPlan.parse("capacity_return@step=1"),
                            log=lambda _m: None, capacity_watch=watch)

        def replan(available):
            world = plan_elastic_world(available, 16)
            return ElasticPlan(trainer=t4, loader=l4, state_factory=sf4,
                               world=world)

        sup = Supervisor(t4, None, sf4, l4, retry=_FAST_RETRY,
                         injector=inj, checkpoint_every_steps=2,
                         replan_cb=replan, capacity_watch=watch)
        state, report = sup.run(epochs=1)
        assert report.completed and report.resizes == []
        assert int(state.step) == 4

    def test_grow_deferred_when_anchor_save_is_lost(self, rig, tmp_path):
        """A grow must anchor on a DURABLE checkpoint: when the boundary
        save's async write fails, the grow is deferred (recorded in
        failures, no resize), the torn label is skipped by later
        restores, and the run still completes at the original world."""
        from distributed_pytorch_training_tpu.parallel import (
            MeshSpec, build_mesh,
        )
        from distributed_pytorch_training_tpu.resilience.__main__ import (
            _build_rig,
        )
        from distributed_pytorch_training_tpu.resilience.capacity import (
            CapacityWatch,
        )
        from distributed_pytorch_training_tpu.resilience.elastic import (
            ElasticPlan,
        )

        mesh4 = build_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
        t4, sf4, l4 = _build_rig(mesh4, seed=0, dataset_size=64,
                                 per_device_batch=4)
        trainer8, state_factory8, make_loader = rig
        watch = CapacityWatch(total=8, available=4)
        inj = FaultInjector(FaultPlan.parse("capacity_return@step=1"),
                            log=lambda _m: None, capacity_watch=watch)
        armed = {"on": True}

        def lose_first_save(_label):
            if armed["on"]:
                armed["on"] = False
                raise RuntimeError("disk gone under the anchor")

        def replan(available):
            return ElasticPlan(trainer=trainer8, loader=make_loader(),
                               state_factory=state_factory8, world=8)

        ckpt = CheckpointManager(str(tmp_path / "ckpt"),
                                 pre_finalize_hook=lose_first_save)
        sup = Supervisor(t4, ckpt, sf4, l4, retry=_FAST_RETRY,
                         injector=inj, checkpoint_every_steps=2,
                         replan_cb=replan, capacity_watch=watch)
        state, report = sup.run(epochs=1)
        ckpt.close()
        assert report.completed and report.resizes == []
        assert any("grow deferred" in f for f in report.failures)
        assert "never finalized" in ckpt.verify(2)  # the lost anchor
        assert int(state.step) == 4  # finished at world 4, undisturbed

    def test_retry_policy_backoff_is_bounded_and_jittered(self):
        import random

        pol = RetryPolicy(max_restarts=10, backoff_base_s=0.5,
                          backoff_factor=2.0, backoff_max_s=3.0,
                          jitter_frac=0.5, seed=7)
        rng = random.Random(pol.seed)
        delays = [pol.delay_s(i, rng) for i in range(1, 9)]
        assert all(d >= 0.5 for d in delays)
        assert all(d <= 3.0 * 1.5 for d in delays)  # cap + max jitter
        assert delays[3] > delays[0]  # grows before the cap
        rng2 = random.Random(pol.seed)
        assert delays == [pol.delay_s(i, rng2)
                          for i in range(1, 9)]  # deterministic


# ---------------------------------------------------------------------------
# the chaos CLI (the demo IS the harness) + packaging
# ---------------------------------------------------------------------------


def test_chaos_cli_recovers_and_verifies_parity(tmp_path, capsys):
    """`python -m ...resilience chaos` on a fast plan: recovery stats on
    stdout, parity verified against the no-fault control run, rc 0."""
    from distributed_pytorch_training_tpu.resilience.__main__ import main

    rc = main(["chaos", "--chaos", "crash@step=2", "--epochs", "1",
               "--checkpoint-every-steps", "2", "--max-restarts", "2",
               "--ckpt-dir", str(tmp_path / "ckpt"), "--json"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    stats = json.loads(out)
    assert rc == 0
    assert stats["completed"] is True
    assert stats["parity_bitwise"] is True
    assert stats["restarts"] == 1
    assert stats["faults_fired"] == ["crash@step=2"]
    assert stats["fence_violations"] == 0
    # the flight recorder's chaos contract (ISSUE 8): the injected fault
    # left a parseable postmortem whose cause quotes the fault label
    assert stats["flights_ok"] is True
    assert any("crash@step=2" in (f["cause"] or "")
               for f in stats["flights"])


@pytest.mark.slow  # ~10 s; narrow edge case — the recover/bidirectional chaos legs keep the CLI path fast
def test_chaos_cli_fixed_world_capacity_return_is_harmless(tmp_path,
                                                           capsys):
    """A capacity_return fault in a FIXED-world schedule (no --elastic,
    no watch) fires into the void by design — a fully-recovered run must
    still be scored RECOVERED (the grow requirement binds only under
    --elastic)."""
    from distributed_pytorch_training_tpu.resilience.__main__ import main

    rc = main(["chaos", "--chaos", "crash@step=2,capacity_return@step=3",
               "--epochs", "1", "--checkpoint-every-steps", "2",
               "--max-restarts", "2",
               "--ckpt-dir", str(tmp_path / "ckpt"), "--json"])
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert stats["completed"] and stats["parity_bitwise"] is True
    assert stats["faults_fired"] == ["crash@step=2",
                                     "capacity_return@step=3"]
    assert stats["resizes"] == []


def _chaos_elastic(tmp_path, capsys, *extra):
    from distributed_pytorch_training_tpu.resilience.__main__ import main

    rc = main(["chaos", "--elastic", "--ckpt-dir", str(tmp_path / "ckpt"),
               "--json", *extra])
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    return rc, stats


def test_chaos_cli_elastic_bidirectional_bitwise_parity(tmp_path, capsys):
    """ISSUE-11 + ISSUE-12 acceptance (the tier-1 elastic smoke): the
    default `resilience chaos --elastic` schedule is now BIDIRECTIONAL —
    replica_death mid-epoch shrinks 8 -> 4 (7 survivors; 4 is the largest
    divisor of the fixed global batch), capacity_return at the step-4
    fence grows it back 4 -> 8 at the next segment boundary (one run, one
    restart, zero restarts for the grow), both resizes are recorded with
    their anchor checkpoints, the death leaves its flight, and the
    post-GROW segment is BITWISE equal to a clean same-seed continuation
    at the full world (restore the grow-anchor label at its recorded
    world, reshard, run the remainder clean)."""
    rc, stats = _chaos_elastic(tmp_path, capsys)
    assert rc == 0
    assert stats["completed"] is True
    assert stats["parity_bitwise"] is True
    assert stats["restarts"] == 1
    assert stats["faults_fired"] == ["replica_death@step=3",
                                     "capacity_return@step=4"]
    assert stats["resizes"] == [
        {"from_world": 8, "to_world": 4, "survivors": 7, "label": 2,
         "epoch": 0, "step": 2, "direction": "shrink"},
        {"from_world": 4, "to_world": 8, "survivors": 8, "label": 6,
         "epoch": 1, "step": 2, "direction": "grow"}]
    assert stats["flights_ok"] is True
    assert any("replica_death" in (f["cause"] or "")
               for f in stats["flights"])


@pytest.mark.slow
def test_chaos_cli_elastic_zero1_int8_ef_residuals(tmp_path, capsys):
    """The elastic reshard carries the FULL zero1 state across the resize
    — flat-padded moments AND the int8 wire's error-feedback residuals —
    and the post-resize segment still pins bitwise (the acceptance's
    'EF residuals included').

    Slow tier (~39 s: a multi-process chaos run with two training
    segments): the state-level half is pinned fast by test_elastic's
    zero1-int8 reshard tests, and elastic chaos-CLI parity by the
    bidirectional / fixed-world legs above."""
    rc, stats = _chaos_elastic(tmp_path, capsys,
                               "--layout", "zero1",
                               "--wire-dtype", "int8")
    assert rc == 0
    assert stats["completed"] and stats["parity_bitwise"] is True
    assert stats["resizes"] and stats["resizes"][0]["to_world"] == 4


@pytest.mark.slow
def test_chaos_cli_elastic_fsdp_int8(tmp_path, capsys):
    """Explicit FSDP across a resize: flat-sharded params + moments +
    per-group EF residuals all re-slice, post-resize bitwise parity."""
    rc, stats = _chaos_elastic(tmp_path, capsys,
                               "--layout", "fsdp",
                               "--wire-dtype", "int8")
    assert rc == 0
    assert stats["completed"] and stats["parity_bitwise"] is True


@pytest.mark.slow
def test_chaos_cli_elastic_double_resize(tmp_path, capsys):
    """The repeat-count schedule `replica_death@step=3x2`: the replay
    re-crosses the fence, the mesh shrinks twice (8 -> 4 -> 2), two
    flights land, and the post-LAST-resize segment pins bitwise (the
    control probes the checkpoint's OWN recorded world — the restored
    label may predate the first resize)."""
    rc, stats = _chaos_elastic(tmp_path, capsys,
                               "--chaos", "replica_death@step=3x2",
                               "--layout", "zero1",
                               "--wire-dtype", "int8")
    assert rc == 0
    assert stats["completed"] and stats["parity_bitwise"] is True
    assert [r["to_world"] for r in stats["resizes"]] == [4, 2]
    assert stats["restarts"] == 2
    causes = [f["cause"] or "" for f in stats["flights"]]
    assert sum("replica_death" in c for c in causes) == 2


@pytest.mark.slow
def test_chaos_cli_full_default_schedule(tmp_path, capsys):
    """The full default schedule (crash + torn save + sigterm) across two
    epochs — the CLI's own acceptance run."""
    from distributed_pytorch_training_tpu.resilience.__main__ import main

    rc = main(["chaos", "--ckpt-dir", str(tmp_path / "ckpt"), "--json"])
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert stats["completed"] and stats["parity_bitwise"]
    assert set(stats["faults_fired"]) == {
        "crash@step=3", "torn_ckpt@save=2", "crash_during_save@save=2",
        "sigterm@step=6"}
    assert stats["faults_unfired"] == []
    # EVERY fault in the default schedule leaves a parseable flight whose
    # cause matches the injected fault (the ISSUE 8 acceptance bar)
    assert stats["flights_ok"] is True
    causes = [f["cause"] or "" for f in stats["flights"]]
    for sig in ("crash@step=3", "crash_during_save@save=2",
                "torn_checkpoint", "sigterm"):
        assert any(sig in c for c in causes), (sig, causes)


def test_resilience_console_script_declared():
    """pyproject registers the `resilience` entry point next to `analysis`
    and it resolves to the CLI main."""
    pyproject = (REPO / "pyproject.toml").read_text()
    assert ('resilience = "distributed_pytorch_training_tpu.resilience.'
            '__main__:main"') in pyproject
    from distributed_pytorch_training_tpu.resilience.__main__ import main
    assert callable(main)


# ---------------------------------------------------------------------------
# TokenLoader fault hook (the LM loader's loader_stall injection point)
# ---------------------------------------------------------------------------


class TestTokenLoaderFaultHook:
    """ISSUE-6 satellite (ROADMAP-carried): the LM TokenLoader carries the
    same ``fault_hook`` / ``loader_stall`` support ShardedLoader has, with
    the chaos injector driving it."""

    def _loader(self, mesh, fault_hook=None):
        from distributed_pytorch_training_tpu.data.text import (
            TokenLoader, synthetic_token_dataset,
        )

        ds = synthetic_token_dataset(32, 16, 128, seed=0)
        return TokenLoader(ds, mesh, per_device_batch=2, shuffle=True,
                           seed=0, fault_hook=fault_hook)

    def test_loader_stall_fires_and_batches_unchanged(self, mesh8):
        """The chaos fault stalls exactly the targeted step and perturbs
        NOTHING about the produced batches (deterministic sampler order is
        the bitwise-parity foundation)."""
        inj = FaultInjector(FaultPlan.parse("loader_stall@step=1:0.15s"),
                            log=lambda _m: None)
        plain = list(self._loader(mesh8).epoch(0))
        t0 = time.monotonic()
        stalled = list(self._loader(mesh8, inj.on_loader_batch).epoch(0))
        assert time.monotonic() - t0 >= 0.15
        assert inj.fired == ["loader_stall@step=1:0.15s"]
        assert len(plain) == len(stalled) == 2  # 32 rows / global 16
        for a, b in zip(plain, stalled):
            np.testing.assert_array_equal(np.asarray(a["input_ids"]),
                                          np.asarray(b["input_ids"]))
            np.testing.assert_array_equal(np.asarray(a["weight"]),
                                          np.asarray(b["weight"]))

    def test_hook_sees_resume_offset(self, mesh8):
        """A supervisor resume enters the epoch at start_step > 0: the hook
        must see ABSOLUTE in-epoch indices (ShardedLoader's convention), or
        a loader_stall@step=k fault would re-target after a restart."""
        seen = []
        list(self._loader(mesh8, seen.append).epoch(0, start_step=1))
        assert seen == [1]

    def test_train_py_wires_the_hook(self):
        """train.py really passes the chaos injector into the LM loader
        (the constraint was carried precisely because it didn't)."""
        src = (REPO / "train.py").read_text()
        lm_loader = src.split("train_loader = TokenLoader", 1)[1]
        assert "fault_hook=(chaos.on_loader_batch" in lm_loader[:400]


# ---------------------------------------------------------------------------
# heartbeat: the extracted deathwatch
# ---------------------------------------------------------------------------


def _listener():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    s.listen(8)
    return s


def _accept_forever(s):
    # a real relay accepts; timeout-polling (not blocking) accept so
    # close() actually stops the port listening (the bench test's trick)
    s.settimeout(0.1)
    while True:
        try:
            conn, _ = s.accept()
            conn.close()
        except socket.timeout:
            continue
        except OSError:
            return


class TestHeartbeat:
    def test_default_ports_include_8087(self, monkeypatch):
        """ADVICE r5 #1 pinned: omitting 8087 left the watch blind to an
        8087-only partial death."""
        monkeypatch.delenv("DPT_RELAY_PORTS", raising=False)
        assert relay_ports() == [8082, 8083, 8087]
        monkeypatch.setenv("DPT_RELAY_PORTS", "9001, bogus,9002")
        assert relay_ports() == [9001, 9002]

    def test_port_listening_probe(self):
        srv = _listener()
        try:
            assert port_listening(srv.getsockname()[1], timeout=0.5)
        finally:
            srv.close()
        bound = socket.socket()
        bound.bind(("127.0.0.1", 0))  # bound but NOT listening
        try:
            assert not port_listening(bound.getsockname()[1], timeout=0.2)
        finally:
            bound.close()

    def test_arm_requires_env_or_confirmation(self, monkeypatch):
        monkeypatch.delenv("DPT_RELAY_PORTS", raising=False)
        assert Deathwatch.arm() is None  # no opt-in: heuristics forbidden
        # opted in but nothing listening: not a tunneled environment
        bound = socket.socket()
        bound.bind(("127.0.0.1", 0))
        try:
            monkeypatch.setenv("DPT_RELAY_PORTS",
                               str(bound.getsockname()[1]))
            assert Deathwatch.arm() is None
        finally:
            bound.close()

    def test_advisory_watch_detects_partial_death(self, monkeypatch):
        """The 1.5s/3-miss lethal semantics, observable: ONE of two armed
        ports dies (partial death hangs compiles like total death) — the
        watch must fire, name the dead port, and report the survivor to
        on_death. lethal=False so the test survives to assert."""
        srv_dies, srv_stays = _listener(), _listener()
        for s in (srv_dies, srv_stays):
            threading.Thread(target=_accept_forever, args=(s,),
                             daemon=True).start()
        seen = {}
        port_dies = srv_dies.getsockname()[1]
        port_stays = srv_stays.getsockname()[1]
        monkeypatch.setenv("DPT_RELAY_PORTS", f"{port_dies},{port_stays}")
        try:
            watch = Deathwatch.arm(
                policy=LivenessPolicy(interval_s=0.05,
                                      connect_timeout_s=0.3, max_misses=3,
                                      lethal=False),
                on_death=lambda dead, alive: seen.update(dead=dead,
                                                         alive=alive),
                log=lambda _m: None)
            assert watch is not None and len(watch.armed_ports) == 2
            time.sleep(0.2)          # a few healthy samples first
            assert not watch.died.is_set()
            srv_dies.close()         # the "compile port" dies
            assert watch.died.wait(timeout=10.0)
            assert seen["dead"] == [port_dies] == watch.dead_ports
            assert seen["alive"] == [port_stays]
        finally:
            srv_dies.close()
            srv_stays.close()

    def test_advisory_watch_escalates_when_owner_wedges(self, monkeypatch):
        """escalate_after_s: an advisory watch whose owner never exits
        (the checkpoint-then-abort wedged in dead-relay RPC retries) must
        fall through to the lethal hard exit — advisory mode cannot hang
        strictly longer than the lethal watch it replaced."""
        from distributed_pytorch_training_tpu.resilience import heartbeat

        srv = _listener()
        threading.Thread(target=_accept_forever, args=(srv,),
                         daemon=True).start()
        port = srv.getsockname()[1]
        monkeypatch.setenv("DPT_RELAY_PORTS", str(port))
        exits = []
        monkeypatch.setattr(heartbeat, "hard_exit",
                            lambda code: exits.append(code))
        try:
            watch = Deathwatch.arm(
                policy=LivenessPolicy(interval_s=0.05,
                                      connect_timeout_s=0.3, max_misses=3,
                                      lethal=False, escalate_after_s=0.2),
                log=lambda _m: None)
            assert watch is not None
            srv.close()  # total death: no survivor, no PJRT-close detour
            assert watch.died.wait(timeout=10.0)
            deadline = time.monotonic() + 10.0
            while not exits and time.monotonic() < deadline:
                time.sleep(0.05)
            assert exits == [heartbeat.DEATHWATCH_EXIT_CODE]
        finally:
            srv.close()

    def test_bench_consumes_the_shared_heartbeat(self):
        """The satellite's anti-drift pin: bench.py's port registry and
        probe ARE the heartbeat module's (no second copy to rot), and the
        inlined deathwatch is gone."""
        sys.path.insert(0, str(REPO))
        import bench

        assert bench._relay_ports is relay_ports
        assert bench._port_listening is port_listening
        src = (REPO / "bench.py").read_text()
        assert "Deathwatch.arm(" in src
        # the one-source-of-truth claim, literally: no local def remains
        assert "def _port_listening" not in src
        assert "def _relay_ports" not in src
        assert "def _try_clean_pjrt_close" not in src
