"""Real-corpus LM end-to-end (VERDICT r4 next-round #7): a committed
public-domain text file is byte-tokenized by the tokenize CLI, loaded from
disk by the token pipeline (synthetic=False), and trained through the full
`train.py` orchestration with decreasing loss — the LM counterpart of
test_e2e.test_train_cli_end_to_end (ref train_ddp.py:314-390 shape, applied
to the GPT-2 config family of BASELINE.json:12)."""

from pathlib import Path

import numpy as np
import pytest

CORPUS = Path(__file__).parent / "data" / "corpus.txt"


def test_tokenize_cli_writes_packed_layout(tmp_path):
    """The tokenize tool's byte-level path: UTF-8 bytes become the token
    ids, split into {family}_train.npy / {family}_val.npy."""
    from distributed_pytorch_training_tpu.data.tokenize import main

    assert main([str(CORPUS), "--tokenizer", "bytes", "--family", "gpt2",
                 "--out", str(tmp_path), "--val-fraction", "0.1"]) == 0
    train = np.load(tmp_path / "gpt2_train.npy")
    val = np.load(tmp_path / "gpt2_val.npy")
    raw = CORPUS.read_bytes()
    assert len(train) + len(val) == len(raw)
    # the tokens ARE the file's bytes, in order
    np.testing.assert_array_equal(train[:64],
                                  np.frombuffer(raw[:64], np.uint8))
    # byte path stores uint16 (tokenize.encode_bytes) with all ids < 256
    assert train.dtype == np.uint16 and train.max() < 256


@pytest.mark.slow
def test_train_cli_lm_on_real_corpus(tmp_path, capsys):
    """CLI-level GPT-2 run on disk tokens: tokenize -> train 2 epochs with a
    shrunk gpt2_124m -> CSV shows decreasing train loss, and the run must
    NOT have fallen back to synthetic data."""
    import train

    from distributed_pytorch_training_tpu.data.tokenize import main as tok

    data_dir = tmp_path / "data"
    tok([str(CORPUS), "--tokenizer", "bytes", "--family", "gpt2",
         "--out", str(data_dir)])

    out = tmp_path / "exp"
    train.main([
        "--model", "gpt2_124m",
        # byte vocab: ids < 256, so a 256-entry embedding suffices and keeps
        # the CPU run fast; depth/width shrunk per the named-config override
        "--model-overrides",
        "vocab_size=256,depth=2,hidden_dim=64,num_heads=2,max_position=64",
        "--data-dir", str(data_dir), "--seq-len", "64",
        # batch 2 x 8 batch shards = global 16 -> 5 steps/epoch on the
        # ~4.3k-token train split, so the print-freq-2 throughput line fires
        "--epochs", "2", "--batch-size", "2", "--lr", "0.001",
        "--optimizer", "adamw", "--print-freq", "2", "--seed", "0",
        "--output-dir", str(out),
    ])
    captured = capsys.readouterr().out
    assert "synthetic" not in captured, "must train on the real corpus"
    assert "Throughput:" in captured

    lines = (out / "metrics_rank0.csv").read_text().strip().splitlines()
    assert lines[0] == ("epoch,train_loss,train_acc,val_loss,val_acc,"
                        "epoch_time_seconds")
    rows = [line.split(",") for line in lines[1:]]
    assert [r[0] for r in rows] == ["1", "2"]
    # real-text byte LM: loss must fall across epochs, from a plausible
    # byte-entropy starting point (ln 256 ~ 5.55 at init)
    assert float(rows[1][1]) < float(rows[0][1])
    assert float(rows[0][1]) < 6.0


@pytest.mark.slow
def test_resume_under_different_mesh_diagnoses_vocab_padding(tmp_path):
    """Param shapes follow the TP layout (vocab padding = lcm(128, model
    axis)); resuming under a different --mesh must fail with a message
    naming the saved vs built vocab rows, not an opaque orbax error."""
    import train

    common = [
        "--model", "gpt2_124m",
        "--model-overrides", "depth=1,hidden_dim=32,num_heads=2,max_position=32",
        "--synthetic", "--synthetic-size", "64", "--seq-len", "32",
        "--epochs", "1", "--batch-size", "2", "--print-freq", "100",
        "--seed", "0", "--output-dir", str(tmp_path / "out"),
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ]
    train.main(common + ["--mesh", "data=4,model=2"])  # padded vocab 50304

    with pytest.raises(RuntimeError, match="vocab rows"):
        train.main(common + ["--mesh", "data=8", "--resume"])  # built 50257
