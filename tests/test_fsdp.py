"""FSDP (ZeRO-style parameter/optimizer sharding over the ``fsdp`` axis).

The reference's DDP keeps a full replica of params + optimizer state on every
device (/root/reference/train_ddp.py:305-310, :339-344); FSDP shards both.
These tests pin the promise at parallel/mesh.py (`fsdp` axis doc): the layout
must actually land on the devices — params AND optimizer moments — and the
math must be bit-comparable to the replicated layout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_training_tpu.models.gpt2 import GPT2LMHead
from distributed_pytorch_training_tpu.parallel import (
    MeshSpec, build_mesh, shard_batch,
)
from distributed_pytorch_training_tpu.training import TrainConfig, Trainer
from distributed_pytorch_training_tpu.training.optim import adamw
from distributed_pytorch_training_tpu.training.tasks import LanguageModelingTask

SEQ = 16
VOCAB = 64


def _tiny_gpt2(**kw):
    return GPT2LMHead(vocab_size=VOCAB, hidden_dim=32, depth=2, num_heads=2,
                      max_position=SEQ, **kw)


def _trainer(mesh, rules):
    t = Trainer(LanguageModelingTask(), mesh, TrainConfig(seed=0), rules=rules)
    state = t.init_state(_tiny_gpt2(), np.zeros((1, SEQ), np.int32),
                         adamw(1e-2), jax.random.PRNGKey(0))
    return t, state


def _batch(mesh, n=8):
    rng = np.random.RandomState(0)
    return shard_batch({
        "input_ids": rng.randint(0, VOCAB, (n, SEQ)).astype(np.int32),
        "weight": np.ones(n, np.float32),
    }, mesh)


@pytest.fixture(scope="module")
def fsdp_mesh(devices):
    return build_mesh(MeshSpec(data=2, fsdp=4), devices=devices)


def _leaves_with_paths(tree):
    return [("/".join(str(getattr(k, "key", getattr(k, "name", k)))
                      for k in path), leaf)
            for path, leaf in jax.tree_util.tree_leaves_with_path(tree)]


@pytest.mark.slow
def test_fsdp_params_and_opt_state_actually_sharded(fsdp_mesh):
    """`--mesh fsdp=4` must place param AND optimizer-moment shards, not
    silently replicate (the round-1/2 advertised-but-absent gap)."""
    _, state = _trainer(fsdp_mesh, GPT2LMHead.partition_rules())

    def fsdp_sharded(pairs):
        out = []
        for path, leaf in pairs:
            if not hasattr(leaf, "sharding"):
                continue
            spec = leaf.sharding.spec
            flat = [a for e in spec if e is not None
                    for a in ((e,) if isinstance(e, str) else e)]
            if "fsdp" in flat:
                out.append((path, leaf))
        return out

    p_sharded = fsdp_sharded(_leaves_with_paths(state.params))
    assert len(p_sharded) >= 8, (
        f"expected most kernels fsdp-sharded, got {[p for p, _ in p_sharded]}")
    # the shards must really be smaller than the leaf (memory win is real)
    for path, leaf in p_sharded:
        shard = leaf.addressable_shards[0].data
        assert np.prod(shard.shape) == np.prod(leaf.shape) // 4, (
            path, shard.shape, leaf.shape)

    o_sharded = fsdp_sharded(_leaves_with_paths(state.opt_state))
    # AdamW holds mu+nu per param -> at least 2x the param hit count
    assert len(o_sharded) >= 2 * len(p_sharded) - 4, (
        f"optimizer moments not sharded: {[p for p, _ in o_sharded]}")


def test_fsdp_matches_replicated_math(fsdp_mesh):
    """Same init key: the fsdp layout must compute the same loss as the
    replicated (DDP) layout — layout is a performance fact, not a math fact."""
    t_rep, s_rep = _trainer(fsdp_mesh, None)
    t_fsdp, s_fsdp = _trainer(fsdp_mesh, GPT2LMHead.partition_rules())
    batch = _batch(fsdp_mesh)

    m_rep = t_rep._eval_step(s_rep, batch)
    m_fsdp = t_fsdp._eval_step(s_fsdp, batch)
    np.testing.assert_allclose(float(m_rep["loss_sum"]),
                               float(m_fsdp["loss_sum"]), rtol=2e-5)
    np.testing.assert_allclose(float(m_rep["correct"]),
                               float(m_fsdp["correct"]), rtol=0)


@pytest.mark.slow
def test_fsdp_training_step_decreases_loss(fsdp_mesh):
    t, state = _trainer(fsdp_mesh, GPT2LMHead.partition_rules())
    batch = _batch(fsdp_mesh)
    key = jax.random.PRNGKey(1)
    losses = []
    for _ in range(8):
        state, metrics = t._train_step(state, batch, key)
        losses.append(float(metrics["loss_sum"]) / float(metrics["weight"]))
    assert losses[-1] < losses[0], losses
    # the updated params keep their fsdp sharding across steps (jit must not
    # silently gather them back to replicated)
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    flat = [a for e in qkv.sharding.spec if e is not None
            for a in ((e,) if isinstance(e, str) else e)]
    assert "fsdp" in flat, qkv.sharding


@pytest.mark.slow
def test_fsdp_times_tp_2d_layout(devices):
    """fsdp=2 x model=2 x data=2: 2-D parameter sharding + DP, one mesh."""
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, model=2), devices=devices)
    _, state = _trainer(mesh, GPT2LMHead.partition_rules())
    fc1 = state.params["block0"]["mlp"]["fc1"]["kernel"]
    assert fc1.sharding.spec == jax.sharding.PartitionSpec("fsdp", "model")
    shard = fc1.addressable_shards[0].data
    assert np.prod(shard.shape) == np.prod(fc1.shape) // 4
    t, s = _trainer(mesh, GPT2LMHead.partition_rules())
    sN, m = t._train_step(s, _batch(mesh), jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss_sum"]))


@pytest.mark.slow
def test_fsdp_checkpoint_roundtrip(fsdp_mesh, tmp_path):
    """Orbax save/restore of an FSDP-sharded TrainState: restored leaves must
    carry the template's fsdp sharding and identical values — the sharded
    multi-host checkpoint story (training/checkpoint.py) on a non-trivial
    layout, not just replicated DDP state."""
    from distributed_pytorch_training_tpu.training.checkpoint import (
        CheckpointManager,
    )

    t, state = _trainer(fsdp_mesh, GPT2LMHead.partition_rules())
    state, _ = t._train_step(state, _batch(fsdp_mesh), jax.random.PRNGKey(1))

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(1, state, wait=True)

    # fresh template (same rules/mesh, different values)
    t2, template = _trainer(fsdp_mesh, GPT2LMHead.partition_rules())
    restored, epoch, step_in_epoch = ckpt.restore_latest(template)
    ckpt.close()
    assert epoch == 1 and step_in_epoch == 0
    assert int(restored.step) == 1

    qkv = restored.params["block0"]["attn"]["qkv"]["kernel"]
    flat = [a for e in qkv.sharding.spec if e is not None
            for a in ((e,) if isinstance(e, str) else e)]
    assert "fsdp" in flat, qkv.sharding  # sharding survived the roundtrip
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(state.params), jax.device_get(restored.params))
