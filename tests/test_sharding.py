"""PartitionRules / shard_pytree / shard_batch tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_training_tpu.parallel import (
    MeshSpec,
    PartitionRules,
    build_mesh,
    shard_batch,
    shard_pytree,
)
from distributed_pytorch_training_tpu.parallel.mesh import DATA, MODEL
from distributed_pytorch_training_tpu.parallel.sharding import tree_specs


def test_rules_first_match_wins():
    rules = PartitionRules([
        (r"attn/qkv/kernel", P(None, MODEL)),
        (r"kernel", P(MODEL, None)),
    ])
    assert rules.spec_for("layer0/attn/qkv/kernel") == P(None, MODEL)
    assert rules.spec_for("layer0/mlp/kernel") == P(MODEL, None)
    assert rules.spec_for("layer0/bias") == P()  # default replicated


def test_rule_ndim_mismatch_raises():
    rules = PartitionRules([(r"kernel", P(None, MODEL))])
    with pytest.raises(ValueError):
        rules.spec_for("x/kernel", ndim=1)


def test_tree_specs_paths():
    rules = PartitionRules([(r"dense/kernel", P(None, MODEL))])
    tree = {"dense": {"kernel": np.zeros((4, 8)), "bias": np.zeros((8,))}}
    specs = tree_specs(tree, rules)
    assert specs["dense"]["kernel"] == P(None, MODEL)
    assert specs["dense"]["bias"] == P()


def test_shard_pytree_replicated_matches_ddp_layout(mesh8):
    tree = {"w": np.ones((4, 4), np.float32)}
    sharded = shard_pytree(tree, mesh8)
    shards = sharded["w"].addressable_shards
    assert len(shards) == 8
    for s in shards:
        np.testing.assert_array_equal(np.asarray(s.data), tree["w"])


def test_shard_pytree_tp_splits(devices):
    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    rules = PartitionRules([(r"kernel", P(None, MODEL))])
    tree = {"kernel": np.arange(32, dtype=np.float32).reshape(4, 8)}
    sharded = shard_pytree(tree, mesh, rules)
    # Each model-shard holds half the columns.
    assert sharded["kernel"].addressable_shards[0].data.shape == (4, 4)
    np.testing.assert_array_equal(np.asarray(sharded["kernel"]), tree["kernel"])


def test_shard_batch_splits_leading_dim(mesh8):
    from distributed_pytorch_training_tpu.parallel.mesh import BATCH_AXES

    batch = {"x": np.arange(32, dtype=np.float32).reshape(16, 2)}
    out = shard_batch(batch, mesh8)
    # the batch rides EVERY batch axis (incl. the two-tier `slice` outer
    # axis, size 1 on a single-slice mesh)
    assert out["x"].sharding.spec == P(BATCH_AXES, None)
    assert out["x"].addressable_shards[0].data.shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(out["x"]), batch["x"])


def test_sharded_compute_correctness(devices):
    """TP matmul under jit equals the unsharded matmul."""
    mesh = build_mesh(MeshSpec(data=2, model=4), devices=devices)
    rules = PartitionRules([(r"w", P(None, MODEL))])
    rng = np.random.RandomState(0)
    params = shard_pytree({"w": rng.randn(8, 16).astype(np.float32)}, mesh, rules)
    x = shard_batch({"x": rng.randn(4, 8).astype(np.float32)}, mesh)

    out = jax.jit(lambda p, b: b["x"] @ p["w"])(params, x)
    expect = np.asarray(x["x"]) @ np.asarray(params["w"])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)


def test_indivisible_dim_degrades_to_replication(devices):
    """A rule splitting a dim the mesh axis cannot divide (GPT-2's 50257-row
    vocab embedding over model=2) must replicate that dim, not crash."""
    from distributed_pytorch_training_tpu.parallel.sharding import feasible_spec

    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    assert feasible_spec(P(MODEL, None), (50257, 8), mesh) == P(None, None)
    assert feasible_spec(P(MODEL, None), (50258, 8), mesh) == P(MODEL, None)

    rules = PartitionRules([(r"embedding", P(MODEL, None))])
    tree = {"embedding": np.zeros((7, 8), np.float32)}  # 7 % 2 != 0
    sharded = shard_pytree(tree, mesh, rules)
    assert sharded["embedding"].sharding.spec == P(None, None)


def test_shard_batch_scalar_leaf_is_replicated(mesh8):
    out = shard_batch({"x": np.zeros((16, 2), np.float32), "step": np.float32(3.0)}, mesh8)
    assert out["step"].sharding.spec == P()
    assert float(out["step"]) == 3.0


def test_degradation_warns_once_and_resets(devices, caplog):
    """The degraded-layout warning fires (users must see silently-replicated
    tensors), dedupes repeats, and `reset_degradation_warnings` re-arms it —
    without the reset, warn-once state leaks across meshes/tests in one
    process (VERDICT r2 minor)."""
    import logging

    from distributed_pytorch_training_tpu.parallel.sharding import (
        feasible_spec, reset_degradation_warnings,
    )

    mesh = build_mesh(MeshSpec(data=4, model=2), devices=devices)
    reset_degradation_warnings()
    with caplog.at_level(logging.WARNING,
                         logger="distributed_pytorch_training_tpu.parallel.sharding"):
        feasible_spec(P(MODEL, None), (50257, 8), mesh)
        feasible_spec(P(MODEL, None), (50257, 8), mesh)  # deduped
    degr = [r for r in caplog.records if "degraded" in r.getMessage()]
    assert len(degr) == 1, [r.getMessage() for r in caplog.records]

    caplog.clear()
    reset_degradation_warnings()
    with caplog.at_level(logging.WARNING,
                         logger="distributed_pytorch_training_tpu.parallel.sharding"):
        feasible_spec(P(MODEL, None), (50257, 8), mesh)
    degr = [r for r in caplog.records if "degraded" in r.getMessage()]
    assert len(degr) == 1, "reset_degradation_warnings must re-arm the warning"
